"""Configuration system with explicit units.

The reference carries its tuning as bare module-level constants
(`/root/reference/server/thymio_project/thymio_project/main.py:17-26`) plus a
slam_toolbox YAML (`server/thymio_project/config/slam_config.yaml`) and env
vars. It also carries a famous unit trap: `SPEED_COEFF` differs 100x between
the server variant (0.0003027, metres) and the pi variant (0.03027,
centimetres) — see SURVEY.md Appendix B. Here every physical quantity carries
its unit in the field name, and configs are frozen dataclasses usable as jit
static arguments.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
from typing import Any, Dict, Optional, Tuple


def _frozen(cls):
    return dataclasses.dataclass(frozen=True)(cls)


@_frozen
class GridConfig:
    """Occupancy-grid geometry + log-odds sensor model.

    Mirrors the capability surface of slam_toolbox's grid parameters
    (`/root/reference/server/thymio_project/config/slam_config.yaml:26-27`:
    resolution 0.05 m, max laser range 12 m), re-expressed for a fixed-shape
    device-resident log-odds grid.
    """

    size_cells: int = 4096            # grid is size x size cells, static shape
    resolution_m: float = 0.05        # metres per cell (slam_config.yaml:26)
    # Local update patch edge; must satisfy
    # patch/2 - align_cols/2 >= max_range_m/resolution_m for full coverage.
    patch_cells: int = 640
    max_range_m: float = 12.0         # slam_config.yaml:27
    align_rows: int = 8               # patch-origin alignment (TPU sublane)
    align_cols: int = 128             # patch-origin alignment (TPU lane)
    # Log-odds inverse sensor model.
    logodds_free: float = -0.40       # increment for cells a beam passes through
    logodds_occ: float = 0.85         # increment for cells a beam terminates in
    logodds_min: float = -4.0
    logodds_max: float = 4.0
    occ_threshold: float = 0.5        # log-odds above which a cell reports occupied
    free_threshold: float = -0.5      # log-odds below which a cell reports free
    hit_tolerance_cells: float = 1.0  # half-width of the "occupied" band, in cells
    # Fused fusion path (ops/fuse_kernel.py): classify -> log-odds fold ->
    # touched-tile accounting in one pass, never materialising the
    # (B, P, P) deltas array in HBM (streaming XLA engine everywhere; a
    # fused Mosaic kernel on TPU keeps the window patch VMEM-resident
    # across the scan batch). False = the pre-fused dispatch chain
    # bit-exactly (classify batch -> sequential fold -> separate
    # full-grid tile hash), property-tested in tests/test_fuse_kernel.py.
    fused_fusion: bool = True

    @property
    def extent_m(self) -> float:
        return self.size_cells * self.resolution_m

    @property
    def origin_m(self) -> Tuple[float, float]:
        """World coordinate of cell (0, 0)'s corner; grid is centred on (0,0)."""
        half = self.extent_m / 2.0
        return (-half, -half)

    @property
    def max_range_cells(self) -> float:
        return self.max_range_m / self.resolution_m

    def contains_m(self, x: float, y: float) -> bool:
        """True when world point (x, y) lies on the grid: finite and
        inside the half-open extent [origin, origin + extent) on both
        axes. Upper bound EXCLUSIVE: x == origin + extent maps to cell
        `size_cells`, which only exists by clipping. THE goal-ingress
        predicate — brain, planner, and HTTP route all gate on this, so
        extent semantics can never diverge between ingresses."""
        if not (math.isfinite(x) and math.isfinite(y)):
            return False
        ox, oy = self.origin_m
        span = self.extent_m
        return ox <= x < ox + span and oy <= y < oy + span


@_frozen
class ScanConfig:
    """LaserScan geometry: the LD06 contract.

    The LD06 spins counterclockwise producing ~360 beams/rotation at ~10 Hz
    (`/root/reference/pi/src/thymio_project/launch/pi_hardware.launch.py:13-21`,
    counterclockwise; `BASELINE.md`). Beams pad to a static length. A range
    reading of exactly 0 is an outlier and is treated as `invalid_range_m`
    (semantics of `server/.../main.py:152`: `ranges[ranges == 0] = 10.0`).
    """

    n_beams: int = 360
    padded_beams: int = 512           # static shape; tail is masked invalid
    angle_min_rad: float = 0.0
    angle_increment_rad: float = 2.0 * math.pi / 360.0
    counterclockwise: bool = True     # pi_hardware.launch.py:20 laser_scan_dir
    invalid_range_m: float = 10.0     # server/.../main.py:152 outlier clamp
    range_min_m: float = 0.02
    range_max_m: float = 12.0


@_frozen
class RobotConfig:
    """Differential-drive Thymio II model with explicit units.

    Calibration from the reference server brain
    (`/root/reference/server/thymio_project/thymio_project/main.py:18-26`) and
    report.pdf §III.D: K_d ~= 0.03027 cm/unit/s == 0.0003027 m/unit/s. The pi
    variant stores the cm figure (`pi/src/.../main.py:23`) — the 100x trap
    this config kills by putting the unit in the name.
    """

    speed_coeff_m_per_unit_s: float = 0.0003027   # server main.py:18 (metres!)
    wheel_base_m: float = 0.0935                  # ROBOT_WIDTH, main.py:19
    cruise_speed_units: int = 100                 # ROBOT_SPEED, main.py:21
    rotation_speed_units: int = 50                # ROTATION_SPEED, main.py:22
    ir_threshold: int = 1800                      # IR_THRESHOLD, main.py:25
    lidar_warn_dist_m: float = 0.25               # LIDAR_WARN_DIST, main.py:26
    lidar_stop_dist_m: float = 0.40               # pi variant stop distance (pi main.py)
    swerve_inner_units: int = -10                 # inner-wheel target during swerve (main.py:168-175)
    control_rate_hz: float = 10.0                 # server loop (main.py:60)
    # Thymio motor target saturation range (|target| <= 600 wire units);
    # every wheel-target producer clamps here BEFORE the int cast so a
    # policy can never command a value the firmware would clip
    # differently than the odometry model assumes.
    motor_limit_units: int = 600
    # Pi variant odometry reads motor *targets* not measured speeds
    # (pi/src/.../main.py:188-191); the sim models this as first-order lag.
    motor_lag_tau_s: float = 0.15
    # Multiplicative wheel-speed measurement noise the SIM feeds the
    # odometry path (report.pdf §V.B: 13% calibration CV motivates a
    # nonzero default). 0.0 = measured speeds equal actual speeds, so
    # the odometry estimate tracks sim ground truth to wire-quantization
    # precision — scripted-trajectory soaks rely on that to keep a
    # goal-regulated robot physically on its lane without scan matching.
    speed_noise_frac: float = 0.05

    @property
    def speed_coeff_cm_per_unit_s(self) -> float:
        return self.speed_coeff_m_per_unit_s * 100.0


@_frozen
class MatcherConfig:
    """Correlative scan-matcher search windows.

    Capability target of slam_toolbox's matcher as configured by
    `/root/reference/server/thymio_project/config/slam_config.yaml:51-66`:
    +-0.5 m translation window, coarse angle +-0.349 rad @ 0.0349,
    fine angle resolution 0.00349, smear deviation 0.1.
    """

    search_half_extent_m: float = 0.5         # slam_config.yaml:51
    coarse_step_m: float = 0.05               # coarse pass at grid resolution
    fine_step_m: float = 0.01                 # slam_config.yaml:52 fine resolution
    coarse_angle_half_rad: float = 0.349      # slam_config.yaml:66
    coarse_angle_step_rad: float = 0.0349     # slam_config.yaml:65
    fine_angle_step_rad: float = 0.00349      # slam_config.yaml:64
    smear_cells: int = 2                      # likelihood-field smear radius (yaml:53)
    min_response: float = 0.1                 # acceptance gate
    # Variance penalties (slam_config.yaml:61-62, Karto semantics): the
    # matcher RANKS candidates by penalty * response — preferring solutions
    # near the odometric prior when responses tie (kills translation-
    # symmetric aliases, e.g. parallel walls) — but GATES on the raw
    # response of the winner. Floors are Karto's defaults.
    distance_variance_penalty_m2: float = 0.5   # yaml:61
    angle_variance_penalty_rad2: float = 1.0    # yaml:62
    min_distance_penalty: float = 0.5
    min_angle_penalty: float = 0.9
    # Coarse-pass correlation in bfloat16 (fp32 accumulate): ~6x faster on
    # the MXU, and a worst-case ~0.4% score perturbation can only flip
    # near-tie COARSE winners — the fine passes re-search +-1 coarse step
    # and every gate (min_response, loop response_fine) reads fp32 scores.
    # TPU-only: off-TPU the matcher ignores it (XLA CPU has no fast bf16
    # conv path and runs orders of magnitude slower than f32).
    coarse_bf16: bool = True
    # Branch-and-bound coarse stage (ops/scan_match module docstring):
    # score the whole window on a max-pyramid's coarsest level (each
    # coarse cell upper-bounds its children, so pruning is admissible),
    # keep the top-K candidate branches per level, descend to exact
    # leaf scores — same argmax contract as the f32 exhaustive sweep at
    # a small fraction of the candidate evaluations (on TPU, coarse_bf16
    # rounding can flip near-tie coarse winners of the EXHAUSTIVE path
    # relative to f32; the pruned path always scores f32). False = the
    # bit-exact exhaustive sweep (the pre-pruning pipeline). Windows too
    # small to build a pyramid over fall through automatically.
    pruned: bool = True
    # Candidate branches kept per DOWNSAMPLED pyramid level. The winner
    # survives as long as its ancestors rank inside the top-K upper
    # bounds at every level; 64 holds argmax parity across the
    # property-test worlds with ~10-40x fewer coarse evaluations at
    # production windows.
    bnb_topk: int = 64
    # Branches entering the final FULL-RESOLUTION leaf round — the only
    # round whose candidate evaluations touch whole P^2 patches, so it
    # dominates the descent's memory traffic. By level 1 the dual-
    # pyramid bounds are tight (2x2-leaf blocks at half resolution);
    # a narrower funnel there is the cheap/safe trade.
    bnb_leaf_topk: int = 16
    # Pyramid depth above full resolution; 0 = auto (deepest level whose
    # top grid keeps >= 3 nodes per axis, capped at 6).
    bnb_levels: int = 0
    # Gating: only match when moved enough (slam_config.yaml:37-38).
    min_travel_m: float = 0.1
    min_heading_rad: float = 0.1


@_frozen
class LoopClosureConfig:
    """Loop-closure gating, per slam_config.yaml:43-58."""

    enabled: bool = True
    search_radius_m: float = 3.0              # yaml:44 loop_search_maximum_distance
    min_chain_size: int = 10                  # yaml:45
    response_coarse: float = 0.35             # yaml:47
    response_fine: float = 0.45               # yaml:48
    loop_window_m: float = 8.0                # yaml:56 loop search space dimension
    # Wide-stage grid downsample: the 8 m loop window is swept on a grid
    # this many times coarser (models/slam two-stage loop verification).
    coarse_downsample: int = 4
    max_poses: int = 1024                     # pose ring-buffer capacity (static)
    max_edges: int = 4096                     # edge buffer capacity (static)
    gn_iters: int = 8                         # Gauss-Newton iterations per solve
    damping: float = 1e-3
    # Cross-robot closure: a key robot with no own-graph candidate may
    # verify against ANOTHER robot's chain map and anchor its own graph to
    # the result (models/fleet._cross_candidates). The reference gets
    # inter-robot consistency for free from its single SLAM node fusing
    # every scan (`pc_server.launch.py:14-19`); here per-robot graphs
    # shard over the fleet axis, so cross-robot constraints are explicit.
    cross_robot: bool = True


@_frozen
class FrontierConfig:
    """Wavefront frontier exploration (the map-based explorer the reference
    lists as future work, report.pdf §VI.2; replaces the reactive subsumption
    navigator at `server/.../main.py:119-196`)."""

    downsample: int = 4               # frontier work at size/downsample resolution
    # Clustering (connected components + summarisation) runs another factor
    # coarser: labels/centroids/assignment at size/(downsample*cluster_downsample).
    # 1 = exact single-level clustering; >1 is the latency path (label
    # propagation and segment reductions shrink by cluster_downsample^2;
    # frontier cells within cluster_downsample coarse cells merge).
    cluster_downsample: int = 4
    max_clusters: int = 64            # static cluster slot count
    min_cluster_cells: int = 4        # ignore tiny frontiers (fine frontier cells)
    # Iteration bounds, expressed in FIRST-LEVEL coarse cells (size/downsample)
    # so their meaning does not change with cluster_downsample: the
    # hierarchical path divides them by cluster_downsample internally
    # (its grid is that much smaller).
    label_prop_iters: int = 96        # connected-component propagation bound
    bfs_iters: int = 512              # multi-source cost-to-go bound
    # Obstacle-aware BFS costs (accurate, heavier) vs Euclidean centroid
    # distance (cheap; what the <5 ms @ 64 robots latency budget buys).
    obstacle_aware: bool = True
    # Obstacle-aware engine: multigrid cost fields (ops/costfield.py) —
    # upper-bound costs, narrow corridors (< 2 coarse cells) may stay
    # overestimated within the refinement budget. exact_bfs=True restores
    # the full-diameter single-level dilation (slow; bfs_iters bound).
    exact_bfs: bool = False
    mg_levels: int = 3                # multigrid resolutions
    mg_refine_iters: int = 8          # doubled sweeps per refinement level
    # Bridge-brain consumption of the published assignments: exploring
    # robots without a manual nav goal steer at their assigned frontier
    # (map-based exploration, report.pdf §VI.2) instead of blind cruise;
    # the reactive shield still outranks. False = the reference's pure
    # subsumption wander.
    seek_assigned: bool = True
    # On-device planned steering for the fleet model: steer at a
    # waypoint descended from a TARGET-seeded cost field instead of
    # straight at the assigned target (frontier.assigned_waypoints).
    # Roughly doubles the obstacle-aware frontier cost (a second
    # cost_fields pass), so it defaults off — the <5 ms p50 @ 64 robots
    # budget was set without it.
    planned_goals: bool = False
    waypoint_lookahead: int = 2       # descent steps, clustering cells
    # Assignments older than this (in control-loop time) are ignored —
    # a dead mapper must not leave robots chasing stale frontiers.
    seek_ttl_s: float = 5.0
    # ---- incremental publish pipeline (ops/frontier_incremental.py) ----
    # Revision-keyed incremental recompute for the BRIDGE publish path
    # (mapper.publish_frontiers): re-coarsen only serving tiles whose
    # `_tile_rev` advanced, run label propagation / summarisation /
    # cost-to-go on the active-region crop, warm-start cost fields from
    # the previous publish, and skip the whole recompute when nothing
    # changed. False = the pre-incremental publish pipeline bit-exactly
    # (one full-grid compute_frontiers per publish). The jitted
    # compute_frontiers / fleet-model paths are unaffected either way.
    incremental: bool = True
    # Safety margin around the observed-region crop, in first-level
    # coarse cells. Parity margin: an optimal detour around observed
    # obstacles leaves the OBSERVED bbox by at most one cell (obstacles
    # live only in observed space), so any pad >= 2 BFS-resolution cells
    # keeps converged cost fields identical to the full-grid solve; the
    # extra margin keeps finite-iteration multigrid boundary effects
    # away from targets and robots.
    crop_pad: int = 32
    # Publish skip: when no tile revision advanced and no robot moved
    # more than this (metres) — nor changed BFS cell — the cached result
    # is republished through fresh reassign/blacklist post-passes. With
    # obstacle-aware costs the cell condition makes the skip
    # output-exact; in Euclidean mode this bounds the assignment drift a
    # skipped sub-threshold move could cause.
    pose_skip_m: float = 0.05
    # Warm-start: carry the previous publish's cost fields (offset by
    # each robot's own previous-field value at its new cell — a valid
    # upper bound by the triangle inequality) as the relaxation init.
    # Only sound while no blocked cell APPEARED in the crop: min-plus
    # relaxation never raises a value, so a stale underestimate through
    # a newly-discovered wall could never heal — new occupancy forces a
    # cold multigrid solve instead.
    warm_start: bool = True
    # Doubled-sweep budget for the warm-started relaxation: the
    # tightening wavefront (2 cells/sweep) must cover the robots'
    # movement since the previous solve, so moves beyond
    # 2*warm_extra_iters - 2 BFS cells force a cold multigrid solve
    # instead. When nothing changed at all (no occupancy flip in the
    # crop, no robot changed cell) the pipeline reuses the carried
    # fields EXACTLY (a 0-sweep re-mask) — the steady-state fast path.
    warm_extra_iters: int = 4
    # ---- decay-aware scoring (scenario-engine follow-up) ----------------
    # Prioritize HEALED/STALE regions for re-verification: under map
    # decay (DecayConfig) evidence fades toward unknown, so a cell that
    # was once mapped reads "unknown" again while still carrying
    # residual sub-threshold log-odds. With `decay_aware` on, frontier
    # clusters whose targets border such touched-but-unknown cells get
    # a cost DISCOUNT in the assignment auction (up to `stale_bonus`
    # fractional, scaled by the stale fraction of the target's
    # neighbourhood) — the fleet re-verifies what the world may have
    # changed instead of merely re-opening it. False (default) is the
    # pre-existing pipeline bit-exactly: no stale mask is computed and
    # costs are untouched (parity-tested). The bridge publish path
    # keeps its incremental pipeline either way: the HEALED/STALE mask
    # is carried tile-incrementally alongside the other coarse masks
    # (`frontier_incremental`; a decay pass bumps every tile revision,
    # so staleness refreshes with them).
    decay_aware: bool = False
    # Maximum fractional cost discount for a fully-stale target
    # neighbourhood; the auction still ranks by distance within equally
    # stale frontiers.
    stale_bonus: float = 0.3


@_frozen
class PlannerConfig:
    """Map-aware global path planning for RViz SetGoal navigation.

    The reference shipped the SetGoal tool publishing `/goal_pose` with no
    consumer (Nav2 was listed as future work, report.pdf §VI.2;
    `server/rviz_config.rviz:193-198`). Round 4 gave the brain straight-line
    goal seeking with the reactive shield; this section adds the Nav2-shaped
    capability behind that same topic: a goal-seeded obstacle-aware
    cost-to-go field over the live map (ops/planner.py, reusing the frontier
    machinery's coarsen + min-plus BFS), greedy-descent path extraction, a
    published `/plan` for RViz, and a lookahead waypoint the brain steers to
    instead of the raw goal — so a goal behind a wall is navigated around,
    not just shielded against.
    """

    enabled: bool = True
    period_s: float = 1.0             # replan cadence (map moves slowly)
    # Descent bound, in first-level coarse cells (size/frontier.downsample);
    # also the static /plan length.
    max_path_len: int = 256
    # Waypoint distance along the path, coarse cells. Far enough that the
    # reactive shield's swerves don't orbit it; near enough that steering
    # straight at it cannot cut a corner by more than the conservative
    # coarsening's ~1-cell wall inflation (the shield covers the rest).
    lookahead_cells: int = 4
    # Brain falls back to straight-line seek when the freshest waypoint is
    # older than this (planner dead / not launched — round-4 behavior).
    waypoint_ttl_s: float = 3.0
    # Goal-seeded BFS bound, in first-level coarse cells. The field must
    # reach the robot for the goal to be declared reachable; each bound
    # unit is one doubled min-plus sweep (radius 2 cells).
    bfs_iters: int = 512
    # Plan for assigned FRONTIERS too (not just the manual nav goal):
    # each replan period the planner computes a path per exploring robot
    # to its /frontiers assignment and publishes per-robot waypoints the
    # brain steers at — frontier exploration that navigates around walls
    # instead of straight-line seeking into them.
    frontier_waypoints: bool = True
    # 3D-aware planning: overlay the voxel map's obstacle slice (any
    # occupied voxel in the robot's height band) as occupied cells in
    # the grid the planner searches — obstacles the 2D LiDAR plane
    # misses (overhangs, low clutter under the scan plane) block plans
    # when a depth camera maps them. Needs the 3D pipeline (depth_cam).
    use_voxel_obstacles: bool = True
    # The height band a robot must clear, metres above the floor. Floor
    # returns stay out of the band (z_min above the ground plane).
    voxel_z_min_m: float = 0.05
    voxel_z_max_m: float = 0.30


@_frozen
class VoxelConfig:
    """3D log-odds voxel grid (BASELINE.json configs[4]: "3D voxel grid
    (OctoMap-style) from simulated depth cam").

    Generalizes the 2D grid capability (slam_config.yaml:26-27) to 3D with
    the same dense inverse-sensor-patch idiom (ops/voxel.py). Memory layout
    is (Z, Y, X) — X on TPU lanes (128-aligned origins), Y on sublanes, Z
    as the small outer axis — and update patches span the FULL Z extent so
    patch origins stay 2D (y0, x0), exactly like the 2D grid's.
    """

    size_x_cells: int = 1024          # grid extent, static shape
    size_y_cells: int = 1024
    size_z_cells: int = 64
    resolution_m: float = 0.05        # same cell size as the 2D grid
    # Local update patch edge (x == y; z is always full). Must satisfy
    # patch/2 - align_x/2 >= max_range_m/resolution_m, the same coverage
    # contract as GridConfig.patch_cells: origin alignment can shift the
    # patch up to align_x/2 cells off-centre, and returns past the slack
    # would fall outside the update region and silently vanish (default:
    # 192 - 64 = 128 cells = 6.4 m >= the 5 m depth-cam range).
    patch_cells: int = 384
    max_range_m: float = 5.0          # depth-cam trust horizon
    align_y: int = 8                  # patch-origin alignment (TPU sublane)
    align_x: int = 128                # patch-origin alignment (TPU lane)
    # Log-odds inverse sensor model (same bounded-relaxation semantics as
    # GridConfig; OctoMap's probHit/probMiss equivalents).
    logodds_free: float = -0.40
    logodds_occ: float = 0.85
    logodds_min: float = -4.0
    logodds_max: float = 4.0
    occ_threshold: float = 0.5
    free_threshold: float = -0.5
    hit_tolerance_cells: float = 1.0  # half-width of the occupied shell, cells
    # Bounded depth-keyframe ring the SLAM-coupled 3D mapper re-fuses
    # from after loop closures (bridge/voxel_mapper.py) — the 3D analog
    # of the 2D scan ring. The cap is PER FLEET (each robot's ring gets
    # cap // n_robots slots) so host memory is sized by this one number:
    # 256 x 160x120 f32 images = ~20 MB regardless of fleet size. When a
    # robot's ring fills, keyframe density halves (even decimation), the
    # thin_keyframes longevity pattern.
    keyframe_cap: int = 256

    @property
    def extent_m(self) -> Tuple[float, float, float]:
        return (self.size_x_cells * self.resolution_m,
                self.size_y_cells * self.resolution_m,
                self.size_z_cells * self.resolution_m)

    @property
    def origin_m(self) -> Tuple[float, float, float]:
        """World coordinate of voxel (0,0,0)'s corner: grid centred on
        (0,0) in x/y, z starts at 0 (ground plane)."""
        ex, ey, _ = self.extent_m
        return (-ex / 2.0, -ey / 2.0, 0.0)


@_frozen
class DepthCamConfig:
    """Simulated pinhole depth camera.

    The reference has no depth sensor — this is the blueprint's 3D
    extension (BASELINE.json configs[4]). Pinhole model, optical
    convention: camera z forward, x right, y down. A reading of exactly 0
    means "no return" and carves NOTHING (unlike the LD06's zero-as-
    outlier rule, server/.../main.py:152 — depth cams return 0 for
    out-of-range or absorptive surfaces, so carving to max range would
    wrongly clear unknown space).
    """

    width_px: int = 160
    height_px: int = 120
    hfov_rad: float = 1.5010          # ~86 deg (RealSense D435-class)
    range_min_m: float = 0.2
    range_max_m: float = 5.0
    mount_height_m: float = 0.25      # camera z above ground on the robot
    mount_pitch_rad: float = 0.0      # >0 tilts the optical axis up

    @property
    def fx(self) -> float:
        return (self.width_px / 2.0) / math.tan(self.hfov_rad / 2.0)

    @property
    def fy(self) -> float:
        return self.fx                # square pixels

    @property
    def cx(self) -> float:
        return self.width_px / 2.0 - 0.5

    @property
    def cy(self) -> float:
        return self.height_px / 2.0 - 0.5


@_frozen
class ResilienceConfig:
    """Fleet supervision + graceful degradation (resilience/ subsystem).

    The reference simply dies when a link or sensor drops (SURVEY.md §5
    "Failure detection / recovery": driver retries only; the map is lost
    on any restart). These knobs parameterize the degraded-mode state
    machine threaded through brain/mapper/planner and the Supervisor's
    restart policy. Staleness thresholds are in CONTROL TICKS, the
    deterministic time base (the repo's TTL doctrine,
    brain._steer_target): wall-clock thresholds would make health
    transitions host-speed-dependent in faster-than-realtime runs.
    """

    enabled: bool = True
    # Robot-level degradation: control ticks without a scan before the
    # robot coasts on odometry (NO_LIDAR: stop commanding motion, keep
    # integrating pose, stop expecting fusion), and before it is
    # declared DEAD (fleet reassigns its frontier work).
    lidar_silent_ticks: int = 10
    dead_after_ticks: int = 30
    # Node-level supervision: supervisor ticks without a heartbeat
    # before a node is declared dead, and the restart policy's
    # exponential backoff (in supervisor ticks) with seeded jitter.
    supervisor_missed_beats: int = 3
    restart_backoff_base_steps: int = 2
    restart_backoff_max_steps: int = 64
    restart_backoff_jitter: float = 0.25
    # Supervisor auto-checkpoint cadence (steps); the resume source for
    # restart-from-checkpoint. 0 disables auto-checkpointing.
    checkpoint_every_steps: int = 50
    # Checkpoint generations kept on disk (io/checkpoint.py): 2 is the
    # historical current + `.prev` last-good pair; larger values retain
    # that many total generations (the extras as numbered `.genNNNNNN`
    # files, GC'd corruption-safely oldest-first) — the lifelong-session
    # bound that keeps a day of rotation cadence from growing the
    # checkpoint directory without limit.
    checkpoint_retain_generations: int = 2
    # Mapper degraded-mode gate: windows whose fused-evidence agreement
    # falls below this are REJECTED (not installed) — a garbage burst
    # from a glitching sensor must not overwrite known-good map. The
    # telemetry threshold (0.5, n_low_agreement_windows) stays separate:
    # this is the do-no-harm floor, far below normal operation.
    window_agreement_reject: float = 0.02
    # HTTP management plane: bounded lock wait before answering 503
    # degraded instead of blocking a worker thread indefinitely.
    http_lock_timeout_s: float = 2.0


@_frozen
class ColdStartConfig:
    """Warm-restart tier: persistent compile cache + AOT executable
    snapshots (io/compile_cache.py, resilience/warmup.py).

    The cost ledger and recompile telemetry (obs/devprof.py) show every
    process restart re-pays full XLA compilation, so the supervisor's
    checkpoint-resume trades availability for a compile storm. These
    knobs arm the warm-restart path: (1) JAX's persistent compilation
    cache wired through launch (bounded on-disk size, LRU-evicted;
    corrupt or incompatible entries degrade to recompile, never crash);
    (2) AOT executable snapshots — compiled executables serialized per
    (function, captured signature) under a compatibility FINGERPRINT
    (jax/jaxlib version, backend, config hash) and served back to live
    calls by a transparent warm-dispatch wrapper; on any mismatch the
    ladder degrades snapshot -> persistent cache -> cold compile; and
    (3) the staged supervisor warm-up (restore, pre-warm entry points
    in priority order, readiness gate) that re-admits a restarted node
    only once warmed, while serving answers from the prior epoch with
    `state=warming`.

    `enabled=False` constructs nothing — no cache config touched, no
    wrapper on any dispatch path, bit-exact pre-PR behavior. Enabled is
    bit-inert: a cache/snapshot hit returns the identical compiled
    executable a cold compile would produce on the same fingerprint
    (warm-vs-cold mission bit-identity is the bench gate).
    """

    enabled: bool = False
    # Cache root directory. "" derives `<checkpoint_dir>/compile_cache`
    # from the launch checkpoint dir; with neither set, the cold-start
    # tier stays off (nowhere to persist).
    cache_dir: str = ""
    # On-disk budget over the whole cache root (XLA cache entries + AOT
    # snapshots); least-recently-used files are evicted past it.
    max_cache_bytes: int = 256 * 1024 * 1024
    # Serialize AOT executable snapshots on `Stack.save_compile_
    # snapshots()` and serve them from the warm pool. Off leaves the
    # persistent cache as the only warm tier.
    aot_snapshots: bool = True
    # Run the staged warm-up at launch when snapshots for this
    # fingerprint exist (the resume-process path); the supervisor
    # restart path always stages regardless.
    prewarm_on_launch: bool = True


@_frozen
class RecoveryConfig:
    """Estimator guardrails (recovery/ subsystem).

    PR 2's resilience layer watches *processes* (heartbeats, links,
    scan arrival); nothing watches the ESTIMATOR itself — a robot whose
    scan-matcher quietly diverges keeps fusing garbage into the shared
    map, and a stuck or oscillating explorer burns the mission clock
    forever (the reference's "Failure detection / recovery" gap,
    SURVEY.md §5). These knobs parameterize (1) the divergence watchdog
    folding the per-step SlamDiag stream into a per-robot health score
    with hysteresis, (2) the quarantine + wide-window relocalization
    path that re-admits a diverged robot only after a verified
    re-anchor, and (3) the anti-stuck recovery ladder (rotate-in-place
    rescan -> backup -> frontier blacklist with TTL -> goal
    reassignment). `enabled=False` restores pre-guardrail behavior
    exactly: no watchdog observations, no quarantine, no overrides.

    Time base: watchdog thresholds count MAPPER OBSERVATIONS (key-scan
    steps — the only steps that add map evidence); anti-stuck
    thresholds count CONTROL TICKS (the repo's deterministic TTL
    doctrine, brain._steer_target).
    """

    # Requires ResilienceConfig.enabled: the guardrails ACT through the
    # FleetHealth ladder (coast, LED, frontier reassignment, /status
    # export) — launch leaves them off when resilience is disabled.
    enabled: bool = True
    # -- divergence watchdog -------------------------------------------------
    # Observations before the score is trusted: with an empty map the
    # matcher legitimately rejects (bootstrap), and declaring divergence
    # there would quarantine a healthy robot at mission start.
    min_keyscans: int = 5
    # Badness EWMA: score = decay * score + (1 - decay) * bad, where
    # bad = agreement_weight * min(1, (1-agreement)/deficit_scale)
    #     + match_weight * (1 - matched)           [key steps only]
    #     + cov_weight * min(1, cov_trace/cov_scale).
    # Observed at FULL scan cadence (sub-gate steps sample
    # models.slam.scan_agreement) — a ghosting sensor fires every scan,
    # not every 0.1 m of travel.
    score_decay: float = 0.7
    match_weight: float = 0.5
    agreement_weight: float = 0.5
    # Healthy scans agree within ~0.05 of 1.0; adversarial scans sit
    # 0.25-0.4 below (measured: ghost_returns 0.5 -> ~0.65, wheel_slip
    # 1.4 -> ~0.75 during drift). The scale maps that gap onto [0, 1].
    agreement_deficit_scale: float = 0.35
    cov_weight: float = 0.1
    cov_scale_m2: float = 0.05
    # Hysteresis: the score must sit at or above the threshold for
    # `diverge_persist_steps` CONSECUTIVE observations to declare
    # ESTIMATOR_DIVERGED — one bad scan is weather, a streak is a fault.
    diverge_threshold: float = 0.4
    diverge_persist_steps: int = 3
    # -- quarantine + relocalization ----------------------------------------
    # Bounded per-robot buffer of quarantined (scan, odom) evidence —
    # telemetry for the operator, never fused (the poses it was paired
    # with are exactly what diverged).
    quarantine_cap: int = 64
    # Re-anchor verification: the wide-window match must ACCEPT with at
    # least this response for `reloc_consecutive` consecutive scans,
    # with the candidate poses agreeing within the consistency radii —
    # one lucky basin must not re-admit a lost robot.
    reloc_min_response: float = 0.35
    reloc_consecutive: int = 2
    reloc_consistency_m: float = 0.2
    reloc_consistency_rad: float = 0.25
    # The verifying scan must also AGREE with the map at the candidate
    # pose: a lost-but-healthy-sensor robot re-admits immediately (its
    # scan fits the map at the true pose), while an ACTIVELY faulting
    # sensor — whose wide match can still find plausible basins — stays
    # quarantined until the fault clears (re-admitting it would resume
    # fusing the same garbage the watchdog just caught).
    reloc_min_agreement: float = 0.8
    # -- anti-stuck recovery ladder -----------------------------------------
    # Stuck: over the last `stuck_window_ticks` control ticks the robot
    # was commanded motion (mean |wheel target| >= min_commanded_units)
    # for >= stuck_commanded_frac of them, yet its net odometric
    # displacement reached under `stuck_displacement_frac` of the
    # distance those commands SHOULD have produced (sum of commanded
    # wheel speed x speed_coeff x dt) — wedged against geometry the
    # shield oscillates on. (Wheels spinning in place feed phantom
    # motion into odometry and are the WATCHDOG's case — they surface
    # as estimator divergence, not as a stuck detection.) The
    # commanded-relative floor is the point: an absolute floor would
    # misread a slow-but-healthy platform as stuck (a cruising Thymio
    # covers only ~0.036 m in 12 ticks).
    stuck_window_ticks: int = 30
    stuck_displacement_frac: float = 0.25
    stuck_commanded_frac: float = 0.6
    min_commanded_units: int = 20
    # Escalating recoveries: rotate-in-place rescan, then reverse out,
    # then blacklist the frontier goal (TTL below) and force
    # reassignment. A re-detection within escalation_memory_ticks
    # escalates to the next rung; a clean stretch resets to rung 0.
    rotate_recovery_ticks: int = 12
    backup_recovery_ticks: int = 10
    escalation_memory_ticks: int = 90
    blacklist_ttl_ticks: int = 300


@_frozen
class DecayConfig:
    """Map healing for dynamic worlds (scenarios/ subsystem).

    Static-world fusion treats occupancy evidence as permanent: a door
    mapped closed saturates at `logodds_max` and needs dozens of free
    observations to flip once it opens — in a world that CHANGES (doors,
    crowds, rearranged furniture) the map must *heal*, not just
    accumulate (ROG-Map / Occupancy-SLAM's robustness-to-stale-evidence
    argument, PAPERS.md). Two knobs implement it, both applied in ONE
    periodic on-device pass over the shared grid
    (`ops/grid.decay_grid`, driven by the mapper's tick clock):

    * multiplicative log-odds decay toward unknown (`factor` every
      `every_n_ticks` mapper ticks) — unobserved stale evidence fades;
    * an evidence saturation cap (`evidence_cap`) — re-observation can
      always flip a cell within a bounded number of contradicting
      scans, because no cell ever gets more entrenched than the cap.

    The decay pass rides the ordinary revision bookkeeping (one
    `map_revision` bump + all tiles marked dirty), so serving deltas,
    the incremental frontier pipeline and the matcher's pyramid caches
    all see healed regions as ordinary revision advances.

    `enabled=False` is EXACT pre-decay fusion: no pass ever runs, no
    tick counter consulted, bit-identical output (the scenario bit-
    exactness property test pins this).
    """

    enabled: bool = False
    # Mapper ticks between decay passes (the deterministic step clock,
    # like every scenario cadence — wall-clock decay would make healing
    # host-speed-dependent in faster-than-realtime runs).
    every_n_ticks: int = 20
    # Multiplier applied to every cell's log-odds per pass (toward 0 =
    # unknown). 1.0 disables fading but keeps the cap.
    factor: float = 0.92
    # |log-odds| clamp applied by the decay pass: bounds how entrenched
    # any evidence can get while the world is allowed to change, so a
    # re-observed contradiction (door opened, crowd moved on) flips the
    # cell within ~cap/|logodds_free| scans.
    evidence_cap: float = 2.0


@_frozen
class DevProfConfig:
    """Device-side performance observability (obs/devprof.py +
    obs/ledger.py).

    PR 9's tracing made the HOST side legible; the device side — where
    the TPU-native mapping math actually runs — stayed a black box: no
    per-dispatch wall time attributed to jitted entry points, no
    FLOPs/bytes cost accounting, no recompile telemetry. These knobs
    arm the dispatch profiler: `enabled=True` wraps every registered
    jitted entry point (the same `_cache_size` registry
    `analysis/compilebudget.py` walks) in a transparent pass-through
    that attributes blocked-on-host dispatch wall time to
    `jax_mapping_device_*` metric families (fixed `HIST_EDGES_S`
    log-bucket histograms, the stage-histogram doctrine), counts
    compiled-variant growth per function
    (`jax_mapping_jit_recompiles_total`), captures one abstract
    arg-signature per compiled variant for the static XLA cost ledger
    (`lowered.compile().cost_analysis()` FLOPs / bytes-accessed,
    exported on `/status` `perf` and dumped by `python -m
    jax_mapping.obs cost-ledger`), and exports backend memory
    watermarks where the backend provides them
    (`device.memory_stats()`; gracefully absent on CPU).

    `enabled=False` constructs NOTHING — no wrapper exists anywhere on
    the dispatch path, bit-exact pre-PR behavior (the
    ObsConfig/DecayConfig doctrine); `enabled=True` is host-side
    bookkeeping only and must be equally bit-inert (both pinned by the
    devprof bit-inertness property test)."""

    enabled: bool = False
    #: Capture one abstract (ShapeDtypeStruct) arg signature per
    #: compiled variant — the cost ledger's re-lowering input. Bounded
    #: per function below.
    capture_signatures: bool = True
    max_signatures_per_fn: int = 8
    #: Emit a `device:<fn>` tracer span per profiled dispatch when a
    #: Tracer is armed. Off by default: dispatch volume would dominate
    #: the span ring, and HTTP-thread dispatches (tile hashing under a
    #: /tiles poll) would inject nondeterministic spans into the
    #: same-seed stream-identity contract.
    trace_spans: bool = False
    #: Export `device.memory_stats()` watermark gauges on /metrics and
    #: /status (backends without the API — CPU — export nothing).
    memory_stats: bool = True


@_frozen
class SloObjective:
    """One declarative freshness/latency objective (obs/slo.py).

    Objectives are evaluated IN-PROCESS once per mapper tick (the
    deterministic step clock — wall-clock evaluation would make alert
    firing host-speed-dependent in faster-than-realtime runs) over the
    pipeline latency ledger (obs/pipeline.py) and the mapper's revision
    counters, on multi-window sliding BREACH counters with classic
    fast/slow burn-rate gating: the alert fires when BOTH the fast
    window (is it burning right now?) and the slow window (has it been
    burning long enough to matter?) exceed their budget fractions, and
    clears when the fast window recovers. Windows are in TICKS and
    burn fractions use the FIXED window sizes as denominators, so two
    same-seed runs evaluate identical breach sequences and fire at the
    identical step (the chaos-determinism contract extended to
    alerting; the FaultPlan partition/reorder windows are the intended
    alert drill)."""

    name: str = ""
    #: Metric kind:
    #:   scan_to_served_p99_ms  — p99 of the ledger's completed
    #:       scan-enqueue→first-client-delivery samples (ms) exceeds
    #:       `threshold`; `max_silent_ticks` adds the tick-clocked
    #:       ingest-stall guard (a bus partition on the scan path
    #:       delivers NO samples — silence past the guard is a breach).
    #:   tile_staleness_revs    — map_revision minus the newest
    #:       revision any client was served exceeds `threshold`.
    #:   tick_deadline_ms       — the mapper tick's wall duration
    #:       exceeds `threshold` ms (deadline-miss fraction is the
    #:       slow-window burn rate).
    metric: str = "scan_to_served_p99_ms"
    threshold: float = 250.0
    #: scan_to_served only: breach when this many consecutive ticks
    #: pass with no scan INSTALLED (after at least one ever installed)
    #: — the freshness question a completed-sample p99 cannot see,
    #: because an ingest outage produces no samples at all. 0 = off.
    max_silent_ticks: int = 0
    fast_window_ticks: int = 20
    slow_window_ticks: int = 120
    #: Budget fraction of breaching ticks per window before it counts
    #: as burning (denominator = the FIXED window size, so a cold
    #: start cannot fire off one breach).
    fast_burn: float = 0.5
    slow_burn: float = 0.25


@_frozen
class ObsConfig:
    """Causal tracing + flight recorder (obs/ subsystem).

    The reference has no instrumentation at all (SURVEY.md §5 "Tracing
    / profiling: none"); these knobs parameterize the observability
    layer: (1) `enabled` arms CAUSAL TRACING — a deterministic
    `TraceContext` (trace ids derived from `(seed, topic, seq)`)
    carried on every Bus publish/delivery and across mapper tick / HTTP
    handler boundaries, with spans exported as Chrome-trace/Perfetto
    JSON (`GET /trace?since=`, `python -m jax_mapping.obs`); two
    same-seed `run_steps` missions emit IDENTICAL trace streams (the
    FaultPlan determinism contract extended to telemetry). (2) the ring
    capacities bound the span ring and the always-on flight recorder
    (obs/recorder.py — structured load-bearing transitions, auto-dumped
    to the checkpoint dir on supervisor restarts / watchdog divergence
    / racewatch reports; the recorder runs regardless of `enabled`,
    a postmortem that needs a flag flipped beforehand is not one).

    `enabled=False` constructs NO tracer — bit-exact pre-obs behavior;
    `enabled=True` is host-side bookkeeping only and must be equally
    bit-inert (both pinned by the obs bit-inertness property test, the
    DecayConfig/ServingConfig doctrine).
    """

    enabled: bool = False
    #: Bounded span-ring capacity (tracing only; ~120 B/span host-side).
    trace_ring: int = 65536
    #: Flight-recorder event-ring capacity (always on).
    recorder_ring: int = 4096
    #: Device-side dispatch profiling + XLA cost ledger (ISSUE 10) —
    #: its own `enabled` knob, independent of tracing: profiling the
    #: device side must not force span-ring bookkeeping on, and vice
    #: versa.
    devprof: DevProfConfig = DevProfConfig()
    #: Freshness SLO objectives (obs/slo.py), evaluated over the
    #: pipeline latency ledger (obs/pipeline.py — constructed whenever
    #: `enabled` is True; per-revision scan-enqueued → installed →
    #: revision-visible → tile-re-encoded → first-client-delivery
    #: waypoints folded into fixed log-bucket hop histograms). Empty =
    #: no SLO engine constructed; `enabled=False` constructs NEITHER
    #: ledger nor engine — bit-exact, the ObsConfig doctrine.
    slo: Tuple[SloObjective, ...] = ()


@_frozen
class ServingConfig:
    """Tiled delta map distribution (serving/ subsystem).

    The reference's management plane re-encodes and re-ships the ENTIRE
    occupancy grid as one PNG to every polling client (`server/.../
    main.py:241-279`), bounded only by a 1 s wall-clock cache — at fleet
    scale and 4096^2 grids the dominant serving cost. These knobs
    parameterize the tile store (fixed-size tiles + a quadtree overview
    pyramid, re-encoded only when a tile's on-device content hash
    changes), the mapper's dirty-tile/revision tracking, and the
    `/map-events` fan-out push channel with per-client bounded queues.
    `enabled=False` is exact pre-PR behavior: no revision tracking, no
    tile store, `/tiles` and `/map-events` answer 404.
    """

    enabled: bool = True
    # Tile edge length in cells at every pyramid level; must divide
    # grid.size_cells (and the voxel height-map edge when the 3D
    # pipeline serves tiles). 256 -> 16x16 tiles over the 4096^2 grid.
    tile_cells: int = 256
    # Overview pyramid depth INCLUDING level 0 (full resolution); each
    # level is 2x coarser (occupied > free > unknown block priority).
    # Levels whose grid would shrink below one tile are skipped.
    pyramid_levels: int = 3
    # Per-client event queue bound (`/map-events`): a slow client's
    # queue drops its OLDEST revision event on overflow (drop-to-latest
    # backpressure) so it can never pin memory or a worker thread.
    event_queue_depth: int = 4
    # Hard cap on any single long-poll wait / SSE stream lifetime, in
    # seconds — the bounded-wait contract of the degraded 503 path
    # applied to the push channel (clients reconnect, SSE-style).
    event_wait_max_s: float = 30.0
    # zlib level for tile PNG encoding (the whole-map routes keep the
    # png codec default).
    png_compress_level: int = 6


@_frozen
class WorldConfig:
    """Bounded-memory robocentric world store (world/ subsystem).

    The pre-PR design allocates ONE fixed-extent grid (bench: 4096^2
    @ 0.05 m) and every subsystem assumes it; a robot that walks off
    the edge or a multi-day lifelong mission is out of scope. These
    knobs parameterize the sliding-window world store
    (`world/store.py`): a fixed-budget device-resident window of
    serving tiles that shifts with the robot via a zero-copy roll
    (one jitted dispatch), an LRU of evicted tiles spilled to host
    RAM and then to disk with per-tile CRC + generation stamps, and a
    memory-pressure governor with a watermark-driven load-shed ladder
    (shrink retention -> coarsen spilled tiles -> refuse admission).

    `windowed=False` is EXACT pre-PR behavior: no store, no new jits,
    the mapper runs the full fixed grid (the knob-off doctrine,
    property-tested across grids, frontier targets and served tile
    hashes). `grid.size_cells` becomes the LOGICAL extent — the
    addressable world — while device bytes scale with the window
    only."""

    windowed: bool = False
    #: Window edge length, in serving tiles: the device-resident grid
    #: is (window_tiles * serving.tile_cells)^2 cells regardless of
    #: the logical extent. Must leave the derived window grid
    #: divisible by every shape contract the fixed grid honors
    #: (patch, frontier downsample, tile_cells).
    window_tiles: int = 8
    #: Recentring hysteresis, in tiles: the window shifts only when
    #: the robot strays within `margin_tiles` of the window edge, and
    #: recentres so the robot sits in the middle band again. 0 shifts
    #: every tile crossing (churn); large margins shift early.
    margin_tiles: int = 1
    #: Host LRU capacity, in evicted tiles, before the governor's
    #: eviction cadence spills the coldest to disk (or drops them
    #: when no spill dir is configured).
    host_tile_budget: int = 256
    #: Governor watermarks, as fractions of `host_tile_budget`:
    #: above `high` the ladder arms (rung 1: faster spill cadence +
    #: shrunk retention); above `critical` it escalates (rung 2:
    #: coarsen spilled-tile retention by `retention_coarsen`; rung 3
    #: under synthetic/pressure squeeze: refuse admission — evicted
    #: tiles degrade to unknown on re-entry).
    host_high_watermark: float = 0.75
    host_critical_watermark: float = 0.92
    #: Disk spill directory; "" = no disk tier (host LRU overflow is
    #: dropped at rung 0 too). Launch derives
    #: `<checkpoint_dir>/world_spill` when a checkpoint dir exists.
    spill_dir: str = ""
    #: Rung-2 retention coarsening: spilled tiles are downsampled by
    #: this factor (max-pool on |logodds|) and re-upsampled on
    #: rehydrate — lossy, bounded, never a crash.
    retention_coarsen: int = 2


@_frozen
class TenancyConfig:
    """Mission multi-tenancy (tenancy/ subsystem).

    "Millions of users" is MANY independent missions, each tiny
    relative to the accelerator — not one giant fleet. These knobs
    parameterize the tenant megabatch (`tenancy/megabatch.py`: mission
    states stacked along a pow2-bucketed leading axis, one jitted step
    per tick for the whole batch) and its control plane
    (`tenancy/controlplane.py`: admit / suspend / resume / evict,
    admission pre-warm through the warm-restart ladder, eviction
    checkpoints, per-tenant serving epoch/revision namespaces).

    `enabled=False` constructs NOTHING — no control plane, no batch,
    no new jitted entry point traced; bit-exact pre-tenancy behavior
    (the ObsConfig/DecayConfig doctrine). Enabled changes no
    single-mission numerics either: a tenant's megabatched trajectory
    is bit-identical to its solo run (the megabatch contract,
    property-tested)."""

    enabled: bool = False
    #: Hard capacity ceiling: `bucket_capacity` refuses admissions
    #: past it, so a runaway admission loop cannot grow device
    #: footprint without bound.
    max_tenants: int = 64
    #: Serve capacities from the BIT-EXACT bucket ladder only
    #: (megabatch.EXACT_BUCKETS — every bucket verified bit-identical
    #: to solo runs on this backend; tops out at 12 on XLA:CPU, where
    #: larger batches vectorize with FMA/SIMD choices the solo
    #: executable does not make). False opts into the full
    #: {2^k} ∪ {3·2^(k-1)} set at any size — throughput mode,
    #: documented ulp-faithful rather than bit-exact on CPU.
    bit_exact_buckets: bool = True
    #: Pre-warm a not-yet-compiled bucket variant through the
    #: StagedWarmup ladder BEFORE the tenant joins the batch (ROADMAP
    #: item 7b pairing). Off = the first tick at a new bucket pays the
    #: compile inline.
    prewarm_on_admit: bool = True
    #: Eviction writes the mission's final state through
    #: `io/checkpoint.save_checkpoint` (generation-retained) when the
    #: control plane has a checkpoint dir.
    checkpoint_on_evict: bool = True

    # -- tenant blast-radius containment (ISSUE 17) ----------------------
    # All default OFF: the defaults reproduce pre-containment behavior
    # bit-exactly (the knob-off doctrine, property-tested). Arming
    # `lane_health` changes no numerics either — the health word is a
    # pure READER fused into the megabatch dispatch.

    #: Compute a per-tenant health word ON DEVICE inside the SAME
    #: `megabatch_step` dispatch (no extra dispatch; the host reads it
    #: at the pending-flag barrier it already pays): bit 0 = NaN/Inf
    #: in the lane's pose / grid-delta leaves, bit 1 = pose-jump
    #: magnitude over `pose_jump_max_m`, bit 2 = accepted-key
    #: match response under `match_floor`. The control plane folds the
    #: word into the healthy -> suspect -> QUARANTINED hysteresis
    #: ladder (tenancy/lanehealth.py, the EstimatorWatchdog semantics
    #: lifted from robots to tenants).
    lane_health: bool = False
    #: Per-tick pose-jump gate, metres: the max over robots of the
    #: within-step estimated-pose translation. A healthy micro mission
    #: moves ~cm/tick; an estimator blow-up teleports.
    pose_jump_max_m: float = 0.5
    #: Match-response floor for ACCEPTED key-step matches; 0.0 disables
    #: the bit (sub-gate steps carry no match information).
    match_floor: float = 0.0
    #: Hysteresis: consecutive flagged ticks before a suspect tenant is
    #: QUARANTINED (its lane frozen in place via the pad-style
    #: `active=False` select — an exact no-op for co-tenants). One
    #: flagged tick already demotes healthy -> suspect; a clean tick
    #: returns suspect -> healthy. There is NO flag-based exit from
    #: quarantine (the watchdog asymmetry): only a verified
    #: re-admission probe resumes the lane.
    quarantine_persist_ticks: int = 2
    #: Re-admission probe cadence, in plane ticks after quarantine: the
    #: probe finite-checks the held last-good state and runs ONE tick
    #: through the solo `fleet_step` executable (never a megabatch
    #: variant); output must stay finite and within the pose-jump gate.
    #: Success resumes the lane and bumps the tenant's epoch.
    readmit_probe_ticks: int = 8
    #: Bounded probe budget: after this many failed probes the tenant
    #: stays quarantined until an operator evicts or resumes it
    #: explicitly — a NaN-poisoned state must not buy a solo dispatch
    #: forever.
    max_readmit_probes: int = 3
    #: Durable control plane: append-only CRC-per-record lifecycle
    #: journal + compaction snapshots under the checkpoint dir
    #: (tenancy/journal.py, the io/checkpoint corruption doctrine:
    #: torn tail truncated, never fatal). `restore()` replays
    #: snapshot+journal and re-admits tenants from their
    #: generation-retained checkpoints with epochs bumped, so a plane
    #: crash with live tenants comes back with the SAME tenant set.
    journal: bool = False
    #: Compact the journal into a registry snapshot every N appended
    #: records (0 = compact only on checkpoint_all/restore).
    journal_compact_every: int = 64
    #: Bounded admission queue: more than this many concurrent
    #: `admit()`/`resume()` calls in flight (the pre-warm window) are
    #: REJECTED with `AdmissionRejected` + a `tenancy_admission_
    #: rejected` flight event instead of blocking without bound behind
    #: the commit lock. 0 = unbounded (pre-containment behavior).
    admission_queue_max: int = 0


@_frozen
class AnalysisConfig:
    """Canonical scenario for the jit recompile-budget tracker
    (`analysis/compilebudget.py`): a deterministic tiny-config stack
    drive whose per-function compiled-variant counts are pinned by the
    committed `analysis/compile_budget.json` ratchet. The parameters
    live HERE — not as constants in the tracker — so the committed
    budget names its provenance and a scenario change is a reviewed
    config diff, never an incidental edit. Not part of `SlamConfig`:
    this configures the *measurement*, not the stack."""

    budget_n_robots: int = 2
    budget_world_cells: int = 96      # plank_course arena edge
    budget_steps: int = 16            # exploration steps driven
    budget_seed: int = 3
    # Tenant-megabatch bucket drive (ISSUE 14): tenant counts stepped
    # through `megabatch_step` at the `micro_config` mission shape —
    # 5 and 6 share the 6-bucket of {2^k} ∪ {3·2^(k-1)}, so exactly
    # TWO variants compile; a bucketing regression (one variant per
    # count) surfaces as a third.
    budget_tenant_counts: Tuple[int, ...] = (3, 5, 6)


@_frozen
class FleetConfig:
    """Multi-robot scaling (BASELINE.json configs 4-5: 8-64 simulated Thymios)."""

    n_robots: int = 8
    batch_scans: int = 16             # scans fused per robot per device step
    mesh_fleet: int = 1               # devices along the robot/fleet axis
    mesh_space: int = 1               # devices along the grid-row axis


@_frozen
class SlamConfig:
    """Top-level bundle; the analog of the reference's whole config surface."""

    grid: GridConfig = GridConfig()
    scan: ScanConfig = ScanConfig()
    robot: RobotConfig = RobotConfig()
    matcher: MatcherConfig = MatcherConfig()
    loop: LoopClosureConfig = LoopClosureConfig()
    # Default FrontierConfig is the hierarchical latency path
    # (cluster work at 4096/(4*4) = 256^2).
    frontier: FrontierConfig = FrontierConfig()
    fleet: FleetConfig = FleetConfig()
    planner: PlannerConfig = PlannerConfig()
    voxel: VoxelConfig = VoxelConfig()
    depthcam: DepthCamConfig = DepthCamConfig()
    resilience: ResilienceConfig = ResilienceConfig()
    recovery: RecoveryConfig = RecoveryConfig()
    serving: ServingConfig = ServingConfig()
    world: WorldConfig = WorldConfig()
    decay: DecayConfig = DecayConfig()
    obs: ObsConfig = ObsConfig()
    cold_start: ColdStartConfig = ColdStartConfig()
    tenancy: TenancyConfig = TenancyConfig()
    # slam_toolbox's operating mode (slam_config.yaml:20: "mapping" —
    # the file's comment offers localization as the alternative).
    # "localization" freezes the map: key scans MATCH against it for
    # pose tracking but never fuse, the pose graph never grows, and
    # loop closure never fires — localize-on-a-known-map, the partner
    # of an imported prior (--map-prior / seed_map_prior).
    mode: str = "mapping"
    map_publish_period_s: float = 5.0         # slam_config.yaml:25
    tf_publish_period_s: float = 0.1          # slam_config.yaml:24
    # README.md:86 / pi/Dockerfile:3: ROS_DOMAIN_ID=42. Read lazily and
    # defensively so a weird env value can't break package import.
    domain_id: int = dataclasses.field(default_factory=lambda: _env_domain_id())

    def replace(self, **kw: Any) -> "SlamConfig":
        return dataclasses.replace(self, **kw)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "SlamConfig":
        raw: Dict[str, Any] = json.loads(text)
        # ObsConfig nests DevProfConfig (the one two-level section):
        # rebuild the inner dataclass so round-tripping a serialized
        # config doesn't leave a bare dict where a frozen (hashable,
        # jit-static-usable) DevProfConfig belongs.
        obs_raw = dict(raw.get("obs", {}))
        if isinstance(obs_raw.get("devprof"), dict):
            obs_raw["devprof"] = DevProfConfig(**obs_raw["devprof"])
        if isinstance(obs_raw.get("slo"), (list, tuple)):
            # Objectives serialize as a list of dicts; rebuild the
            # frozen (hashable, jit-static-usable) tuple the same way
            # devprof rebuilds its nested dataclass.
            obs_raw["slo"] = tuple(
                SloObjective(**o) if isinstance(o, dict) else o
                for o in obs_raw["slo"])
        return SlamConfig(
            grid=GridConfig(**raw.get("grid", {})),
            scan=ScanConfig(**raw.get("scan", {})),
            robot=RobotConfig(**raw.get("robot", {})),
            matcher=MatcherConfig(**raw.get("matcher", {})),
            loop=LoopClosureConfig(**raw.get("loop", {})),
            frontier=FrontierConfig(**raw.get("frontier", {})),
            fleet=FleetConfig(**raw.get("fleet", {})),
            planner=PlannerConfig(**raw.get("planner", {})),
            voxel=VoxelConfig(**raw.get("voxel", {})),
            depthcam=DepthCamConfig(**raw.get("depthcam", {})),
            resilience=ResilienceConfig(**raw.get("resilience", {})),
            recovery=RecoveryConfig(**raw.get("recovery", {})),
            serving=ServingConfig(**raw.get("serving", {})),
            world=WorldConfig(**raw.get("world", {})),
            decay=DecayConfig(**raw.get("decay", {})),
            obs=ObsConfig(**obs_raw),
            cold_start=ColdStartConfig(**raw.get("cold_start", {})),
            tenancy=TenancyConfig(**raw.get("tenancy", {})),
            **{k: v for k, v in raw.items()
               if k in ("mode", "map_publish_period_s",
                        "tf_publish_period_s", "domain_id")},
        )


def tiny_config(n_robots: int = 2) -> SlamConfig:
    """Small static shapes for CPU tests and multi-chip dry runs."""
    return SlamConfig(
        grid=GridConfig(size_cells=256, patch_cells=128, max_range_m=3.0,
                        align_rows=8, align_cols=8),
        scan=ScanConfig(n_beams=90, padded_beams=128, range_max_m=3.0,
                        angle_increment_rad=2.0 * math.pi / 90.0),
        matcher=MatcherConfig(search_half_extent_m=0.25),
        loop=LoopClosureConfig(max_poses=64, max_edges=256, gn_iters=4,
                               coarse_downsample=2),
        frontier=FrontierConfig(downsample=2, max_clusters=16,
                                label_prop_iters=24, bfs_iters=64),
        fleet=FleetConfig(n_robots=n_robots, batch_scans=4),
        # patch/2 - align/2 = 28 cells = 1.4 m >= the 1.2 m trust horizon.
        voxel=VoxelConfig(size_x_cells=128, size_y_cells=128,
                          size_z_cells=16, patch_cells=64, max_range_m=1.2,
                          align_y=8, align_x=8),
        depthcam=DepthCamConfig(width_px=40, height_px=30,
                                range_max_m=1.2),
        # Short staleness horizons so chaos tests exercise the full
        # degrade -> dead -> rejoin ladder within a short mission.
        resilience=ResilienceConfig(lidar_silent_ticks=8,
                                    dead_after_ticks=20,
                                    supervisor_missed_beats=3,
                                    restart_backoff_base_steps=2,
                                    restart_backoff_max_steps=16,
                                    checkpoint_every_steps=25),
        # Short watchdog/anti-stuck horizons so adversarial-fault tests
        # walk the full diverge -> quarantine -> relocalize -> re-admit
        # (and stuck -> rotate -> backup -> blacklist) ladders within a
        # short mission.
        recovery=RecoveryConfig(min_keyscans=2,
                                score_decay=0.5,
                                diverge_threshold=0.4,
                                diverge_persist_steps=2,
                                quarantine_cap=32,
                                reloc_consecutive=2,
                                stuck_window_ticks=12,
                                stuck_displacement_frac=0.25,
                                rotate_recovery_ticks=6,
                                backup_recovery_ticks=5,
                                escalation_memory_ticks=40,
                                blacklist_ttl_ticks=80),
        # 4x4 tiles over the 256^2 grid; short event waits so serving
        # tests never block near a timeout.
        serving=ServingConfig(tile_cells=64, pyramid_levels=3,
                              event_wait_max_s=5.0),
    )


def micro_config(n_robots: int = 1) -> SlamConfig:
    """Smallest-legal static shapes: the mission-multi-tenancy regime
    (MANY missions, each tiny relative to the accelerator). One shared
    definition for the tenant compile-budget scenario, the tenancy
    test suite and `bench.py --suite tenant`, so the committed budget
    names a reproducible mission shape. Scan keeps >= 30 live beams
    (the explorer's front-cone slices need them); the patch-coverage
    contract holds at 24/2 - 8/2 = 8 cells = 0.4 m."""
    return SlamConfig(
        grid=GridConfig(size_cells=64, patch_cells=24, max_range_m=0.4,
                        align_rows=8, align_cols=8),
        scan=ScanConfig(n_beams=36, padded_beams=64, range_max_m=0.4,
                        angle_increment_rad=2.0 * math.pi / 36.0),
        matcher=MatcherConfig(search_half_extent_m=0.05,
                              coarse_angle_half_rad=0.0698,
                              coarse_angle_step_rad=0.0349,
                              fine_angle_step_rad=0.0175,
                              fine_step_m=0.025),
        loop=LoopClosureConfig(max_poses=32, max_edges=128, gn_iters=2,
                               min_chain_size=6, loop_window_m=2.0,
                               coarse_downsample=2),
        frontier=FrontierConfig(downsample=2, cluster_downsample=2,
                                max_clusters=8, min_cluster_cells=2,
                                label_prop_iters=16, bfs_iters=32,
                                mg_levels=2, mg_refine_iters=4),
        fleet=FleetConfig(n_robots=n_robots, batch_scans=1),
        # 4x4 tiles over the 64^2 grid so the micro shape can run the
        # full deployed stack (serving included) in benches and tests.
        serving=ServingConfig(tile_cells=16, pyramid_levels=2,
                              event_wait_max_s=5.0),
    )


def configs_equivalent(json_a: Optional[str], json_b: Optional[str]) -> bool:
    """Semantic config-drift comparison for checkpoint/bag guards.

    Parses both sides through `SlamConfig.from_json` — which applies
    defaults for absent sections and fields — and compares the resulting
    frozen dataclasses. Plain string comparison would refuse every
    checkpoint and bag recorded before a config section EXISTED (adding
    `voxel`/`depthcam` in round 4 would have orphaned all round-3
    recordings despite zero 2D state drift). Unparseable or genuinely
    different configs still refuse.
    """
    if json_a == json_b:
        return True
    if json_a is None or json_b is None:
        return False
    try:
        a = SlamConfig.from_json(json_a)
        b = SlamConfig.from_json(json_b)
        # `mode` is an OPERATING mode, not a state-shape parameter: a
        # checkpoint mapped in "mapping" and resumed under
        # "localization" (map a site, then localize on it) is the
        # feature's core flow, not drift. `obs` is pure telemetry —
        # tracing on/off changes no state shape and no bit of the map
        # (the obs bit-inertness property test), so a checkpoint from a
        # traced run loads into an untraced stack and vice versa.
        # `cold_start` is equally bit-inert infrastructure (a cache or
        # snapshot hit returns the identical executable a cold compile
        # would): a checkpoint saved by a warm-restart-armed stack must
        # resume in a cold one and vice versa — the restart bench's
        # cold/warm twins load the SAME checkpoint by construction.
        # `tenancy` is bit-inert the same way: a megabatched tenant's
        # trajectory is bit-identical to its solo run, so an eviction
        # checkpoint written by a tenancy-armed control plane must
        # resume in a plain solo stack and vice versa.
        return a.replace(mode="mapping", obs=ObsConfig(),
                         cold_start=ColdStartConfig(),
                         tenancy=TenancyConfig()) \
            == b.replace(mode="mapping", obs=ObsConfig(),
                         cold_start=ColdStartConfig(),
                         tenancy=TenancyConfig())
    except (TypeError, ValueError, KeyError, AttributeError):
        # AttributeError: valid JSON that is not an object ('"x"', '[]')
        # reaches raw.get() — a corrupted config must refuse, not crash.
        return False


def ensure_valid_mode(cfg: "SlamConfig") -> None:
    """ONE definition of the operating-mode guard for every step entry
    (models/slam.slam_step, models/fleet.fleet_step,
    parallel/fleet_sharded.make_fleet_step): an unknown mode must refuse
    loudly in ALL of them — a missed copy would silently fall through to
    the mapping branch."""
    if cfg.mode not in ("mapping", "localization"):
        raise ValueError(f"unknown SlamConfig.mode {cfg.mode!r} "
                         "(mapping | localization)")


def _env_domain_id() -> int:
    try:
        return int(os.environ.get("ROS_DOMAIN_ID", "42"))
    except ValueError:
        return 42


def sign_extend_16bit(raw):
    """Thymio motor speeds arrive as unsigned 16-bit; negative speeds wrap.

    Semantics of `server/.../main.py:101-102` (`if rl > 32767: rl -= 65536`),
    vectorised. Works on python ints and numpy or jax arrays of any integer
    dtype (including uint16, where the subtraction must not wrap).
    """
    import numpy as _np
    if isinstance(raw, (int, float)):
        return raw - 65536 if raw > 32767 else raw
    if isinstance(raw, _np.ndarray):
        arr = raw.astype(_np.int32)
        return _np.where(arr > 32767, arr - 65536, arr)
    import jax.numpy as jnp
    arr = jnp.asarray(raw).astype(jnp.int32)
    return jnp.where(arr > 32767, arr - 65536, arr)
