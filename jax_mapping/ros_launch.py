"""`jax-mapping-ros`: one-command ROS 2 bring-up, the reference's
`ros2 launch thymio_project pc_server.launch.py` equivalent
(`/root/reference/server/thymio_project/launch/pc_server.launch.py:12-34`
starts slam_toolbox + the brain node + RViz; here one process boots the
whole simulated stack, mirrors it onto real DDS through the rclpy adapter,
and prints the RViz command).

Usage (with a ROS 2 Jazzy environment sourced):

    jax-mapping-ros                     # sim stack + /map /scan /pose ...
    jax-mapping-ros --robots 4          # fleet
    jax-mapping-ros --live-hardware     # inbound /scan + /odom feed the
                                        # mapper (a real ldlidar driver
                                        # publishes; nothing is simulated)
    rviz2 -d "$(jax-mapping-ros --print-rviz-config)"

Without rclpy importable this exits with the adapter's explanatory error
(the rest of the framework runs without ROS; see bridge/rclpy_adapter.py).
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jax-mapping-ros",
        description="Bridge the jax_mapping stack onto a live ROS 2 graph.")
    p.add_argument("--robots", type=int, default=1)
    p.add_argument("--world", choices=("arena", "rooms"), default="rooms")
    p.add_argument("--world-cells", type=int, default=256)
    p.add_argument("--http-port", type=int, default=None,
                   help="also serve the map HTTP API on this port")
    p.add_argument("--config", type=str, default=None,
                   help="SlamConfig JSON file (default: tiny sim config)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--duration-s", type=float, default=0.0,
                   help="run this long then exit (0 = until Ctrl-C)")
    p.add_argument("--live-hardware", action="store_true",
                   help="inbound /scan + /odom from real drivers feed the "
                        "mapper; the simulator is not started")
    p.add_argument("--depth-cam", action="store_true",
                   help="run the 3D voxel pipeline too: simulated depth "
                        "cameras feed a shared voxel grid, exported on "
                        "/voxel_points (RViz PointCloud2) and HTTP "
                        "/voxel-image")
    p.add_argument("--joy-device", type=str, default=None, metavar="DEV",
                   help="read a joystick at this evdev node (e.g. "
                        "/dev/input/event3) and publish /cmd_vel teleop "
                        "(joystick.yaml semantics: deadman button 0, "
                        "axes 2/3, autorepeat 20 Hz)")
    p.add_argument("--map-prior", type=str, default=None, metavar="YAML",
                   help="seed the mapper with a ROS map_server map "
                        "(map.yaml + map.pgm) before mapping")
    p.add_argument("--localization", action="store_true",
                   help="freeze the map (SlamConfig.mode=localization): "
                        "scans match for pose tracking only; pair with "
                        "--map-prior")
    p.add_argument("--print-rviz-config", action="store_true",
                   help="print the bundled RViz config path and exit")
    return p


def rviz_config_path() -> str:
    import os
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "configs", "jax_mapping.rviz")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.print_rviz_config:
        print(rviz_config_path())
        return 0

    from jax_mapping.bridge.rclpy_adapter import RclpyAdapter, rclpy_available
    if not rclpy_available():
        print("jax-mapping-ros: rclpy is not importable — source a ROS 2 "
              "(Jazzy) environment first; see README 'ROS 2 / RViz'.",
              file=sys.stderr)
        return 2

    # Operator guard (VERDICT r3 weak #1): a wedged TPU tunnel hangs jax
    # backend init forever; probe in a bounded subprocess and restart on
    # virtual CPU if so. After the rclpy check so a no-ROS environment
    # still gets its fast explanatory exit.
    from jax_mapping.utils.backend_guard import ensure_responsive_backend
    ensure_responsive_backend(
        "jax-mapping-ros",
        argv=["-m", "jax_mapping.ros_launch"]
             + (list(argv) if argv is not None else sys.argv[1:]))

    from jax_mapping.config import SlamConfig, tiny_config

    n_robots = max(1, args.robots)
    if args.config:
        with open(args.config) as f:
            cfg = SlamConfig.from_json(f.read())
    else:
        cfg = tiny_config(n_robots=n_robots)
    if args.localization:
        cfg = cfg.replace(mode="localization")

    if args.live_hardware:
        # Live mode = the reference's PC-server role alone
        # (pc_server.launch.py: slam + map server; the robot side runs on
        # real hardware): mapper + HTTP API only. No simulator — real
        # /scan and /odom arrive via the inbound adapter on the SAME bus
        # topics the sim would use, so booting the sim would interleave
        # simulated and real sensor data. Outbound excludes scan/odom for
        # the same reason mirrored: this node subscribing /scan while
        # republishing its bus copy back to /scan would echo-loop DDS.
        stack = _launch_live_stack(cfg, http_port=args.http_port,
                                   n_robots=n_robots)
        inbound = ("cmd_vel", "scan", "odom", "initialpose", "goal_pose")
        # No scan/odom echo (see above), but the live mapper still
        # publishes /frontiers and the standalone planner /plan — keep
        # the RViz marker + Path displays fed.
        outbound = ("map", "map_updates", "pose", "frontiers", "plan")
    else:
        from jax_mapping.bridge.launch import launch_sim_stack
        from jax_mapping.sim import world as W
        if args.world == "arena":
            world = W.empty_arena(args.world_cells, cfg.grid.resolution_m)
        else:
            world = W.rooms_world(args.world_cells, cfg.grid.resolution_m,
                                  seed=args.seed)
        stack = launch_sim_stack(cfg, world, n_robots=n_robots,
                                 http_port=args.http_port, realtime=True,
                                 seed=args.seed, depth_cam=args.depth_cam)
        inbound = ("cmd_vel", "initialpose", "goal_pose")
        outbound = RclpyAdapter.OUTBOUND_DEFAULT

    if args.map_prior:
        from jax_mapping.io import rosmap
        try:
            n_occ = rosmap.seed_mapper(stack.mapper, args.map_prior,
                                       cfg.grid)
        except rosmap.SEED_ERRORS as e:
            print(f"jax-mapping-ros: cannot seed --map-prior "
                  f"{args.map_prior}: {e}", file=sys.stderr)
            stack.shutdown()
            return 2
        print(f"jax-mapping-ros: seeded map prior from {args.map_prior} "
              f"({n_occ} occupied cells)")

    adapter = RclpyAdapter(stack.bus, cfg, tf=stack.tf, inbound=inbound,
                           outbound=outbound, n_robots=n_robots)
    adapter.spin()
    joy = None
    if args.joy_device:
        from jax_mapping.bridge.joydev import attach_joystick
        try:
            joy = attach_joystick(stack.bus, args.joy_device)
            print(f"jax-mapping-ros: joystick at {args.joy_device} -> "
                  "/cmd_vel (hold button 0 to drive)")
        except OSError as e:
            print(f"jax-mapping-ros: cannot open joystick "
                  f"{args.joy_device}: {e}", file=sys.stderr)
    if not args.live_hardware:
        # A pad means MANUAL drive: the brain applies /cmd_vel only while
        # not exploring (brain._manual_targets — the reference's override
        # semantics), so auto-starting exploration would silently discard
        # every pad command. The operator flips modes via HTTP /start.
        if joy is None:
            stack.brain.start_exploring()
        else:
            print("jax-mapping-ros: manual-drive mode (pad attached); "
                  "start autonomous exploration via HTTP /start")
        print("jax-mapping-ros: sim stack up — /map /map_updates /pose "
              "/poses /scan /odom /tf out, /cmd_vel in")
    else:
        print("jax-mapping-ros: live stack up — /map /map_updates /pose "
              "/poses /tf out; /scan /odom /cmd_vel in feed the mapper")
    print(f"  rviz2 -d {rviz_config_path()}")
    try:
        t0 = time.time()
        while args.duration_s <= 0 or time.time() - t0 < args.duration_s:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    finally:
        if joy is not None:
            joy.close()
        adapter.shutdown()
        stack.shutdown()
    return 0


def _launch_live_stack(cfg, http_port=None, n_robots: int = 1):
    """Mapper + planner + API + TF, fed by real inbound /scan + /odom.

    The planner runs STANDALONE (brain=None): RViz SetGoal publishes
    /goal_pose over DDS, the planner answers with /plan — the operator
    sees the route on the live map and an external follower (Nav2-style)
    can consume it; there is no brain to steer in live mode (the robot
    side runs its own controller on real hardware)."""
    import dataclasses as _dc

    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.http_api import MapApiServer
    from jax_mapping.bridge.launch import LASER_MOUNT_Z_M
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.bridge.messages import Header, TransformStamped
    from jax_mapping.bridge.node import Executor
    from jax_mapping.bridge.planner import PlannerNode
    from jax_mapping.bridge.tf import TfTree

    bus = Bus(domain_id=cfg.domain_id)
    tf = TfTree()
    tf.set_static_transform(TransformStamped(
        header=Header(frame_id="base_link"), child_frame_id="base_laser",
        z=LASER_MOUNT_Z_M))
    mapper = MapperNode(cfg, bus, tf=tf, n_robots=n_robots)
    planner = None
    if cfg.planner.enabled:
        planner = PlannerNode(cfg, bus, mapper=mapper, brain=None)
    api = None
    if http_port is not None:
        api = MapApiServer(bus, brain=None, port=http_port,
                           mapper=mapper, planner=planner)
        api.serve_thread()
    executor = Executor([mapper] + ([planner] if planner else []))
    executor.spin_thread()

    @_dc.dataclass
    class LiveStack:
        bus: object
        tf: object
        mapper: object
        api: object
        executor: object
        planner: object = None

        def shutdown(self):
            if self.api is not None:
                self.api.shutdown()
            self.executor.shutdown()

    return LiveStack(bus=bus, tf=tf, mapper=mapper, api=api,
                     executor=executor, planner=planner)


if __name__ == "__main__":
    sys.exit(main())
