"""ctypes binding + wire codec for the C++ LD06 ingest pipeline.

`Ld06Parser` wraps `src/ld06.cpp` (built on demand with g++ into
``build/libld06.so``); `encode_packets` produces spec-conformant LD06 byte
streams from range arrays so the simulated fleet can feed the *native* path
the same bytes real hardware would (UART framing per
`/root/reference/pi/src/thymio_project/launch/pi_hardware.launch.py:17-18`,
230400 baud; packet layout per the ldrobot datasheet — see ld06.cpp header).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "ld06.cpp")
_SO = os.path.join(_DIR, "build", "libld06.so")

PACKET_BYTES = 47
POINTS_PER_PACKET = 12

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _crc8_table() -> np.ndarray:
    t = np.zeros(256, np.uint8)
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = ((crc << 1) ^ 0x4D) if crc & 0x80 else (crc << 1)
            crc &= 0xFF
        t[i] = crc
    return t


_CRC_TABLE = _crc8_table()


def crc8(data: bytes) -> int:
    crc = 0
    for b in data:
        crc = _CRC_TABLE[crc ^ b]
    return int(crc)


def _build() -> Optional[str]:
    """Compile the shared lib; returns an error string or None."""
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", _SO]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        return f"g++ unavailable: {e}"
    if proc.returncode != 0:
        return f"g++ failed: {proc.stderr[-2000:]}"
    return None


def _load() -> Tuple[Optional[ctypes.CDLL], Optional[str]]:
    global _lib, _build_error
    with _lock:
        if _lib is not None or _build_error is not None:
            return _lib, _build_error
        if not os.path.exists(_SO) or \
                os.path.getmtime(_SO) < os.path.getmtime(_SRC):
            err = _build()
            if err is not None:
                _build_error = err
                return None, err
        lib = ctypes.CDLL(_SO)
        lib.ld06_create.restype = ctypes.c_void_p
        lib.ld06_create.argtypes = [ctypes.c_int, ctypes.c_int,
                                    ctypes.c_float]
        lib.ld06_destroy.argtypes = [ctypes.c_void_p]
        lib.ld06_feed.restype = ctypes.c_int
        lib.ld06_feed.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_uint8),
                                  ctypes.c_int]
        lib.ld06_take_scan.restype = ctypes.c_int
        lib.ld06_take_scan.argtypes = [ctypes.c_void_p,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int]
        lib.ld06_speed.restype = ctypes.c_double
        lib.ld06_speed.argtypes = [ctypes.c_void_p]
        lib.ld06_stat.restype = ctypes.c_long
        lib.ld06_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return _lib, None


def native_available() -> bool:
    lib, _ = _load()
    return lib is not None


_STATS = {"packets": 0, "crc_errors": 1, "resyncs": 2, "points": 3,
          "points_filtered": 4, "scans": 5}


class Ld06Parser:
    """Feed raw bytes, take complete 360° scans.

    Uses the C++ pipeline when buildable; otherwise raises (there is no
    silent Python fallback — the native path IS the component; tests gate
    on `native_available()`).
    """

    def __init__(self, n_beams: int = 360, min_confidence: int = 15,
                 band_m: float = 0.15):
        lib, err = _load()
        if lib is None:
            raise RuntimeError(f"libld06 unavailable: {err}")
        self._lib = lib
        self.n_beams = n_beams
        self._h = lib.ld06_create(n_beams, min_confidence,
                                  ctypes.c_float(band_m))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ld06_destroy(h)
            self._h = None

    def feed(self, data: bytes) -> int:
        """Returns the number of points parsed from complete packets."""
        arr = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        return self._lib.ld06_feed(self._h, arr, len(data))

    def take_scan(self) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """(ranges_m, intensities), both (n_beams,), or None if no complete
        rotation is pending. Beam i covers [i, i+1) * 360/n_beams degrees;
        0.0 = no return (the outlier code downstream treats as far,
        `server/.../main.py:152`)."""
        ranges = np.zeros(self.n_beams, np.float32)
        intens = np.zeros(self.n_beams, np.float32)
        ok = self._lib.ld06_take_scan(
            self._h,
            ranges.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            intens.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self.n_beams)
        if not ok:
            return None
        return ranges, intens

    @property
    def speed_deg_s(self) -> float:
        return self._lib.ld06_speed(self._h)

    def stats(self) -> dict:
        return {k: int(self._lib.ld06_stat(self._h, v))
                for k, v in _STATS.items()}


def encode_packets(ranges_m: np.ndarray, confidences: Optional[np.ndarray]
                   = None, speed_deg_s: int = 3600,
                   start_angle_deg: float = 0.0,
                   timestamp_ms: int = 0) -> bytes:
    """Encode one rotation of beam ranges into LD06 packets.

    Produces ceil(n/12) spec-conformant 47-byte packets sweeping from
    `start_angle_deg` through 360°. Used by the sim to drive the native
    parser with real wire bytes, and by tests as the golden encoder.
    """
    r = np.asarray(ranges_m, np.float64)
    n = len(r)
    conf = (np.full(n, 200, np.int32) if confidences is None
            else np.asarray(confidences, np.int32))
    out = bytearray()
    deg_per_beam = 360.0 / n
    i = 0
    while i < n:
        chunk = min(POINTS_PER_PACKET, n - i)
        idx = np.arange(i, i + POINTS_PER_PACKET) % n     # pad by wrapping
        start = (start_angle_deg + i * deg_per_beam) % 360.0
        end = (start_angle_deg
               + (i + POINTS_PER_PACKET - 1) * deg_per_beam) % 360.0
        pkt = bytearray()
        pkt += bytes([0x54, 0x2C])
        pkt += int(speed_deg_s).to_bytes(2, "little")
        pkt += int(round(start * 100)).to_bytes(2, "little")
        for j in idx:
            mm = int(round(max(r[j], 0.0) * 1000.0))
            pkt += int(min(mm, 0xFFFF)).to_bytes(2, "little")
            pkt += bytes([int(np.clip(conf[j], 0, 255))])
        pkt += int(round(end * 100)).to_bytes(2, "little")
        pkt += int(timestamp_ms % 30000).to_bytes(2, "little")
        pkt += bytes([crc8(bytes(pkt))])
        assert len(pkt) == PACKET_BYTES
        out += pkt
        i += chunk
    return bytes(out)
