// LD06 LiDAR ingest: stream packet parser + ToF band filter + scan
// assembler, C++ with a C ABI for ctypes.
//
// Native-equivalent of the reference's vendored ldlidar_stl_ros2 driver
// pipeline (SURVEY.md §2.3): serial bytes -> lipkg packet parse ->
// tofbf filter -> LaserScan assembly (`/root/reference/pi/build/
// ldlidar_stl_ros2/CMakeFiles/.../link.txt` TU list). Re-designed, not
// translated: a single resync-tolerant ring parser feeding a beam-indexed
// rotation accumulator, so the Python side receives fixed-shape arrays
// ready for device padding.
//
// LD06 wire format (public ldrobot datasheet): 47-byte packet
//   [0]  0x54 header
//   [1]  0x2C ver_len (12 points)
//   [2:4]   speed, deg/s, LE
//   [4:6]   start angle, 0.01 deg, LE
//   [6:42]  12 x { distance mm (2B LE), confidence (1B) }
//   [42:44] end angle, 0.01 deg, LE
//   [44:46] timestamp ms, LE
//   [46] CRC8 over bytes [0:46]
//
// Build: g++ -O2 -std=c++17 -shared -fPIC ld06.cpp -o libld06.so

#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>

namespace {

constexpr uint8_t kHeader = 0x54;
constexpr uint8_t kVerLen = 0x2C;
constexpr int kPacketBytes = 47;
constexpr int kPointsPerPacket = 12;

// CRC8, poly 0x4D, init 0 (ldrobot reference implementation's table
// parameters; table generated at startup rather than pasted).
struct Crc8Table {
  uint8_t t[256];
  Crc8Table() {
    for (int i = 0; i < 256; ++i) {
      uint8_t crc = static_cast<uint8_t>(i);
      for (int b = 0; b < 8; ++b)
        crc = (crc & 0x80) ? static_cast<uint8_t>((crc << 1) ^ 0x4D)
                           : static_cast<uint8_t>(crc << 1);
      t[i] = crc;
    }
  }
};
const Crc8Table kCrc;

uint8_t crc8(const uint8_t* data, int len) {
  uint8_t crc = 0;
  for (int i = 0; i < len; ++i) crc = kCrc.t[(crc ^ data[i]) & 0xFF];
  return crc;
}

uint16_t le16(const uint8_t* p) {
  return static_cast<uint16_t>(p[0] | (p[1] << 8));
}

struct Point {
  float angle_deg;    // [0, 360)
  float dist_m;       // 0 = no return
  uint8_t confidence;
};

struct Stats {
  long packets = 0;
  long crc_errors = 0;
  long resyncs = 0;
  long points = 0;
  long points_filtered = 0;
  long scans = 0;
};

// ToF band filter (role of the reference driver's tofbf.cpp): reject
// low-confidence returns and isolated range spikes whose neighbours
// disagree by more than a band around the local median.
class TofBandFilter {
 public:
  explicit TofBandFilter(uint8_t min_confidence = 15,
                         float band_m = 0.15f)
      : min_confidence_(min_confidence), band_m_(band_m) {}

  // In-place over one packet's points; neighbours within the packet.
  int apply(std::vector<Point>& pts) const {
    int removed = 0;
    const int n = static_cast<int>(pts.size());
    for (int i = 0; i < n; ++i) {
      Point& p = pts[i];
      if (p.dist_m <= 0.0f) continue;
      if (p.confidence < min_confidence_) {
        p.dist_m = 0.0f;
        ++removed;
        continue;
      }
      // 3-neighbourhood median spike test.
      float a = pts[(i + n - 1) % n].dist_m;
      float b = pts[(i + 1) % n].dist_m;
      if (a > 0.0f && b > 0.0f) {
        float lo = a < b ? a : b, hi = a < b ? b : a;
        if (p.dist_m < lo - band_m_ || p.dist_m > hi + band_m_) {
          // isolated spike only if the neighbours agree with each other
          if (hi - lo < band_m_) {
            p.dist_m = 0.0f;
            ++removed;
          }
        }
      }
    }
    return removed;
  }

 private:
  uint8_t min_confidence_;
  float band_m_;
};

// One full rotation, beam-indexed.
class ScanAssembler {
 public:
  explicit ScanAssembler(int n_beams) : n_beams_(n_beams) {
    reset();
  }

  void reset() {
    ranges_.assign(n_beams_, 0.0f);
    intensities_.assign(n_beams_, 0.0f);
    have_.assign(n_beams_, 0);
    last_angle_ = -1.0f;
    accum_deg_ = 0.0f;
  }

  // Returns true when a rotation completed (caller takes the scan first).
  bool add(const Point& p) {
    bool completed = false;
    if (last_angle_ >= 0.0f) {
      float d = p.angle_deg - last_angle_;
      if (d < -180.0f) d += 360.0f;       // wrapped past 360
      if (d > 0.0f) accum_deg_ += d;
      if (accum_deg_ >= 360.0f) completed = true;
    }
    last_angle_ = p.angle_deg;
    if (completed) return true;           // point belongs to next scan
    int beam = static_cast<int>(p.angle_deg / 360.0f * n_beams_);
    if (beam >= 0 && beam < n_beams_ && p.dist_m > 0.0f) {
      ranges_[beam] = p.dist_m;
      intensities_[beam] = static_cast<float>(p.confidence);
      have_[beam] = 1;
    }
    return false;
  }

  void take(float* ranges_out, float* intens_out) {
    std::memcpy(ranges_out, ranges_.data(), n_beams_ * sizeof(float));
    std::memcpy(intens_out, intensities_.data(), n_beams_ * sizeof(float));
    float carry_a = last_angle_;
    reset();
    last_angle_ = carry_a;
    accum_deg_ = 0.0f;
  }

  int n_beams() const { return n_beams_; }

 private:
  int n_beams_;
  std::vector<float> ranges_, intensities_;
  std::vector<uint8_t> have_;
  float last_angle_;
  float accum_deg_;
};

class Ld06Driver {
 public:
  Ld06Driver(int n_beams, uint8_t min_confidence, float band_m)
      : filter_(min_confidence, band_m), assembler_(n_beams) {}

  int feed(const uint8_t* data, int len) {
    buf_.insert(buf_.end(), data, data + len);
    int new_points = 0;
    while (buf_.size() >= kPacketBytes) {
      if (buf_[0] != kHeader || buf_[1] != kVerLen) {
        buf_.pop_front();
        ++stats_.resyncs;
        continue;
      }
      uint8_t pkt[kPacketBytes];
      for (int i = 0; i < kPacketBytes; ++i) pkt[i] = buf_[i];
      if (crc8(pkt, kPacketBytes - 1) != pkt[kPacketBytes - 1]) {
        buf_.pop_front();                 // bad packet: shift + resync
        ++stats_.crc_errors;
        continue;
      }
      for (int i = 0; i < kPacketBytes; ++i) buf_.pop_front();
      parse_packet(pkt);
      new_points += kPointsPerPacket;
    }
    return new_points;
  }

  bool take_scan(float* ranges_out, float* intens_out, int n_beams) {
    if (!scan_ready_ || n_beams != assembler_.n_beams()) return false;
    std::memcpy(ranges_out, pending_ranges_.data(),
                n_beams * sizeof(float));
    std::memcpy(intens_out, pending_intens_.data(),
                n_beams * sizeof(float));
    scan_ready_ = false;
    return true;
  }

  double speed_deg_s() const { return speed_deg_s_; }

  long stat(int which) const {
    switch (which) {
      case 0: return stats_.packets;
      case 1: return stats_.crc_errors;
      case 2: return stats_.resyncs;
      case 3: return stats_.points;
      case 4: return stats_.points_filtered;
      case 5: return stats_.scans;
      default: return -1;
    }
  }

 private:
  void parse_packet(const uint8_t* pkt) {
    ++stats_.packets;
    speed_deg_s_ = le16(pkt + 2);
    float start = le16(pkt + 4) * 0.01f;
    float end = le16(pkt + 42) * 0.01f;
    float span = end - start;
    if (span < 0.0f) span += 360.0f;
    std::vector<Point> pts(kPointsPerPacket);
    for (int i = 0; i < kPointsPerPacket; ++i) {
      const uint8_t* p = pkt + 6 + i * 3;
      float ang = start + span * i / (kPointsPerPacket - 1);
      if (ang >= 360.0f) ang -= 360.0f;
      pts[i] = {ang, le16(p) * 0.001f, p[2]};
    }
    stats_.points += kPointsPerPacket;
    stats_.points_filtered += filter_.apply(pts);
    for (const Point& p : pts) {
      if (assembler_.add(p)) {
        // Rotation complete: stage the finished scan, then add the point
        // to the fresh one.
        pending_ranges_.assign(assembler_.n_beams(), 0.0f);
        pending_intens_.assign(assembler_.n_beams(), 0.0f);
        assembler_.take(pending_ranges_.data(), pending_intens_.data());
        scan_ready_ = true;
        ++stats_.scans;
        assembler_.add(p);
      }
    }
  }

  std::deque<uint8_t> buf_;
  TofBandFilter filter_;
  ScanAssembler assembler_;
  std::vector<float> pending_ranges_, pending_intens_;
  bool scan_ready_ = false;
  double speed_deg_s_ = 0.0;
  Stats stats_;
};

}  // namespace

extern "C" {

void* ld06_create(int n_beams, int min_confidence, float band_m) {
  return new Ld06Driver(n_beams, static_cast<uint8_t>(min_confidence),
                        band_m);
}

void ld06_destroy(void* h) { delete static_cast<Ld06Driver*>(h); }

int ld06_feed(void* h, const uint8_t* data, int len) {
  return static_cast<Ld06Driver*>(h)->feed(data, len);
}

int ld06_take_scan(void* h, float* ranges_out, float* intens_out,
                   int n_beams) {
  return static_cast<Ld06Driver*>(h)->take_scan(ranges_out, intens_out,
                                                n_beams)
             ? 1
             : 0;
}

double ld06_speed(void* h) {
  return static_cast<Ld06Driver*>(h)->speed_deg_s();
}

long ld06_stat(void* h, int which) {
  return static_cast<Ld06Driver*>(h)->stat(which);
}

}  // extern "C"
