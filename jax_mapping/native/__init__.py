"""Native (C++) host-side components.

The reference's only native first-party obligation is the LD06 sensor-ingest
path (SURVEY.md §2.3); `ld06` provides it: a C++ stream parser/filter/
assembler built on demand with g++, a ctypes binding, and an LD06 packet
*encoder* so the simulator can exercise the real wire format end-to-end.
"""

from jax_mapping.native.ld06 import (  # noqa: F401
    Ld06Parser, encode_packets, native_available,
)
