"""Observability subsystem (ISSUE 9): causal tracing, flight recorder,
declarative metrics registry, postmortem trace-diff.

The reference judged throughput by watching RViz (SURVEY.md §5
"Tracing / profiling: none"); this package gives the framework the
observability layer a serving/training stack has, WITHOUT breaking the
bit-determinism contract every prior PR defended:

* `trace`    — `TraceContext`/`Tracer`: deterministic trace ids derived
               from `(seed, topic, seq)`, carried across Bus
               publish/delivery, mapper ticks and HTTP handlers; two
               same-seed `run_steps` missions emit identical streams.
               Gated by `ObsConfig.enabled` (False = no Tracer exists,
               bit-exact pre-obs behavior).
* `recorder` — `flight_recorder`: always-on bounded ring of structured
               load-bearing transitions, auto-dumped to the checkpoint
               dir on supervisor restarts, watchdog divergence and
               racewatch reports.
* `registry` — `MetricsRegistry`: the declarative Prometheus exposition
               that replaced `http_api.py`'s hand-built `/metrics`
               string (existing families byte-compatible).
* `export`   — Chrome-trace/Perfetto JSON (also `GET /trace?since=`).
* `diff`     — same-seed trace-diff: the first divergence point of two
               event/span streams, for actionable determinism gates.
* `devprof`  — `DispatchProfiler` (ISSUE 10): wraps the registered
               jitted entry points (the compilebudget `_cache_size`
               registry) to attribute dispatch wall time per function
               (fixed log-bucket histograms on `/metrics`), count
               runtime recompiles (`jax_mapping_jit_recompiles_total`)
               and capture abstract signatures per compiled variant.
               Gated by `ObsConfig.devprof.enabled` (False = no
               wrapper exists, bit-exact).
* `ledger`   — `CostLedger`: static XLA FLOPs/bytes-accessed per
               compiled variant via `lowered.compile().cost_analysis()`
               over the profiler's signatures, cross-checked against
               `analysis/compile_budget.json`.
* `pipeline` — `PipelineLedger` (ISSUE 15): per-revision freshness
               waypoints (scan enqueued → installed → notified → tile
               re-encoded → first client delivery) folded into fixed
               log-bucket hop histograms + the end-to-end
               `scan_to_served` family, per-tenant sliced; the
               Server-Timing revision-age source and the critical-path
               CLI's record feed. Rides the `ObsConfig.enabled` gate.
* `slo`      — `SloEngine` (ISSUE 15): the declarative freshness
               objectives in `ObsConfig.slo`, evaluated per mapper
               tick on multi-window sliding breach counters with
               fast/slow burn-rate alerting — alerts flight-recorded,
               on `/status.slo` + `jax_mapping_slo_*`, deterministic
               firing steps across same-seed runs.

`python -m jax_mapping.obs` is the CLI (diff two dumps, export a dump
to a Perfetto-loadable trace, run the cost ledger). Importing the
package never imports jax — devprof/ledger bind jax lazily at
install/collect time; everything else is host-side stdlib.
"""

from jax_mapping.obs.devprof import (                      # noqa: F401
    DispatchProfiler, abstract_signature,
)
from jax_mapping.obs.diff import (                         # noqa: F401
    Divergence, diff_dumps, diff_streams, normalize_events,
)
from jax_mapping.obs.ledger import (                       # noqa: F401
    CostLedger, run_cost_ledger,
)
from jax_mapping.obs.export import (                       # noqa: F401
    chrome_events, dump_to_chrome, write_chrome_trace,
)
from jax_mapping.obs.pipeline import (                     # noqa: F401
    FixedHistogram, PipelineLedger,
)
from jax_mapping.obs.recorder import (                     # noqa: F401
    FlightRecorder, flight_recorder,
)
from jax_mapping.obs.slo import (                          # noqa: F401
    SloEngine,
)
from jax_mapping.obs.registry import (                     # noqa: F401
    Family, MetricsRegistry, histogram_samples, summary_samples,
)
from jax_mapping.obs.trace import (                        # noqa: F401
    TraceContext, Tracer, h64,
)
