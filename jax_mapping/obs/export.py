"""Chrome-trace / Perfetto export for tracer spans and recorder dumps.

One converter, three consumers: `GET /trace?since=` serves live spans,
`python -m jax_mapping.obs export` converts a flight-recorder dump to a
`chrome://tracing` / Perfetto-loadable file, and tests read the event
shape. Pure stdlib (the `python -m` entry must start fast, no jax
import — the `analysis/__main__.py` precedent).
"""

from __future__ import annotations

import json
from typing import Iterable, List


def chrome_events(spans: Iterable[dict]) -> List[dict]:
    """Tracer span records -> Chrome Trace Event Format 'X' (complete)
    events. Ids ride in `args` (Perfetto's flow/query surface); instant
    spans get a 1 us floor so they stay visible on the timeline."""
    out = []
    for s in spans:
        out.append({
            "name": s["name"],
            "ph": "X",
            "ts": round(float(s.get("ts_us", 0.0)), 3),
            "dur": max(round(float(s.get("dur_us", 0.0)), 3), 1.0),
            "pid": 1,
            "tid": int(s.get("tid", 1)),
            "args": {
                "trace_id": f"{s['trace_id']:016x}",
                "span_id": f"{s['span_id']:016x}",
                "parent_span": f"{s['parent_span']:016x}",
                "seq": s.get("seq"),
            },
        })
    return out


def recorder_events_as_chrome(events: Iterable[dict]) -> List[dict]:
    """Flight-recorder events -> instant ('i') marks on their own track,
    so a dump's transitions overlay the span timeline in one view."""
    out = []
    for i, e in enumerate(events):
        args = {k: v for k, v in e.items()
                if k not in ("kind", "wall_ts")}
        out.append({
            "name": e.get("kind", "event"),
            "ph": "i",
            "s": "g",                       # global-scope instant mark
            "ts": float(i),                 # ring order; dumps lack a
            "pid": 1, "tid": 0,             # shared clock with spans
            "args": args,
        })
    return out


def dump_to_chrome(dump: dict) -> dict:
    """A flight-recorder dump (obs/recorder.py JSON) -> one Chrome
    trace document: spans as complete events, recorder transitions as
    instant marks."""
    return {"traceEvents": chrome_events(dump.get("spans", ()))
            + recorder_events_as_chrome(dump.get("events", ())),
            "otherData": {"reason": dump.get("reason", "")}}


def write_chrome_trace(path: str, spans: Iterable[dict],
                       events: Iterable[dict] = ()) -> str:
    with open(path, "w") as f:
        json.dump({"traceEvents": chrome_events(spans)
                   + recorder_events_as_chrome(events)}, f)
    return path
