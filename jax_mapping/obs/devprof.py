"""Dispatch profiler: device-side performance attribution (ISSUE 10).

PR 9 made the HOST side legible — causal spans, flight recorder,
declarative `/metrics` — but the device side, where the paper's
TPU-native mapping math actually runs, stayed a black box: nothing
attributed wall time to the jitted entry points, nothing counted
recompiles at runtime, nothing watched backend memory. This module
closes that gap WITHOUT touching a single kernel:

* `DispatchProfiler.install()` walks the same registry
  `analysis/compilebudget.py` walks — every module attribute under the
  package prefix exposing a callable `_cache_size` (the PjitFunction
  surface) — and rebinds EVERY alias of each jitted function (module
  attrs and from-import bindings alike resolve to module namespaces)
  to one transparent `_ProfiledJit` wrapper.
* Each host-level call records blocked-on-dispatch wall time into a
  per-function fixed log-bucket histogram (`HIST_EDGES_S`, the stage-
  histogram doctrine: two runs compare bucket-for-bucket), a call
  counter, and — by polling `_cache_size()` — compiled-variant growth:
  the runtime recompile telemetry the static C4 checker and the
  cold-cache compile-budget gate cannot see (`jax_mapping_jit_
  recompiles_total` on `/metrics`).
* On each variant growth the wrapper captures ONE abstract signature
  (arrays → `jax.ShapeDtypeStruct`, static/hashable args verbatim),
  bounded per function — the re-lowering input `obs/ledger.py` feeds
  to `lowered.compile().cost_analysis()` for the static FLOPs/bytes
  cost ledger.
* Calls made UNDER AN ACTIVE TRACE (a wrapped function invoked while
  another jit traces its caller) bypass recording entirely: trace-time
  excursions are compile cost, not dispatch cost, and counting them
  would double-book every retrace.

`DevProfConfig.enabled=False` constructs nothing — no wrapper exists
anywhere on the dispatch path, bit-exact pre-PR behavior; enabled is
pure host-side bookkeeping (bit-inert, property-tested). jax imports
are lazy (install time, never module import time): importing
`jax_mapping.obs` stays jax-free, the package contract since PR 9.

Thread contract: stats mutate only under `_lock` (racewatch-gated —
see analysis/protection.py); dispatches arrive concurrently from the
mapper tick thread, HTTP workers (serving tile hashing) and test
drivers. The module-level `_installed` singleton guard serializes
install/uninstall under `_INSTALL_LOCK` — wrappers are process-global
state, two live profilers would double-wrap.
"""

from __future__ import annotations

import bisect
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from jax_mapping.utils.profiling import HIST_EDGES_S

#: Process-global install guard: module-attribute rebinding is
#: process-wide, so at most one profiler may be installed at a time.
_INSTALL_LOCK = threading.Lock()
_installed: Optional["DispatchProfiler"] = None

_trace_state_clean = None


def _trace_clean() -> bool:
    """True when no jax trace is active on this thread (lazy-bound so
    importing this module never imports jax)."""
    global _trace_state_clean
    if _trace_state_clean is None:
        import jax
        _trace_state_clean = jax.core.trace_state_clean
    return _trace_state_clean()


def abstract_signature(args: tuple, kwargs: dict):
    """(args, kwargs) with every array-typed leaf replaced by a
    `jax.ShapeDtypeStruct` — exactly what `PjitFunction.lower` accepts
    for AOT re-lowering. Non-array leaves (frozen config dataclasses,
    python scalars used as static args) pass through verbatim: the
    ledger re-lowers with the same static values the live call used."""
    import jax

    def absify(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
        return x

    return jax.tree_util.tree_map(absify, (args, kwargs))


class _FnProfile:
    """Per-function dispatch accounting; mutated only under the
    profiler's `_lock`."""

    __slots__ = ("name", "count", "total_s", "max_s", "buckets",
                 "cache_size", "n_compiles", "signatures")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        #: Per-bucket (non-cumulative) counts over HIST_EDGES_S;
        #: [-1] is overflow — the StageTimer layout, so the /metrics
        #: exposition shares one helper.
        self.buckets = [0] * (len(HIST_EDGES_S) + 1)
        #: Compiled-variant high-water (`_cache_size()` last seen).
        self.cache_size = 0
        #: Total compile events observed while profiled (cache growth;
        #: the first compile counts — "recompiles" in the Prometheus
        #: family name means "compiles the warm steady state should not
        #: be paying", and the committed budget says how many are
        #: sanctioned).
        self.n_compiles = 0
        #: [(key, (abstract_args, abstract_kwargs))] — one per observed
        #: compiled variant, bounded by DevProfConfig.
        self.signatures: List[Tuple[str, tuple]] = []


class _ProfiledJit:
    """Transparent pass-through wrapper for one jitted entry point.

    Everything but `__call__` forwards to the wrapped function —
    `_cache_size`, `lower`, `__name__`, `__module__` — so registry
    walks (compilebudget), AOT lowering and introspection behave as if
    the wrapper were not there."""

    __slots__ = ("_fn", "_prof", "_name")

    def __init__(self, fn, prof: "DispatchProfiler", name: str):
        self._fn = fn
        self._prof = prof
        self._name = name

    def __call__(self, *args, **kwargs):
        if not _trace_clean():
            # Trace-time excursion (this call is being traced into a
            # caller's jaxpr): compile cost, not dispatch cost.
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        try:
            return self._fn(*args, **kwargs)
        finally:
            self._prof._record(self, time.perf_counter() - t0,
                               args, kwargs)

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_fn"), item)

    # `__module__`/`__doc__` land in every class dict at class-creation
    # time, so instance lookup finds THEM instead of falling through to
    # __getattr__ — and a wrapper reporting `jax_mapping.obs.devprof`
    # as the wrapped function's module would corrupt the compilebudget
    # registry's owner-qualified names while profiled. Forward them
    # explicitly. (`__qualname__` cannot be a property — class creation
    # requires a str — and nothing keys on it; the class's own is
    # fine.)
    @property
    def __module__(self):
        return getattr(self._fn, "__module__", None)

    @property
    def __doc__(self):
        return getattr(self._fn, "__doc__", None)

    def __repr__(self) -> str:
        return f"<profiled {self._name}>"


class DispatchProfiler:
    """Wrap the package's jitted entry points; attribute dispatch wall
    time, recompiles and cost-ledger signatures per function."""

    def __init__(self, cfg=None, tracer=None):
        if cfg is None:
            from jax_mapping.config import DevProfConfig
            cfg = DevProfConfig(enabled=True)
        self.cfg = cfg
        self.tracer = tracer
        self._lock = threading.Lock()
        self._profiles: Dict[str, _FnProfile] = {}
        #: [(wrapper, [(module, attr)])] — the rebind log uninstall
        #: replays. Mutated only during install/uninstall under
        #: `_INSTALL_LOCK`.
        self._bindings: List[Tuple[_ProfiledJit, list]] = []
        self.installed = False

    # -- install / uninstall -------------------------------------------------

    def install(self, prefix: str = "jax_mapping") -> int:
        """Wrap every currently-importable jitted entry point under
        `prefix`; returns how many NEW functions were wrapped. May be
        called again after further imports (incremental — already-
        wrapped functions are skipped); a second live profiler is
        refused (wrappers are process-global)."""
        global _installed
        with _INSTALL_LOCK:
            if _installed is not None and _installed is not self:
                raise RuntimeError(
                    "another DispatchProfiler is installed — uninstall "
                    "it first (wrappers are process-global)")
            targets: Dict[int, Tuple[object, list]] = {}
            for mod_name in sorted(sys.modules):
                mod = sys.modules[mod_name]
                if mod is None or not mod_name.startswith(prefix):
                    continue
                for attr in sorted(vars(mod)):
                    fn = vars(mod)[attr]
                    if isinstance(fn, _ProfiledJit):
                        continue
                    cache_size = getattr(fn, "_cache_size", None)
                    if not callable(cache_size) or not callable(fn):
                        continue
                    ent = targets.setdefault(id(fn), (fn, []))
                    ent[1].append((mod, attr))
            for fn, sites in targets.values():
                name = self._qualify(fn, sites, prefix)
                wrapper = _ProfiledJit(fn, self, name)
                for mod, attr in sites:
                    setattr(mod, attr, wrapper)
                self._bindings.append((wrapper, sites))
                try:
                    baseline = int(fn._cache_size())
                except Exception:                   # noqa: BLE001
                    baseline = 0
                with self._lock:
                    prof = self._profiles.setdefault(name,
                                                     _FnProfile(name))
                    # Compiles counted SINCE install: in a warm process
                    # (tests, a long-lived operator session) the first
                    # profiled call must not inherit every variant the
                    # process compiled before profiling was armed.
                    prof.cache_size = max(prof.cache_size, baseline)
            _installed = self
            self.installed = True
            return len(targets)

    @staticmethod
    def _qualify(fn, sites, prefix: str) -> str:
        """The compilebudget naming contract: defining module + name,
        stable across from-import aliases. ONE definition shared with
        the warm-pool/snapshot naming (io/compile_cache.qualified_name)
        — exact agreement is load-bearing: AOT snapshots are saved
        under the profiler's names and matched by the pool's walk, and
        a drift between two hand-copies would silently make every
        snapshot unmatchable (lazy import: obs stays jax-free and
        io-free at module import)."""
        from jax_mapping.io.compile_cache import qualified_name
        return qualified_name(fn, sites[0][0].__name__, sites[0][1],
                              prefix)

    def rebaseline(self, names=None) -> int:
        """Adopt each wrapped function's CURRENT compiled-variant count
        as the recompile baseline without counting the delta — the
        warm-restart contract (ISSUE 12): variants brought in by the
        staged warm-up through the persistent compile cache (or served
        by AOT snapshots, which never grow the jit cache at all) are
        cold-start repayment, not live recompiles, and
        `jax_mapping_jit_recompiles_total` must stay zero across a warm
        restart exactly as it does across install. Returns how many
        functions moved their baseline. `names` limits the sweep."""
        with _INSTALL_LOCK:
            bindings = list(self._bindings)
        moved = 0
        for wrapper, _sites in bindings:
            if names is not None and wrapper._name not in names:
                continue
            try:
                cache = int(wrapper._fn._cache_size())
            except Exception:                       # noqa: BLE001
                continue
            with self._lock:
                st = self._profiles.setdefault(wrapper._name,
                                               _FnProfile(wrapper._name))
                if cache > st.cache_size:
                    st.cache_size = cache
                    moved += 1
        return moved

    def uninstall(self) -> None:
        """Restore the original functions at every site that still
        holds our wrapper (a site reassigned since install is left
        alone). Idempotent; safe to call from Stack.shutdown twice."""
        global _installed
        with _INSTALL_LOCK:
            for wrapper, sites in self._bindings:
                for mod, attr in sites:
                    if vars(mod).get(attr) is wrapper:
                        setattr(mod, attr, wrapper._fn)
            self._bindings = []
            if _installed is self:
                _installed = None
            self.installed = False

    # -- recording (any thread) ----------------------------------------------

    def _record(self, wrapper: _ProfiledJit, dt_s: float,
                args: tuple, kwargs: dict) -> None:
        try:
            cache = int(wrapper._fn._cache_size())
        except Exception:                           # noqa: BLE001
            cache = -1
        capture = None
        with self._lock:
            st = self._profiles.setdefault(wrapper._name,
                                           _FnProfile(wrapper._name))
            st.count += 1
            st.total_s += dt_s
            st.max_s = max(st.max_s, dt_s)
            st.buckets[bisect.bisect_left(HIST_EDGES_S, dt_s)] += 1
            if cache > st.cache_size:
                st.n_compiles += cache - st.cache_size
                st.cache_size = cache
                if self.cfg.capture_signatures and \
                        len(st.signatures) < self.cfg.max_signatures_per_fn:
                    capture = st
        if capture is not None:
            # Abstraction outside the lock (tree_map over a whole
            # SlamState costs more than a histogram bump); the append
            # re-takes the lock and dedups — a racing twin costs one
            # redundant abstraction, never a lost variant.
            try:
                sig = abstract_signature(args, kwargs)
                key = repr(sig)
            except Exception:                       # noqa: BLE001
                sig = key = None          # unabstractable tree: skip
            if sig is not None and key is not None:
                with self._lock:
                    if key not in [k for k, _ in capture.signatures] \
                            and len(capture.signatures) \
                            < self.cfg.max_signatures_per_fn:
                        capture.signatures.append((key, sig))
        if self.cfg.trace_spans and self.tracer is not None:
            self.tracer.emit(f"device:{wrapper._name}")

    # -- export ---------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """Per-function dispatch accounting for `/status` `perf` (only
        functions actually dispatched — wrapped-but-idle entries are
        noise an operator scrolls past)."""
        with self._lock:
            return {
                name: {
                    "count": st.count,
                    "total_ms": round(st.total_s * 1e3, 3),
                    "mean_ms": round(st.total_s * 1e3
                                     / max(st.count, 1), 3),
                    "max_ms": round(st.max_s * 1e3, 3),
                    "compiled_variants": st.cache_size,
                    "n_compiles": st.n_compiles,
                    "n_signatures": len(st.signatures),
                } for name, st in sorted(self._profiles.items())
                if st.count > 0
            }

    def histograms(self) -> Dict[str, dict]:
        """Per-function fixed log-bucket dispatch histograms — the
        `jax_mapping_device_dispatch_seconds` family source (StageTimer
        layout: edges + per-bucket counts + sum + count)."""
        with self._lock:
            return {
                name: {
                    "edges_s": HIST_EDGES_S,
                    "buckets": list(st.buckets),
                    "sum_s": st.total_s,
                    "count": st.count,
                } for name, st in sorted(self._profiles.items())
                if st.count > 0
            }

    def recompiles(self) -> Dict[str, int]:
        """Compile events per function while profiled — the
        `jax_mapping_jit_recompiles_total{fn=...}` source (every
        profiled function reports, 0 included: an absent label and a
        zero counter mean different things to a rate() query)."""
        with self._lock:
            return {name: st.n_compiles
                    for name, st in sorted(self._profiles.items())}

    def signatures(self) -> Dict[str, List[tuple]]:
        """Captured abstract signatures per function (ledger input)."""
        with self._lock:
            return {name: [sig for _, sig in st.signatures]
                    for name, st in self._profiles.items()
                    if st.signatures}

    def raw_fn(self, name: str):
        """The unwrapped function for `name`, or None — the ledger
        lowers through this so its AOT calls don't count as
        dispatches."""
        with _INSTALL_LOCK:
            for wrapper, _ in self._bindings:
                if wrapper._name == name:
                    return wrapper._fn
        return None

    def memory_stats(self) -> Optional[Dict[str, dict]]:
        """Backend memory watermarks per device, or None when no
        visible backend provides `memory_stats()` (CPU) or the knob is
        off — the graceful-None contract."""
        if not self.cfg.memory_stats:
            return None
        import jax
        out: Dict[str, dict] = {}
        for d in jax.devices():
            try:
                ms = d.memory_stats()
            except Exception:                       # noqa: BLE001
                ms = None
            if not ms:
                continue
            out[f"{d.platform}:{d.id}"] = {
                k: int(v) for k, v in ms.items()
                if k in ("bytes_in_use", "peak_bytes_in_use",
                         "bytes_limit", "largest_alloc_size")}
        return out or None
