"""Static XLA cost ledger: FLOPs / bytes-accessed per jitted function
per compiled variant (ISSUE 10).

The dispatch profiler (obs/devprof.py) says how long each entry point
BLOCKED the host; this module says what the compiled program COSTS —
XLA's own static cost model (`lowered.compile().cost_analysis()`),
collected per captured abstract signature, so the perf trajectory can
distinguish "the kernel got slower" from "the kernel got bigger" and
the pod-scale work (ROADMAP items 3–4) can budget FLOPs before it
budgets wall clock.

Collection is EXPLICIT, never implicit: re-lowering + AOT compilation
costs seconds per variant, so `collect()` runs from the CLI (`python
-m jax_mapping.obs cost-ledger`), the compile-budget gate
(`compilebudget --check --ledger`) and tests — `/status` `perf`
exports whatever has been collected so far plus the uncollected count
(an HTTP handler must never compile). Results cache per (function,
signature): a second collect() is free.

`cross_check()` closes the loop with `analysis/compile_budget.json`:
every budgeted function must have ledger coverage, and the profiler's
observed variant count must not exceed the committed budget — the
ratchet contract, applied to the runtime-observed registry.

jax imports are lazy (collect time only): importing `jax_mapping.obs`
stays jax-free.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


def _normalize_cost(ca) -> Optional[dict]:
    """`cost_analysis()` returns a dict (or a one-per-device list of
    dicts) of XLA cost-model properties; keep the portable core."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None
    out = {}
    if "flops" in ca:
        out["flops"] = float(ca["flops"])
    if "bytes accessed" in ca:
        out["bytes_accessed"] = float(ca["bytes accessed"])
    for k in ("optimal_seconds", "transcendentals"):
        if k in ca:
            out[k] = float(ca[k])
    return out or None


class CostLedger:
    """FLOPs/bytes-accessed per (jitted function, compiled variant),
    fed by a DispatchProfiler's captured signatures."""

    def __init__(self, profiler):
        self.profiler = profiler
        self._lock = threading.Lock()
        #: ONE keyed structure, {fn_name: {signature_repr: entry}} —
        #: an entry of None marks a reservation whose (slow, unlocked)
        #: AOT compile is in flight. One field on purpose: a separate
        #: done-set alongside an entry list would be a correlated pair
        #: readable across two lock sections (the C2 tear class).
        self._collected: Dict[str, Dict[str, Optional[dict]]] = {}

    # -- collection (explicit, expensive) -------------------------------------

    def collect(self) -> Dict[str, List[dict]]:
        """AOT re-lower + compile every captured-but-uncollected
        signature and record its cost analysis. Returns the full
        ledger. Failures record an `error` entry instead of raising —
        one unlowerable signature must not hide the other 14
        functions' costs."""
        sigs = self.profiler.signatures()
        for name, variants in sorted(sigs.items()):
            fn = self.profiler.raw_fn(name)
            if fn is None:
                continue
            for sig in variants:
                key = repr(sig)
                with self._lock:
                    slot = self._collected.setdefault(name, {})
                    if key in slot:
                        continue
                    # Reserve (None) before the unlocked compile so a
                    # concurrent collect never compiles the same
                    # variant twice.
                    slot[key] = None
                entry = self._collect_one(fn, sig)
                with self._lock:
                    self._collected[name][key] = entry
        return self.snapshot()

    @staticmethod
    def _collect_one(fn, sig) -> dict:
        args, kwargs = sig
        entry = {"signature": _sig_label(sig)}
        try:
            compiled = fn.lower(*args, **kwargs).compile()
            cost = _normalize_cost(compiled.cost_analysis())
            if cost is None:
                entry["error"] = "backend returned no cost analysis"
            else:
                entry.update(cost)
        except Exception as e:                      # noqa: BLE001
            entry["error"] = f"{type(e).__name__}: {e}"
        return entry

    # -- export ----------------------------------------------------------------

    def snapshot(self) -> Dict[str, List[dict]]:
        with self._lock:
            return {
                name: [dict(e) for e in slots.values()
                       if e is not None]
                for name, slots in sorted(self._collected.items())
                if any(e is not None for e in slots.values())}

    def n_uncollected(self) -> int:
        """Captured signatures with no FINISHED ledger entry yet
        (in-flight reservations count — they have no data to export)."""
        sigs = self.profiler.signatures()
        with self._lock:
            return sum(
                1 for name, variants in sigs.items()
                for sig in variants
                if self._collected.get(name, {}).get(repr(sig)) is None)

    # -- the budget cross-check -------------------------------------------------

    def cross_check(self, budget_path: Optional[str] = None
                    ) -> List[str]:
        """Violations against `analysis/compile_budget.json`: a
        budgeted function with no ledger coverage (never dispatched or
        never costed — the attribution layer has a hole), a costed
        variant count EXCEEDING the budget (runtime recompile
        regression), or coverage without FLOPs/bytes (the backend or a
        signature failed). Empty list = clean."""
        from jax_mapping.analysis.compilebudget import (
            Budget, default_budget_path)
        budget = Budget.load(budget_path or default_budget_path())
        entries = self.snapshot()
        recompiles = self.profiler.recompiles()
        out: List[str] = []
        for e in budget.entries:
            name = e["name"]
            got = entries.get(name)
            if not got:
                out.append(f"{name}: budgeted but no cost-ledger "
                           "coverage (never dispatched under the "
                           "profiler, or signature capture missed it)")
                continue
            if len(got) > e["max"]:
                out.append(f"{name}: {len(got)} costed variant(s) "
                           f"exceeds budget {e['max']}")
            bad = [v for v in got if "flops" not in v
                   or "bytes_accessed" not in v]
            for v in bad:
                out.append(f"{name}: variant {v['signature']} has no "
                           f"FLOPs/bytes ({v.get('error', 'missing')})")
            observed = recompiles.get(name, 0)
            if observed > e["max"]:
                out.append(f"{name}: profiler observed {observed} "
                           f"compile(s), budget allows {e['max']}")
        return out


def _sig_label(sig) -> str:
    """Compact human-readable variant label: array leaves as
    shape/dtype, everything else by type name."""
    args, kwargs = sig

    def leaf(x):
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            dims = "x".join(map(str, x.shape)) or "scalar"
            return f"{dims}:{x.dtype}"
        return type(x).__name__

    def walk(x):
        # NamedTuple pytrees (SlamState and friends) before the plain
        # tuple branch — they ARE tuples, and the type name is the
        # readable part of the label.
        if isinstance(x, tuple) and hasattr(x, "_fields"):
            return type(x).__name__ + "(" + ",".join(
                walk(v) for v in x) + ")"
        if isinstance(x, (list, tuple)):
            return "(" + ",".join(walk(v) for v in x) + ")"
        if isinstance(x, dict):
            return "{" + ",".join(f"{k}={walk(v)}"
                                  for k, v in sorted(x.items())) + "}"
        return leaf(x)

    label = walk(args)
    if kwargs:
        label += walk(kwargs)
    return label


def run_cost_ledger(analysis_cfg=None):
    """Drive the canonical compile-budget scenario with a
    DispatchProfiler installed and return `(measured_cache_sizes,
    profiler, ledger)` — the shared machinery behind `python -m
    jax_mapping.obs cost-ledger` and `compilebudget --check --ledger`.

    Imports every package submodule FIRST so lazily-imported jitted
    entry points (serving, pyramid, relocalize) exist before install —
    a function imported mid-scenario would dodge the wrapper and
    surface as a coverage hole. Must run with cold jit caches (a fresh
    process) for the variant counts to mean anything, the
    compilebudget contract."""
    import importlib
    import pkgutil

    import jax_mapping
    for m in pkgutil.walk_packages(jax_mapping.__path__,
                                   prefix="jax_mapping."):
        try:
            importlib.import_module(m.name)
        except Exception:                           # noqa: BLE001
            continue              # optional deps (ros adapters) absent

    from jax_mapping.analysis.compilebudget import measure_scenario
    from jax_mapping.config import DevProfConfig
    from jax_mapping.obs.devprof import DispatchProfiler

    profiler = DispatchProfiler(DevProfConfig(
        enabled=True, max_signatures_per_fn=16))
    profiler.install()
    try:
        measured = measure_scenario(analysis_cfg)
        ledger = CostLedger(profiler)
        ledger.collect()
    finally:
        profiler.uninstall()
    return measured, profiler, ledger
