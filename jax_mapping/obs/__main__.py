"""`python -m jax_mapping.obs` — the postmortem CLI.

Subcommands (pure stdlib, fast start — the analysis/__main__ precedent;
no jax import):

    diff A.json B.json     Trace-diff two flight-recorder dumps (or raw
                           {"events": [...], "spans": [...]} documents)
                           from two same-seed runs; prints the first
                           divergence point per stream. Exit 0 when
                           identical, 1 on divergence, 2 on usage.
    export DUMP [-o OUT]   Convert a flight-recorder dump to a Chrome-
                           trace/Perfetto JSON (default OUT:
                           DUMP + ".trace.json").
    cost-ledger [-o OUT]   Run the canonical compile-budget scenario
                           with the dispatch profiler installed and
                           print the static XLA cost ledger (FLOPs /
                           bytes-accessed per jitted function per
                           compiled variant) plus the compile-budget
                           cross-check as one JSON document. The one
                           subcommand that imports jax (and should run
                           in a fresh process: cold caches are what
                           make the variant counts meaningful). Exit 0
                           clean, 1 on cross-check violations, 2 on
                           error.

Postmortem workflow (README "Observability"): a chaos gate fails -> the
recorder auto-dumped to the checkpoint dir -> `diff` the failing run's
dump against a green same-seed run's to get the first divergent
transition instead of a grid diff.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from jax_mapping.obs.diff import diff_dumps
from jax_mapping.obs.export import dump_to_chrome


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return doc


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jax_mapping.obs",
        description="observability postmortem tools (trace-diff, "
                    "Perfetto export)")
    sub = p.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("diff", help="first divergence of two same-seed "
                                    "event/span streams")
    d.add_argument("a")
    d.add_argument("b")
    e = sub.add_parser("export", help="flight-recorder dump -> Chrome-"
                                      "trace/Perfetto JSON")
    e.add_argument("dump")
    e.add_argument("-o", "--out", default=None)
    c = sub.add_parser("cost-ledger",
                       help="static XLA FLOPs/bytes ledger over the "
                            "canonical scenario (imports jax)")
    c.add_argument("-o", "--out", default=None)
    c.add_argument("--budget", default=None, metavar="JSON")
    try:
        args = p.parse_args(argv)
    except SystemExit as ex:
        return 2 if ex.code not in (0, None) else 0

    try:
        if args.cmd == "diff":
            res = diff_dumps(_load(args.a), _load(args.b))
            for stream in ("events", "spans"):
                div = res[stream]
                if div is None:
                    print(f"{stream}: identical")
                else:
                    print(f"{stream}: " + div.describe())
            return 0 if res["identical"] else 1
        if args.cmd == "export":
            out = args.out or (args.dump + ".trace.json")
            doc = dump_to_chrome(_load(args.dump))
            with open(out, "w") as f:
                json.dump(doc, f)
            print(f"wrote {out} ({len(doc['traceEvents'])} events)")
            return 0
        if args.cmd == "cost-ledger":
            import contextlib
            from jax_mapping.obs.ledger import run_cost_ledger
            # Stack bring-up chatter goes to stderr: stdout is exactly
            # one JSON document (the compilebudget --measure contract).
            try:
                with contextlib.redirect_stdout(sys.stderr):
                    measured, profiler, ledger = run_cost_ledger()
                    violations = ledger.cross_check(args.budget)
            except Exception as ex:                 # noqa: BLE001
                print(f"cost-ledger: scenario failed: {ex}",
                      file=sys.stderr)
                return 2
            doc = {"functions": ledger.snapshot(),
                   "dispatch": profiler.snapshot(),
                   "compiled_variants": measured,
                   "cross_check": violations}
            text = json.dumps(doc, indent=1, sort_keys=True)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(text + "\n")
            print(text)
            return 1 if violations else 0
    except (OSError, ValueError, KeyError) as ex:
        print(f"error: {ex}", file=sys.stderr)
        return 2
    return 2


if __name__ == "__main__":
    sys.exit(main())
