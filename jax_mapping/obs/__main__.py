"""`python -m jax_mapping.obs` — the postmortem CLI.

Subcommands (pure stdlib, fast start — the analysis/__main__ precedent;
no jax import):

    diff A.json B.json     Trace-diff two flight-recorder dumps (or raw
                           {"events": [...], "spans": [...]} documents)
                           from two same-seed runs; prints the first
                           divergence point per stream. Exit 0 when
                           identical, 1 on divergence, 2 on usage.
    export DUMP [-o OUT]   Convert a flight-recorder dump to a Chrome-
                           trace/Perfetto JSON (default OUT:
                           DUMP + ".trace.json").
    critical-path DUMP [BASELINE]
                           Walk a dump's `pipeline` section (the
                           latency ledger's completed-revision records,
                           obs/pipeline.py) and report the per-revision
                           scan→served critical path — which hop
                           (fuse / notify / encode / deliver) dominated
                           each revision, aggregate hop shares, and the
                           slowest revisions. With BASELINE, diff the
                           two runs' records through obs/diff.py
                           normalization (hop durations and the
                           dominance they imply are volatile; the
                           deterministic structure — revision, tick,
                           tenant sequence — must match for two
                           same-seed runs). Exit 0 identical/ok, 1 on
                           structural divergence, 2 on usage/no
                           records.
    cost-ledger [-o OUT]   Run the canonical compile-budget scenario
                           with the dispatch profiler installed and
                           print the static XLA cost ledger (FLOPs /
                           bytes-accessed per jitted function per
                           compiled variant) plus the compile-budget
                           cross-check as one JSON document. The one
                           subcommand that imports jax (and should run
                           in a fresh process: cold caches are what
                           make the variant counts meaningful). Exit 0
                           clean, 1 on cross-check violations, 2 on
                           error.

Postmortem workflow (README "Observability"): a chaos gate fails -> the
recorder auto-dumped to the checkpoint dir -> `diff` the failing run's
dump against a green same-seed run's to get the first divergent
transition instead of a grid diff.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from jax_mapping.obs.diff import diff_dumps
from jax_mapping.obs.export import dump_to_chrome


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: expected a JSON object")
    return doc


def _critical_path(dump_path: str, baseline_path: Optional[str]) -> int:
    """The critical-path analyzer (see module docstring)."""
    from jax_mapping.obs.diff import VOLATILE_FIELDS, diff_streams
    from jax_mapping.obs.pipeline import HOPS, RECORD_VOLATILE
    recs = _load(dump_path).get("pipeline") or []
    if not recs:
        print("no pipeline records in dump (ledger absent, or no "
              "revision completed a client delivery)", file=sys.stderr)
        return 2
    dominant = {}
    hop_total = {}
    for r in recs:
        dominant[r.get("critical")] = \
            dominant.get(r.get("critical"), 0) + 1
        for hop, ms in (r.get("hops_ms") or {}).items():
            hop_total[hop] = hop_total.get(hop, 0.0) + ms
    total = sum(hop_total.values()) or 1.0
    print(f"{len(recs)} completed revision(s), "
          f"{len({r.get('tenant', '') for r in recs})} tenant "
          f"namespace(s)")
    print("hop shares (summed hop time; dominant = revisions this hop "
          "was the critical one):")
    for hop in list(HOPS) + sorted(set(hop_total) - set(HOPS)):
        if hop not in hop_total:
            continue
        print(f"  {hop:<8} {hop_total[hop]:>10.1f} ms "
              f"({100.0 * hop_total[hop] / total:5.1f}%)  "
              f"dominant in {dominant.get(hop, 0)} revision(s)")
    slowest = sorted(recs, key=lambda r: -r.get("total_ms", 0.0))[:5]
    print("slowest revisions (scan→served):")
    for r in slowest:
        tenant = r.get("tenant") or "-"
        print(f"  rev {r.get('revision')} (tenant {tenant}, tick "
              f"{r.get('tick')}): {r.get('total_ms', 0.0):.1f} ms, "
              f"critical hop = {r.get('critical')}")
    if baseline_path is None:
        return 0
    base = _load(baseline_path).get("pipeline") or []
    div = diff_streams(recs, base,
                       ignore=tuple(VOLATILE_FIELDS)
                       + tuple(RECORD_VOLATILE))
    if div is None:
        print("baseline: structurally identical (same revision/tick/"
              "tenant sequence; hop timings are volatile by design)")
        return 0
    print("baseline: " + div.describe())
    return 1


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m jax_mapping.obs",
        description="observability postmortem tools (trace-diff, "
                    "Perfetto export)")
    sub = p.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("diff", help="first divergence of two same-seed "
                                    "event/span streams")
    d.add_argument("a")
    d.add_argument("b")
    e = sub.add_parser("export", help="flight-recorder dump -> Chrome-"
                                      "trace/Perfetto JSON")
    e.add_argument("dump")
    e.add_argument("-o", "--out", default=None)
    c = sub.add_parser("cost-ledger",
                       help="static XLA FLOPs/bytes ledger over the "
                            "canonical scenario (imports jax)")
    c.add_argument("-o", "--out", default=None)
    c.add_argument("--budget", default=None, metavar="JSON")
    k = sub.add_parser("critical-path",
                       help="per-revision scan→served critical path "
                            "from a dump's pipeline records")
    k.add_argument("dump")
    k.add_argument("baseline", nargs="?", default=None)
    try:
        args = p.parse_args(argv)
    except SystemExit as ex:
        return 2 if ex.code not in (0, None) else 0

    try:
        if args.cmd == "diff":
            res = diff_dumps(_load(args.a), _load(args.b))
            for stream in ("events", "spans"):
                div = res[stream]
                if div is None:
                    print(f"{stream}: identical")
                else:
                    print(f"{stream}: " + div.describe())
            return 0 if res["identical"] else 1
        if args.cmd == "export":
            out = args.out or (args.dump + ".trace.json")
            doc = dump_to_chrome(_load(args.dump))
            with open(out, "w") as f:
                json.dump(doc, f)
            print(f"wrote {out} ({len(doc['traceEvents'])} events)")
            return 0
        if args.cmd == "critical-path":
            return _critical_path(args.dump, args.baseline)
        if args.cmd == "cost-ledger":
            import contextlib
            from jax_mapping.obs.ledger import run_cost_ledger
            # Stack bring-up chatter goes to stderr: stdout is exactly
            # one JSON document (the compilebudget --measure contract).
            try:
                with contextlib.redirect_stdout(sys.stderr):
                    measured, profiler, ledger = run_cost_ledger()
                    violations = ledger.cross_check(args.budget)
            except Exception as ex:                 # noqa: BLE001
                print(f"cost-ledger: scenario failed: {ex}",
                      file=sys.stderr)
                return 2
            doc = {"functions": ledger.snapshot(),
                   "dispatch": profiler.snapshot(),
                   "compiled_variants": measured,
                   "cross_check": violations}
            text = json.dumps(doc, indent=1, sort_keys=True)
            if args.out:
                with open(args.out, "w") as f:
                    f.write(text + "\n")
            print(text)
            return 1 if violations else 0
    except (OSError, ValueError, KeyError) as ex:
        print(f"error: {ex}", file=sys.stderr)
        return 2
    return 2


if __name__ == "__main__":
    sys.exit(main())
