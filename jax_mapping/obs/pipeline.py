"""Pipeline latency ledger: per-revision freshness waypoints.

The question a production operator asks of a mapping service is not
"how fast is one dispatch" (obs/devprof.py answers that) but *how stale
is the map a client is looking at, and is that within budget?* —
end-to-end, across the queueing, fusion, encoding and serving hops the
per-stage timers each see only a slice of. The ledger stamps every map
revision's waypoints as they happen:

    scan enqueued   (mapper._scan_cb, the oldest scan of the step)
      → installed   (mapper._finish_step: evidence in the shared grid,
                     map_revision bumped)
      → notified    (mapper tick end: revision fanned to listeners —
                     the /map-events nudge)
      → encoded     (serving/tiles.TileStore commit: tiles re-encoded
                     at or past the revision)
      → delivered   (the first /tiles response that confirms a client
                     holds the revision — a 304 confirms exactly as a
                     body does)

and folds the hop latencies into fixed log-bucket histograms
(`utils/profiling.HIST_EDGES_S`, the stage-histogram doctrine: every
histogram in the repo shares one bucket grid so runs compare
bucket-for-bucket) plus the end-to-end `scan_to_served` family — all
exported on `/metrics`, with per-tenant slicing via the tenancy serving
namespaces (a tenant's revisions stamp under its own label).

All timestamps are the SERVER's `time.perf_counter()` — revision ages
served to clients (the `Server-Timing`-style header on /tiles) are
server monotonic deltas, never cross-host wall clocks, so a client
measures observed staleness without trusting anyone's wall clock.

A revision that is never individually served is not lost: serving any
NEWER revision completes every older pending one (a client that holds
revision N+1 is at least as fresh as N — freshness is cumulative, the
drop-to-latest event-channel argument). Completed revisions land in a
bounded record ring (`records()`) that flight-recorder dumps carry as a
`pipeline` section and `python -m jax_mapping.obs critical-path` walks
to report which hop dominated each revision's scan→served path.

Constructed only when `ObsConfig.enabled` (the Tracer gate): disabled
means no ledger object exists anywhere — bit-exact, host-side-only
either way. Pure stdlib + the profiling bucket grid; no jax import.
"""

from __future__ import annotations

import bisect
import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

from jax_mapping.utils.profiling import HIST_EDGES_S

#: Hop names, pipeline order. `scan_to_served` is the end-to-end family
#: (enqueue → first client delivery), reported alongside but not a hop.
HOPS: Tuple[str, ...] = ("fuse", "notify", "encode", "deliver")

#: Volatile fields of a completed-revision record: wall durations and
#: everything derived from them (which hop dominated is a timing fact).
#: `python -m jax_mapping.obs critical-path A B` diffs two same-seed
#: runs' records with these ignored on top of obs/diff.VOLATILE_FIELDS
#: — the deterministic structure (revision, tick, tenant) must match.
RECORD_VOLATILE: Tuple[str, ...] = ("hops_ms", "total_ms", "critical")


class FixedHistogram:
    """One fixed log-bucket latency histogram (HIST_EDGES_S grid) with
    bucket-based percentile estimation — the registry's histogram
    machinery as a standalone accumulator, for recorders that live
    outside the process-wide StageTimer (per-hop ledger slices, the
    loadgen's per-client request latencies). NOT thread-safe: callers
    guard it (the ledger under its `_lock`; loadgen stats are
    single-writer per client thread)."""

    __slots__ = ("buckets", "total_s", "count")

    def __init__(self) -> None:
        self.buckets = [0] * (len(HIST_EDGES_S) + 1)
        self.total_s = 0.0
        self.count = 0

    def observe(self, dt_s: float) -> None:
        self.buckets[bisect.bisect_left(HIST_EDGES_S, dt_s)] += 1
        self.total_s += dt_s
        self.count += 1

    def percentile_ms(self, p: float) -> Optional[float]:
        """Bucket-resolved percentile (upper-edge estimate, the
        conservative read a log-bucket histogram supports; the
        overflow bucket reports the last edge). None when empty."""
        if self.count == 0:
            return None
        rank = max(1, -(-self.count * p // 100))       # ceil
        cum = 0
        for k, n in enumerate(self.buckets):
            cum += n
            if cum >= rank:
                edge = HIST_EDGES_S[min(k, len(HIST_EDGES_S) - 1)]
                return edge * 1e3
        return HIST_EDGES_S[-1] * 1e3

    def summary(self) -> dict:
        return {"edges_s": HIST_EDGES_S, "buckets": list(self.buckets),
                "sum_s": self.total_s, "count": self.count}


class PipelineLedger:
    """Per-revision waypoint stamps → hop histograms + e2e samples.

    Thread contract: every stamp mutator and reader serializes on ONE
    `_lock` (racewatch-gated — see analysis/protection.py): stamps
    arrive from the mapper tick thread (installed/notified), HTTP
    worker threads (encoded via TileStore refresh, delivered on /tiles
    responses) and the tenancy stepping thread at once. All work per
    stamp is a few dict ops — orders of magnitude off the <5% tick
    overhead gate (BENCH_OBS_r03).
    """

    def __init__(self, pending_cap: int = 512, sample_window: int = 512,
                 record_cap: int = 1024, age_cap: int = 1024):
        self._lock = threading.Lock()
        #: (tenant, revision) -> waypoint stamp dict, insertion-ordered
        #: (revisions are monotone per tenant).
        self._pending: Dict[Tuple[str, int], dict] = {}
        self._pending_cap = pending_cap
        #: (hop, tenant) -> FixedHistogram; hop "scan_to_served" is the
        #: end-to-end family.
        self._hists: Dict[Tuple[str, str], FixedHistogram] = {}
        #: tenant -> bounded deque of completed e2e latencies (ms) —
        #: the SLO engine's p99 window.
        self._samples: Dict[str, collections.deque] = {}
        self._sample_window = sample_window
        self._records: collections.deque = collections.deque(
            maxlen=record_cap)
        #: tenant -> bounded {revision: install perf_counter} for the
        #: Server-Timing revision-age header (served revisions may long
        #: outlive their pending entry).
        self._ages: Dict[str, "collections.OrderedDict"] = {}
        self._age_cap = age_cap
        self._tick = 0
        #: tenant -> highest revision already notify-/encode-stamped:
        #: the per-tick `notified()` call skips the (bounded but large)
        #: pending scan when nothing new installed since the last one.
        self._notified_rev: Dict[str, int] = {}
        self._encoded_rev: Dict[str, int] = {}
        self._last_install_tick: Dict[str, int] = {}
        #: tenant -> (tick, revision) of the newest client-confirmed
        #: delivery (a 304 on the current revision counts: the client
        #: HAS it).
        self._last_delivered: Dict[str, Tuple[int, int]] = {}
        #: tenant -> serving epoch of the newest delivery: an epoch
        #: advance (supervisor restart, tenant re-admission) restarts
        #: revision numbering BELOW the old delivered mark, and
        #: without this reset the staleness objective would read
        #: negative — i.e. be blind — until the new epoch's revisions
        #: outgrew the old epoch's mark.
        self._delivered_epoch: Dict[str, int] = {}
        #: Write witness for racewatch (every mutator bumps it under
        #: `_lock`) and the one-glance stamp-volume number.
        self.n_stamps = 0
        self.n_completed = 0
        self.n_evicted = 0

    # -- stamping (mapper tick / tenancy step / HTTP threads) ----------------

    def note_tick(self, tick: int) -> None:
        """The mapper's deterministic step clock — stamps taken off the
        tick thread (deliveries) carry the tick current at that
        moment."""
        with self._lock:
            self._tick = int(tick)
            self.n_stamps += 1

    def installed(self, revision: int, enq_t: Optional[float] = None,
                  tick: Optional[int] = None, tenant: str = "",
                  ingest: bool = True) -> None:
        """Evidence installed + revision bumped. `enq_t` is the OLDEST
        fused scan's enqueue stamp (worst-case freshness); tenancy
        installs have no scan hop and pass None. `ingest=False` marks
        a content mutation that is NOT sensor ingest (a decay pass):
        it stamps the revision's age/waypoints but must not advance
        the SLO engine's ingest-stall clock — a healing pass running
        through a scan-path outage would otherwise mask the very
        silence the `max_silent_ticks` guard exists to catch (caught
        live by the verify drive: the alert flapped mid-partition on
        every decay cadence)."""
        now = time.perf_counter()
        with self._lock:
            self.n_stamps += 1
            t = int(tick) if tick is not None else self._tick
            self._pending[(tenant, int(revision))] = {
                "enq": enq_t, "install": now, "notify": None,
                "encode": None, "tick": t}
            if ingest:
                self._last_install_tick[tenant] = t
            ages = self._ages.setdefault(tenant,
                                         collections.OrderedDict())
            ages[int(revision)] = now
            # Re-inserting an existing key (a restarted epoch replays
            # old revision numbers) updates the value IN PLACE without
            # reordering — move it to the tail explicitly, or
            # `revision_age_ms(None)` (the newest-install read behind
            # /map-image and SSE headers) would keep returning the OLD
            # epoch's max revision with its pre-restart stamp forever,
            # and the LRU eviction below would evict the new epoch's
            # live keys while retaining the stale tail.
            ages.move_to_end(int(revision))
            while len(ages) > self._age_cap:
                ages.popitem(last=False)
            if enq_t is not None:
                self._observe("fuse", tenant, now - enq_t)
            # Bound the pending table: a mission nobody serves must not
            # grow host memory through the ledger watching it.
            while len(self._pending) > self._pending_cap:
                self._pending.pop(next(iter(self._pending)))
                self.n_evicted += 1

    def notified(self, revision: int, tenant: str = "") -> None:
        """Revision fanned out to listeners (mapper tick end) — marks
        every pending revision at or below it. High-water-marked: the
        mapper calls this every tick, and re-scanning the pending
        table when nothing new installed would make the idle-tick cost
        proportional to the table size."""
        now = time.perf_counter()
        with self._lock:
            self.n_stamps += 1
            # Skip ONLY the exact idle repeat (the every-tick call with
            # no new install). An equality check, not <=: a restarted
            # epoch legitimately restarts revision numbering below the
            # old mark and must scan again (already-stamped entries are
            # skipped individually).
            if revision == self._notified_rev.get(tenant):
                return
            self._notified_rev[tenant] = int(revision)
            for (tn, rev), ent in self._pending.items():
                if tn == tenant and rev <= revision \
                        and ent["notify"] is None:
                    ent["notify"] = now
                    self._observe("notify", tenant,
                                  max(0.0, now - ent["install"]))

    def encoded(self, revision: int, tenant: str = "") -> None:
        """Tile store committed a refresh at `revision`: every pending
        revision at or below it is now re-encoded (or superseded by
        newer content — freshness-equivalent either way)."""
        now = time.perf_counter()
        with self._lock:
            self.n_stamps += 1
            if revision == self._encoded_rev.get(tenant):
                return                  # exact idle repeat (see above)
            self._encoded_rev[tenant] = int(revision)
            for (tn, rev), ent in self._pending.items():
                if tn == tenant and rev <= revision \
                        and ent["encode"] is None:
                    ent["encode"] = now
                    base = ent["notify"] if ent["notify"] is not None \
                        else ent["install"]
                    self._observe("encode", tenant,
                                  max(0.0, now - base))

    def delivered(self, revision: int, tenant: str = "",
                  epoch: Optional[int] = None) -> None:
        """A client response confirmed the client holds `revision`
        (body or 304): completes every pending revision at or below it
        — the first delivery is each one's freshness endpoint.
        `epoch` is the serving restart epoch the response was stamped
        with (when the caller knows it): an advance RESETS the
        delivered mark, since the new epoch's smaller revision numbers
        are the freshest content there is. The exact idle repeat (the
        steady 304-poll case: same epoch, same revision, nothing
        pending) returns without scanning the pending table — the
        per-REQUEST path must not pay an O(pending) walk under the
        lock the mapper tick contends for."""
        now = time.perf_counter()
        with self._lock:
            self.n_stamps += 1
            if epoch is not None \
                    and epoch != self._delivered_epoch.get(tenant):
                self._delivered_epoch[tenant] = int(epoch)
                self._last_delivered.pop(tenant, None)
            mark = self._last_delivered.get(tenant)
            if mark is not None and revision == mark[1]:
                return
            done = sorted(k for k in self._pending
                          if k[0] == tenant and k[1] <= revision)
            for key in done:
                ent = self._pending.pop(key)
                base = ent["encode"] or ent["notify"] or ent["install"]
                hops = {"fuse": (None if ent["enq"] is None else
                                 (ent["install"] - ent["enq"]) * 1e3)}
                hops["notify"] = (
                    None if ent["notify"] is None else
                    max(0.0, ent["notify"] - ent["install"]) * 1e3)
                hops["encode"] = (
                    None if ent["encode"] is None else
                    max(0.0, ent["encode"]
                        - (ent["notify"] or ent["install"])) * 1e3)
                hops["deliver"] = max(0.0, now - base) * 1e3
                self._observe("deliver", tenant, max(0.0, now - base))
                start = ent["enq"] if ent["enq"] is not None \
                    else ent["install"]
                total_ms = max(0.0, now - start) * 1e3
                if ent["enq"] is not None:
                    self._observe("scan_to_served", tenant,
                                  max(0.0, now - ent["enq"]))
                    self._samples.setdefault(
                        tenant, collections.deque(
                            maxlen=self._sample_window)
                    ).append(total_ms)
                present = {h: v for h, v in hops.items()
                           if v is not None}
                self.n_completed += 1
                self._records.append({
                    "revision": key[1], "tenant": tenant,
                    "tick": ent["tick"],
                    "hops_ms": {h: round(v, 3)
                                for h, v in present.items()},
                    "total_ms": round(total_ms, 3),
                    "critical": max(present, key=present.get)})
            prev = self._last_delivered.get(tenant)
            if prev is None or revision >= prev[1]:
                self._last_delivered[tenant] = (self._tick,
                                                int(revision))

    def _observe(self, hop: str, tenant: str, dt_s: float) -> None:
        """Caller holds `_lock` (every mutator does; racewatch-gated)."""
        self._hists.setdefault((hop, tenant),
                               FixedHistogram()).observe(dt_s)

    # -- reading (SLO engine / HTTP exports / Server-Timing) -----------------

    def revision_age_ms(self, revision: Optional[int] = None,
                        tenant: str = "") -> Optional[float]:
        """Server-monotonic age of `revision`'s install (None = the
        newest installed revision) in milliseconds — the Server-Timing
        header's `age;dur=` value. None when the revision predates the
        ledger (a restore-resumed revision, a pre-obs epoch): better no
        header than a fabricated age."""
        now = time.perf_counter()
        with self._lock:
            ages = self._ages.get(tenant)
            if not ages:
                return None
            if revision is None:
                return (now - ages[next(reversed(ages))]) * 1e3
            best = None
            for rev, t in ages.items():
                if rev <= revision and (best is None or rev > best[0]):
                    best = (rev, t)
            return None if best is None else (now - best[1]) * 1e3

    def p99_ms(self, tenant: str = "") -> Optional[float]:
        """p99 over the sliding window of completed scan→served
        samples (exact over the bounded window, not bucket-resolved:
        the SLO threshold compare deserves the real value)."""
        with self._lock:
            win = self._samples.get(tenant)
            if not win:
                return None
            xs = sorted(win)
        return xs[max(0, -(-len(xs) * 99 // 100) - 1)]

    def last_install_tick(self, tenant: str = "") -> Optional[int]:
        with self._lock:
            return self._last_install_tick.get(tenant)

    def last_delivered(self, tenant: str = ""
                       ) -> Optional[Tuple[int, int]]:
        """(tick, revision) of the newest client-confirmed delivery."""
        with self._lock:
            return self._last_delivered.get(tenant)

    def histograms(self) -> Dict[Tuple[str, str], dict]:
        """(hop, tenant) -> histogram summary — the /metrics source."""
        with self._lock:
            return {k: h.summary() for k, h in self._hists.items()}

    def records(self, n: Optional[int] = None) -> List[dict]:
        """Completed-revision records, oldest first (bounded ring) —
        the flight-dump `pipeline` section / critical-path input."""
        with self._lock:
            out = [dict(r) for r in self._records]
        return out if n is None else out[-n:]

    def status(self) -> dict:
        """One-glance `/status.pipeline` summary."""
        with self._lock:
            pending = len(self._pending)
            samples = {t: len(w) for t, w in self._samples.items()}
            last_inst = dict(self._last_install_tick)
            last_del = dict(self._last_delivered)
            n_completed, n_evicted = self.n_completed, self.n_evicted
        out = {
            "pending_revisions": pending,
            "completed_revisions": n_completed,
            "evicted_revisions": n_evicted,
            "samples_windowed": samples,
            "last_install_tick": last_inst,
            "last_delivered": {t: {"tick": v[0], "revision": v[1]}
                               for t, v in last_del.items()},
        }
        p99 = self.p99_ms()
        if p99 is not None:
            out["scan_to_served_p99_ms"] = round(p99, 3)
        return out
