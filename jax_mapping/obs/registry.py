"""Declarative Prometheus exposition — the `/metrics` assembly as data.

The HTTP plane's `/metrics` used to be ~200 lines of hand-interleaved
f-strings in `bridge/http_api.py`: every new subsystem appended its own
`lines += [...]` block, ordering and formatting were implicit in code
flow, and nothing could enumerate "what metrics does this server
export". This module replaces that with a registry of declared metric
FAMILIES: each family is `(name, type, collect)` where `collect`
returns the family's samples (or None to omit it this render — the
conditional-subsystem pattern), and multi-family sources share one
consistent snapshot (e.g. everything under the HTTP stats lock).

The render contract is BYTE-compatibility: registration order is
exposition order, values are pre-formatted strings, so the refactored
`/metrics` reproduces the historical document exactly for every family
that existed before it (pinned by tests) — dashboards and scrape
configs survive the refactor untouched. New families (bus subscription
health, stage-latency histograms, obs counters) append after the
historical tail.

Helpers `histogram_samples`/`summary_samples` encode the exposition
shapes the repo uses (cumulative `_bucket{le=}` lines + `_sum`/`_count`;
the `_ms` summary family) so a new histogram cannot get the cumulative
sum wrong in one hand-rolled copy.
"""

from __future__ import annotations

import threading
from typing import Callable, Iterable, List, NamedTuple, Optional, Tuple

#: One sample line: (suffix appended to the family name — labels and/or
#: a `_bucket`/`_sum`/`_count` series suffix — and the pre-formatted
#: value string).
Sample = Tuple[str, str]


class Family(NamedTuple):
    """One `# TYPE` block: header + its sample lines."""

    name: str
    mtype: str                        # counter | gauge | histogram | summary
    samples: Tuple[Sample, ...]


class MetricsRegistry:
    """Ordered registry of metric sources.

    A *source* is a callable returning an iterable of `Family` (or
    None/() to emit nothing) — one source may emit several families
    from one consistent snapshot. `family(...)` is the single-family
    convenience. `render()` walks sources in registration order.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._sources: List[Callable[[], Optional[Iterable[Family]]]] = []

    def add_source(self, fn: Callable[[], Optional[Iterable[Family]]]
                   ) -> "MetricsRegistry":
        with self._lock:
            self._sources.append(fn)
        return self

    def family(self, name: str, mtype: str,
               collect: Callable[[], Optional[Iterable[Sample]]]
               ) -> "MetricsRegistry":
        """Declare one family; `collect` returns its samples, or None
        to omit the whole family (absent subsystem)."""
        def src() -> Optional[Iterable[Family]]:
            samples = collect()
            if samples is None:
                return None
            return (Family(name, mtype, tuple(samples)),)
        return self.add_source(src)

    def render(self) -> str:
        with self._lock:
            sources = list(self._sources)
        lines: List[str] = []
        for src in sources:
            for fam in (src() or ()):
                lines.append(f"# TYPE {fam.name} {fam.mtype}")
                for suffix, value in fam.samples:
                    lines.append(f"{fam.name}{suffix} {value}")
        return "\n".join(lines) + "\n"


def histogram_samples(edges, counts, total, count,
                      le_fmt: Callable[[float], str] = str,
                      sum_fmt: str = "{:.6f}") -> List[Sample]:
    """Cumulative `_bucket{le=}` lines + `+Inf` + `_sum`/`_count` from
    per-bucket counts (`counts` has len(edges)+1 entries, the last the
    overflow bucket)."""
    out: List[Sample] = []
    cum = 0
    for le, n in zip(edges, counts):
        cum += n
        out.append((f'_bucket{{le="{le_fmt(le)}"}}', str(cum)))
    out.append(('_bucket{le="+Inf"}', str(cum + counts[-1])))
    out.append(("_sum", sum_fmt.format(total)))
    out.append(("_count", str(count)))
    return out


def summary_samples(count, total, fmt: str = "{:.3f}") -> List[Sample]:
    """The repo's `_count`/`_sum` summary shape (stage `_ms` families)."""
    return [("_count", str(count)), ("_sum", fmt.format(total))]


def labeled_histogram_samples(labels: str, edges, counts, total, count,
                              le_fmt: Callable[[float], str] = str,
                              sum_fmt: str = "{:.6f}") -> List[Sample]:
    """`histogram_samples` with a fixed label set on every series —
    ONE histogram family sliced by label (the devprof per-function
    dispatch family: `..._bucket{fn="x",le="0.001"}`) instead of a
    family per slice. `labels` is the pre-rendered inner label string
    (e.g. `fn="jax_mapping.ops.grid.fuse_scans_window"`)."""
    out: List[Sample] = []
    cum = 0
    for le, n in zip(edges, counts):
        cum += n
        out.append((f'_bucket{{{labels},le="{le_fmt(le)}"}}', str(cum)))
    out.append((f'_bucket{{{labels},le="+Inf"}}',
                str(cum + counts[-1])))
    out.append((f"_sum{{{labels}}}", sum_fmt.format(total)))
    out.append((f"_count{{{labels}}}", str(count)))
    return out
