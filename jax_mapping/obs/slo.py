"""Freshness SLO engine: declarative objectives, burn-rate alerting.

Takes the `SloObjective` tuple declared in `ObsConfig.slo` and
evaluates it IN-PROCESS once per mapper tick over the pipeline latency
ledger (obs/pipeline.py) and the mapper's revision counter — no scrape
loop, no external alertmanager: the stack that serves the map also
knows, live, whether it is meeting its freshness budget.

Alert policy is the classic multi-window burn rate: each objective
keeps a FAST and a SLOW sliding window of per-tick breach bits; the
alert FIRES when both windows exceed their budget fractions (the fast
window says "it is burning right now", the slow window says "long
enough to matter — not one hiccup") and CLEARS when the fast window
recovers. Everything is clocked in TICKS with FIXED window sizes as
burn denominators, so two same-seed runs — including chaos runs, where
a seeded FaultPlan partition window starves the scan path — fire and
clear at the IDENTICAL step: the chaos-determinism contract extended
to alerting. (Wall-latency breach predicates like `tick_deadline_ms`
are inherently host-speed-dependent; the determinism contract covers
the tick-clocked predicates the chaos drills use.)

Fired/cleared transitions are flight-recorded (`slo_alert` events with
the objective name and tick — the postmortem stream shows WHEN the
budget broke relative to the fault windows around it), exported on
`/status.slo` and the `jax_mapping_slo_*` metric families, and
surfaced in `MissionReport.slo_alerts`.

Constructed only when `ObsConfig.enabled` AND objectives are declared
— absent both, no engine object exists anywhere (bit-exact, the
ObsConfig doctrine). Pure stdlib; no jax import.
"""

from __future__ import annotations

import collections
import threading
from typing import List, Optional, Sequence, Tuple


class _ObjectiveState:
    __slots__ = ("cfg", "fast", "slow", "n_fast", "n_slow", "firing",
                 "value", "n_fired", "n_cleared", "breach_ticks",
                 "last_fire_tick", "last_clear_tick", "silent_ticks")

    def __init__(self, cfg):
        self.cfg = cfg
        self.fast = collections.deque(maxlen=max(1,
                                                 cfg.fast_window_ticks))
        self.slow = collections.deque(maxlen=max(1,
                                                 cfg.slow_window_ticks))
        self.n_fast = 0
        self.n_slow = 0
        self.firing = False
        self.value: Optional[float] = None
        self.silent_ticks: Optional[int] = None
        self.n_fired = 0
        self.n_cleared = 0
        self.breach_ticks = 0
        self.last_fire_tick: Optional[int] = None
        self.last_clear_tick: Optional[int] = None

    def label(self) -> str:
        return self.cfg.name or self.cfg.metric


class SloEngine:
    """Evaluate declared objectives once per tick; fire/clear alerts.

    Thread contract: `evaluate` runs on the mapper tick thread; the
    HTTP plane reads `status()`/`metric_families()` from worker
    threads — all state mutates and reads under ONE `_lock`
    (racewatch-gated, analysis/protection.py).
    """

    def __init__(self, objectives: Sequence, pipeline=None,
                 tenant: str = ""):
        self._lock = threading.Lock()
        self._objs: List[_ObjectiveState] = [
            _ObjectiveState(o) for o in objectives]
        #: The pipeline ledger the freshness predicates read (may be
        #: None: freshness objectives then never breach — nothing to
        #: measure — while tick_deadline_ms still works).
        self.pipeline = pipeline
        self.tenant = tenant
        #: Bounded alert history: (tick, objective label, state).
        self._alerts: collections.deque = collections.deque(maxlen=256)
        self.n_evaluations = 0

    # -- evaluation (mapper tick thread) -------------------------------------

    def evaluate(self, tick: int, tick_ms: Optional[float] = None,
                 map_revision: Optional[int] = None) -> None:
        """One evaluation step. `tick` is the mapper's deterministic
        step clock; `tick_ms` the just-finished tick's wall duration;
        `map_revision` the mapper's current revision counter."""
        transitions: List[Tuple[int, str, str]] = []
        with self._lock:
            self.n_evaluations += 1
            for st in self._objs:
                breach = self._measure(st, tick, tick_ms, map_revision)
                st.breach_ticks += int(breach)
                if len(st.fast) == st.fast.maxlen:
                    st.n_fast -= st.fast[0]
                st.fast.append(int(breach))
                st.n_fast += int(breach)
                if len(st.slow) == st.slow.maxlen:
                    st.n_slow -= st.slow[0]
                st.slow.append(int(breach))
                st.n_slow += int(breach)
                burn_fast = st.n_fast / st.fast.maxlen
                burn_slow = st.n_slow / st.slow.maxlen
                if not st.firing and burn_fast >= st.cfg.fast_burn \
                        and burn_slow >= st.cfg.slow_burn:
                    st.firing = True
                    st.n_fired += 1
                    st.last_fire_tick = tick
                    transitions.append((tick, st.label(), "firing"))
                elif st.firing and burn_fast < st.cfg.fast_burn:
                    st.firing = False
                    st.n_cleared += 1
                    st.last_clear_tick = tick
                    transitions.append((tick, st.label(), "clear"))
            self._alerts.extend(transitions)
        # Flight-record OUTSIDE our lock (the B2 discipline: no foreign
        # code under a lock); fields are deterministic (tick, name,
        # state) so same-seed recorder streams stay diffable to zero.
        if transitions:
            from jax_mapping.obs.recorder import flight_recorder
            for t, name, state in transitions:
                flight_recorder.record("slo_alert", objective=name,
                                       state=state, tick=t)

    def _measure(self, st: _ObjectiveState, tick: int,
                 tick_ms: Optional[float],
                 map_revision: Optional[int]) -> bool:
        """One objective's breach bit for this tick (caller holds
        `_lock`; the ledger has its own)."""
        cfg = st.cfg
        st.silent_ticks = None
        if cfg.metric == "scan_to_served_p99_ms":
            p99 = None if self.pipeline is None \
                else self.pipeline.p99_ms(self.tenant)
            st.value = p99
            breach = p99 is not None and p99 > cfg.threshold
            if cfg.max_silent_ticks > 0 and self.pipeline is not None:
                li = self.pipeline.last_install_tick(self.tenant)
                if li is not None:
                    st.silent_ticks = tick - li
                    if st.silent_ticks > cfg.max_silent_ticks:
                        # Ingest stall: no scan has reached the map for
                        # longer than the budget — the failure mode a
                        # completed-sample p99 is blind to (a partition
                        # produces no samples at all).
                        breach = True
            return breach
        if cfg.metric == "tile_staleness_revs":
            if map_revision is None:
                st.value = None
                return False
            last = None if self.pipeline is None \
                else self.pipeline.last_delivered(self.tenant)
            served_rev = 0 if last is None else last[1]
            st.value = float(map_revision - served_rev)
            return st.value > cfg.threshold
        if cfg.metric == "tick_deadline_ms":
            st.value = tick_ms
            return tick_ms is not None and tick_ms > cfg.threshold
        # Unknown metric: declared config is validated at construction
        # by the config tests; refuse to guess at runtime.
        st.value = None
        return False

    # -- exports (HTTP threads / missions) -----------------------------------

    def alerts(self) -> List[Tuple[int, str, str]]:
        """Bounded (tick, objective, state) transition history."""
        with self._lock:
            return list(self._alerts)

    def firing(self) -> List[str]:
        with self._lock:
            return [st.label() for st in self._objs if st.firing]

    def status(self) -> dict:
        """`/status.slo`: the whole freshness-budget picture."""
        with self._lock:
            objs = []
            for st in self._objs:
                d = {
                    "name": st.label(),
                    "metric": st.cfg.metric,
                    "threshold": st.cfg.threshold,
                    "value": (None if st.value is None
                              else round(st.value, 3)),
                    "burn_fast": round(st.n_fast / st.fast.maxlen, 4),
                    "burn_slow": round(st.n_slow / st.slow.maxlen, 4),
                    "windows_ticks": [st.fast.maxlen, st.slow.maxlen],
                    "firing": st.firing,
                    "n_fired": st.n_fired,
                    "n_cleared": st.n_cleared,
                    "breach_ticks": st.breach_ticks,
                    "last_fire_tick": st.last_fire_tick,
                    "last_clear_tick": st.last_clear_tick,
                }
                if st.silent_ticks is not None:
                    d["silent_ticks"] = st.silent_ticks
                objs.append(d)
            return {"objectives": objs,
                    "n_evaluations": self.n_evaluations,
                    "alerts": list(self._alerts)[-16:]}

    def metric_families(self):
        """`jax_mapping_slo_*` families for the /metrics registry —
        ONE consistent snapshot per render (the tenancy pattern)."""
        from jax_mapping.obs.registry import Family
        with self._lock:
            rows = [(st.label(), st) for st in self._objs]
            fams = [
                Family("jax_mapping_slo_firing", "gauge",
                       tuple((f'{{objective="{n}"}}',
                              str(int(st.firing))) for n, st in rows)),
                Family("jax_mapping_slo_burn_rate_fast", "gauge",
                       tuple((f'{{objective="{n}"}}',
                              f"{st.n_fast / st.fast.maxlen:.4f}")
                             for n, st in rows)),
                Family("jax_mapping_slo_burn_rate_slow", "gauge",
                       tuple((f'{{objective="{n}"}}',
                              f"{st.n_slow / st.slow.maxlen:.4f}")
                             for n, st in rows)),
                Family("jax_mapping_slo_breach_ticks_total", "counter",
                       tuple((f'{{objective="{n}"}}',
                              str(st.breach_ticks)) for n, st in rows)),
                Family("jax_mapping_slo_alerts_fired_total", "counter",
                       tuple((f'{{objective="{n}"}}', str(st.n_fired))
                             for n, st in rows)),
                Family("jax_mapping_slo_alerts_cleared_total",
                       "counter",
                       tuple((f'{{objective="{n}"}}', str(st.n_cleared))
                             for n, st in rows)),
            ]
        return fams
