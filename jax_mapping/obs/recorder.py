"""Flight recorder: an always-on bounded ring of load-bearing events.

Chaos postmortems used to require RE-RUNNING whole missions because the
system's state transitions existed only as counters — `/metrics` could
say a gate failed, never *why*. The recorder keeps the last N
structured events (map-revision advances, restart epochs, FleetHealth
ladder moves, FaultPlan window open/close, decay passes, rendezvous
merge handshakes, checkpoint save/load) in one lock-guarded ring, and
DUMPS them — plus the tracer's recent spans when one is attached — to
the checkpoint directory when something goes wrong: a supervisor
restart, a watchdog divergence declaration, a racewatch report. The
dump is the first artifact to read after a failed chaos gate; two
same-seed runs record identical streams (timestamps and absolute
sequence numbers aside — `obs/diff.py` normalizes those away), so a
trace-diff of two dumps names the first divergent TRANSITION, not just
"the arrays differ".

Always on (unlike tracing, which `ObsConfig.enabled` gates): recording
is one locked deque append per *state transition* — orders of magnitude
off the hot path — and a postmortem that needs a flag flipped
beforehand is not a postmortem. Pure stdlib, no jax import.

`flight_recorder` is the process-wide instance (the `global_metrics`
pattern): io-, resilience- and scenario-layer code records without
plumbing an object through every constructor; `launch_sim_stack` points
it at the stack's checkpoint dir and tracer.
"""

from __future__ import annotations

import collections
import json
import os
import re
import threading
import time
from typing import List, Optional

#: Dump-path history kept on the instance (postmortem linkage, e.g.
#: MissionReport) — bounded so a pathological restart loop cannot grow
#: host memory through the recorder that exists to debug it.
_MAX_DUMP_PATHS = 64

#: Dump FILES kept on disk per dump dir, newest win — the same restart
#: loop must not fill the checkpoint volume either (each dump can be
#: multi-MB of ring + spans; `retain_generations` bounds the sibling
#: checkpoint files, this bounds the postmortems).
_MAX_DUMP_FILES = 32


class FlightRecorder:
    """Bounded structured-event ring + fault-triggered dumps."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        #: Events ever recorded; also each event's monotone `seq` stamp.
        self.n_events = 0
        self.n_dumps = 0
        #: Filename index reservation — distinct from `n_dumps` (count
        #: of dumps that reached disk): reserved under the ring lock
        #: BEFORE the write so two threads dumping concurrently (tick
        #: watchdog vs supervisor restart) never share a flight_NNNN
        #: slot and overwrite each other.
        self._dump_seq = 0
        #: Paths of written dumps, oldest first (bounded).
        self.dumps: List[str] = []
        self._dump_dir: Optional[str] = None
        self._tracer = None
        self._pipeline = None

    # -- wiring (launch layer) ----------------------------------------------

    def configure(self, dump_dir: Optional[str] = None, tracer=None,
                  capacity: Optional[int] = None,
                  pipeline=None) -> None:
        """Point the recorder at a stack's checkpoint dir, tracer and
        pipeline latency ledger (each launch re-configures; the
        recorder itself is process-wide). `dump_dir=None` disables
        file dumps — events still record. A capacity change rebuilds
        the ring, keeping the newest events. An attached ledger's
        completed-revision records ride each dump as its `pipeline`
        section (the critical-path CLI's input; obs/diff.py compares
        only events+spans, so dumps stay same-seed-diffable to zero —
        hop durations are wall time)."""
        with self._lock:
            self._dump_dir = dump_dir
            self._tracer = tracer
            self._pipeline = pipeline
            if capacity is not None and capacity != self._ring.maxlen:
                self._ring = collections.deque(self._ring,
                                               maxlen=capacity)

    # -- recording (any thread) ----------------------------------------------

    def record(self, kind: str, **fields) -> None:
        """Append one structured event. `fields` must be JSON-able and
        DETERMINISTIC (step/tick/revision numbers, names — never wall
        times or absolute paths: the same-seed stream-identity contract
        covers everything but the auto-added `seq`/`wall_ts`)."""
        with self._lock:
            self.n_events += 1
            ev = {"seq": self.n_events, "kind": kind,
                  "wall_ts": time.time()}
            ev.update(fields)
            self._ring.append(ev)

    # -- reading --------------------------------------------------------------

    def mark(self) -> int:
        """Current event count — pass to `events_since` to scope a run
        (the process-wide recorder outlives any one stack)."""
        with self._lock:
            return self.n_events

    def events_since(self, mark: int = 0) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._ring if e["seq"] > mark]

    def stats(self) -> dict:
        with self._lock:
            return {"n_events": self.n_events, "n_dumps": self.n_dumps,
                    "ring_len": len(self._ring)}

    # -- postmortem dumps ------------------------------------------------------

    def dump(self, reason: str) -> Optional[str]:
        """Write the ring (and the attached tracer's recent spans) to
        `<dump_dir>/flight_<n>_<reason>.json`; returns the path, or
        None when no dump dir is configured. Never raises — a failing
        postmortem write must not take down the recovery path that
        triggered it."""
        snap = self._snapshot(reason)
        if snap is None:
            return None
        return self._write(*snap)

    def dump_async(self, reason: str) -> Optional[str]:
        """`dump` with the disk work off the caller's thread: the ring
        and span SNAPSHOT happens now (same-seed stream identity needs
        the content pinned at the trigger, not at whenever a writer
        thread gets scheduled), the json+file I/O runs on a one-shot
        thread. For dump sites on a control period — the mapper tick
        watchdog must not stall every robot's fusion behind a multi-MB
        write at exactly the moment an estimator is struggling.
        Returns the path the dump WILL land at (None: no dump dir)."""
        snap = self._snapshot(reason)
        if snap is None:
            return None
        payload, path = snap
        threading.Thread(target=self._write, args=(payload, path),
                         name="flight-recorder-dump", daemon=True).start()
        return path

    def _snapshot(self, reason: str):
        """Capture (payload, path) at the trigger and record the
        `postmortem_dump` transition — recorded HERE, not after the
        write, so the event stream is identical whether the disk
        cooperates or not (and regardless of writer-thread timing)."""
        with self._lock:
            dump_dir = self._dump_dir
            tracer = self._tracer
            pipeline = self._pipeline
            events = [dict(e) for e in self._ring]
            if dump_dir is not None:
                n = self._dump_seq
                self._dump_seq += 1
        if dump_dir is None:
            return None
        spans = tracer.spans_since(0) if tracer is not None else []
        records = pipeline.records() if pipeline is not None else []
        safe = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:80]
        path = os.path.join(dump_dir, f"flight_{n:04d}_{safe}.json")
        # The dump is itself a load-bearing transition (path kept to a
        # basename: absolute tmp dirs would break stream identity; the
        # diff tool additionally ignores `path`).
        self.record("postmortem_dump", reason=reason,
                    path=os.path.basename(path))
        payload = {"reason": reason, "wall_time": time.time(),
                   "events": events, "spans": spans,
                   "pipeline": records}
        return payload, path

    def _write(self, payload: dict, path: str) -> Optional[str]:
        dump_dir = os.path.dirname(path)
        try:
            os.makedirs(dump_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f)
        except (OSError, TypeError, ValueError):
            # TypeError/ValueError: a record() call site slipped a
            # non-JSON field (e.g. a numpy scalar) past review — the
            # "never raises" contract outranks losing that dump.
            return None
        with self._lock:
            self.n_dumps += 1
            self.dumps.append(path)
            del self.dumps[:-_MAX_DUMP_PATHS]
        self._gc_dump_files(dump_dir)
        return path

    @staticmethod
    def _gc_dump_files(dump_dir: str) -> None:
        """Keep the newest `_MAX_DUMP_FILES` flight_*.json on disk."""
        try:
            names = [f for f in os.listdir(dump_dir)
                     if f.startswith("flight_") and f.endswith(".json")]
            if len(names) <= _MAX_DUMP_FILES:
                return
            full = [os.path.join(dump_dir, f) for f in names]
            full.sort(key=lambda p: (os.path.getmtime(p), p))
            for p in full[:-_MAX_DUMP_FILES]:
                os.remove(p)
        except OSError:
            pass


#: The process-wide recorder (the `global_metrics` pattern).
flight_recorder = FlightRecorder()
