"""Causal tracing with DETERMINISTIC ids — the FaultPlan contract
extended to telemetry.

A `TraceContext` is the (trace_id, span_id, parent_span) triple carried
across the system's async boundaries: `Bus` publish/delivery (the
context rides a parallel queue next to each subscription's mailbox),
mapper tick, and HTTP handlers. Ids are NOT random: a root context
created at a bus publish derives its trace id from `(seed, topic, seq)`
— the launch seed, the topic string, and that topic's monotone publish
count — and every child span id hashes down from its parent. Two
same-seed deterministic runs (`Stack.run_steps`) therefore emit
IDENTICAL trace streams, which is what makes `obs/diff.py` able to
answer "*where* did two supposedly-bit-identical runs diverge" instead
of only "they differ".

Spans land in one bounded, lock-guarded ring (the flight-recorder
discipline: never block the hot path, never grow without bound) and
export as Chrome-trace/Perfetto JSON via `obs/export.py`, `GET
/trace?since=` and `python -m jax_mapping.obs`.

Everything here is host-side stdlib — no jax import, nothing on the
device path, so `ObsConfig(enabled=False)` (no Tracer constructed) is
bit-exact pre-obs behavior and `enabled=True` may not perturb a single
array (the obs bit-inertness property test pins both).
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import threading
import time
from typing import Dict, Iterator, List, NamedTuple, Optional, Tuple


class TraceContext(NamedTuple):
    """One hop of a causal chain. `parent_span == 0` marks a root."""

    trace_id: int
    span_id: int
    parent_span: int = 0


def h64(*parts) -> int:
    """Deterministic 64-bit id from the parts' string forms (blake2b —
    stable across processes and runs, unlike `hash()` under
    PYTHONHASHSEED). Never returns 0: 0 is the 'no parent' sentinel."""
    h = hashlib.blake2b(digest_size=8)
    for p in parts:
        h.update(str(p).encode())
        h.update(b"\x00")
    return int.from_bytes(h.digest(), "big") or 1


class Tracer:
    """Deterministic span factory + bounded span ring.

    Thread contract: the current context is THREAD-LOCAL (`use`/`span`
    set it around callback delivery and handler bodies); the ring, the
    span counter and the per-scope sequence table mutate only under
    `_lock` (racewatch-gated — see analysis/protection.py). Sequence
    numbers are per (kind, scope) so bus traffic on one topic can never
    perturb another topic's ids, and HTTP-created roots (live polls are
    inherently nondeterministic) never touch the topic scopes the
    deterministic-stream contract covers.
    """

    def __init__(self, seed: int = 0, capacity: int = 65536):
        self.seed = seed
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        #: Spans ever recorded (also the per-span monotone `seq` stamp
        #: `/trace?since=` filters on). Guarded by `_lock` like the ring.
        self.n_spans = 0
        self._seq: Dict[Tuple[str, str], int] = {}
        self._t0 = time.perf_counter()

    # -- current-context plumbing (thread-local) -----------------------------

    def current(self) -> Optional[TraceContext]:
        return getattr(self._tls, "ctx", None)

    @contextlib.contextmanager
    def use(self, ctx: Optional[TraceContext]) -> Iterator[None]:
        """Make `ctx` the thread's current context for a block (the bus
        sets the publish context around callback delivery, so a
        subscriber callback reads its causal parent via `current()`)."""
        prev = getattr(self._tls, "ctx", None)
        self._tls.ctx = ctx
        try:
            yield
        finally:
            self._tls.ctx = prev

    # -- id derivation -------------------------------------------------------

    def _next_seq(self, kind: str, scope: str) -> int:
        with self._lock:
            key = (kind, scope)
            self._seq[key] = self._seq.get(key, 0) + 1
            return self._seq[key]

    def derive(self, parent: Optional[TraceContext], name: str,
               key=0) -> TraceContext:
        """Child of `parent`, or a fresh deterministic root when there
        is no parent. `key` disambiguates same-name siblings (the
        mapper passes (robot, scan stamp))."""
        if parent is None:
            seq = self._next_seq("root", name)
            trace_id = h64("trace", self.seed, name, seq)
            return TraceContext(trace_id, h64("span", trace_id, key), 0)
        return TraceContext(
            parent.trace_id,
            h64("span", parent.trace_id, parent.span_id, name, key),
            parent.span_id)

    # -- the bus boundary ----------------------------------------------------

    def on_publish(self, topic: str) -> TraceContext:
        """Derive the context one bus publish carries. No ambient
        context (a sensor/timer origin) starts a ROOT whose trace id is
        `h64("trace", seed, topic, seq)` — the deterministic-stream
        anchor; a publish inside a traced callback chains as a child."""
        parent = self.current()
        seq = self._next_seq("topic", topic)
        if parent is None:
            trace_id = h64("trace", self.seed, topic, seq)
            ctx = TraceContext(trace_id, h64("span", trace_id), 0)
        else:
            ctx = TraceContext(
                parent.trace_id,
                h64("span", parent.trace_id, parent.span_id, topic, seq),
                parent.span_id)
        self._record(f"publish:{topic}", ctx, 0.0)
        return ctx

    # -- span emission -------------------------------------------------------

    def emit(self, name: str, parent: Optional[TraceContext] = None,
             key=0) -> TraceContext:
        """Record one instant span (e.g. `mapper.fuse` per fused scan).
        Explicit `parent` beats the ambient context; both absent makes
        a root."""
        ctx = self.derive(parent if parent is not None else self.current(),
                          name, key)
        self._record(name, ctx, 0.0)
        return ctx

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[TraceContext] = None,
             key=0) -> Iterator[TraceContext]:
        """Timed span that is also the block's current context, so
        publishes inside chain under it (mapper tick, HTTP handler)."""
        ctx = self.derive(parent if parent is not None else self.current(),
                          name, key)
        t0 = time.perf_counter()
        with self.use(ctx):
            try:
                yield ctx
            finally:
                self._record(name, ctx, time.perf_counter() - t0, t0=t0)

    def _record(self, name: str, ctx: TraceContext, dur_s: float,
                t0: Optional[float] = None) -> None:
        start = t0 if t0 is not None else time.perf_counter()
        with self._lock:
            self.n_spans += 1
            self._spans.append({
                "seq": self.n_spans,
                "name": name,
                "trace_id": ctx.trace_id,
                "span_id": ctx.span_id,
                "parent_span": ctx.parent_span,
                # Wall-ish fields for Perfetto; the diff tool drops them
                # (they are the one nondeterministic part of a span).
                "ts_us": (start - self._t0) * 1e6,
                "dur_us": dur_s * 1e6,
                "tid": threading.get_ident() & 0xFFFF,
            })

    # -- export --------------------------------------------------------------

    def spans_since(self, seq: int = 0) -> List[dict]:
        """Spans with `seq` stamps strictly greater than `seq`, oldest
        first (the `/trace?since=` contract); copies, never live ring
        entries. Seq stamps are append-ordered, so the tail is found by
        walking from the newest end — an incremental `/trace` poll
        holds the emission lock (shared with every hot-path span
        record) for O(new spans), not a full 64k-ring scan."""
        refs: List[dict] = []
        with self._lock:
            # References only under the lock (span dicts are immutable
            # once emplaced by _record) — the dict copies of a full-ring
            # read (a postmortem dump) happen outside, off the lock
            # every hot-path span emit contends on.
            for s in reversed(self._spans):
                if s["seq"] <= seq:
                    break
                refs.append(s)
        refs.reverse()
        return [dict(s) for s in refs]

    def last_seq(self) -> int:
        with self._lock:
            return self.n_spans

    def stats(self) -> dict:
        with self._lock:
            return {"n_spans": self.n_spans, "ring_len": len(self._spans)}
