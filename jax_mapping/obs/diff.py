"""Trace-diff: turn "two runs must be bit-identical" into a WHERE.

Every determinism gate in this repo (FaultPlan chaos soaks, the
scenario engine, the obs stream-identity contract) ends in a bare
array/stream compare: it can say two same-seed runs diverged, never
where. This module compares two event/span streams after NORMALIZING
away the fields that are legitimately nondeterministic (wall
timestamps, durations, thread ids, absolute sequence stamps, dump
paths) and reports the FIRST divergence point — the index, both sides'
events, and a unified summary — so a failed gate hands the operator
the first transition that differed instead of a 4096^2 grid diff.

Works on flight-recorder event streams and tracer span streams alike
(both are lists of flat dicts); `python -m jax_mapping.obs diff a b`
wraps it for dump files. Pure stdlib, no jax import.
"""

from __future__ import annotations

from typing import Iterable, List, NamedTuple, Optional, Sequence, Tuple

#: Fields that may differ between two same-seed runs by design: wall
#: clocks, host timing, thread identity, the process-lifetime absolute
#: counters, and dump file names (numbered per process).
VOLATILE_FIELDS = ("seq", "wall_ts", "ts_us", "dur_us", "tid", "path")


def normalize_events(events: Iterable[dict],
                     ignore: Sequence[str] = VOLATILE_FIELDS
                     ) -> List[Tuple]:
    """Each event reduced to a sorted (key, value) tuple with the
    volatile fields dropped — the comparable causal content."""
    out = []
    for e in events:
        out.append(tuple(sorted((k, v) for k, v in e.items()
                                if k not in ignore)))
    return out


class Divergence(NamedTuple):
    """First point two streams disagree. `index` is the position in the
    normalized streams; a side is None when that stream simply ended
    (length mismatch)."""

    index: int
    a: Optional[dict]
    b: Optional[dict]

    def describe(self) -> str:
        def fmt(side, e):
            if e is None:
                return f"  {side}: <stream ended>"
            return f"  {side}: " + ", ".join(
                f"{k}={v!r}" for k, v in sorted(e.items())
                if k not in VOLATILE_FIELDS)
        return (f"first divergence at event #{self.index}:\n"
                + fmt("A", self.a) + "\n" + fmt("B", self.b))


def diff_streams(a: Sequence[dict], b: Sequence[dict],
                 ignore: Sequence[str] = VOLATILE_FIELDS
                 ) -> Optional[Divergence]:
    """None when the normalized streams are identical, else the first
    divergence point with the ORIGINAL (un-normalized) events attached
    so the report keeps timestamps for human context."""
    na, nb = normalize_events(a, ignore), normalize_events(b, ignore)
    for i, (ea, eb) in enumerate(zip(na, nb)):
        if ea != eb:
            return Divergence(i, dict(a[i]), dict(b[i]))
    if len(na) != len(nb):
        i = min(len(na), len(nb))
        return Divergence(i,
                          dict(a[i]) if i < len(a) else None,
                          dict(b[i]) if i < len(b) else None)
    return None


def diff_dumps(dump_a: dict, dump_b: dict) -> dict:
    """Compare two flight-recorder dump documents (events AND spans);
    returns {"events": Divergence|None, "spans": Divergence|None,
    "identical": bool} — the postmortem workflow's one-call answer."""
    ev = diff_streams(dump_a.get("events", ()), dump_b.get("events", ()))
    sp = diff_streams(dump_a.get("spans", ()), dump_b.get("spans", ()))
    return {"events": ev, "spans": sp,
            "identical": ev is None and sp is None}
