"""Degraded-mode state machine: per-robot + per-link health for the fleet.

The reference's failure handling is per-module heroics (driver retries in
`main.py:198-200`, nothing else — SURVEY.md §5 "Failure detection /
recovery"); there is no shared notion of "robot 2's lidar is silent" that
the brain, mapper, planner, and HTTP plane could all act on. `FleetHealth`
is that shared notion: a small, lock-guarded registry the nodes FEED
(brain notes scans and the driver link, mapper notes fusion trouble) and
READ (brain coasts a NO_LIDAR robot, mapper/planner reassign a DEAD
robot's frontiers, the HTTP plane exports it all on /status and /metrics).

Time base: CONTROL TICKS, not wall clock (the repo's TTL doctrine,
brain._steer_target): faster-than-realtime runs must walk the identical
degrade -> dead -> rejoin ladder a realtime mission would, or chaos tests
become host-speed-dependent.

Per-robot ladder:

    OK ──(lidar_silent_ticks without a scan)──▶ NO_LIDAR (coast: hold
      position on odometry, stop expecting fusion, LED orange)
    NO_LIDAR ──(dead_after_ticks without a scan)──▶ DEAD (fleet
      reassigns its frontier work; planner stops planning for it)
    any ──(a scan arrives)──▶ OK (rejoin: the mapper relocalizes by
      matching the robot's next scans against the shared map)
    OK ──(recovery watchdog declares the estimator diverged)──▶
      ESTIMATOR_DIVERGED (scans flow but the estimate is garbage: the
      mapper quarantines the robot's evidence and relocalizes it; the
      brain coasts it; cleared only by a verified re-anchor —
      recovery/watchdog.py). Staleness outranks this rung.

The driver link is fleet-wide (one dongle): OK / OFFLINE / RECOVERING,
fed by the brain's connect machinery; RECOVERING is the one-tick
safe-stop window after a reconnect (motors zeroed, LED red) that keeps
stale pre-fault wheel targets from replaying.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from jax_mapping.config import ResilienceConfig

#: Per-robot states.
OK = "ok"
NO_LIDAR = "no_lidar"
DEAD = "dead"
#: Estimator-health rung (recovery/watchdog.py): scans are FLOWING but
#: the SLAM estimate is garbage — the mapper quarantines this robot's
#: evidence and relocalizes it; the brain coasts it (like NO_LIDAR: the
#: pose it would steer by is exactly what diverged). Staleness outranks
#: it: a diverged robot whose lidar then goes silent walks the normal
#: NO_LIDAR -> DEAD ladder (silence is the more severe fact).
ESTIMATOR_DIVERGED = "estimator_diverged"

#: Driver-link states.
DRIVER_OK = "ok"
DRIVER_OFFLINE = "offline"
DRIVER_RECOVERING = "recovering"


class LockTimeout(RuntimeError):
    """A bounded lock acquisition expired — the HTTP plane's signal to
    answer 503 degraded instead of hanging a worker thread behind a
    wedged node (http_api's bounded-wait contract)."""


class FleetHealth:
    """Thread-safe health registry; a LEAF in the lock order (its methods
    never call out while holding `_lock`, so no node lock ever nests
    inside it — the B1 checker's invariant by construction)."""

    def __init__(self, cfg: ResilienceConfig, n_robots: int):
        self.cfg = cfg
        self.n_robots = n_robots
        self._lock = threading.Lock()
        #: Last control tick a scan arrived, per robot. Boot counts as
        #: tick 0 "activity" so a robot gets lidar_silent_ticks of grace
        #: before its first scan instead of booting degraded.
        self._last_scan_tick = [0] * n_robots
        self._tick = 0
        self._driver = DRIVER_OK
        #: Per-robot current state (recomputed on note_tick) + the
        #: transition log chaos tests assert against:
        #: (tick, "robot<i>"|"driver", old, new).
        self._robot_state = [OK] * n_robots
        #: Estimator-diverged flags (recovery watchdog feeder). A set
        #: flag folds into the ladder on note_tick; it never overrides
        #: staleness (DEAD/NO_LIDAR are the more severe facts).
        self._estimator_diverged = [False] * n_robots
        self.transitions: List[tuple] = []

    # -- feeders (brain/mapper threads) -------------------------------------

    def note_scan(self, robot: int, tick: int) -> None:
        with self._lock:
            self._last_scan_tick[robot] = max(
                self._last_scan_tick[robot], tick)

    def note_tick(self, tick: int) -> None:
        """Advance the health clock (brain.update_loop, once per control
        tick) and fold any staleness into the per-robot states. Ladder
        moves also land in the flight recorder — recorded AFTER the
        lock releases (leaf-lock discipline: no foreign code under
        `_lock`, the B2 doctrine applied to our own leaf)."""
        moved = []
        with self._lock:
            self._tick = max(self._tick, tick)
            for i in range(self.n_robots):
                silent = self._tick - self._last_scan_tick[i]
                if silent > self.cfg.dead_after_ticks:
                    new = DEAD
                elif silent > self.cfg.lidar_silent_ticks:
                    new = NO_LIDAR
                elif self._estimator_diverged[i]:
                    new = ESTIMATOR_DIVERGED
                else:
                    new = OK
                old = self._robot_state[i]
                if new != old:
                    self._robot_state[i] = new
                    self.transitions.append(
                        (self._tick, f"robot{i}", old, new))
                    moved.append((self._tick, f"robot{i}", old, new))
        if moved:
            from jax_mapping.obs.recorder import flight_recorder
            for t, name, old, new in moved:
                flight_recorder.record("health", name=name, old=old,
                                       new=new, tick=t)

    def note_estimator(self, robot: int, diverged: bool) -> None:
        """Recovery-watchdog feeder: flag (or clear) robot `robot`'s
        estimator as diverged. Folds into the ladder on the next
        note_tick (the control-tick clock, like every transition)."""
        with self._lock:
            self._estimator_diverged[robot] = diverged

    def absorb(self, other: "FleetHealth") -> None:
        """Rendezvous merge (scenarios/rendezvous.py): fold another
        fleet's registry into this one — joined robot i becomes robot
        `n_robots + i`, entering at its current ladder state with fresh
        scan grace on THIS fleet's clock (its old fleet's tick base is
        meaningless here). Reads `other` through its public snapshot
        BEFORE taking our lock — FleetHealth is a leaf; two leaf locks
        must never nest."""
        states = other.robot_states()
        snap = other.snapshot()
        with self._lock:
            base = self.n_robots
            self.n_robots += len(states)
            self._last_scan_tick += [self._tick] * len(states)
            self._robot_state += states
            self._estimator_diverged += list(snap["estimator_diverged"])
            for i, s in enumerate(states):
                self.transitions.append(
                    (self._tick, f"robot{base + i}", "absorbed", s))

    def note_driver(self, state: str) -> None:
        assert state in (DRIVER_OK, DRIVER_OFFLINE, DRIVER_RECOVERING)
        moved = None
        with self._lock:
            if state != self._driver:
                moved = (self._tick, "driver", self._driver, state)
                self.transitions.append(moved)
                self._driver = state
        if moved is not None:
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("health", name="driver",
                                   old=moved[2], new=moved[3],
                                   tick=moved[0])

    # -- readers (any thread) ------------------------------------------------

    @property
    def driver(self) -> str:
        with self._lock:
            return self._driver

    def robot_states(self) -> List[str]:
        with self._lock:
            return list(self._robot_state)

    def alive_mask(self) -> np.ndarray:
        """(R,) bool: robots not declared DEAD — the mask the frontier
        auction and the planner honor."""
        with self._lock:
            return np.array([s != DEAD for s in self._robot_state])

    def lidar_ok_mask(self) -> np.ndarray:
        """(R,) bool: robots whose lidar is fresh — the others coast
        (no commanded motion; odometry keeps integrating)."""
        with self._lock:
            return np.array([s == OK for s in self._robot_state])

    def assignable_mask(self) -> np.ndarray:
        """(R,) bool: robots the frontier auction may leave assignments
        with. DEAD robots cannot map; ESTIMATOR_DIVERGED robots coast
        while the mapper relocalizes them, so a frontier pinned to one
        would stall until the re-anchor — hand it to a healthy robot
        instead (mapper._reassign_dead's mask)."""
        with self._lock:
            return np.array([s not in (DEAD, ESTIMATOR_DIVERGED)
                             for s in self._robot_state])

    def diverged_mask(self) -> np.ndarray:
        """(R,) bool: robots currently on the ESTIMATOR_DIVERGED rung
        (the brain's LED + coast annotations)."""
        with self._lock:
            return np.array([s == ESTIMATOR_DIVERGED
                             for s in self._robot_state])

    def snapshot(self) -> dict:
        """The /status export: one dict an operator (or a test) reads
        the whole degraded-mode picture from."""
        with self._lock:
            return {
                "driver": self._driver,
                "robots": list(self._robot_state),
                "tick": self._tick,
                "last_scan_tick": list(self._last_scan_tick),
                "estimator_diverged": list(self._estimator_diverged),
                "n_transitions": len(self.transitions),
            }

    def transitions_for(self, name: str) -> List[tuple]:
        """The (tick, old, new) ladder one component walked — direct
        assertion surface for degraded-mode tests."""
        with self._lock:
            return [(t, a, b) for t, n, a, b in self.transitions
                    if n == name]


def acquire_bounded(lock, timeout_s: Optional[float], what: str) -> None:
    """Acquire `lock`, raising LockTimeout after `timeout_s` (None =
    block forever — the in-process callers' behavior). ONE bounded-wait
    implementation for every handler the HTTP plane must not hang in."""
    if timeout_s is None:
        lock.acquire()
        return
    if not lock.acquire(timeout=timeout_s):
        raise LockTimeout(
            f"{what} lock not acquired within {timeout_s}s — node "
            "wedged or under heavy load")
