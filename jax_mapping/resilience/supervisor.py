"""Supervisor: heartbeat monitoring + restart-with-backoff for the stack.

The reference's node graph has no supervisor — a crashed slam_toolbox
takes the map with it and a human restarts the launch file from scratch
(SURVEY.md §5: "the map is lost on any restart"). This node watches the
`/heartbeat` topic every framework node beats on, declares a node dead
after `ResilienceConfig.supervisor_missed_beats` supervisor ticks without
a beat, and applies a restart policy with exponential backoff and SEEDED
jitter (deterministic across same-seed runs; a fleet of supervisors never
restarts in lockstep).

Restart is delegated: the launch layer registers a restarter callable per
node name (e.g. `Stack.restart_mapper`, which rebuilds the MapperNode and
resumes it from the latest auto-checkpoint with pose re-anchoring —
`io.checkpoint.load_checkpoint_with_fallback` degrades to the rotated
last-good file when the newest checkpoint is corrupt). The supervisor
also owns the auto-checkpoint cadence: it invokes a registered
checkpointer every `checkpoint_every_steps` ticks, so there IS a recent
generation to resume from when the crash comes.

Time base: supervisor TICKS (one per `Stack.run_steps` step in
deterministic mode, one per timer period in realtime mode) — the repo's
deterministic-time doctrine; wall-clock supervision would make chaos
tests host-speed-dependent.

Threading: a Node like any other — the heartbeat subscription and the
timer callback are serialized by `Node._cb_lock`, and `tick()` plus the
export readers (`status`, `is_alive`, ...) take the same re-entrant
lock themselves, so HTTP worker threads polling /status never iterate
`_restart_due` mid-mutation (deterministic `run_steps` calls `tick()`
directly, outside the timer guard). No second lock exists, so the
supervisor cannot deadlock against node locks (B1 by construction);
restarters invoked from `tick()` may take node/bus locks freely —
nothing acquires the supervisor's lock while holding those.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Dict, List, Optional

from jax_mapping.bridge.bus import Bus
from jax_mapping.bridge.node import Node
from jax_mapping.config import ResilienceConfig


class Supervisor(Node):
    """Watches heartbeats; schedules and executes restarts."""

    def __init__(self, cfg: ResilienceConfig, bus: Bus, seed: int = 0,
                 tick_period_s: float = 0.1):
        super().__init__("supervisor", bus)
        self.cfg = cfg
        self._rng = random.Random(seed)
        self.n_ticks = 0
        #: name -> (last seq, supervisor tick the beat arrived). A fresh
        #: registration/restart seeds a grace entry at the current tick.
        self._beats: Dict[str, tuple] = {}
        self._restarters: Dict[str, Optional[Callable[[], None]]] = {}
        #: name -> tick the restart attempt is due (node currently dead).
        self._restart_due: Dict[str, int] = {}
        self._n_restarts: Dict[str, int] = {}
        #: Event log chaos tests assert against:
        #: (tick, name, "dead"|"restart"|"restart_failed", detail).
        self.events: List[tuple] = []
        #: Every scheduled backoff: (name, attempt#, backoff_ticks) —
        #: the exponential-growth assertion surface.
        self.backoff_log: List[tuple] = []
        self._checkpointer: Optional[Callable[[], None]] = None
        self.n_checkpoints = 0
        self.n_checkpoint_errors = 0
        self.create_subscription("/heartbeat", self._hb_cb)
        self.create_timer(tick_period_s, self.tick)

    # -- wiring (launch layer) ----------------------------------------------

    def register(self, name: str,
                 restarter: Optional[Callable[[], None]] = None) -> None:
        """Watch node `name`; with a restarter, dead nodes are restarted
        (without one, death is only declared and exported)."""
        self._restarters[name] = restarter
        self._beats[name] = (-1, self.n_ticks)          # boot grace

    def attach_checkpointer(self, fn: Callable[[], None]) -> None:
        """The auto-checkpoint hook (launch wires `Stack`'s saver)."""
        self._checkpointer = fn

    # -- heartbeat ingestion -------------------------------------------------

    def _hb_cb(self, msg) -> None:
        self._beats[msg.node] = (int(msg.seq), self.n_ticks)

    def backoff_ticks(self, attempt: int) -> int:
        """Restart delay for the attempt-th consecutive restart:
        base * 2^attempt capped at max, times seeded jitter in
        [1, 1+jitter). Deterministic for a given seed and call
        sequence."""
        raw = min(self.cfg.restart_backoff_base_steps * (2 ** attempt),
                  self.cfg.restart_backoff_max_steps)
        return max(1, int(round(
            raw * (1.0 + self.cfg.restart_backoff_jitter
                   * self._rng.random()))))

    # -- the supervision loop ------------------------------------------------

    def tick(self) -> None:
        # Serialized with the heartbeat subscription AND the export
        # readers via the node's re-entrant _cb_lock (the timer path
        # already holds it; deterministic run_steps calls arrive bare).
        with self._cb_lock:
            self._tick_locked()

    def _tick_locked(self) -> None:
        self.n_ticks += 1
        now = self.n_ticks
        if self._checkpointer is not None \
                and self.cfg.checkpoint_every_steps > 0 \
                and now % self.cfg.checkpoint_every_steps == 0:
            try:
                self._checkpointer()
                self.n_checkpoints += 1
            except Exception as e:               # noqa: BLE001
                # A failing auto-save must not take down supervision —
                # the previous generation is still on disk.
                self.n_checkpoint_errors += 1
                self.events.append((now, "checkpoint", "error", str(e)))
        for name in list(self._restarters):
            if name in self._restart_due:
                self._attempt_restart(name, now)
                continue
            _seq, at = self._beats.get(name, (-1, 0))
            if now - at > self.cfg.supervisor_missed_beats:
                self._declare_dead(name, now)

    def _declare_dead(self, name: str, now: int) -> None:
        attempt = self._n_restarts.get(name, 0)
        delay = self.backoff_ticks(attempt)
        self.backoff_log.append((name, attempt, delay))
        self._restart_due[name] = now + delay
        self.events.append((now, name, "dead",
                            f"restart due in {delay} ticks"))
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("supervisor_dead", node=name, tick=now,
                               restart_in=delay)

    def _attempt_restart(self, name: str, now: int) -> None:
        # Beats resumed while the restart was pending (transient stall,
        # external recovery): cancel it — destroying a LIVE node would
        # throw away everything since the last checkpoint to cure a
        # hiccup that already healed.
        _seq, at = self._beats.get(name, (-1, 0))
        if now - at <= self.cfg.supervisor_missed_beats:
            del self._restart_due[name]
            self.events.append((now, name, "recovered",
                                "beats resumed before restart"))
            return
        if now < self._restart_due[name]:
            return
        restarter = self._restarters.get(name)
        if restarter is None:
            # Unrestartable node: stay declared dead (exported on
            # /status) until beats resume (handled above).
            return
        self._n_restarts[name] = self._n_restarts.get(name, 0) + 1
        try:
            restarter()
        except Exception as e:                   # noqa: BLE001
            attempt = self._n_restarts[name]
            delay = self.backoff_ticks(attempt)
            self.backoff_log.append((name, attempt, delay))
            self._restart_due[name] = now + delay
            self.events.append((now, name, "restart_failed",
                                f"{e}; retry in {delay} ticks"))
            return
        del self._restart_due[name]
        self._beats[name] = (-1, now)            # fresh grace window
        self.events.append((now, name, "restart",
                            f"attempt {self._n_restarts[name]}"))
        # Postmortem hook (ISSUE 9): the restart IS the fault-recovery
        # moment — dump the flight recorder (ring still holds the
        # transitions that led here) to the checkpoint dir.
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("supervisor_restart", node=name,
                               tick=now,
                               attempt=self._n_restarts[name])
        flight_recorder.dump(f"supervisor_restart_{name}")

    # -- export ---------------------------------------------------------------

    def dead_nodes(self) -> List[str]:
        with self._cb_lock:
            return sorted(self._restart_due)

    def is_alive(self, name: str) -> bool:
        with self._cb_lock:
            return name not in self._restart_due

    def n_restarts(self, name: str) -> int:
        with self._cb_lock:
            return self._n_restarts.get(name, 0)

    def status(self) -> dict:
        """The /status export (and the soak test's assertion surface)."""
        with self._cb_lock:
            return {
                "watched": sorted(self._restarters),
                "dead": sorted(self._restart_due),
                "ticks": self.n_ticks,
                "restarts": dict(self._n_restarts),
                "checkpoints": self.n_checkpoints,
                "checkpoint_errors": self.n_checkpoint_errors,
                "n_events": len(self.events),
            }

    def heartbeat_ages(self) -> Dict[str, int]:
        """Supervisor ticks since each watched node last beat."""
        with self._cb_lock:
            return {name: self.n_ticks - self._beats.get(name, (-1, 0))[1]
                    for name in self._restarters}


def beat(pub, node_name: str, seq: int, payload: Optional[dict] = None
         ) -> None:
    """Publish one heartbeat. Shared by every beating node so the
    payload shape can never drift between them."""
    from jax_mapping.bridge.messages import Header, Heartbeat
    pub.publish(Heartbeat(header=Header(stamp=time.monotonic()),
                          node=node_name, seq=seq,
                          payload=payload or {}))


class Heartbeater:
    """One per beating node: owns the `/heartbeat` publisher and the
    monotone seq counter, so every node beats through the identical
    plumbing instead of re-implementing pub + counter in its loop."""

    def __init__(self, node: Node):
        self._pub = node.create_publisher("/heartbeat")
        self._name = node.name
        self.seq = 0

    def beat(self, payload: Optional[dict] = None) -> None:
        self.seq += 1
        beat(self._pub, self._name, self.seq, payload)
