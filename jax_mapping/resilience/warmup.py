"""Availability-aware staged warm-up: restore → pre-warm → re-admit.

PR 2's supervisor restart was restore-and-go: the resumed mapper
re-entered service immediately and paid its XLA compilation lazily,
scan by scan — the PR 10 cost ledger shows a restarted process spends
its first minutes compiling, not mapping. This module makes the
restart a STAGED path:

1. **restoring** — the checkpoint loads (with the PR 2/8 generation
   fallback ladder);
2. **warming** — the jitted entry points are pre-warmed in priority
   order — fusion first (the mapper's time-to-first-fused-scan is the
   availability metric), then matching, then exploration — from the
   warm tiers in `io/compile_cache.py`: an AOT snapshot serves the
   executable outright, otherwise a zeros-materialized call through
   the persistent compilation cache, otherwise a cold compile (the
   fallback ladder, never a crash). Meanwhile serving keeps answering
   from the LAST epoch with `state=warming` instead of blocking
   (bridge/http_api.py);
3. **ready** — a READINESS GATE checks the warmed compiled-variant
   counts against the committed `analysis/compile_budget.json` (a
   warm-up that compiled MORE variants than the budget sanctions is a
   recompile regression surfacing at the worst possible moment), the
   dispatch profiler re-baselines so cache-/AOT-warmed variants never
   count as live recompiles, and only then does the restarter return —
   which is what re-admits the node into supervision (the supervisor's
   fresh heartbeat grace) and FleetHealth-driven work assignment.

Deterministic by construction: pre-warm calls are pure functions on
zeros, so two same-seed kill+resume missions stay bit-identical — the
chaos determinism contract extended to the restart path.

Thread contract: the state machine's fields mutate only under `_lock`
(declared in analysis/protection.py, racewatch-gated); pre-warm's jax
work runs outside it. HTTP workers read `state()`/`snapshot()`
concurrently with the restarting step thread.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

#: Warm-up priority classes, in order: the fusion tier gates
#: time-to-first-fused-scan (slam_step IS the mapper's fuse entry),
#: matching gates the first key scan, exploration gates the first
#: publish; everything else (sim, serving hashes, planner) follows.
#: Classification is by qualified-name substring — the registry's
#: naming contract (module + function name).
_PRIORITY_CLASSES = (
    ("fusion", ("fuse", "slam_step", "sensor_kernel")),
    ("match", ("match", "pyramid", "scan_agreement", "posegraph")),
    ("frontier", ("frontier", "costfield", "planner")),
)

IDLE = "idle"
RESTORING = "restoring"
WARMING = "warming"
READY = "ready"


def warmup_class(name: str) -> int:
    """Priority class index for a qualified entry-point name (lower
    warms earlier; unclassified names warm last)."""
    for i, (_label, needles) in enumerate(_PRIORITY_CLASSES):
        if any(n in name for n in needles):
            return i
    return len(_PRIORITY_CLASSES)


def warmup_order(names) -> List[str]:
    """Names sorted fusion → match → frontier → rest, alphabetical
    within a class (deterministic walk order)."""
    return sorted(names, key=lambda n: (warmup_class(n), n))


class StagedWarmup:
    """The restart state machine + pre-warm driver."""

    def __init__(self, cache=None, devprof=None,
                 budget_path: Optional[str] = None):
        #: io/compile_cache.CompileCacheManager, or None (in-process
        #: restart with no cold-start tier: the stages still run, the
        #: pre-warm degenerates to already-warm skips).
        self.cache = cache
        self.devprof = devprof
        self.budget_path = budget_path
        self._lock = threading.Lock()
        self._state = IDLE
        #: [(fn_name, how)] per warmed signature, in warm order —
        #: how ∈ {aot, prewarmed, in_process, error}.
        self._warmed: List[tuple] = []
        self._report: Dict[str, object] = {}

    # -- state machine -------------------------------------------------------

    def _move(self, new: str) -> None:
        with self._lock:
            old = self._state
            self._state = new
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("warmup_stage", old=old, new=new)

    def begin_restore(self) -> None:
        self._move(RESTORING)

    def begin_warming(self) -> None:
        self._move(WARMING)

    def mark_ready(self) -> None:
        self._move(READY)

    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> dict:
        """The /status export + test assertion surface."""
        with self._lock:
            return {"state": self._state,
                    "n_warmed": len(self._warmed),
                    "warmed": list(self._warmed),
                    "report": dict(self._report)}

    # -- pre-warm ------------------------------------------------------------

    def prewarm(self, signatures: Optional[Dict[str, list]] = None,
                force: bool = False, manifest: bool = True) -> dict:
        """Warm the captured entry points in priority order and run the
        readiness gate. `signatures` maps qualified names to captured
        abstract signatures (the dispatch profiler's live capture, or
        the snapshot manifest's persisted ones); the cache manager's
        loaded pool supplies AOT entries on top. `force` warms the
        EXPLICITLY-passed names even when their functions already hold
        compiled variants — the tenant control plane's admission case,
        where a NEW bucket shape of an already-warm entry point must
        compile before the tenant joins (an already-compiled signature
        is a cheap jit-cache hit, so forcing never recompiles). Names
        that came only from the AOT manifest keep the in-process
        short-circuit regardless: forcing them would re-execute every
        persisted signature per admission. `manifest=False` skips the
        AOT-manifest merge entirely and warms ONLY the passed
        signatures — the tenant admission case again, where one new
        bucket variant must not drag the whole persisted warm sweep
        behind it (the restart path keeps the full merge). Returns
        the report (also kept for `snapshot()`). Never raises —
        per-signature failures are counted and the ladder degrades."""
        from jax_mapping.io.compile_cache import (materialize_zeros,
                                                  resolve_entry_point)
        t0 = time.perf_counter()
        baseline_sizes = self._cache_sizes()
        sigs: Dict[str, list] = {}
        pool_names = []
        if self.cache is not None and manifest:
            loaded = self.cache.load_aot()
            for name, ss in loaded["signatures"].items():
                sigs.setdefault(name, []).extend(ss)
            pool_names = loaded["pool_names"]
            if loaded["n_loaded"] and not self.cache.pool.installed:
                self.cache.pool.install()
        for name, ss in (signatures or {}).items():
            for s in ss:
                if all(repr(s) != repr(x) for x in sigs.get(name, [])):
                    sigs.setdefault(name, []).append(s)
        warmed: List[tuple] = []
        n_errors = 0
        for name in warmup_order(sigs):
            fn = resolve_entry_point(name)
            if fn is None:
                warmed.append((name, "error"))
                n_errors += 1
                continue
            forced = force and name in (signatures or {})
            try:
                already = not forced and int(fn._cache_size()) > 0
            except Exception:                       # noqa: BLE001
                already = False
            if already:
                # In-process restart: the jit cache survived the node;
                # nothing to pay, nothing to pre-warm.
                warmed.append((name, "in_process"))
                continue
            pooled = set()
            if self.cache is not None and name in pool_names:
                pooled = self.cache.pool.keys_for(name)
            for sig in sigs[name]:
                key = self._sig_key(sig)
                if key is not None and key in pooled:
                    # The AOT tier serves this variant — no re-trace,
                    # no jit-cache growth. Execute it once on zeros so
                    # the exported program's compile (a persistent-
                    # cache hit, normally) is paid HERE, inside the
                    # warm-up, never by the first live call; a failing
                    # snapshot degrades to the pre-warm rung below.
                    ent = self.cache.pool.entry(name, key)
                    try:
                        zargs, zkwargs = materialize_zeros(sig)
                        compiled, mode, dyn_idx, dyn_kw = ent
                        if mode == "dyn":
                            compiled(*[zargs[i] for i in dyn_idx],
                                     **{k: zkwargs[k] for k in dyn_kw})
                        else:
                            compiled(*zargs, **zkwargs)
                        warmed.append((name, "aot"))
                        continue
                    except Exception:               # noqa: BLE001
                        self.cache.pool.drop(name, key)
                try:
                    zargs, zkwargs = materialize_zeros(sig)
                    fn(*zargs, **zkwargs)
                    warmed.append((name, "prewarmed"))
                except Exception:                   # noqa: BLE001
                    warmed.append((name, "error"))
                    n_errors += 1
        report = {
            "n_warmed": len([w for w in warmed if w[1] != "error"]),
            "n_errors": n_errors,
            "n_aot": len([w for w in warmed if w[1] == "aot"]),
            "n_prewarmed": len([w for w in warmed
                                if w[1] == "prewarmed"]),
            "n_in_process": len([w for w in warmed
                                 if w[1] == "in_process"]),
            "warm_s": round(time.perf_counter() - t0, 3),
        }
        report["readiness_violations"] = self._readiness(baseline_sizes)
        if self.devprof is not None:
            # Satellite contract: cache-/AOT-warmed variants are NOT
            # live recompiles — the profiler's baseline moves to the
            # post-warm-up cache sizes before service resumes.
            report["n_rebaselined"] = self.devprof.rebaseline()
        with self._lock:
            self._warmed = warmed
            self._report = report
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record(
            "warmup_ready", n_warmed=report["n_warmed"],
            n_aot=report["n_aot"], n_errors=report["n_errors"],
            n_readiness_violations=len(report["readiness_violations"]))
        return report

    @staticmethod
    def _sig_key(sig: tuple) -> Optional[str]:
        """The pool's signature key for an already-abstract captured
        signature (the devprof key contract)."""
        try:
            return repr(sig)
        except Exception:                           # noqa: BLE001
            return None

    @staticmethod
    def _cache_sizes() -> Dict[str, int]:
        try:
            from jax_mapping.analysis.compilebudget import \
                snapshot_cache_sizes
            return snapshot_cache_sizes()
        except Exception:                           # noqa: BLE001
            return {}

    def _readiness(self, baseline: Dict[str, int]) -> List[str]:
        """The readiness gate: a budgeted function THIS warm-up grew
        past its `compile_budget.json` ceiling is a recompile
        regression surfacing on the restart path — report it. The gate
        compares against the pre-warm-up baseline because the budget is
        defined for a COLD canonical scenario: in a fresh resume
        process baseline is zero and the check is absolute, while in a
        warm long-lived process (in-process restarts, test suites) the
        accumulated variant history is not this warm-up's doing and
        must not cry wolf. Violations are reported (and
        flight-recorded via the caller), not raised: a degraded
        warm-up still re-admits; it just says so."""
        path = self.budget_path
        if path is None:
            from jax_mapping.analysis.compilebudget import \
                default_budget_path
            path = default_budget_path()
        try:
            from jax_mapping.analysis.compilebudget import Budget
            budget = Budget.load(path)
        except Exception:                           # noqa: BLE001
            return ["compile budget unreadable — readiness unchecked"]
        sizes = self._cache_sizes()
        out = []
        for e in budget.entries:
            n = sizes.get(e["name"], 0)
            if n > e["max"] and n > baseline.get(e["name"], 0):
                out.append(f"{e['name']}: {n} compiled variant(s) after "
                           f"warm-up exceeds budget {e['max']}")
        return out
