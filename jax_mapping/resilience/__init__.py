"""Fleet supervision, graceful degradation, and deterministic chaos.

The subsystem ISSUE 2 adds on top of the per-module robustness islands
(driver retries, bus drop knobs, transport reconnect, checkpointing):

* `health`     — FleetHealth, the shared degraded-mode state machine
                 (per-robot OK/NO_LIDAR/DEAD ladder, driver link state)
                 plus the HTTP plane's bounded-lock primitives.
* `supervisor` — Supervisor node: heartbeat monitoring, exponential-
                 backoff restart policy, auto-checkpoint cadence.
* `faultplan`  — FaultEvent/FaultPlan: scripted, seeded, reproducible
                 multi-fault missions injected at existing boundaries.
* `warmup`     — StagedWarmup: the availability-aware restart path
                 (ISSUE 12) — restore, pre-warm jitted entry points in
                 priority order from the io/compile_cache.py warm
                 tiers, readiness-gate against the compile budget, and
                 only then re-admit the node.

Import order note: `bridge.brain` imports `resilience.health` at module
top, and `faultplan` needs `bridge.brain.robot_ns` — the latter import
is function-local (lazy) so this package stays importable from either
direction.
"""

from jax_mapping.resilience.health import (  # noqa: F401
    DEAD, DRIVER_OFFLINE, DRIVER_OK, DRIVER_RECOVERING,
    ESTIMATOR_DIVERGED, NO_LIDAR, OK,
    FleetHealth, LockTimeout, acquire_bounded,
)
from jax_mapping.resilience.supervisor import (  # noqa: F401
    Heartbeater, Supervisor, beat,
)
from jax_mapping.resilience.faultplan import (  # noqa: F401
    SENSOR_KINDS, FaultEvent, FaultPlan, random_plan,
)
from jax_mapping.resilience.warmup import (  # noqa: F401
    StagedWarmup, warmup_order,
)
