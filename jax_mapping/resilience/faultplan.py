"""Deterministic chaos injection: a scripted, seeded schedule of faults.

The fault-injection knobs this repo accumulated — bus drop/reorder
probabilities, driver read-failure injection, transport disconnects,
checkpoint files that can rot — are islands: each is reachable only from
hand-written test code, so no test can exercise a *mission* where several
of them fire in sequence. A `FaultPlan` is that mission script: an
ordered list of `FaultEvent`s, each firing at a specific `Stack.run_steps`
step index and auto-clearing after `duration` steps, injected at the
EXISTING boundaries (bus partition/probability setters, driver injection
fields, node kill) — no monkeypatching, so the chaos path exercises the
same code real faults would.

Determinism: events fire on the deterministic step clock; the only
randomness is the constructor's seeded RNG, used by `random_plan` to
GENERATE schedules — applying a given plan is fully deterministic, so a
chaos soak can assert two same-seed runs produce identical maps.

Fault kinds and their boundaries:

    lidar_dead          bus.partition("{ns}scan") — the robot's scan
                        stream goes dark (transport dead / sensor loss);
                        heals after `duration`.
    driver_offline      driver.fail_reads_after = now — the next read
                        raises DriverError; the brain's catch-all drops
                        the link (`main.py:198-200` semantics); clears
                        after `duration` (reconnect probe then succeeds).
    bus_drop            bus.set_fault_injection(drop_prob=value) for the
                        window — lossy-Wi-Fi weather (report.pdf §V.A).
    bus_reorder         same, reorder_prob.
    kill_node           Stack.kill_node(name) — destroy the node
                        mid-mission; the Supervisor notices the silent
                        heartbeat and restarts it (mapper: from the
                        latest checkpoint, pose re-anchored).
    kill_robot          partition the robot's scan topic AND disable its
                        motors (driver.set_robot_enabled) — mid-mission
                        robot loss; FleetHealth declares it DEAD and the
                        fleet reassigns its frontier work.
    rejoin_robot        undo kill_robot — the robot relocalizes through
                        the mapper's normal matching against the shared
                        map.
    corrupt_checkpoint  truncate the file at `name` (default: the
                        stack's auto-checkpoint) — the power-loss /
                        bit-rot case the CRC32 + last-good rotation in
                        io/checkpoint.py exists for.

Adversarial SENSOR faults (ISSUE 3): unlike the kinds above, these do
not silence anything — the sensors keep reporting, plausibly and
wrongly, which is precisely what the recovery/ watchdog exists to
catch. Injected at the sim boundaries (`SimNode.set_*`, which delegate
to `sim/thymio.apply_wheel_slip` / `sim/lidar.apply_lidar_miscal` /
`apply_ghost_returns`); ghost beams are seeded per (launch seed, step,
robot), so same-seed chaos runs stay bit-identical.

    wheel_slip          measured wheel speeds biased by `value`
                        (e.g. 1.3 = odometry reads 30% fast; ground
                        truth motion untouched) — slip / miscalibrated
                        SPEED_COEFF (report.pdf §V.B: 13% CV).
    lidar_miscal        lidar mount rotated by `value` radians — every
                        beam reports a rotated world angle under its
                        old label.
    ghost_returns       a seeded `value` fraction of live beams replaced
                        with spurious short ranges (dust / multipath /
                        hostile reflector).
    scan_jam            ranges frozen at the jam-onset reading, stamps
                        stay fresh — a wedged sensor that looks alive.

WORLD kinds (ISSUE 8, scenario engine): the world ITSELF changes —
nothing is faulty, but evidence the mapper fused honestly goes stale
and must heal (DecayConfig semantics). Injected at the SimNode's world-
dynamics boundary (`SimNode.set_door`/`set_crowd`, which delegate to a
`scenarios.WorldDynamics` attached at launch); both compose by the same
refcount/worst-of rules as every other windowed kind, and two same-seed
runs mutate the world bit-identically.

    door_close          fill door rectangle `name` (registered with the
                        WorldDynamics) with wall for the window;
                        overlapping windows on one door refcount — the
                        first to clear must not re-open a door another
                        window still holds shut.
    crowd               a moving occupied blob (seeded deterministic
                        orbit) of radius `value` metres; `robot` is the
                        crowd id (its path seed). Overlapping windows
                        on one crowd id run the WORST (largest) radius.

INFRASTRUCTURE kind (ISSUE 12, warm-restart tier): the fault targets
the restart path's own acceleration layer — the mission must keep its
results bit-identical while restarts degrade from warm to cold.

    cache_wipe          delete the stack's compile-cache root
                        (persistent XLA cache + AOT snapshots) and
                        suppress cache writes for the window
                        (`CompileCacheManager.wipe_hold/release`);
                        overlapping windows refcount — the first to
                        clear must not re-enable a cache another still
                        holds wiped. A restart inside the window is a
                        genuinely cold restart; the stack degrades to
                        plain recompile, never crashes. No-op (noted in
                        the log) on stacks without a cold-start tier,
                        like corrupt_checkpoint with no file.

TENANT kinds (ISSUE 17, blast-radius containment): the fault targets
ONE tenant of a megabatched control plane — the containment contract
is that co-tenants never notice (bit-identical to a no-fault twin)
while the sentinels quarantine the victim. Injected at the plane's
own chaos seams (`TenantControlPlane.set_tenant_poison` /
`state_jump_tenant`), never by reaching into the batch from outside.
`name` is the tenant id for both. No-op (noted) on stacks without a
tenancy plane.

    tenant_poison       NaN the tenant's est-pose lane inputs every
                        tick of the window (covariance collapse /
                        odometry blow-up); overlapping windows on one
                        tenant refcount — the first to clear must not
                        un-poison a lane another window still holds.
                        The NONFINITE sentinel quarantines the lane
                        within the hysteresis budget.
    tenant_state_jump   teleport the tenant's estimated poses by
                        `value` metres (one-shot): survivable-state
                        corruption — the poses stay finite, but scan
                        matching against the tenant's own map degrades,
                        which the MATCH-FLOOR sentinel catches.
    controlplane_crash  kill the plane mid-mission and rebuild it from
                        its journal + checkpoints
                        (`Stack.crash_controlplane`): the in-memory
                        registry is lost, `restore()` replays
                        snapshot+journal, and every tenant comes back
                        with its epoch advanced (clients resync via
                        the epoch protocol).

MEMORY kinds (ISSUE 18, bounded-memory world): the fault targets the
windowed world store's retention tiers — the contract is that memory
starvation DEGRADES (shed harder, coarsen, refuse admission; tiles
re-read as unknown) and storage rot is DETECTED (CRC), never a crash
or silent wrong-map. Injected at the store's own chaos seams
(`WorldStore.hold_pressure` / `corrupt_spill`). No-op (noted) on
stacks without a windowed world.

    memory_pressure     synthetic host-budget squeeze: the effective
                        LRU budget shrinks by `value` (0.55 = the
                        governor plans against 45% of the configured
                        tiles) for the window; overlapping windows
                        compose WORST-OF through the governor's named
                        holds — the first to clear must not relax a
                        squeeze another window still holds.
    spill_corrupt       flip a CRC-detectable bit in up to `value`
                        spilled tiles (frame checksum re-stamped =
                        silent at-rest rot); one-shot and permanent —
                        the next rehydrate of a hit tile must degrade
                        it to unknown with a flight event, never raise.
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Dict, List, Optional

#: Adversarial sensor-fault kinds (SimNode boundary; recovery/ targets).
SENSOR_KINDS = frozenset({
    "wheel_slip", "lidar_miscal", "ghost_returns", "scan_jam",
})

#: Dynamic-world scenario kinds (SimNode world-dynamics boundary;
#: the decaying mapper's healing path is their target).
WORLD_KINDS = frozenset({"door_close", "crowd"})

#: Tenant blast-radius kinds (TenantControlPlane chaos-seam boundary;
#: the containment ladder + durable registry are their targets).
TENANT_KINDS = frozenset({
    "tenant_poison", "tenant_state_jump", "controlplane_crash",
})

#: Bounded-memory world kinds (WorldStore chaos-seam boundary; the
#: pressure governor + spill CRC integrity are their targets).
MEMORY_KINDS = frozenset({"memory_pressure", "spill_corrupt"})

KINDS = frozenset({
    "lidar_dead", "driver_offline", "bus_drop", "bus_reorder",
    "kill_node", "kill_robot", "rejoin_robot", "corrupt_checkpoint",
    "cache_wipe",
}) | SENSOR_KINDS | WORLD_KINDS | TENANT_KINDS | MEMORY_KINDS


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. `step` is the Stack.run_steps index it fires
    at; `duration` > 0 auto-clears that many steps later (0 = permanent
    or cleared by a paired event, e.g. kill_robot/rejoin_robot)."""

    step: int
    kind: str
    robot: int = 0
    duration: int = 0
    value: float = 0.0          # kind-specific (drop/reorder probability)
    name: str = ""              # node name / file path

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {sorted(KINDS)})")
        if self.step < 0 or self.duration < 0:
            raise ValueError("step and duration must be >= 0")
        # Value-carrying sensor kinds refuse the 0.0 default: for
        # wheel_slip it is the worst possible fault (a 0x measured-speed
        # factor is total odometry blackout, not slip — 1.0 is healthy),
        # and for miscal/ghosts it is a silent no-op that would let a
        # chaos test "pass" while never injecting the fault it scripted.
        if self.kind == "wheel_slip" and self.value <= 0.0:
            raise ValueError(
                "wheel_slip needs value > 0: the measured-speed factor "
                "(1.0 = healthy, e.g. 1.3 = odometry reads 30% fast)")
        if self.kind in ("lidar_miscal", "ghost_returns") \
                and self.value == 0.0:
            raise ValueError(
                f"{self.kind} needs a nonzero value (the angular offset "
                "in rad / the ghosted beam fraction) — 0.0 injects "
                "nothing")
        if self.kind == "door_close" and not self.name:
            raise ValueError(
                "door_close needs name = a door registered with the "
                "stack's WorldDynamics (an unnamed close is a no-op a "
                "scenario would silently 'pass' with)")
        if self.kind == "crowd" and self.value <= 0.0:
            raise ValueError(
                "crowd needs value > 0: the blob radius in metres "
                "(0.0 stamps nothing)")
        if self.kind in ("tenant_poison", "tenant_state_jump") \
                and not self.name:
            raise ValueError(
                f"{self.kind} needs name = the target tenant id (an "
                "unnamed tenant fault is a no-op a chaos drill would "
                "silently 'pass' with)")
        if self.kind == "tenant_state_jump" and self.value <= 0.0:
            raise ValueError(
                "tenant_state_jump needs value > 0: the teleport "
                "distance in metres (0.0 jumps nowhere)")
        if self.kind == "memory_pressure" \
                and not 0.0 < self.value <= 1.0:
            raise ValueError(
                "memory_pressure needs 0 < value <= 1: the budget "
                "squeeze fraction (0.0 squeezes nothing, and a chaos "
                "test would silently 'pass' without it)")
        if self.kind == "spill_corrupt" and self.value < 1.0:
            raise ValueError(
                "spill_corrupt needs value >= 1: the number of spilled "
                "tiles to rot (0 corrupts nothing)")


class FaultPlan:
    """Apply a schedule of FaultEvents against a running Stack.

    `apply(stack, step)` is called once per step (Stack.run_steps does
    this automatically when a plan is attached); it runs due clears,
    then fires due events. `log` records every action as
    (step, description) — two same-seed runs of the same plan produce
    identical logs, the soak test's determinism anchor."""

    def __init__(self, events: List[FaultEvent], seed: int = 0):
        self.events = sorted(events, key=lambda e: (e.step, e.kind,
                                                    e.robot))
        self.seed = seed
        self._rng = random.Random(seed)
        #: Faults random_plan ASKED for but could not place (same-
        #: resource overlap rejection saturated its resample budget) —
        #: 0 for hand-written plans. A soak that believes it injected
        #: n_faults must be able to see how many it actually got.
        self.generation_shortfall = 0
        self._fired = [False] * len(self.events)
        #: (due_step, callable, description) pending auto-clears.
        self._clears: List[tuple] = []
        self.log: List[tuple] = []
        # Overlap bookkeeping: clears are REFCOUNTED so two windows on
        # the same resource compose — the first window's clear must not
        # heal a partition (or restore weather) the second still holds.
        self._partition_refs: Dict[str, int] = {}
        self._robot_kill_refs: Dict[int, int] = {}
        self._driver_refs = 0
        #: knob -> (baseline captured at first fire, active values).
        self._weather: Dict[str, tuple] = {}
        #: (kind, robot) -> active values for the sensor-fault kinds —
        #: the weather pattern per robot: overlapping windows compose by
        #: running the WORST active value, the identity baseline returns
        #: when the last window clears.
        self._sensor: Dict[tuple, list] = {}
        #: door name -> held-closure refcount (the partition pattern:
        #: last window out re-opens the door).
        self._door_refs: Dict[str, int] = {}
        #: crowd id -> active radii (the sensor pattern: the sim runs
        #: the WORST = largest active blob, gone when none remain).
        self._crowd: Dict[int, list] = {}
        #: tenant id -> held-poison refcount (the partition pattern:
        #: last window out un-poisons the lane).
        self._tenant_poison_refs: Dict[str, int] = {}

    # -- boundary helpers ----------------------------------------------------

    @staticmethod
    def _scan_topic(stack, robot: int) -> str:
        from jax_mapping.bridge.brain import robot_ns
        return f"{robot_ns(robot, stack.brain.n_robots)}scan"

    def _note(self, step: int, desc: str) -> None:
        self.log.append((step, desc))
        # Every window open/clear is a load-bearing transition: the
        # flight recorder stream interleaves the chaos script with the
        # system's reactions, which is the whole point of a postmortem
        # ("the gate failed two events after `door_close door0`").
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("fault", step=step, desc=desc)

    # -- the per-step hook ---------------------------------------------------

    def apply(self, stack, step: int) -> None:
        still_pending = []
        for due, fn, desc in self._clears:
            if step >= due:
                fn()
                self._note(step, f"clear: {desc}")
            else:
                still_pending.append((due, fn, desc))
        self._clears = still_pending
        for i, ev in enumerate(self.events):
            if not self._fired[i] and ev.step <= step:
                self._fired[i] = True
                self._fire(stack, ev, step)

    # -- refcounted resource holds (overlapping windows compose) -----------

    def _hold_partition(self, bus, topic: str) -> None:
        self._partition_refs[topic] = \
            self._partition_refs.get(topic, 0) + 1
        bus.partition(topic)

    def _release_partition(self, bus, topic: str) -> None:
        n = self._partition_refs.get(topic, 1) - 1
        self._partition_refs[topic] = max(0, n)
        if n <= 0:
            bus.heal(topic)                  # last window out heals

    def _apply_weather(self, bus, key: str, value: Optional[float]
                       ) -> None:
        """Add (value) or remove (None pops the given value via the
        caller) one active weather window; the bus runs the WORST of the
        active windows, reverting to the pre-chaos baseline when the
        last one clears."""
        base, active = self._weather.setdefault(
            key, (getattr(bus, key), []))
        if value is not None:
            active.append(value)
        bus.set_fault_injection(**{key: max(active) if active else base})

    def _apply_sensor(self, stack, kind: str, robot: int,
                      value: Optional[float]) -> None:
        """Add (value) or remove (None; caller popped the list) one
        active sensor-fault window for (kind, robot); the sim runs the
        WORST of the active windows, identity when none remain."""
        active = self._sensor.setdefault((kind, robot), [])
        if value is not None:
            active.append(value)
        sim = stack.sim
        if kind == "wheel_slip":
            # Worst = farthest from the healthy 1.0 factor.
            worst = max(active, key=lambda v: abs(v - 1.0)) \
                if active else 1.0
            sim.set_wheel_slip(robot, worst)
        elif kind == "lidar_miscal":
            worst = max(active, key=abs) if active else 0.0
            sim.set_lidar_miscal(robot, worst)
        elif kind == "ghost_returns":
            sim.set_ghost_returns(robot, max(active) if active else 0.0)
        elif kind == "scan_jam":
            sim.set_scan_jam(robot, bool(active))

    # -- world-kind holds (scenarios/dynamics.py boundary) -------------------

    def _hold_door(self, sim, name: str) -> None:
        self._door_refs[name] = self._door_refs.get(name, 0) + 1
        sim.set_door(name, True)

    def _release_door(self, sim, name: str) -> None:
        n = self._door_refs.get(name, 1) - 1
        self._door_refs[name] = max(0, n)
        if n <= 0:
            sim.set_door(name, False)        # last window out re-opens

    def _apply_crowd(self, sim, cid: int,
                     radius: Optional[float]) -> None:
        """Add (radius) or remove (None; caller popped the list) one
        active crowd window for `cid`; the sim runs the WORST (largest)
        active blob, none when the last window clears."""
        active = self._crowd.setdefault(cid, [])
        if radius is not None:
            active.append(radius)
        sim.set_crowd(cid, max(active) if active else None)

    def _fire(self, stack, ev: FaultEvent, step: int) -> None:
        bus = stack.bus
        if ev.kind == "door_close":
            self._hold_door(stack.sim, ev.name)
            self._note(step, f"door_close {ev.name}")
            if ev.duration:
                def _reopen(name=ev.name):
                    self._release_door(stack.sim, name)
                self._clears.append((step + ev.duration, _reopen,
                                     f"door_close {ev.name}"))
        elif ev.kind == "crowd":
            self._apply_crowd(stack.sim, ev.robot, ev.value)
            self._note(step, f"crowd {ev.robot} r={ev.value}m")
            if ev.duration:
                def _clear_crowd(cid=ev.robot, value=ev.value):
                    self._crowd[cid].remove(value)
                    self._apply_crowd(stack.sim, cid, None)
                self._clears.append((step + ev.duration, _clear_crowd,
                                     f"crowd {ev.robot}"))
        elif ev.kind in SENSOR_KINDS:
            self._apply_sensor(stack, ev.kind, ev.robot, ev.value)
            self._note(step, f"{ev.kind} robot{ev.robot}={ev.value}")
            if ev.duration:
                def _clear_sensor(kind=ev.kind, robot=ev.robot,
                                  value=ev.value):
                    self._sensor[(kind, robot)].remove(value)
                    self._apply_sensor(stack, kind, robot, None)
                self._clears.append((step + ev.duration, _clear_sensor,
                                     f"{ev.kind} robot{ev.robot}"))
        elif ev.kind == "lidar_dead":
            topic = self._scan_topic(stack, ev.robot)
            self._hold_partition(bus, topic)
            self._note(step, f"lidar_dead robot{ev.robot}")
            if ev.duration:
                self._clears.append((
                    step + ev.duration,
                    lambda: self._release_partition(bus, topic),
                    f"lidar_dead robot{ev.robot}"))
        elif ev.kind == "driver_offline":
            drv = stack.driver
            self._driver_refs += 1
            drv.fail_reads_after = drv._n_reads
            self._note(step, "driver_offline")
            if ev.duration:
                def _heal_driver():
                    self._driver_refs -= 1
                    if self._driver_refs <= 0:
                        drv.fail_reads_after = None
                self._clears.append((step + ev.duration, _heal_driver,
                                     "driver_offline"))
        elif ev.kind in ("bus_drop", "bus_reorder"):
            key = "drop_prob" if ev.kind == "bus_drop" else "reorder_prob"
            self._apply_weather(bus, key, ev.value)
            self._note(step, f"{ev.kind}={ev.value}")
            if ev.duration:
                def _clear_weather(key=key, value=ev.value):
                    self._weather[key][1].remove(value)
                    self._apply_weather(bus, key, None)
                self._clears.append((step + ev.duration, _clear_weather,
                                     f"{ev.kind}"))
        elif ev.kind == "kill_node":
            stack.kill_node(ev.name or "jax_mapper")
            self._note(step, f"kill_node {ev.name or 'jax_mapper'}")
        elif ev.kind == "kill_robot":
            topic = self._scan_topic(stack, ev.robot)
            self._hold_partition(bus, topic)
            self._robot_kill_refs[ev.robot] = \
                self._robot_kill_refs.get(ev.robot, 0) + 1
            stack.driver.set_robot_enabled(ev.robot, False)
            self._note(step, f"kill_robot robot{ev.robot}")
            if ev.duration:
                self._clears.append((
                    step + ev.duration,
                    lambda: self._rejoin(stack, ev.robot),
                    f"kill_robot robot{ev.robot}"))
        elif ev.kind == "rejoin_robot":
            self._rejoin(stack, ev.robot)
            self._note(step, f"rejoin_robot robot{ev.robot}")
        elif ev.kind == "cache_wipe":
            mgr = getattr(stack, "compile_cache", None)
            if mgr is None:
                self._note(step, "cache_wipe skipped (no compile "
                                 "cache on this stack)")
            else:
                mgr.wipe_hold()
                self._note(step, "cache_wipe")
                if ev.duration:
                    def _rearm(m=mgr):
                        m.wipe_release()
                    self._clears.append((step + ev.duration, _rearm,
                                         "cache_wipe"))
        elif ev.kind in ("tenant_poison", "tenant_state_jump"):
            plane = getattr(stack, "tenancy", None)
            if plane is None:
                self._note(step, f"{ev.kind} skipped (no tenant "
                                 "control plane on this stack)")
            elif ev.kind == "tenant_poison":
                self._hold_tenant_poison(plane, ev.name)
                self._note(step, f"tenant_poison {ev.name}")
                if ev.duration:
                    def _unpoison(tid=ev.name):
                        # Re-read the plane at clear time: a
                        # controlplane_crash inside the window must
                        # clear against the RESTORED plane, not the
                        # dead one.
                        self._release_tenant_poison(
                            getattr(stack, "tenancy", None), tid)
                    self._clears.append((step + ev.duration, _unpoison,
                                         f"tenant_poison {ev.name}"))
            else:
                plane.state_jump_tenant(ev.name, ev.value)
                self._note(step,
                           f"tenant_state_jump {ev.name}={ev.value}m")
        elif ev.kind == "controlplane_crash":
            crash = getattr(stack, "crash_controlplane", None)
            if crash is None or getattr(stack, "tenancy", None) is None:
                self._note(step, "controlplane_crash skipped (no "
                                 "tenant control plane on this stack)")
            else:
                report = crash()
                self._note(step, "controlplane_crash restored="
                                 f"{len(report.get('restored', []))} "
                                 f"lost={len(report.get('lost', []))}")
        elif ev.kind in MEMORY_KINDS:
            store = getattr(stack, "world", None) or \
                getattr(getattr(stack, "mapper", None), "world", None)
            if store is None:
                self._note(step, f"{ev.kind} skipped (no windowed "
                                 "world store on this stack)")
            elif ev.kind == "memory_pressure":
                # One named hold per EVENT (step disambiguates two
                # same-kind windows): overlapping holds compose
                # worst-of inside the governor, and each window's
                # clear releases only its own name.
                hold = f"chaos@{ev.step}"
                store.hold_pressure(hold, ev.value)
                self._note(step, f"memory_pressure={ev.value}")
                if ev.duration:
                    def _relax(name=hold):
                        # Re-read the store at clear time: a kill_node
                        # inside the window replaced the mapper (and
                        # its store), and the governor holds died with
                        # it — releasing against the dead store is the
                        # harmless branch.
                        s = getattr(stack, "world", None) or \
                            getattr(getattr(stack, "mapper", None),
                                    "world", None)
                        if s is not None:
                            s.release_pressure(name)
                    self._clears.append((step + ev.duration, _relax,
                                         "memory_pressure"))
            else:
                hit = store.corrupt_spill(max(1, int(ev.value)))
                if hit:
                    self._note(step, f"spill_corrupt {len(hit)} "
                                     f"tile(s): {sorted(hit)}")
                else:
                    self._note(step, "spill_corrupt skipped (no "
                                     "spilled tiles to rot)")
        elif ev.kind == "corrupt_checkpoint":
            path = ev.name or getattr(stack, "auto_checkpoint_path", "")
            if path and os.path.exists(path):
                size = os.path.getsize(path)
                with open(path, "rb+") as f:
                    f.truncate(max(1, int(size * 0.6)))
                self._note(step, f"corrupt_checkpoint {path} "
                                 f"({size} -> {max(1, int(size * 0.6))}B)")
            else:
                self._note(step, f"corrupt_checkpoint skipped "
                                 f"(no file at {path!r})")

    def _hold_tenant_poison(self, plane, tid: str) -> None:
        self._tenant_poison_refs[tid] = \
            self._tenant_poison_refs.get(tid, 0) + 1
        plane.set_tenant_poison(tid, True)

    def _release_tenant_poison(self, plane, tid: str) -> None:
        n = self._tenant_poison_refs.get(tid, 1) - 1
        self._tenant_poison_refs[tid] = max(0, n)
        if n <= 0 and plane is not None:
            plane.set_tenant_poison(tid, False)  # last window out

    def _rejoin(self, stack, robot: int) -> None:
        if self._robot_kill_refs.get(robot, 0) <= 0:
            # No kill held: a stray rejoin_robot must not heal a
            # partition some OTHER window (e.g. lidar_dead) still owns.
            return
        self._robot_kill_refs[robot] -= 1
        self._release_partition(stack.bus, self._scan_topic(stack, robot))
        if self._robot_kill_refs[robot] == 0:
            stack.driver.set_robot_enabled(robot, True)

    def done(self) -> bool:
        return all(self._fired) and not self._clears

    def summary(self) -> List[str]:
        return [f"step {s}: {d}" for s, d in self.log]


def _fault_resource(kind: str, robot: int, name: str = "") -> tuple:
    """The resource a fault window occupies, for overlap rejection:
    two windows on one resource would need refcount composition at
    APPLY time (hand-written plans may still do that deliberately);
    generated fuzz keeps windows disjoint so each fault's effect — and
    the recovery it provokes — is attributable to one event."""
    if kind in ("lidar_dead", "lidar_miscal", "ghost_returns",
                "scan_jam"):
        return ("scan", robot)
    if kind == "wheel_slip":
        return ("odom", robot)
    if kind == "driver_offline":
        return ("driver",)
    if kind == "door_close":
        return ("door", name)
    if kind == "crowd":
        return ("crowd", robot)          # robot field = crowd id
    if kind == "cache_wipe":
        return ("cache",)                # one compile cache per stack
    if kind in ("tenant_poison", "tenant_state_jump"):
        return ("tenant", name)          # name field = tenant id
    if kind == "controlplane_crash":
        return ("controlplane",)         # one plane per stack
    if kind == "memory_pressure":
        return ("memory",)               # one host LRU per stack
    if kind in ("spill_corrupt", "corrupt_checkpoint"):
        # One durable-storage resource: rotting the spill file AND
        # truncating a checkpoint in one window would make the
        # degradation unattributable (both heal through re-anchor /
        # rehydrate paths that share the postmortem).
        return ("checkpoint",)
    return ("bus", kind)                 # bus_drop / bus_reorder


def _sample_value(rng: random.Random, kind: str) -> float:
    """Kind-appropriate magnitudes: bus weather as before; wheel slip a
    1.15-1.5x odometry bias; miscal 0.05-0.3 rad (sign sampled);
    ghosts on 10-40% of beams; crowd blobs 0.15-0.4 m radius."""
    if kind.startswith("bus_"):
        return round(rng.uniform(0.2, 0.7), 3)
    if kind == "wheel_slip":
        return round(rng.uniform(1.15, 1.5), 3)
    if kind == "lidar_miscal":
        return round(rng.choice((-1.0, 1.0)) * rng.uniform(0.05, 0.3), 3)
    if kind == "ghost_returns":
        return round(rng.uniform(0.1, 0.4), 3)
    if kind == "crowd":
        return round(rng.uniform(0.15, 0.4), 3)
    if kind == "tenant_state_jump":
        # Well past any honest per-tick translation, well inside the
        # arena: the jump must corrupt, not escape the map.
        return round(rng.uniform(0.5, 2.0), 3)
    if kind == "memory_pressure":
        # Deep enough that the governor must climb a rung, shy of the
        # budget floor (1.0 would plan against a single tile).
        return round(rng.uniform(0.4, 0.9), 3)
    if kind == "spill_corrupt":
        return float(rng.randrange(1, 4))
    return 0.0


def random_plan(mission_steps: int, n_faults: int = 3, seed: int = 0,
                n_robots: int = 1, door_names=(),
                n_crowds: int = 0,
                allow_cache_wipe: bool = False,
                tenant_ids=(),
                allow_controlplane_crash: bool = False,
                allow_world_faults: bool = False) -> FaultPlan:
    """Generate a reproducible schedule: `seed` fully determines the
    fault mix, placement, and durations (fuzz-style soak variety with
    CI-replayable failures). Samples the adversarial sensor kinds
    alongside the transport/driver faults, and REJECTS overlapping
    windows on the same resource at generation time (resampling,
    bounded) — generated chaos keeps each fault's effect attributable.
    Short missions can saturate every resource before n_faults place;
    the dropped count is exposed as `plan.generation_shortfall`, never
    silently swallowed.

    Dynamic-world kinds join the pool only when the stack can run them:
    `door_names` (the doors registered with its WorldDynamics) admits
    `door_close` windows (one door = one resource), `n_crowds` > 0
    admits `crowd` windows with kind-appropriate blob radii (one crowd
    id = one resource), `allow_cache_wipe` admits `cache_wipe` windows
    (stacks with a cold-start compile cache; the one cache = one
    resource), `tenant_ids` (ids live on the stack's tenancy plane)
    admits `tenant_poison` / `tenant_state_jump` windows (one tenant =
    one resource), `allow_controlplane_crash` admits ONE
    `controlplane_crash` per plan (the one plane = one resource), and
    `allow_world_faults` admits `memory_pressure` windows (the one
    host LRU = one resource) and one-shot `spill_corrupt` rots (the
    one durable-storage resource, shared with checkpoint truncation)
    for stacks running a windowed world store. Default arguments
    reproduce the pre-scenario sampler bit-for-bit."""
    rng = random.Random(seed)
    kinds = ["lidar_dead", "driver_offline", "bus_drop", "bus_reorder",
             "wheel_slip", "lidar_miscal", "ghost_returns", "scan_jam"]
    door_names = list(door_names)
    tenant_ids = list(tenant_ids)
    if door_names:
        kinds.append("door_close")
    if n_crowds > 0:
        kinds.append("crowd")
    if allow_cache_wipe:
        kinds.append("cache_wipe")
    if tenant_ids:
        kinds += ["tenant_poison", "tenant_state_jump"]
    if allow_controlplane_crash:
        kinds.append("controlplane_crash")
    if allow_world_faults:
        kinds += ["memory_pressure", "spill_corrupt"]
    events: List[FaultEvent] = []
    occupied: List[tuple] = []           # (resource, start, end)
    shortfall = 0
    for _ in range(n_faults):
        for _attempt in range(64):       # bounded resample budget
            kind = rng.choice(kinds)
            step = rng.randrange(1, max(2, mission_steps - 10))
            duration = rng.randrange(3, 12)
            robot = rng.randrange(n_crowds) if kind == "crowd" \
                else rng.randrange(n_robots)
            name = ""
            if kind == "door_close":
                name = rng.choice(door_names)
            elif kind in ("tenant_poison", "tenant_state_jump"):
                name = rng.choice(tenant_ids)
            res = _fault_resource(kind, robot, name)
            start, end = step, step + duration
            if kind == "controlplane_crash":
                # The crash occupies the plane for the WHOLE mission:
                # one crash per plan (a second restore would re-bump
                # every epoch and make no fault attributable to either).
                start, end = 0, mission_steps
            if any(r == res and start <= e and s <= end
                   for r, s, e in occupied):
                continue                 # same-resource overlap: reject
            occupied.append((res, start, end))
            events.append(FaultEvent(
                step=step, kind=kind, robot=robot, duration=duration,
                value=_sample_value(rng, kind), name=name))
            break
        else:
            shortfall += 1               # every resource window taken
    plan = FaultPlan(events, seed=seed)
    plan.generation_shortfall = shortfall
    return plan
