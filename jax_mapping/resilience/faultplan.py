"""Deterministic chaos injection: a scripted, seeded schedule of faults.

The fault-injection knobs this repo accumulated — bus drop/reorder
probabilities, driver read-failure injection, transport disconnects,
checkpoint files that can rot — are islands: each is reachable only from
hand-written test code, so no test can exercise a *mission* where several
of them fire in sequence. A `FaultPlan` is that mission script: an
ordered list of `FaultEvent`s, each firing at a specific `Stack.run_steps`
step index and auto-clearing after `duration` steps, injected at the
EXISTING boundaries (bus partition/probability setters, driver injection
fields, node kill) — no monkeypatching, so the chaos path exercises the
same code real faults would.

Determinism: events fire on the deterministic step clock; the only
randomness is the constructor's seeded RNG, used by `random_plan` to
GENERATE schedules — applying a given plan is fully deterministic, so a
chaos soak can assert two same-seed runs produce identical maps.

Fault kinds and their boundaries:

    lidar_dead          bus.partition("{ns}scan") — the robot's scan
                        stream goes dark (transport dead / sensor loss);
                        heals after `duration`.
    driver_offline      driver.fail_reads_after = now — the next read
                        raises DriverError; the brain's catch-all drops
                        the link (`main.py:198-200` semantics); clears
                        after `duration` (reconnect probe then succeeds).
    bus_drop            bus.set_fault_injection(drop_prob=value) for the
                        window — lossy-Wi-Fi weather (report.pdf §V.A).
    bus_reorder         same, reorder_prob.
    kill_node           Stack.kill_node(name) — destroy the node
                        mid-mission; the Supervisor notices the silent
                        heartbeat and restarts it (mapper: from the
                        latest checkpoint, pose re-anchored).
    kill_robot          partition the robot's scan topic AND disable its
                        motors (driver.set_robot_enabled) — mid-mission
                        robot loss; FleetHealth declares it DEAD and the
                        fleet reassigns its frontier work.
    rejoin_robot        undo kill_robot — the robot relocalizes through
                        the mapper's normal matching against the shared
                        map.
    corrupt_checkpoint  truncate the file at `name` (default: the
                        stack's auto-checkpoint) — the power-loss /
                        bit-rot case the CRC32 + last-good rotation in
                        io/checkpoint.py exists for.
"""

from __future__ import annotations

import dataclasses
import os
import random
from typing import Dict, List, Optional

KINDS = frozenset({
    "lidar_dead", "driver_offline", "bus_drop", "bus_reorder",
    "kill_node", "kill_robot", "rejoin_robot", "corrupt_checkpoint",
})


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault. `step` is the Stack.run_steps index it fires
    at; `duration` > 0 auto-clears that many steps later (0 = permanent
    or cleared by a paired event, e.g. kill_robot/rejoin_robot)."""

    step: int
    kind: str
    robot: int = 0
    duration: int = 0
    value: float = 0.0          # kind-specific (drop/reorder probability)
    name: str = ""              # node name / file path

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {sorted(KINDS)})")
        if self.step < 0 or self.duration < 0:
            raise ValueError("step and duration must be >= 0")


class FaultPlan:
    """Apply a schedule of FaultEvents against a running Stack.

    `apply(stack, step)` is called once per step (Stack.run_steps does
    this automatically when a plan is attached); it runs due clears,
    then fires due events. `log` records every action as
    (step, description) — two same-seed runs of the same plan produce
    identical logs, the soak test's determinism anchor."""

    def __init__(self, events: List[FaultEvent], seed: int = 0):
        self.events = sorted(events, key=lambda e: (e.step, e.kind,
                                                    e.robot))
        self.seed = seed
        self._rng = random.Random(seed)
        self._fired = [False] * len(self.events)
        #: (due_step, callable, description) pending auto-clears.
        self._clears: List[tuple] = []
        self.log: List[tuple] = []
        # Overlap bookkeeping: clears are REFCOUNTED so two windows on
        # the same resource compose — the first window's clear must not
        # heal a partition (or restore weather) the second still holds.
        self._partition_refs: Dict[str, int] = {}
        self._robot_kill_refs: Dict[int, int] = {}
        self._driver_refs = 0
        #: knob -> (baseline captured at first fire, active values).
        self._weather: Dict[str, tuple] = {}

    # -- boundary helpers ----------------------------------------------------

    @staticmethod
    def _scan_topic(stack, robot: int) -> str:
        from jax_mapping.bridge.brain import robot_ns
        return f"{robot_ns(robot, stack.brain.n_robots)}scan"

    def _note(self, step: int, desc: str) -> None:
        self.log.append((step, desc))

    # -- the per-step hook ---------------------------------------------------

    def apply(self, stack, step: int) -> None:
        still_pending = []
        for due, fn, desc in self._clears:
            if step >= due:
                fn()
                self._note(step, f"clear: {desc}")
            else:
                still_pending.append((due, fn, desc))
        self._clears = still_pending
        for i, ev in enumerate(self.events):
            if not self._fired[i] and ev.step <= step:
                self._fired[i] = True
                self._fire(stack, ev, step)

    # -- refcounted resource holds (overlapping windows compose) -----------

    def _hold_partition(self, bus, topic: str) -> None:
        self._partition_refs[topic] = \
            self._partition_refs.get(topic, 0) + 1
        bus.partition(topic)

    def _release_partition(self, bus, topic: str) -> None:
        n = self._partition_refs.get(topic, 1) - 1
        self._partition_refs[topic] = max(0, n)
        if n <= 0:
            bus.heal(topic)                  # last window out heals

    def _apply_weather(self, bus, key: str, value: Optional[float]
                       ) -> None:
        """Add (value) or remove (None pops the given value via the
        caller) one active weather window; the bus runs the WORST of the
        active windows, reverting to the pre-chaos baseline when the
        last one clears."""
        base, active = self._weather.setdefault(
            key, (getattr(bus, key), []))
        if value is not None:
            active.append(value)
        bus.set_fault_injection(**{key: max(active) if active else base})

    def _fire(self, stack, ev: FaultEvent, step: int) -> None:
        bus = stack.bus
        if ev.kind == "lidar_dead":
            topic = self._scan_topic(stack, ev.robot)
            self._hold_partition(bus, topic)
            self._note(step, f"lidar_dead robot{ev.robot}")
            if ev.duration:
                self._clears.append((
                    step + ev.duration,
                    lambda: self._release_partition(bus, topic),
                    f"lidar_dead robot{ev.robot}"))
        elif ev.kind == "driver_offline":
            drv = stack.driver
            self._driver_refs += 1
            drv.fail_reads_after = drv._n_reads
            self._note(step, "driver_offline")
            if ev.duration:
                def _heal_driver():
                    self._driver_refs -= 1
                    if self._driver_refs <= 0:
                        drv.fail_reads_after = None
                self._clears.append((step + ev.duration, _heal_driver,
                                     "driver_offline"))
        elif ev.kind in ("bus_drop", "bus_reorder"):
            key = "drop_prob" if ev.kind == "bus_drop" else "reorder_prob"
            self._apply_weather(bus, key, ev.value)
            self._note(step, f"{ev.kind}={ev.value}")
            if ev.duration:
                def _clear_weather(key=key, value=ev.value):
                    self._weather[key][1].remove(value)
                    self._apply_weather(bus, key, None)
                self._clears.append((step + ev.duration, _clear_weather,
                                     f"{ev.kind}"))
        elif ev.kind == "kill_node":
            stack.kill_node(ev.name or "jax_mapper")
            self._note(step, f"kill_node {ev.name or 'jax_mapper'}")
        elif ev.kind == "kill_robot":
            topic = self._scan_topic(stack, ev.robot)
            self._hold_partition(bus, topic)
            self._robot_kill_refs[ev.robot] = \
                self._robot_kill_refs.get(ev.robot, 0) + 1
            stack.driver.set_robot_enabled(ev.robot, False)
            self._note(step, f"kill_robot robot{ev.robot}")
            if ev.duration:
                self._clears.append((
                    step + ev.duration,
                    lambda: self._rejoin(stack, ev.robot),
                    f"kill_robot robot{ev.robot}"))
        elif ev.kind == "rejoin_robot":
            self._rejoin(stack, ev.robot)
            self._note(step, f"rejoin_robot robot{ev.robot}")
        elif ev.kind == "corrupt_checkpoint":
            path = ev.name or getattr(stack, "auto_checkpoint_path", "")
            if path and os.path.exists(path):
                size = os.path.getsize(path)
                with open(path, "rb+") as f:
                    f.truncate(max(1, int(size * 0.6)))
                self._note(step, f"corrupt_checkpoint {path} "
                                 f"({size} -> {max(1, int(size * 0.6))}B)")
            else:
                self._note(step, f"corrupt_checkpoint skipped "
                                 f"(no file at {path!r})")

    def _rejoin(self, stack, robot: int) -> None:
        if self._robot_kill_refs.get(robot, 0) <= 0:
            # No kill held: a stray rejoin_robot must not heal a
            # partition some OTHER window (e.g. lidar_dead) still owns.
            return
        self._robot_kill_refs[robot] -= 1
        self._release_partition(stack.bus, self._scan_topic(stack, robot))
        if self._robot_kill_refs[robot] == 0:
            stack.driver.set_robot_enabled(robot, True)

    def done(self) -> bool:
        return all(self._fired) and not self._clears

    def summary(self) -> List[str]:
        return [f"step {s}: {d}" for s, d in self.log]


def random_plan(mission_steps: int, n_faults: int = 3, seed: int = 0,
                n_robots: int = 1) -> FaultPlan:
    """Generate a reproducible schedule: `seed` fully determines the
    fault mix, placement, and durations (fuzz-style soak variety with
    CI-replayable failures)."""
    rng = random.Random(seed)
    kinds = ["lidar_dead", "driver_offline", "bus_drop", "bus_reorder"]
    events = []
    for _ in range(n_faults):
        kind = rng.choice(kinds)
        step = rng.randrange(1, max(2, mission_steps - 10))
        duration = rng.randrange(3, 12)
        events.append(FaultEvent(
            step=step, kind=kind,
            robot=rng.randrange(n_robots), duration=duration,
            value=round(rng.uniform(0.2, 0.7), 3)
            if kind.startswith("bus_") else 0.0))
    return FaultPlan(events, seed=seed)
