"""C4 — compiled-shape churn at jit trace boundaries.

XLA compiles one program per (static-arg values, operand shapes)
signature. A jitted call site whose static argument — or whose operand
*shape* — derives from an unbucketed runtime quantity (a queue length,
`len(scans)`, `arr.shape[0]` of a cropped region) compiles a fresh
program per distinct value: a recompile storm that looks like a hang on
TPU (seconds of XLA per tick) and quietly dominates CPU benchmarks.
The repo's standing fix is pow2-style bucketing BEFORE the boundary
(PR 6 bucketed crop spans to ``2**k ∪ 3·2**(k-1)``; the compile-budget
runtime tracker pins the residual).

The checker taints *dynamic-size sources* — `len(...)`,
`.shape`/`.size` reads, `count_nonzero` — through an ordered walk, and
flags, at call sites of known jit entry points (the package-wide
registry):

* a **static-position argument** (static_argnums/static_argnames)
  whose expression is dynamic-tainted, and
* a **traced operand** built by slicing with a dynamic-tainted bound
  (``arr[:n]`` — the shape IS the slice length).

Bucketing sanitizes: calls whose name matches ``bucket``/``pow2``/
``next_pow``/``pad_to``, and explicit ``2 ** k`` / ``1 << k``
arithmetic. Constants, config attributes and trace-static `.shape`
reads INSIDE jitted code are not dynamic — the checker only seeds
taint from host-side size reads in the calling function.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule

_BUCKET_NAME = re.compile(r"bucket|pow2|pow_two|next_pow|pad_to",
                          re.IGNORECASE)
_DYNAMIC_SIZE_ATTRS = {"shape", "size"}
_DYNAMIC_SIZE_CALLS = {"len"}
_DYNAMIC_SIZE_NP = {"numpy.count_nonzero", "numpy.sum"}
#: array reductions whose VALUE is runtime data — `int(mask.sum())` in
#: a static position compiles one program per distinct count.
_DYNAMIC_SIZE_METHODS = {"sum", "count_nonzero", "item", "nonzero"}


def _is_bucketing_call(call: ast.Call) -> bool:
    name = None
    if isinstance(call.func, ast.Name):
        name = call.func.id
    elif isinstance(call.func, ast.Attribute):
        name = call.func.attr
    return name is not None and bool(_BUCKET_NAME.search(name))


def _is_pow2_expr(node: ast.AST) -> bool:
    """`2 ** k` / `1 << k` anywhere inside `node`."""
    for n in ast.walk(node):
        if isinstance(n, ast.BinOp):
            if isinstance(n.op, ast.Pow) \
                    and isinstance(n.left, ast.Constant) \
                    and n.left.value == 2:
                return True
            if isinstance(n.op, ast.LShift) \
                    and isinstance(n.left, ast.Constant) \
                    and n.left.value == 1:
                return True
    return False


def _sanitized(expr: ast.AST) -> bool:
    if _is_pow2_expr(expr):
        return True
    return any(_is_bucketing_call(n) for n in ast.walk(expr)
               if isinstance(n, ast.Call))


class ShapeChurnChecker:
    id = "C4-shape-churn"

    def __init__(self, shared=None):
        from jax_mapping.analysis.jax_hazards import _SharedRegistry
        self._shared = shared or _SharedRegistry()

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        registry = self._shared.get(modules)
        findings: List[Finding] = []
        for mod in modules:
            imports = A.import_table(mod.tree)
            for func, symbol, _cls in A.walk_functions(mod.tree):
                # Inside jitted bodies, shapes are trace-static Python
                # ints — churn is a CALLER-side hazard.
                if any(A.jit_decorator_info(d, imports) is not None
                       for d in getattr(func, "decorator_list", ())):
                    continue
                findings += self._scan(mod, func, symbol, imports,
                                       registry)
        return findings

    # -- dynamic-size taint --------------------------------------------------

    def _rhs_dynamic(self, value: ast.AST, imports: Dict[str, str],
                     tainted: Set[str]) -> Optional[bool]:
        if _sanitized(value):
            return False
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                if isinstance(n.func, ast.Name) \
                        and n.func.id in _DYNAMIC_SIZE_CALLS:
                    return True
                tgt = A.resolve(n.func, imports) or ""
                if tgt in _DYNAMIC_SIZE_NP:
                    return True
                if isinstance(n.func, ast.Attribute) \
                        and n.func.attr in _DYNAMIC_SIZE_METHODS:
                    return True
            elif isinstance(n, ast.Attribute) \
                    and n.attr in _DYNAMIC_SIZE_ATTRS:
                return True
            elif isinstance(n, ast.Name) and n.id in tainted:
                return True
        return None

    def _expr_dynamic(self, expr: ast.AST, imports: Dict[str, str],
                      tainted: Set[str]) -> bool:
        return self._rhs_dynamic(expr, imports, tainted) is True

    # -- the pass ------------------------------------------------------------

    def _scan(self, mod: SourceModule, func: ast.FunctionDef, symbol: str,
              imports: Dict[str, str], registry) -> List[Finding]:
        tainted: Set[str] = set()
        findings: List[Finding] = []

        def check_call(call: ast.Call) -> None:
            tgt = A.resolve_call_target(call, mod, imports)
            site = registry.get(tgt) if tgt else None
            if site is None:
                return
            params = site.params
            static = site.static_params
            for idx, arg in enumerate(call.args):
                pname = params[idx] if idx < len(params) else None
                if pname in static:
                    if self._expr_dynamic(arg, imports, tainted):
                        findings.append(mod.finding(
                            self.id, "error", arg, symbol,
                            f"static argument `{pname}` of jitted "
                            f"`{site.func.name}` derives from an "
                            "unbucketed runtime size — one XLA "
                            "compile per distinct value (recompile "
                            "storm); bucket it (2**k-style) before "
                            "the trace boundary"))
                else:
                    self._check_operand(mod, call, arg, symbol, site,
                                        imports, tainted, findings)
            for kw in call.keywords:
                if kw.arg in static \
                        and self._expr_dynamic(kw.value, imports, tainted):
                    findings.append(mod.finding(
                        self.id, "error", kw.value, symbol,
                        f"static argument `{kw.arg}` of jitted "
                        f"`{site.func.name}` derives from an unbucketed "
                        "runtime size — one XLA compile per distinct "
                        "value; bucket it before the trace boundary"))

        def on_stmt(stmt: ast.stmt) -> None:
            for call in A.statement_calls(stmt):
                check_call(call)

        # TaintWalk's default name propagation deliberately treats
        # .shape/len as trace-static; here they ARE the taint source,
        # so this checker runs its own ordered walk re-judging every
        # assignment through `_rhs_dynamic`.
        self._run_with_sizes(tainted, on_stmt, func.body, imports)
        return findings

    class _Walk:
        """Mutable taint-set handle for the ordered walk."""
        def __init__(self, tainted: Set[str], on_stmt):
            self.tainted = tainted
            self.on_stmt = on_stmt

    def _run_with_sizes(self, tainted: Set[str], on_stmt,
                        body: List[ast.stmt],
                        imports: Dict[str, str]) -> None:
        walk = self._Walk(tainted, on_stmt)
        self._run_body(walk, body, imports)

    def _run_body(self, walk: "_Walk", body: List[ast.stmt],
                  imports: Dict[str, str]) -> None:
        for stmt in body:
            walk.on_stmt(stmt)
            if isinstance(stmt, ast.Assign) or (
                    isinstance(stmt, ast.AnnAssign)
                    and stmt.value is not None):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                verdict = self._rhs_dynamic(stmt.value, imports,
                                            walk.tainted)
                for t in targets:
                    names = A.target_names(t)
                    if verdict:
                        walk.tainted |= names
                    else:
                        walk.tainted -= names
            elif isinstance(stmt, ast.AugAssign):
                if self._rhs_dynamic(stmt.value, imports, walk.tainted):
                    walk.tainted |= A.target_names(stmt.target)
            elif isinstance(stmt, ast.For):
                if self._rhs_dynamic(stmt.iter, imports, walk.tainted):
                    walk.tainted |= A.target_names(stmt.target)
                self._run_body(walk, stmt.body, imports)
                self._run_body(walk, stmt.orelse, imports)
            elif isinstance(stmt, (ast.While, ast.If)):
                self._run_body(walk, stmt.body, imports)
                self._run_body(walk, stmt.orelse, imports)
            elif isinstance(stmt, ast.With):
                self._run_body(walk, stmt.body, imports)
            elif isinstance(stmt, ast.Try):
                self._run_body(walk, stmt.body, imports)
                for h in stmt.handlers:
                    self._run_body(walk, h.body, imports)
                self._run_body(walk, stmt.orelse, imports)
                self._run_body(walk, stmt.finalbody, imports)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue

    def _check_operand(self, mod: SourceModule, call: ast.Call,
                       arg: ast.AST, symbol: str, site,
                       imports: Dict[str, str], tainted: Set[str],
                       findings: List[Finding]) -> None:
        """Traced operands sliced to a dynamic length: `f(arr[:n])`."""
        for sub in [n for n in ast.walk(arg)
                    if isinstance(n, ast.Subscript)]:
            slices = sub.slice.elts if isinstance(sub.slice, ast.Tuple) \
                else [sub.slice]
            for s in slices:
                if not isinstance(s, ast.Slice):
                    continue
                for bound in (s.lower, s.upper):
                    if bound is None or _sanitized(bound):
                        continue
                    if self._expr_dynamic(bound, imports, tainted):
                        findings.append(mod.finding(
                            self.id, "error", sub, symbol,
                            f"operand of jitted `{site.func.name}` "
                            "sliced to an unbucketed runtime length — "
                            "each distinct shape is one fresh XLA "
                            "compile; bucket/pad the length before "
                            "the trace boundary"))
                        return
