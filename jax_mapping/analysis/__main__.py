"""`python -m jax_mapping.analysis` — the lint CLI as a module entry
point, for environments that run the package from a checkout without
installed console scripts (CI containers, notebooks). Identical
arguments and exit-code contract as `jax-mapping-lint` (see cli.py)."""

import sys

from jax_mapping.analysis.cli import main

if __name__ == "__main__":
    sys.exit(main())
