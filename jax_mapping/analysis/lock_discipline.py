"""Family B — lock-discipline checkers for the threaded bridge layer.

The bridge's thread topology: the executor spins timers on one thread,
the bus delivers subscription callbacks inline on the *publisher's*
thread, HTTP handlers arrive on ThreadingHTTPServer workers, and every
node serializes its own callbacks behind `Node._cb_lock`. Each class
guards its shared state with an instance lock (`self._lock` /
`self._state_lock`) — safety rests on three conventions this module
checks mechanically:

B1 `B1-lock-order`      every thread acquires locks in one global
                        order. The checker builds a static acquisition
                        graph — nodes are `Class.attr` locks, edges are
                        "acquired B while holding A" from nested `with`
                        blocks and from `self.m()` calls inside a lock
                        body whose callee (transitively) acquires — and
                        reports any strongly-connected component
                        (= potential deadlock cycle).
B2 `B2-callback-lock`   no callback/publish under a lock: invoking
                        `*.callback(...)`, `*_cb(...)` or
                        `*.publish(...)` while holding a lock hands
                        control to arbitrary foreign code (bus delivery
                        is inline!) that may try to take the same lock.
B3 `B3-unguarded-write` state written without the lock that guards it
                        elsewhere: in a class that owns a lock, an
                        attribute both accessed under `with self.<lock>`
                        and *written* outside any lock body (outside
                        `__init__`) is a torn-read hazard. Deliberate
                        single-writer/GIL-atomic sites are baselined,
                        with the justification in the baseline note.
                        The checker understands the `_locked` helper
                        convention interprocedurally: a private method
                        whose every same-class call site runs with a
                        lock held (lexically, or via a caller that
                        itself qualifies) is walked as lock-held — but
                        a method that escapes as a value (callback
                        reference) or is reachable from any unlocked
                        site is not.

Known static blind spots (the runtime `lockwatch` recorder covers the
live stack where these matter): cross-*object* edges (`sub._offer`
under the bus lock), and `Node._cb_lock` chains created by inline bus
delivery across nodes.

`build_lock_graph(modules)` exposes the B1 graph so tests can validate
it against `lockwatch`-observed runtime orderings.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule

#: Condition-protocol methods that are lock-safe by design.
_LOCK_PROTOCOL = {"notify", "notify_all", "wait", "wait_for", "acquire",
                  "release", "locked"}
#: call names that hand control to foreign code.
_CALLBACK_ATTRS = {"callback", "publish"}


@dataclass
class LockGraph:
    #: "Class.attr" -> "Class.attr" acquisition-order edges, each with
    #: the (module, node, symbol) site where the edge was introduced.
    edges: Dict[Tuple[str, str], Tuple[SourceModule, ast.AST, str]] = \
        field(default_factory=dict)

    def edge_set(self) -> Set[Tuple[str, str]]:
        return set(self.edges)

    def sccs(self) -> List[List[str]]:
        """Cycle-forming lock sets: Tarjan SCCs of size > 1, plus
        self-loops."""
        graph: Dict[str, Set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on_stack: Set[str] = set()
        stack: List[str] = []
        out: List[List[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            for w in graph[v]:
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1 or v in graph[v]:
                    out.append(sorted(comp))

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        return out


def _lock_aliases(cls: "A.ClassInfo") -> Dict[str, str]:
    """Condition attrs constructed over a sibling lock share its
    identity: `self._not_empty = threading.Condition(self._lock)` means
    acquiring `_not_empty` IS acquiring `_lock`."""
    aliases = {attr: attr for attr in cls.lock_attrs}
    for meth in cls.methods.values():
        for node in ast.walk(meth):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.value, ast.Call):
                attr = A._self_attr(node.targets[0])
                if attr in cls.lock_attrs \
                        and cls.lock_attrs[attr] == "Condition" \
                        and node.value.args:
                    shared = A._self_attr(node.value.args[0])
                    if shared in cls.lock_attrs:
                        aliases[attr] = shared
    return aliases


class _ClassWalker:
    """Walks one class's methods tracking the held-lock stack; emits
    acquisition edges, callback-under-lock findings, and per-attribute
    guarded/unguarded access records."""

    def __init__(self, cls: "A.ClassInfo", graph: LockGraph,
                 checker_id_b2: Optional[str]):
        self.cls = cls
        self.graph = graph
        self.b2_id = checker_id_b2
        self.aliases = _lock_aliases(cls)
        self.b2: List[Tuple[ast.AST, str, str]] = []  # (site, symbol, lock)
        #: attr -> guarded access exists anywhere in the class
        self.guarded: Set[str] = set()
        #: (attr, site node, symbol) unguarded writes outside __init__
        self.unguarded_writes: List[Tuple[str, ast.AST, str]] = []
        self._acquires_cache: Dict[str, Set[str]] = {}
        #: True while walking a method that qualifies for the `_locked`
        #: helper convention (see _entry_locked_map)
        self._entry_locked_now = False

    def lock_name(self, attr: str) -> str:
        return f"{self.cls.name}.{self.aliases.get(attr, attr)}"

    def _with_lock_attr(self, item: ast.withitem) -> Optional[str]:
        attr = A._self_attr(item.context_expr)
        return attr if attr in self.cls.lock_attrs else None

    # transitive lock set a method acquires (for call-under-lock edges)
    def method_acquires(self, name: str,
                        _seen: Optional[Set[str]] = None) -> Set[str]:
        if name in self._acquires_cache:
            return self._acquires_cache[name]
        seen = _seen if _seen is not None else set()
        if name in seen or name not in self.cls.methods:
            return set()
        seen.add(name)
        out: Set[str] = set()
        meth = self.cls.methods[name]
        for node in ast.walk(meth):
            if isinstance(node, ast.With):
                for item in node.items:
                    attr = self._with_lock_attr(item)
                    if attr is not None:
                        out.add(self.lock_name(attr))
        for callee in A.self_calls(meth):
            out |= self.method_acquires(callee, seen)
        if _seen is None:
            self._acquires_cache[name] = out
        return out

    def walk(self) -> None:
        entry = self._entry_locked_map()
        for name, meth in self.cls.methods.items():
            self._entry_locked_now = entry.get(name, False)
            self._walk_body(meth.body, [], f"{self.cls.name}.{name}",
                            in_init=(name == "__init__"))
        self._entry_locked_now = False

    # -- the `_locked` helper convention (B3 interprocedural step) -------
    #
    # A private method whose EVERY same-class reference runs with a lock
    # held — lexically at the call site, or transitively because the
    # caller itself qualifies — executes under that lock at runtime even
    # though no `with` statement is visible in its own body. Treating
    # its attribute accesses as unguarded would force either inlining
    # every helper into the guarded block or baselining true positives,
    # and the zero-suppression tiers forbid the latter. Public and
    # dunder methods never qualify (they are entered from outside the
    # class), nor does a method that escapes as a value (a callback
    # reference is an unlocked entry point we cannot see).

    def _entry_locked_map(self) -> Dict[str, bool]:
        sites: Dict[str, List[Tuple[str, bool]]] = {}
        for name, meth in self.cls.methods.items():
            self._collect_sites(meth.body, False, name, sites)
        cand = {n for n in sites
                if n in self.cls.methods and n.startswith("_")
                and not n.startswith("__")}
        locked = {n: True for n in cand}
        changed = True
        while changed:          # monotone: True flips False, never back
            changed = False
            for n in cand:
                if locked[n] and not all(
                        lex or locked.get(caller, False)
                        for caller, lex in sites[n]):
                    locked[n] = False
                    changed = True
        return locked

    def _collect_sites(self, node, held: bool, caller: str,
                       sites: Dict[str, List[Tuple[str, bool]]]) -> None:
        if isinstance(node, list):
            for n in node:
                self._collect_sites(n, held, caller, sites)
            return
        if isinstance(node, ast.With):
            h = held or any(self._with_lock_attr(i) is not None
                            for i in node.items)
            self._collect_sites(node.items, held, caller, sites)
            self._collect_sites(node.body, h, caller, sites)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # nested defs run later, unheld
        if isinstance(node, ast.Call):
            m = A._self_attr(node.func)
            if m is not None and m in self.cls.methods:
                sites.setdefault(m, []).append((caller, held))
                for sub in ast.iter_child_nodes(node):
                    if sub is not node.func:
                        self._collect_sites(sub, held, caller, sites)
                return
        elif isinstance(node, ast.Attribute):
            m = A._self_attr(node)
            if m is not None and m in self.cls.methods:
                # `self._helper` escaping as a value: an entry point
                # whose lock posture we cannot see — count it unlocked.
                sites.setdefault(m, []).append((caller, False))
        for sub in ast.iter_child_nodes(node):
            self._collect_sites(sub, held, caller, sites)

    def _walk_body(self, body: List[ast.stmt], held: List[str],
                   symbol: str, in_init: bool) -> None:
        for stmt in body:
            self._visit(stmt, held, symbol, in_init)

    def _visit(self, node: ast.AST, held: List[str], symbol: str,
               in_init: bool) -> None:
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                attr = self._with_lock_attr(item)
                if attr is None:
                    continue
                lock = self.lock_name(attr)
                for h in held:
                    if h != lock:
                        self.graph.edges.setdefault(
                            (h, lock),
                            (self.cls.module, item.context_expr, symbol))
                acquired.append(lock)
            self._walk_body(node.body, held + acquired, symbol, in_init)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # nested defs run later, unheld
        # attribute accesses for B3
        for sub in ast.iter_child_nodes(node):
            self._visit(sub, held, symbol, in_init)
        if isinstance(node, ast.Call):
            self._visit_call(node, held, symbol)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else \
                [node.target]
            for t in targets:
                attr = self._store_attr(t)
                if attr is None or attr in self.cls.lock_attrs:
                    continue
                if held or self._entry_locked_now:
                    self.guarded.add(attr)
                elif not in_init:
                    self.unguarded_writes.append((attr, node, symbol))
        elif isinstance(node, ast.Attribute) \
                and (held or self._entry_locked_now):
            attr = A._self_attr(node)
            if attr is not None and attr not in self.cls.lock_attrs:
                self.guarded.add(attr)

    @staticmethod
    def _store_attr(target: ast.AST) -> Optional[str]:
        """self.X = / self.X[...] = / self.X.append is NOT a store —
        only direct attribute stores and subscript stores on self.X."""
        if isinstance(target, ast.Subscript):
            target = target.value
        return A._self_attr(target)

    def _visit_call(self, call: ast.Call, held: List[str],
                    symbol: str) -> None:
        # edges through same-class method calls made while holding
        m = A._self_attr(call.func)
        if m is not None and m in self.cls.methods and held:
            for lock in self.method_acquires(m):
                for h in held:
                    if h != lock:
                        self.graph.edges.setdefault(
                            (h, lock), (self.cls.module, call, symbol))
        # B2: callback / publish invoked while holding a lock
        if self.b2_id is None or not held:
            return
        name = None
        if isinstance(call.func, ast.Attribute):
            name = call.func.attr
        elif isinstance(call.func, ast.Name):
            name = call.func.id
        if name is None or name in _LOCK_PROTOCOL:
            return
        if name in _CALLBACK_ATTRS or name.endswith("_cb"):
            self.b2.append((call, symbol, held[-1]))


def _walk_all(modules: Sequence[SourceModule], b2: bool
              ) -> Tuple[LockGraph, List["_ClassWalker"]]:
    graph = LockGraph()
    walkers = []
    for mod in modules:
        for cls in A.collect_classes(mod):
            if not cls.lock_attrs:
                continue
            w = _ClassWalker(cls, graph, "B2-callback-lock" if b2 else None)
            w.walk()
            walkers.append(w)
    return graph, walkers


class _SharedWalk:
    """One `_walk_all` pass feeding all three B checkers. `all_checkers`
    hands the trio a shared instance so a full analysis run walks each
    locked class once, not three times; a checker constructed on its
    own (fixture tests) gets a private one. Re-keyed by the identity of
    the module set, so reuse across analyses stays correct."""

    def __init__(self):
        self._key = None
        self._result = None

    def get(self, modules: Sequence[SourceModule]
            ) -> Tuple[LockGraph, List["_ClassWalker"]]:
        key = tuple(id(m) for m in modules)
        if key != self._key:
            self._result = _walk_all(modules, b2=True)
            self._key = key
        return self._result


def build_lock_graph(modules: Sequence[SourceModule]) -> LockGraph:
    """The static acquisition-order graph (the B1 input), exposed for
    tests to validate against `lockwatch` runtime observations."""
    return _walk_all(modules, b2=False)[0]


class LockOrderChecker:
    id = "B1-lock-order"

    def __init__(self, shared: Optional[_SharedWalk] = None):
        self._shared = shared or _SharedWalk()

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        graph, _walkers = self._shared.get(modules)
        findings = []
        for comp in graph.sccs():
            comp_set = set(comp)
            sites = [(edge, site) for edge, site in graph.edges.items()
                     if edge[0] in comp_set and edge[1] in comp_set]
            for (a, b), (mod, node, symbol) in sorted(
                    sites, key=lambda e: (e[1][0].path,
                                          getattr(e[1][1], "lineno", 0))):
                findings.append(mod.finding(
                    self.id, "error", node, symbol,
                    f"lock-order cycle among {comp}: this site orders "
                    f"{a} -> {b}, another site orders the reverse — "
                    "potential deadlock"))
        return findings


class CallbackUnderLockChecker:
    id = "B2-callback-lock"

    def __init__(self, shared: Optional[_SharedWalk] = None):
        self._shared = shared or _SharedWalk()

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        _graph, walkers = self._shared.get(modules)
        findings = []
        for w in walkers:
            for call, symbol, lock in w.b2:
                name = (call.func.attr if isinstance(call.func,
                                                     ast.Attribute)
                        else call.func.id)
                findings.append(w.cls.module.finding(
                    self.id, "error", call, symbol,
                    f"`{name}(...)` invoked while holding {lock} — "
                    "bus delivery is inline, so this re-enters foreign "
                    "code under the lock"))
        return findings


class UnguardedWriteChecker:
    id = "B3-unguarded-write"

    def __init__(self, shared: Optional[_SharedWalk] = None):
        self._shared = shared or _SharedWalk()

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        _graph, walkers = self._shared.get(modules)
        findings = []
        for w in walkers:
            for attr, node, symbol in w.unguarded_writes:
                if attr in w.guarded:
                    lock = next(iter(w.cls.lock_attrs))
                    findings.append(w.cls.module.finding(
                        self.id, "warning", node, symbol,
                        f"`self.{attr}` written without a lock but "
                        f"accessed under `self.{lock}` elsewhere in "
                        f"{w.cls.name} — torn-read hazard (baseline "
                        "deliberate single-writer sites with a note)"))
        return findings
