"""Family A — JAX hazard checkers.

A1 `A1-host-sync`    host syncs (`np.asarray`, `np.array`, `float()`,
                     `.item()`, `.tolist()`) on traced values inside
                     jitted functions (error — breaks tracing or forces
                     a device round-trip per call), and on device values
                     inside per-tick bridge code (warning — each is a
                     blocking transfer in the hot loop; sanctioned
                     boundary sites live in the baseline).
A2 `A2-jit-hygiene`  jit-boundary hazards: Python `if`/`while` on traced
                     values (TracerBoolConversionError at best, silent
                     trace-time constant at worst), `for` over a traced
                     range (concretization), static_argnums out of
                     range, unhashable literals passed in static
                     positions at call sites of known jit entry points
                     (recompile storm / TypeError).
A3 `A3-dtype-drift`  float64 leaking toward TPU-path arrays: explicit
                     `np.float64`, `dtype=float`, and dtype-less
                     `np.array([...])` over float literals (NumPy
                     defaults to float64; x64-disabled JAX then inserts
                     a silent downcast per transfer).
A4 `A4-impure-jit`   impurity under trace: `time.*` / `random.*` /
                     `np.random.*` calls and `self.<attr>` mutation
                     inside jitted functions or their package-local
                     callees (executed once at trace time, then frozen
                     into the compiled program).

Hot-path roots for A1's per-tick rule are discovered, not configured:
any method registered via `self.create_timer(period, self.m)` plus
everything reachable from it through `self.m()` calls — so new nodes
are covered the day they gain a timer.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule

#: numpy conversion calls that synchronize device values onto the host.
_HOST_CONVERTERS = {"numpy.asarray", "numpy.array"}
#: method names that synchronize when invoked on a device array.
_SYNC_METHODS = {"item", "tolist"}


def _np_target(call: ast.Call, imports: Dict[str, str]) -> str:
    return A.resolve(call.func, imports) or ""


def _function_registry(modules: Sequence[SourceModule]
                       ) -> Dict[Tuple[str, str],
                                 Tuple[SourceModule, ast.FunctionDef]]:
    reg: Dict[Tuple[str, str], Tuple[SourceModule, ast.FunctionDef]] = {}
    for mod in modules:
        for func, _sym, cls in A.walk_functions(mod.tree):
            if cls is None and isinstance(func, ast.FunctionDef):
                reg[(mod.dotted, func.name)] = (mod, func)
    return reg


class _SharedRegistry:
    """One `build_jit_registry` pass feeding A1/A2/A4 (the analogue of
    `lock_discipline._SharedWalk`): `all_checkers` hands the trio a
    shared instance so a full analysis walks every module once for jit
    discovery, not three times; a checker constructed on its own gets a
    private one. Re-keyed by module-set identity."""

    def __init__(self):
        self._key = None
        self._registry = None

    def get(self, modules: Sequence[SourceModule]):
        key = tuple(id(m) for m in modules)
        if key != self._key:
            self._registry = A.build_jit_registry(modules)
            self._key = key
        return self._registry


class _Base:
    id = ""
    severity = "error"

    def __init__(self, shared: Optional[_SharedRegistry] = None):
        self._shared = shared or _SharedRegistry()

    def jit_registry(self, modules: Sequence[SourceModule]):
        return self._shared.get(modules)

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        raise NotImplementedError


# -- A1 ----------------------------------------------------------------------

class HostSyncChecker(_Base):
    id = "A1-host-sync"

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        registry = self.jit_registry(modules)
        findings: List[Finding] = []
        for mod in modules:
            imports = A.import_table(mod.tree)

            def jit_call(call: ast.Call) -> bool:
                tgt = A.resolve_call_target(call, mod, imports)
                return tgt is not None and tgt in registry

            # Inside jitted functions: any sync on a traced value.
            for site in registry.values():
                if site.module is not mod:
                    continue
                findings += self._scan(
                    mod, site.func, site.symbol, imports,
                    seeds=site.traced_params, severity="error",
                    context="inside @jax.jit", call_taints=jit_call,
                    call_sanitizes=None, flag_converters_always=False)

            # Per-tick hot paths: syncs on values produced by jit entry
            # points (device arrays crossing back to the host).
            for cls in A.collect_classes(mod):
                for name in self._hot_methods(cls):
                    meth = cls.methods[name]
                    findings += self._scan(
                        mod, meth, f"{cls.name}.{name}", imports,
                        seeds=set(), severity="warning",
                        context="in per-tick hot path",
                        call_taints=jit_call,
                        call_sanitizes=lambda c: _np_target(c, imports)
                        in _HOST_CONVERTERS,
                        flag_converters_always=False)
        return findings

    @staticmethod
    def _hot_methods(cls: "A.ClassInfo") -> Set[str]:
        """Timer callbacks plus their transitive same-class callees."""
        seen: Set[str] = set()
        frontier = [m for m in cls.timer_callbacks if m in cls.methods]
        while frontier:
            m = frontier.pop()
            if m in seen:
                continue
            seen.add(m)
            frontier += [c for c in A.self_calls(cls.methods[m])
                         if c in cls.methods and c not in seen]
        return seen

    def _scan(self, mod: SourceModule, func: ast.FunctionDef, symbol: str,
              imports: Dict[str, str], seeds: Set[str], severity: str,
              context: str, call_taints, call_sanitizes,
              flag_converters_always: bool) -> List[Finding]:
        findings: List[Finding] = []

        def on_stmt(stmt: ast.stmt, _tainted: Set[str]) -> None:
            for call in A.statement_calls(stmt):
                tgt = _np_target(call, imports)
                if tgt in _HOST_CONVERTERS and call.args and (
                        flag_converters_always
                        or walk.is_tainted(call.args[0])):
                    findings.append(mod.finding(
                        self.id, severity, call, symbol,
                        f"{tgt.replace('numpy.', 'np.')} on a "
                        f"device/traced value {context} forces a host "
                        "sync"))
                elif isinstance(call.func, ast.Name) \
                        and call.func.id == "float" and call.args \
                        and walk.is_tainted(call.args[0]):
                    findings.append(mod.finding(
                        self.id, severity, call, symbol,
                        f"float() on a device/traced value {context} "
                        "forces a host sync"))
                elif isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _SYNC_METHODS:
                    recv = call.func.value
                    base = A.receiver_base(recv)
                    # Name-rooted receivers go by the taint set; a
                    # call-rooted chain (`jnp.sum(x).item()`, base is
                    # None) is judged by the expression's own names —
                    # the most common one-line form of the hazard.
                    if (base is not None and base in walk.tainted) or \
                            (base is None and walk.is_tainted(recv)):
                        findings.append(mod.finding(
                            self.id, severity, call, symbol,
                            f".{call.func.attr}() on a device/traced "
                            f"value {context} forces a host sync"))

        walk = A.TaintWalk(tainted=set(seeds), call_taints=call_taints,
                           call_sanitizes=call_sanitizes, on_stmt=on_stmt)
        walk.run(func.body)
        return findings


# -- A2 ----------------------------------------------------------------------

_traced_test_names = A.traced_names


class JitHygieneChecker(_Base):
    id = "A2-jit-hygiene"

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        registry = self.jit_registry(modules)
        findings: List[Finding] = []
        for site in registry.values():
            mod = site.module
            nparams = len(site.params)
            for i in site.static_argnums:
                if not 0 <= i < nparams:
                    findings.append(mod.finding(
                        self.id, "error", site.decorator, site.symbol,
                        f"static_argnums index {i} out of range for "
                        f"{nparams} parameters"))
            findings += self._scan_body(site)
        findings += self._scan_call_sites(modules, registry)
        return findings

    def _scan_body(self, site: "A.JitSite") -> List[Finding]:
        mod, symbol = site.module, site.symbol
        findings: List[Finding] = []

        def on_stmt(stmt: ast.stmt, tainted: Set[str]) -> None:
            if isinstance(stmt, (ast.If, ast.While)):
                bad = _traced_test_names(stmt.test) & tainted
                if bad:
                    kind = "while" if isinstance(stmt, ast.While) else "if"
                    findings.append(mod.finding(
                        self.id, "error", stmt, symbol,
                        f"Python `{kind}` on traced value(s) "
                        f"{sorted(bad)} inside @jax.jit — use lax.cond/"
                        "lax.while_loop or jnp.where"))
            elif isinstance(stmt, ast.For) \
                    and isinstance(stmt.iter, ast.Call) \
                    and isinstance(stmt.iter.func, ast.Name) \
                    and stmt.iter.func.id == "range":
                bad = set()
                for arg in stmt.iter.args:
                    bad |= _traced_test_names(arg) & tainted
                if bad:
                    findings.append(mod.finding(
                        self.id, "error", stmt, symbol,
                        f"Python `for` over range of traced value(s) "
                        f"{sorted(bad)} inside @jax.jit — concretization "
                        "error or per-shape unroll"))

        walk = A.TaintWalk(tainted=set(site.traced_params),
                           on_stmt=on_stmt)
        walk.run(site.func.body)
        return findings

    def _scan_call_sites(self, modules: Sequence[SourceModule],
                         registry) -> List[Finding]:
        findings: List[Finding] = []
        for mod in modules:
            imports = A.import_table(mod.tree)
            for func, symbol, _cls in A.walk_functions(mod.tree):
                for call in [n for n in ast.walk(func)
                             if isinstance(n, ast.Call)]:
                    tgt = A.resolve_call_target(call, mod, imports)
                    site = registry.get(tgt) if tgt else None
                    if site is None:
                        continue
                    for i in site.static_argnums:
                        if i < len(call.args) and isinstance(
                                call.args[i],
                                (ast.List, ast.Dict, ast.Set)):
                            findings.append(mod.finding(
                                self.id, "error", call.args[i], symbol,
                                f"unhashable literal passed in static "
                                f"position {i} of jitted "
                                f"`{site.func.name}` — TypeError at "
                                "call time (or a recompile per value)"))
        return findings


# -- A3 ----------------------------------------------------------------------

#: path segments marking modules whose arrays feed the device path.
_TPU_PATH_SEGMENTS = {"ops", "models", "parallel", "native", "bridge",
                      "sim"}
#: numpy constructors whose dtype defaults to float64 over float data.
_F64_DEFAULT_CTORS = {"numpy.array", "numpy.asarray", "numpy.full"}


def _has_float_literal(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Constant) and isinstance(n.value, float)
               for n in ast.walk(node))


class DtypeDriftChecker(_Base):
    id = "A3-dtype-drift"

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in modules:
            if not _TPU_PATH_SEGMENTS & set(mod.path.split("/")[:-1]):
                continue
            imports = A.import_table(mod.tree)
            sym_of = {}
            for func, symbol, _cls in A.walk_functions(mod.tree):
                for n in ast.walk(func):
                    sym_of.setdefault(id(n), symbol)
            for node in ast.walk(mod.tree):
                symbol = sym_of.get(id(node), "")
                if isinstance(node, ast.Attribute) \
                        and node.attr == "float64":
                    tgt = A.resolve(node, imports) or ""
                    if tgt in ("numpy.float64", "jax.numpy.float64"):
                        findings.append(mod.finding(
                            self.id, "warning", node, symbol,
                            "explicit float64 in a TPU-path module — "
                            "x64-disabled JAX downcasts per transfer; "
                            "use float32 (or baseline a deliberate "
                            "host-side use)"))
                elif isinstance(node, ast.Call):
                    findings += self._check_call(mod, node, symbol,
                                                 imports)
        return findings

    def _check_call(self, mod, call: ast.Call, symbol: str,
                    imports) -> List[Finding]:
        out = []
        for kw in call.keywords:
            if kw.arg == "dtype" and isinstance(kw.value, ast.Name) \
                    and kw.value.id == "float":
                out.append(mod.finding(
                    self.id, "warning", kw.value, symbol,
                    "dtype=float is float64 — name the width "
                    "(np.float32) in TPU-path code"))
        tgt = A.resolve(call.func, imports) or ""
        if tgt in _F64_DEFAULT_CTORS and call.args \
                and isinstance(call.args[0], (ast.List, ast.Tuple)) \
                and _has_float_literal(call.args[0]) \
                and len(call.args) < 2 \
                and not any(kw.arg == "dtype" for kw in call.keywords):
            out.append(mod.finding(
                self.id, "warning", call, symbol,
                f"{tgt.replace('numpy.', 'np.')} over float literals "
                "without dtype defaults to float64 in a TPU-path "
                "module"))
        return out


# -- A4 ----------------------------------------------------------------------

_IMPURE_PREFIXES = ("time.", "random.", "numpy.random.")


class ImpureJitChecker(_Base):
    id = "A4-impure-jit"

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        registry = self.jit_registry(modules)
        functions = _function_registry(modules)
        findings: List[Finding] = []
        seen: Set[Tuple[str, str]] = set()
        # BFS from each jit site through package-local callees: trace
        # time runs the whole Python call tree, so impurity anywhere
        # beneath the jit boundary freezes into the compiled program.
        frontier: List[Tuple[SourceModule, ast.FunctionDef, str, int]] = [
            (s.module, s.func, s.symbol, 0) for s in registry.values()]
        while frontier:
            mod, func, symbol, depth = frontier.pop()
            key = (mod.dotted, symbol)
            if key in seen:
                continue
            seen.add(key)
            imports = A.import_table(mod.tree)
            findings += self._scan(mod, func, symbol, imports)
            if depth >= 2:
                continue
            for call in [n for n in ast.walk(func)
                         if isinstance(n, ast.Call)]:
                tgt = A.resolve_call_target(call, mod, imports)
                if tgt and tgt in functions and tgt not in registry:
                    cmod, cfunc = functions[tgt]
                    frontier.append((cmod, cfunc, cfunc.name, depth + 1))
        return findings

    def _scan(self, mod, func, symbol, imports) -> List[Finding]:
        out = []
        for node in ast.walk(func):
            if isinstance(node, ast.Call):
                tgt = A.resolve(node.func, imports) or ""
                if tgt.startswith(_IMPURE_PREFIXES):
                    out.append(mod.finding(
                        self.id, "error", node, symbol,
                        f"`{tgt}` under jit runs ONCE at trace time and "
                        "is frozen into the compiled program"))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if A._self_attr(t) is not None:
                        out.append(mod.finding(
                            self.id, "error", node, symbol,
                            "mutation of `self` under jit happens at "
                            "trace time only — the compiled program "
                            "never repeats it"))
        return out
