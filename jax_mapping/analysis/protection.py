"""The lock-protection map: which lock guards which correlated state.

The B-family checkers *derive* lock/state relations from syntax (any
attr touched under ``with self.<lock>``). That is the right default for
"is this write guarded at all" (B3), but two hazard families need more
than derivation can give:

* **Snapshot tears (C2)** are about *correlation*: ``poses`` and
  ``grid`` are each individually guarded, yet reading them in two
  separate lock regions produces a pose/grid pairing no writer ever
  created (the ``publish_frontiers`` tear fixed in PR 6). Which fields
  form one consistent snapshot is a *design fact*, not a syntactic one
  — so it is declared here, reviewed like code.
* **The dynamic race detector (racewatch)** implements Eraser's lockset
  refinement, which needs to know which fields are *supposed* to be
  lock-protected shared state (fields deliberately read lock-free by
  the /status counter convention must not be watched — Eraser would
  correctly empty their candidate lockset and incorrectly call it a
  bug).

One map feeds both: a :class:`LockGroup` names a class, the lock
attribute, and the set of instance fields that form one correlated
snapshot under it. `REPO_PROTECTION` is the committed map for this
repo's bridge/serving classes; checkers and racewatch default to it
but accept a custom list so fixture tests declare their own.

Curation rules (enforced by tests/test_analysis_selfcheck.py):

* every named class must exist in the package and own the named lock;
* every named field must be assigned somewhere in that class;
* fields read lock-free BY DESIGN (monotonic counters: `map_revision`
  via `serving_revision()`, `n_images_fused`, tick counters) are listed
  in `lockfree_ok`, NOT in `fields` — the C2 checker still treats their
  *in-region* reads as part of the snapshot, but racewatch must not
  watch them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Sequence


@dataclass(frozen=True)
class LockGroup:
    """One correlated-snapshot declaration.

    `cls`: class name (matches `ClassInfo.name`).
    `lock_attr`: the instance lock attribute guarding the group.
    `fields`: instance attributes forming ONE consistent snapshot —
        reading two of them in two separate atomic sections is a tear,
        and EVERY access must hold the lock (racewatch instruments
        exactly these).
    `lockfree_ok`: attributes in the same consistency story whose
        design sanctions lock-free accesses — monotonic counters read
        by the /status convention, set-once references None-checked
        before locking, single-writer fields whose owning thread reads
        its own state bare (the baselined-B3 discipline). C2 counts
        their in-region reads as part of the snapshot; racewatch must
        NOT watch them (Eraser would empty their candidate lockset and
        report the *convention*).
    `extra_locks`: further lock attributes racewatch must instrument so
        held-locksets are accurate (e.g. the TileStore's
        `_refresh_lock`, under which `_install` legitimately reads hash
        state before committing under `_lock`).
    """
    cls: str
    lock_attr: str
    fields: FrozenSet[str]
    lockfree_ok: FrozenSet[str] = frozenset()
    extra_locks: FrozenSet[str] = frozenset()

    @property
    def all_fields(self) -> FrozenSet[str]:
        return self.fields | self.lockfree_ok

    def watchable_fields(self) -> FrozenSet[str]:
        """What racewatch instruments: strictly lock-guarded fields."""
        return self.fields


def group(cls: str, lock_attr: str, fields: Sequence[str],
          lockfree_ok: Sequence[str] = (),
          extra_locks: Sequence[str] = ()) -> LockGroup:
    return LockGroup(cls=cls, lock_attr=lock_attr,
                     fields=frozenset(fields),
                     lockfree_ok=frozenset(lockfree_ok),
                     extra_locks=frozenset(extra_locks))


#: The committed map. Each entry documents a consistency contract the
#: code comments already state in prose; a PR that changes the contract
#: must change this map in the same diff (the selfcheck pins existence
#: of every class/lock/field so renames can't silently orphan a row).
REPO_PROTECTION: List[LockGroup] = [
    # The 2D mapper's publish/serving snapshot: poses, shared grid,
    # revision and the dirty-tile bookkeeping move together — the PR 6
    # tear fix put all four under ONE _state_lock section. `states` is
    # single-writer (the tick thread reads its own entries bare, the
    # baselined-B3 `_prev_paired` discipline) and `map_revision` is a
    # /status-convention counter: both are snapshot members for C2 but
    # out of racewatch's scope.
    group("MapperNode", "_state_lock",
          ["shared_grid", "_dirty_tiles"],
          lockfree_ok=["map_revision", "states", "_tile_rev",
                       # Serving restart epoch: set once before the
                       # replacement node serves (launch.restart_mapper),
                       # then read-only. Decay clock: tick-thread-only
                       # state, the _prev_paired single-writer
                       # discipline (its grid swap runs under
                       # _state_lock like every install).
                       "restart_epoch", "_decay_ticks"],
          extra_locks=["_dirty_lock"]),
    # Scripted world dynamics (scenarios/dynamics.py): the door/crowd
    # registries and the change flag move together — FaultPlan mutators
    # and the SimNode composer may live on different threads in
    # realtime stacks. n_recomposes is a /status-convention counter.
    group("WorldDynamics", "_lock",
          ["_door_closed", "_crowds", "_dirty"],
          lockfree_ok=["n_recomposes"]),
    # Rendezvous merger (scenarios/rendezvous.py): the verification
    # streak is the guarded correlated state; the published merge
    # result is single-writer (the stack-driving thread) and set-once —
    # post-merge readers take it bare by design, like the mapper's
    # states.
    group("RendezvousMerger", "_lock",
          ["_streak", "n_attempts", "n_accepted"],
          lockfree_ok=["transform", "merged_grid", "merged_states",
                       "merged"]),
    # The voxel mapper's grid/revision pair (the PR 4 ordering hazard)
    # plus the keyframe ring the closure re-fuse reads with them.
    group("VoxelMapperNode", "_lock",
          ["grid", "_keyframes"],
          lockfree_ok=["map_revision", "n_images_fused"]),
    # Tile store: bytes, stamps, hash state and the store revision are
    # installed atomically — a reader pairing tiles from one install
    # with the revision of another would violate the no-stale-serve
    # contract in serving/tiles.py's module docstring. `_refresh_lock`
    # is instrumented too: `_install` legitimately reads `_hashes`
    # under it alone (single-flighted), so without it in the lockset
    # the candidate for `_hashes` empties spuriously.
    group("TileStore", "_lock",
          ["_tiles", "_hashes", "_level_sizes", "revision"],
          extra_locks=["_refresh_lock"]),
    # Event channel: subscriber list + the closed-subscriber drop
    # carry-over (n_dropped_total must stay Prometheus-monotonic).
    group("EventChannel", "_lock",
          ["_subs", "_n_dropped_closed"]),
    # Per-client event mailbox: queue contents and the closed flag.
    group("EventSubscription", "_lock",
          ["_queue", "_closed"]),
    # Causal tracer (obs/trace.py): the span ring, the ever-recorded
    # counter (also the per-span `seq` stamp `/trace?since=` filters
    # on) and the per-scope sequence table mutate together — spans are
    # emitted from the bus delivery, mapper tick, brain tick AND HTTP
    # handler threads at once, which is exactly the cross-thread
    # emission the obs racewatch gate hammers (tests/test_obs.py).
    group("Tracer", "_lock",
          ["_spans", "n_spans", "_seq"]),
    # Flight recorder (obs/recorder.py): event ring + counter move
    # together under `_lock`; the dump bookkeeping is read lock-free by
    # design (MissionReport links `dumps` basenames post-mission, the
    # /status counter convention), and the configure() targets are
    # re-pointed between stacks but always under the lock.
    group("FlightRecorder", "_lock",
          ["_ring", "n_events", "_dump_dir", "_tracer", "_dump_seq",
           "_pipeline"],
          lockfree_ok=["n_dumps", "dumps"]),
    # Pipeline latency ledger (obs/pipeline.py): the pending waypoint
    # table, hop histograms, sample windows, record ring and the
    # last-install/last-delivered marks mutate together under `_lock`
    # from the mapper tick thread (installed/notified), HTTP workers
    # (encoded on tile-store refresh, delivered on /tiles responses)
    # and the tenancy stepping thread at once — exactly the
    # cross-thread stamp emission the ledger racewatch gate hammers
    # (tests/test_obs.py). `n_stamps` is the setattr write witness
    # (container mutation records as a read — the documented racewatch
    # limit); the completion counters read lock-free by the /status
    # convention.
    group("PipelineLedger", "_lock",
          ["_pending", "_hists", "_samples", "_records", "_ages",
           "_last_install_tick", "_last_delivered", "_delivered_epoch",
           "_tick", "_notified_rev", "_encoded_rev", "n_stamps"],
          lockfree_ok=["n_completed", "n_evicted"]),
    # Freshness SLO engine (obs/slo.py): per-objective window state
    # and the alert history move together — the mapper tick thread
    # evaluates while HTTP workers read status()/metric_families().
    group("SloEngine", "_lock",
          ["_objs", "_alerts", "n_evaluations"]),
    # Declarative /metrics registry (obs/registry.py): the source list
    # is append-only under `_lock`; render() snapshots it there, then
    # collects outside (no foreign collector code under our lock).
    group("MetricsRegistry", "_lock",
          ["_sources"]),
    # Dispatch profiler (obs/devprof.py): the per-function profile
    # table mutates under `_lock` from every thread that dispatches a
    # wrapped jitted function at once — mapper tick, HTTP workers
    # (serving tile hashing), test drivers — exactly the cross-thread
    # emission the devprof racewatch gate hammers (tests/test_obs.py).
    # `_bindings`/`installed` are install-time state serialized by the
    # module-level _INSTALL_LOCK (not an instance attribute, so out of
    # racewatch's instance scope — the lockfree_ok escape documents
    # that, it does not sanction bare mutation).
    group("DispatchProfiler", "_lock",
          ["_profiles"],
          lockfree_ok=["_bindings", "installed"]),
    # Cost ledger (obs/ledger.py): ONE keyed structure holds both the
    # reservation (None entry, AOT compile in flight) and the finished
    # cost entries — deliberately a single field so there is no
    # correlated pair to tear across collect()'s two lock sections
    # (the C2 class this layout exists to avoid).
    group("CostLedger", "_lock",
          ["_collected"]),
    # Staged warm-up state machine (resilience/warmup.py): the stage,
    # the warmed-entry log and the report install together at each
    # transition; HTTP workers read snapshot()/state() while the
    # restarting step thread moves the machine — exactly the
    # cross-thread window the warm-up racewatch gate hammers. The
    # wiring references (cache/devprof/budget_path) are set-once at
    # construction, read-only after (the lockfree_ok convention).
    group("StagedWarmup", "_lock",
          ["_state", "_warmed", "_report"],
          lockfree_ok=["cache", "devprof", "budget_path"]),
    # Compile-cache manager (io/compile_cache.py): wipe refcount +
    # counters move together under `_lock` (a cache_wipe window racing
    # a status read must never tear refs from counts); `enabled` and
    # `fingerprint` are set-once-per-enable flags read bare by the
    # status convention, file I/O runs outside the lock entirely.
    group("CompileCacheManager", "_lock",
          ["_wipe_refs", "_counts"],
          lockfree_ok=["enabled", "fingerprint"]),
    # Tenant control plane (tenancy/controlplane.py): the mission
    # registry, lane order, live batch, warmed-bucket set, per-tenant
    # tile stores and the lifecycle counters form ONE consistent
    # snapshot under `_lock` — admissions/evictions from operator or
    # HTTP threads race the stepping thread, which is exactly the
    # cross-thread churn the tenancy racewatch gate hammers
    # (tests/test_tenancy.py), joined in this PR by the lane-health
    # ladder, the poison set and the quarantine/admission counters —
    # the sentinel fold and the /status reader race across threads
    # (tests/test_tenant_containment.py's racewatch gate). The wiring
    # references (cfg, world_res_m, checkpoint_dir, warmup, pipeline,
    # _journal) are set-once at construction, read-only after (the
    # StagedWarmup convention; the journal's own file state is only
    # ever touched under `_lock`).
    group("TenantControlPlane", "_lock",
          ["_missions", "_order", "_prev_order", "_batch",
           "_warmed_buckets", "_tile_stores", "_last_diag",
           "_lanehealth", "_poisoned", "_admissions_in_flight",
           "n_admitted", "n_evicted", "n_suspended", "n_resumed",
           "n_prewarms", "n_ticks", "n_compactions",
           "n_quarantined", "n_admissions_rejected"],
          lockfree_ok=["cfg", "world_res_m", "checkpoint_dir",
                       "warmup", "pipeline", "_journal"]),
    # Warm dispatch pool (io/compile_cache.py): the entry table and its
    # serve/fallthrough/drop counters mutate together from every thread
    # that dispatches a wrapped entry point; `_bindings`/`installed`
    # are install-time state serialized by the module _INSTALL_LOCK
    # (the DispatchProfiler escape, documented not sanctioned).
    group("WarmPool", "_lock",
          ["_entries", "n_served", "n_fallthrough", "n_dropped"],
          lockfree_ok=["_bindings", "installed"]),
    # Sliding-window world store (world/store.py): the host LRU, the
    # away-set (the serving evicted-marker mask), the in-flight
    # prefetch table and the admission generation stamp mutate
    # together under `_lock` — the mapper tick thread evicts and
    # rehydrates while HTTP workers compose serving mosaics and read
    # /status, exactly the evict-vs-serve pair the world racewatch
    # gate hammers (tests/test_world.py). `origin_tile` and
    # `decay_epoch` are tick-thread single-writer (shift()/
    # note_decay_pass() run only on the mapper tick, which also owns
    # the device grid; foreign readers take the point-in-time value by
    # the /status convention); `eviction_epoch` bumps under the lock
    # but is read bare as the serving ETag suffix; the schedule log is
    # appended from both in- and out-of-lock sites by design (the
    # shift note stamps on the single-writer tick thread). `spill` and
    # `governor` are set-once wiring references.
    group("WorldStore", "_lock",
          ["_host", "_away", "_pending", "_gen"],
          lockfree_ok=["origin_tile", "decay_epoch", "eviction_epoch",
                       "schedule", "n_schedule_events", "n_shifts",
                       "n_evictions", "n_rehydrated_host",
                       "n_rehydrated_disk", "n_lost",
                       "n_corrupt_spills", "spill", "governor"]),
    # Memory-pressure governor (world/governor.py): its own `_lock`
    # guards only the named-hold table (FaultPlan threads arm/clear
    # squeezes while the tick thread reads the worst-of). The rung and
    # the shed counters are serialized by the STORE's `_lock` instead
    # (observe()/_shed() run only inside WorldStore lock sections) and
    # read bare by /status — out of this lock's racewatch scope, same
    # as the DispatchProfiler's module-lock escape.
    group("MemoryGovernor", "_lock",
          ["_pressure"],
          lockfree_ok=["rung", "n_spills", "n_drops", "n_coarsened",
                       "n_refused", "n_rung_changes", "cfg"]),
    # Disk spill tier (world/spill.py): the offset index is the
    # guarded state — eviction appends from the tick thread while
    # prefetch threads seek-read and chaos rewrites frames. `_f` is
    # opened once at (single-threaded) construction and thereafter a
    # read-only reference whose file OPERATIONS serialize under
    # `_lock`; the read/corrupt counters follow the /status
    # convention (n_reads deliberately increments outside the lock —
    # a monotonic gauge, not snapshot state).
    group("SpillStore", "_lock",
          ["_index"],
          lockfree_ok=["_f", "n_appends", "n_reads",
                       "n_corrupt_reads", "n_truncated_bytes"]),
]


def groups_by_class(protection: Sequence[LockGroup] = None
                    ) -> Dict[str, List[LockGroup]]:
    out: Dict[str, List[LockGroup]] = {}
    for g in (REPO_PROTECTION if protection is None else protection):
        out.setdefault(g.cls, []).append(g)
    return out
