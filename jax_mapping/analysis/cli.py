"""`jax-mapping-lint` — run the repo's static-analysis pass.

    jax-mapping-lint jax_mapping/                 # full pass, committed
                                                  # baseline applied
    jax-mapping-lint --no-baseline jax_mapping/   # everything, raw
    jax-mapping-lint --write-baseline jax_mapping/  # accept current
                                                  # findings (ratchet)
    jax-mapping-lint --format json jax_mapping/   # machine-readable
    jax-mapping-lint --format github jax_mapping/ # CI annotations

Also invocable as `python -m jax_mapping.analysis` (the module entry
point mirrors the console script for environments without installed
scripts).

Exit-code contract (stable; CI consumers branch on it):

    0  clean — every finding baselined (or none at all)
    1  findings — at least one NON-baselined finding was reported
    2  internal/usage error — bad flags, unreadable paths, syntax
       errors in analyzed sources, corrupt baseline; NEVER used for
       findings, so a pipeline can distinguish "the code is dirty"
       from "the linter could not run"

`--format github` emits one `::error file=...,line=...::message`
workflow-command annotation per non-baselined finding (GitHub renders
them inline on the PR diff), followed by the usual summary on stderr.

The tier-1 gate (`tests/test_analysis_selfcheck.py`) is exactly "exit
code 0 over `jax_mapping/` with the committed baseline".
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from jax_mapping.analysis.core import (
    Baseline, all_checkers, analyze_modules, default_baseline_path,
    load_package_modules, load_paths,
)


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="jax-mapping-lint",
        description="JAX hazard + lock-discipline static analysis for "
                    "jax_mapping.")
    p.add_argument("paths", nargs="*",
                   help="files/directories to analyze (default: the "
                        "installed jax_mapping package)")
    p.add_argument("--baseline", default=None, metavar="JSON",
                   help="baseline file (default: the committed "
                        "analysis/baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, baselined or not")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline "
                        "file and exit 0")
    p.add_argument("--format", choices=("text", "json", "github"),
                   default="text")
    p.add_argument("--checker", action="append", default=None,
                   metavar="ID", help="run only these checker ids "
                   "(repeatable), e.g. --checker B1-lock-order")
    p.add_argument("--list-checkers", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _parser().parse_args(argv)
    checkers = all_checkers()
    if args.list_checkers:
        for c in checkers:
            print(c.id)
        return 0
    if args.checker:
        known = {c.id for c in checkers}
        unknown = set(args.checker) - known
        if unknown:
            print(f"unknown checker id(s): {sorted(unknown)}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2
        checkers = [c for c in checkers if c.id in args.checker]

    try:
        modules = (load_paths(args.paths) if args.paths
                   else load_package_modules())
    except (OSError, SyntaxError) as e:
        print(f"jax-mapping-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    baseline = None
    try:
        if not args.no_baseline and not args.write_baseline \
                and os.path.exists(baseline_path):
            baseline = Baseline.load(baseline_path)
    except (OSError, ValueError) as e:       # ValueError covers bad JSON
        print(f"jax-mapping-lint: baseline {baseline_path}: {e}",
              file=sys.stderr)
        return 2

    # --write-baseline merge preflight BEFORE the (expensive) analysis:
    # a corrupt existing baseline must refuse immediately, not after
    # seconds of checker work it will then throw away.
    existing = None
    if args.write_baseline and os.path.exists(baseline_path):
        try:
            existing = Baseline.load(baseline_path).suppressions
        except (OSError, ValueError) as e:
            print(f"jax-mapping-lint: baseline {baseline_path}: {e} "
                  "— refusing to overwrite what cannot be merged",
                  file=sys.stderr)
            return 2

    res = analyze_modules(modules, baseline, checkers)

    if args.write_baseline:
        # Merge, never clobber: keep the notes of entries that are
        # still live, and keep entries this scoped run could not have
        # re-observed (filtered-out checkers / unanalyzed files) —
        # otherwise `--write-baseline --checker B1-lock-order` would
        # silently delete every A-family suppression.
        notes, keep = {}, []
        if existing is not None:
            ids = {c.id for c in checkers}
            analyzed = {m.path for m in modules}
            # An entry may be dropped (trusted to re-appear as a
            # finding if still valid) only when this run could have
            # re-observed it: its checker ran, its file was analyzed,
            # and the run had full cross-module context — a subset run
            # finds strictly less (the A checkers need the package-wide
            # jit registry) and must not destroy entries it cannot see.
            full_context = {s["path"] for s in existing} <= analyzed
            for s in existing:
                key = (s["checker"], s["path"], s.get("symbol", ""),
                       s.get("code", ""))
                if full_context and s["checker"] in ids \
                        and s["path"] in analyzed:
                    if s.get("note"):
                        notes[key] = s["note"]
                else:
                    keep.append(s)
        Baseline.dump(res.all_findings, baseline_path, notes=notes,
                      keep=keep)
        print(f"wrote {len(res.all_findings) + len(keep)} "
              f"suppression(s) to {baseline_path}")
        return 0

    if args.format == "github":
        # GitHub workflow commands: one annotation per finding, pinned
        # to file+line so the PR diff shows it inline. Newlines and
        # the %/CR/LF command metacharacters are escaped per the
        # workflow-command spec; the summary goes to stderr so stdout
        # stays machine-consumable.
        def esc(s: str) -> str:
            return (s.replace("%", "%25").replace("\r", "%0D")
                    .replace("\n", "%0A"))

        for f in res.findings:
            level = "error" if f.severity == "error" else "warning"
            print(f"::{level} file={esc(f.path)},line={f.line},"
                  f"title={esc(f.checker)}::{esc(f.message)}")
        print(f"{res.n_files} files: {len(res.findings)} new "
              f"finding(s), {len(res.baselined)} baselined",
              file=sys.stderr)
        return 1 if res.findings else 0

    if args.format == "json":
        print(json.dumps({
            "files": res.n_files,
            "findings": [vars(f) for f in res.findings],
            "baselined": [vars(f) for f in res.baselined],
            "unused_suppressions": res.unused_suppressions,
        }, indent=1))
        return 1 if res.findings else 0

    for f in res.findings:
        print(f.format())
    for s in res.unused_suppressions:
        print(f"note: unused baseline suppression: {s['checker']} "
              f"{s['path']} [{s.get('symbol', '')}] — ratchet it out")
    print(f"{res.n_files} files: {len(res.findings)} new finding(s), "
          f"{len(res.baselined)} baselined"
          + (f", {len(res.unused_suppressions)} unused suppression(s)"
             if res.unused_suppressions else ""))
    return 1 if res.findings else 0


if __name__ == "__main__":
    sys.exit(main())
