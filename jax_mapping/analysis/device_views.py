"""C3 — mutation of read-only `np.asarray` device views.

`np.asarray(<jax device array>)` does NOT copy: on CPU backends it
returns a zero-copy view of the device buffer with
``flags.writeable == False``. Any later in-place write raises
``ValueError: assignment destination is read-only`` — but only on the
code path that actually writes, which is how the PR 6 gotcha (the
incremental frontier pipeline's tile-observed mask) survived review:
the mutation sat behind a fault-injection branch. The fix is always the
same: ``np.array(...)`` (or ``.copy()``) when the host needs to write.

The checker runs one ordered taint pass per function:

* **device taint**: values produced by calls into the package's jit
  registry, by ``jax.*``/``jnp.*`` calls, or by attribute calls that
  resolve through a class's module-alias table (``self._V = V`` in
  ``__init__`` makes ``self._V.height_map(...)`` resolve to
  ``jax_mapping.ops.voxel.height_map``) — the same name-convention
  resolution the A family uses.
* **view taint**: ``np.asarray(x)`` of a device-tainted ``x``.
  Subscripts of a view are views (`depths[k]` of a read-only stack is
  read-only); ``np.array(x)`` / ``x.copy()`` / ``.astype(...)`` clear
  both taints (fresh writable buffer).
* **flagged sinks** on view-tainted names: subscript stores, augmented
  assignment, in-place methods (`fill`, `sort`, `put`, ...),
  ``np.copyto(view, ...)``, and ``out=view`` keywords.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule

#: ndarray methods that write through the receiver.
_INPLACE_METHODS = {"fill", "sort", "partition", "put", "itemset",
                    "setfield", "resize"}
#: calls that return a FRESH writable array (clear both taints).
_COPYING_CALLS = {"numpy.array", "numpy.ascontiguousarray",
                  "numpy.copy"}
_COPYING_METHODS = {"copy", "astype"}


def class_module_aliases(cls: "A.ClassInfo",
                         imports: Dict[str, str]) -> Dict[str, str]:
    """`self.<attr>` -> dotted module for `self._V = V`-style stashes
    of imported modules on the instance (incl. tuple assigns:
    `self._V, self._jnp = V, jnp`)."""
    out: Dict[str, str] = {}

    def record(target: ast.AST, value: ast.AST) -> None:
        attr = A._self_attr(target)
        if attr is not None and isinstance(value, ast.Name) \
                and value.id in imports:
            out[attr] = imports[value.id]

    for meth in cls.methods.values():
        for node in ast.walk(meth):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if isinstance(t, ast.Tuple) \
                        and isinstance(node.value, ast.Tuple) \
                        and len(t.elts) == len(node.value.elts):
                    for te, ve in zip(t.elts, node.value.elts):
                        record(te, ve)
                else:
                    record(t, node.value)
    return out


class DeviceViewMutationChecker:
    id = "C3-device-view"

    def __init__(self, shared=None):
        from jax_mapping.analysis.jax_hazards import _SharedRegistry
        self._shared = shared or _SharedRegistry()

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        registry = self._shared.get(modules)
        findings: List[Finding] = []
        for mod in modules:
            imports = A.import_table(mod.tree)
            alias_of_class: Dict[str, Dict[str, str]] = {
                cls.name: class_module_aliases(cls, imports)
                for cls in A.collect_classes(mod)}
            for func, symbol, cls_name in A.walk_functions(mod.tree):
                aliases = alias_of_class.get(cls_name, {})
                findings += self._scan(mod, func, symbol, imports,
                                       aliases, registry)
        return findings

    # -- resolution ----------------------------------------------------------

    def _call_target(self, call: ast.Call, mod: SourceModule,
                     imports: Dict[str, str],
                     aliases: Dict[str, str]) -> Optional[str]:
        """Fully-qualified dotted target of a call, resolving
        `self._V.height_map` through the class alias table."""
        f = call.func
        if isinstance(f, ast.Attribute):
            base = A._self_attr(f.value)
            if base is not None and base in aliases:
                return f"{aliases[base]}.{f.attr}"
        return A.resolve(f, imports)

    def _is_device_call(self, call: ast.Call, mod: SourceModule,
                        imports: Dict[str, str], aliases: Dict[str, str],
                        registry) -> bool:
        tgt = self._call_target(call, mod, imports, aliases)
        if tgt is not None:
            if tgt.startswith("jax."):
                return True
            module, _, name = tgt.rpartition(".")
            if (module, name) in registry:
                return True
        # Bare-name / from-import call sites (same-module jitted fns).
        pair = A.resolve_call_target(call, mod, imports)
        return pair is not None and pair in registry

    # -- the pass ------------------------------------------------------------

    def _scan(self, mod: SourceModule, func: ast.FunctionDef, symbol: str,
              imports: Dict[str, str], aliases: Dict[str, str],
              registry) -> List[Finding]:
        device: Set[str] = set()
        view: Set[str] = set()
        findings: List[Finding] = []

        def names_of(expr: ast.AST) -> Set[str]:
            return {n.id for n in ast.walk(expr)
                    if isinstance(n, ast.Name)}

        def classify(value: ast.AST) -> Optional[str]:
            """'view' | 'device' | 'clean' | None (propagate by names)."""
            for call in [n for n in ast.walk(value)
                         if isinstance(n, ast.Call)]:
                tgt = self._call_target(call, mod, imports, aliases) or ""
                if tgt in _COPYING_CALLS:
                    return "clean"
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _COPYING_METHODS:
                    return "clean"
                if tgt == "numpy.asarray" and call.args and (
                        names_of(call.args[0]) & (device | view)
                        or any(self._is_device_call(c, mod, imports,
                                                    aliases, registry)
                               for c in ast.walk(call.args[0])
                               if isinstance(c, ast.Call))):
                    return "view"
                if self._is_device_call(call, mod, imports, aliases,
                                        registry):
                    return "device"
            return None

        def flag(node: ast.AST, what: str) -> None:
            findings.append(mod.finding(
                self.id, "error", node, symbol,
                f"{what} a read-only np.asarray device view — "
                "np.asarray of a device array does not copy and its "
                "buffer is not writable (ValueError at runtime, often "
                "only on a rare branch); np.array-copy it before "
                "writing"))

        def check_sinks(stmt: ast.stmt) -> None:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    base = t.value if isinstance(t, ast.Subscript) else None
                    if base is not None and names_of(base) & view:
                        flag(stmt, "subscript-assigning into")
            elif isinstance(stmt, ast.AugAssign):
                t = stmt.target
                if isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Name) and t.id in view:
                    flag(stmt, "augmented-assigning into")
            for call in A.statement_calls(stmt):
                tgt = self._call_target(call, mod, imports, aliases) or ""
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr in _INPLACE_METHODS \
                        and names_of(call.func.value) & view:
                    flag(call, f"calling .{call.func.attr}() on")
                if tgt in ("numpy.copyto", "numpy.place", "numpy.putmask",
                           "numpy.put") and call.args \
                        and names_of(call.args[0]) & view:
                    flag(call, "passing as the destination of an "
                               "in-place numpy op")
                for kw in call.keywords:
                    if kw.arg == "out" and names_of(kw.value) & view:
                        flag(call, "passing as out= to")

        def on_stmt(stmt: ast.stmt, _tainted: Set[str]) -> None:
            check_sinks(stmt)

        # An ordered pass with two taint sets: reuse TaintWalk's control
        # flow by driving assignments through classify().
        def run_body(body: List[ast.stmt]) -> None:
            for stmt in body:
                on_stmt(stmt, set())
                if isinstance(stmt, ast.Assign) or (
                        isinstance(stmt, ast.AnnAssign)
                        and stmt.value is not None):
                    targets = stmt.targets if isinstance(stmt, ast.Assign) \
                        else [stmt.target]
                    verdict = classify(stmt.value)
                    if verdict is None:
                        # Taint propagates only through direct aliasing
                        # (`y = x`, `y = x[k]`, `y = x.T`): a container
                        # or arithmetic over a view is a fresh object —
                        # `summary = {"k": int(view.sum())}` must not
                        # make `summary[...] = ...` a finding.
                        base = stmt.value
                        while isinstance(base, (ast.Subscript,
                                                ast.Attribute)):
                            base = base.value
                        src = ({base.id} if isinstance(base, ast.Name)
                               else set())
                        verdict = ("view" if src & view
                                   else "device" if src & device
                                   else "clean")
                    for t in targets:
                        bound = A.target_names(t)
                        # a subscript store binds no fresh local
                        if isinstance(t, ast.Subscript):
                            continue
                        view.difference_update(bound)
                        device.difference_update(bound)
                        if verdict == "view":
                            view.update(bound)
                        elif verdict == "device":
                            device.update(bound)
                elif isinstance(stmt, (ast.For,)):
                    run_body(stmt.body)
                    run_body(stmt.orelse)
                elif isinstance(stmt, (ast.While, ast.If)):
                    run_body(stmt.body)
                    run_body(stmt.orelse)
                elif isinstance(stmt, ast.With):
                    run_body(stmt.body)
                elif isinstance(stmt, ast.Try):
                    run_body(stmt.body)
                    for h in stmt.handlers:
                        run_body(h.body)
                    run_body(stmt.orelse)
                    run_body(stmt.finalbody)
                elif isinstance(stmt, (ast.FunctionDef,
                                       ast.AsyncFunctionDef,
                                       ast.ClassDef)):
                    continue

        run_body(func.body)
        return findings
