"""Shared AST machinery for the checker families.

Everything here is deliberately *syntactic* — no imports are executed,
no types inferred. Resolution is by name through each module's import
table, which is exactly as strong as the repo's own conventions
(`import jax.numpy as jnp`, `from jax_mapping.ops import planner as P`)
and degrades to silence, not false positives, on code that breaks them.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from jax_mapping.analysis.core import SourceModule


# -- imports -----------------------------------------------------------------

def import_table(tree: ast.Module) -> Dict[str, str]:
    """Alias -> dotted target for module-level imports.

    `import jax.numpy as jnp`         -> {"jnp": "jax.numpy"}
    `import functools`                -> {"functools": "functools"}
    `from jax_mapping.ops import planner as P`
                                      -> {"P": "jax_mapping.ops.planner"}
    `from jax_mapping.bridge.brain import brain_tick`
                                      -> {"brain_tick":
                                          "jax_mapping.bridge.brain.brain_tick"}
    Function-local imports are included too (the repo defers heavy
    imports into tick bodies).
    """
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    table[a.asname] = a.name
                else:
                    table[a.name.split(".")[0]] = a.name.split(".")[0]
                    table[a.name] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                table[a.asname or a.name] = f"{node.module}.{a.name}"
    return table


def dotted(node: ast.AST) -> Optional[str]:
    """`a.b.c` expression -> "a.b.c"; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Expression -> fully-qualified dotted name through the import
    table: `jnp.asarray` -> "jax.numpy.asarray"."""
    d = dotted(node)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    base = imports.get(head, head)
    return f"{base}.{rest}" if rest else base


# -- symbols -----------------------------------------------------------------

def walk_functions(tree: ast.Module) -> Iterator[Tuple[ast.AST, str,
                                                       Optional[str]]]:
    """Yield (funcdef, dotted symbol, enclosing class name) for every
    function/method, depth-first."""
    def rec(node, prefix: str, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{prefix}{child.name}"
                yield child, sym, cls
                yield from rec(child, f"{sym}.", cls)
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.", child.name)
    yield from rec(tree, "", None)


def param_names(func: ast.FunctionDef) -> List[str]:
    a = func.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def names_in(node: ast.AST) -> Set[str]:
    """Every bare Name loaded anywhere inside `node`."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


#: attributes whose access yields trace-STATIC metadata, not values.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def traced_names(node: ast.AST) -> Set[str]:
    """Names in `node` whose *values* flow into the result — skipping
    trace-static subexpressions: `x is None` identity checks, `len(x)`,
    `isinstance(x, T)`, and `.shape`/`.ndim`/`.dtype`/`.size` access.
    `B = ranges.shape[0]` therefore taints nothing: under jit, shapes
    are Python ints at trace time."""
    out: Set[str] = set()
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops):
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id in ("len", "isinstance"):
            continue
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            continue
        if isinstance(n, ast.Name):
            out.add(n.id)
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


def target_names(target: ast.AST) -> Set[str]:
    """Names *bound* by an assignment target (x, (a, b), x[i] binds x)."""
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            break                    # self.x = ... binds no local
    return out


def receiver_base(node: ast.AST) -> Optional[str]:
    """The root Name of an attribute/subscript chain (`r.path_xy[v]`
    -> "r"); None when rooted elsewhere (call result, literal)."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


# -- jit registry ------------------------------------------------------------

@dataclass
class JitSite:
    module: SourceModule
    func: ast.FunctionDef
    symbol: str
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()
    decorator: ast.AST = None

    @property
    def params(self) -> List[str]:
        return param_names(self.func)

    @property
    def static_params(self) -> Set[str]:
        ps = self.params
        out = {ps[i] for i in self.static_argnums if 0 <= i < len(ps)}
        out |= set(self.static_argnames) & set(ps)
        return out

    @property
    def traced_params(self) -> Set[str]:
        return set(self.params) - self.static_params


def _const_ints(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _const_strs(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def jit_decorator_info(dec: ast.AST, imports: Dict[str, str]
                       ) -> Optional[Tuple[Tuple[int, ...],
                                           Tuple[str, ...]]]:
    """(static_argnums, static_argnames) when `dec` is a jit decorator:
    `@jax.jit`, `@jit`, `@functools.partial(jax.jit, static_argnums=..)`
    or `@jax.jit(...)` called with keyword statics. None otherwise."""
    if resolve(dec, imports) == "jax.jit":
        return (), ()
    if not isinstance(dec, ast.Call):
        return None
    fn = resolve(dec.func, imports)
    if fn == "jax.jit":
        call = dec
    elif fn == "functools.partial" and dec.args \
            and resolve(dec.args[0], imports) == "jax.jit":
        call = dec
    else:
        return None
    nums: Tuple[int, ...] = ()
    names: Tuple[str, ...] = ()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums = _const_ints(kw.value)
        elif kw.arg == "static_argnames":
            names = _const_strs(kw.value)
    return nums, names


def build_jit_registry(modules: Sequence[SourceModule]
                       ) -> Dict[Tuple[str, str], JitSite]:
    """(module dotted name, function name) -> JitSite, package-wide."""
    registry: Dict[Tuple[str, str], JitSite] = {}
    for mod in modules:
        imports = import_table(mod.tree)
        for func, symbol, _cls in walk_functions(mod.tree):
            for dec in getattr(func, "decorator_list", ()):
                info = jit_decorator_info(dec, imports)
                if info is not None:
                    registry[(mod.dotted, func.name)] = JitSite(
                        module=mod, func=func, symbol=symbol,
                        static_argnums=info[0], static_argnames=info[1],
                        decorator=dec)
                    break
    return registry


def resolve_call_target(call: ast.Call, mod: SourceModule,
                        imports: Dict[str, str]) -> Optional[Tuple[str,
                                                                   str]]:
    """Call site -> (module dotted, func name) candidate for registry
    lookup. `brain_tick(...)` in its own module -> (mod, brain_tick);
    `P.plan_to_goal(...)` -> (resolved P, plan_to_goal)."""
    f = call.func
    if isinstance(f, ast.Name):
        tgt = imports.get(f.id)
        if tgt and "." in tgt:                   # from-import of a symbol
            m, _, n = tgt.rpartition(".")
            return m, n
        return mod.dotted, f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        base = imports.get(f.value.id)
        if base:
            return base, f.attr
    return None


# -- ordered, lightly flow-sensitive taint walk ------------------------------

@dataclass
class TaintWalk:
    """Statement-ordered walk of one function body tracking a tainted
    name set. Callers subscribe via `on_expr` (called with each visited
    statement-level expression while the *current* taint set applies)
    and supply `call_taints` / `call_sanitizes` predicates deciding
    whether an assignment's RHS call introduces or clears taint.

    Single forward pass, branches visited in order without merge —
    a linter's approximation, biased toward the repo's straight-line
    tick bodies."""
    tainted: Set[str]
    call_taints: object = None           # Callable[[ast.Call], bool]
    call_sanitizes: object = None        # Callable[[ast.Call], bool]
    on_stmt: object = None               # Callable[[ast.stmt, Set[str]], None]

    def is_tainted(self, expr: ast.AST) -> bool:
        """Tainted names in `expr`, or a taint-introducing call nested
        anywhere in it (`float(step(x))` must flag even though `step`'s
        RESULT never got a name)."""
        if traced_names(expr) & self.tainted:
            return True
        return self._rhs_taints(expr) is True

    def _rhs_taints(self, value: ast.AST) -> Optional[bool]:
        """True taint / False sanitize / None = propagate by names."""
        for call in [n for n in ast.walk(value)
                     if isinstance(n, ast.Call)]:
            if self.call_sanitizes and self.call_sanitizes(call):
                return False
            if self.call_taints and self.call_taints(call):
                return True
        return None

    def _assign(self, targets: List[ast.AST], value: ast.AST) -> None:
        verdict = self._rhs_taints(value)
        if verdict is None:
            verdict = self.is_tainted(value)
        for t in targets:
            names = target_names(t)
            if verdict:
                self.tainted |= names
            else:
                self.tainted -= names

    def run(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if self.on_stmt:
                self.on_stmt(stmt, self.tainted)
            if isinstance(stmt, ast.Assign):
                self._assign(stmt.targets, stmt.value)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._assign([stmt.target], stmt.value)
            elif isinstance(stmt, ast.AugAssign):
                if self.is_tainted(stmt.value):
                    self.tainted |= target_names(stmt.target)
            elif isinstance(stmt, ast.For):
                if self.is_tainted(stmt.iter):
                    self.tainted |= target_names(stmt.target)
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, (ast.While, ast.If)):
                self.run(stmt.body)
                self.run(stmt.orelse)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    if item.optional_vars is not None \
                            and self.is_tainted(item.context_expr):
                        self.tainted |= target_names(item.optional_vars)
                self.run(stmt.body)
            elif isinstance(stmt, ast.Try):
                self.run(stmt.body)
                for h in stmt.handlers:
                    self.run(h.body)
                self.run(stmt.orelse)
                self.run(stmt.finalbody)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue                     # nested defs analyzed separately


def statement_calls(stmt: ast.stmt) -> List[ast.Call]:
    """Every Call in `stmt`'s own expressions — nested statements are
    excluded (TaintWalk.run visits them with their own on_stmt call, so
    descending here would double-count), as are nested def bodies."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.stmt, ast.ExceptHandler)):
                continue
            if isinstance(child, ast.Call):
                out.append(child)
            stack.append(child)
    return out


# -- class structure (bridge checkers + hot-path roots) ----------------------

@dataclass
class ClassInfo:
    module: SourceModule
    node: ast.ClassDef
    name: str
    methods: Dict[str, ast.FunctionDef] = field(default_factory=dict)
    #: self attrs assigned threading.Lock()/RLock()/Condition() in any
    #: method, attr -> "Lock"|"RLock"|"Condition"
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    #: methods registered as timer callbacks (per-tick hot roots)
    timer_callbacks: Set[str] = field(default_factory=set)
    #: methods registered as subscription callbacks
    sub_callbacks: Set[str] = field(default_factory=set)


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _callback_method(arg: ast.AST) -> Optional[str]:
    """`self.tick` or `functools.partial(self._scan_cb, i)` -> method."""
    m = _self_attr(arg)
    if m is not None:
        return m
    if isinstance(arg, ast.Call) and arg.args:
        fn = dotted(arg.func) or ""
        if fn.endswith("partial"):
            return _self_attr(arg.args[0])
    return None


def collect_classes(mod: SourceModule) -> List[ClassInfo]:
    imports = import_table(mod.tree)
    out: List[ClassInfo] = []
    for node in mod.tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        info = ClassInfo(module=mod, node=node, name=node.name)
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                info.methods[item.name] = item
        for meth in info.methods.values():
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    if attr is None or not isinstance(sub.value, ast.Call):
                        continue
                    target = resolve(sub.value.func, imports) or ""
                    kind = target.rpartition(".")[2]
                    if target.startswith("threading.") and kind in (
                            "Lock", "RLock", "Condition"):
                        info.lock_attrs[attr] = kind
                elif isinstance(sub, ast.Call):
                    fn = dotted(sub.func) or ""
                    if fn == "self.create_timer" and len(sub.args) >= 2:
                        cb = _callback_method(sub.args[1])
                        if cb:
                            info.timer_callbacks.add(cb)
                    elif fn == "self.create_subscription" \
                            and len(sub.args) >= 2:
                        cb = _callback_method(sub.args[1])
                        if cb:
                            info.sub_callbacks.add(cb)
        out.append(info)
    return out


def self_calls(func: ast.FunctionDef) -> Set[str]:
    """Names of same-class methods invoked as `self.m(...)`."""
    out: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            m = _self_attr(node.func)
            if m is not None:
                out.add(m)
    return out
