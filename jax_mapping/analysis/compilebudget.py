"""Runtime jit-recompile budget — the dynamic half of C4.

The static C4 checker catches *syntactic* shape churn (unbucketed
slices and static args at jit call sites); whatever it cannot see —
list-stacked batches, thread-timing-dependent shapes, config drift —
shows up at runtime as jit cache entries. This module pins them: every
jitted function in the package reports its compiled-variant count
(`PjitFunction._cache_size()`) after a canonical deterministic
scenario, and the committed `analysis/compile_budget.json` is the
ratchet — the same contract as `baseline.json`:

* the gate (`tests/test_analysis_selfcheck.py`) runs the scenario in a
  fresh subprocess (cold caches) and fails if any function compiled
  MORE variants than budgeted — a recompile regression;
* a budget entry whose function no longer exists or no longer compiles
  is *stale* and fails the gate — the budget only ratchets down;
* entries above 1 variant carry a `note` explaining which shapes are
  expected (pow2 buckets, window-vs-single paths) — growth without a
  justification cannot land.

`python -m jax_mapping.analysis.compilebudget --measure` prints the
counts, `--write-budget` regenerates the file (preserving notes),
`--check` is the gate (exit 0 clean / 1 violations / 2 error). The
scenario parameters live in `config.AnalysisConfig` so the committed
budget is reproducible by construction.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple


def default_budget_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "compile_budget.json")


# -- measurement -------------------------------------------------------------

def snapshot_cache_sizes(prefix: str = "jax_mapping") -> Dict[str, int]:
    """Compiled-variant count per jitted function currently imported
    under `prefix`, keyed by the DEFINING module + name (stable across
    from-import aliases; deduped by object identity)."""
    sizes: Dict[str, int] = {}
    seen: set = set()
    for mod_name, mod in sorted(sys.modules.items()):
        if mod is None or not mod_name.startswith(prefix):
            continue
        for attr in sorted(vars(mod)):
            fn = vars(mod)[attr]
            cache_size = getattr(fn, "_cache_size", None)
            if not callable(cache_size) or id(fn) in seen:
                continue
            seen.add(id(fn))
            owner = getattr(fn, "__module__", mod_name) or mod_name
            name = getattr(fn, "__name__", attr) or attr
            if not owner.startswith(prefix):
                owner = mod_name        # lambdas / wrapped externals
            try:
                sizes[f"{owner}.{name}"] = int(cache_size())
            except Exception:           # noqa: BLE001 — introspection only
                continue
    return sizes


def measure_scenario(analysis_cfg=None) -> Dict[str, int]:
    """Run the canonical deterministic scenario and snapshot compile
    counts. MUST run with cold jit caches (a fresh process) for the
    numbers to mean anything — the gate enforces that by
    subprocessing; calling it mid-process returns whatever the process
    already compiled on top."""
    from jax_mapping.config import AnalysisConfig, tiny_config
    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.sim import world as W

    a = analysis_cfg or AnalysisConfig()
    cfg = tiny_config(n_robots=a.budget_n_robots)
    world = W.plank_course(a.budget_world_cells, cfg.grid.resolution_m,
                           n_planks=4, seed=a.budget_seed)
    st = launch_sim_stack(cfg, world, n_robots=a.budget_n_robots,
                          http_port=0, realtime=False,
                          seed=a.budget_seed)
    try:
        st.brain.start_exploring()
        st.run_steps(a.budget_steps)
        st.mapper.publish_map()
        # Serving-side compiles (tile hashing, gray conversion, pyramid
        # downsample) run on refresh, normally from the HTTP plane; two
        # refreshes exercise both the first-install and the diff path
        # (verified to add no compiles beyond the first — the counts
        # are shape-driven, not content-driven).
        if st.api is not None and st.api.serving is not None \
                and st.api.serving.map_store is not None:
            st.api.serving.map_store.refresh()
            st.api.serving.map_store.refresh()
        # Bucketed fuse entry (ISSUE 11): the short mission rarely
        # queues a variable-length batch, but the budget must still pin
        # the bucket variant set ({2^k} ∪ {3·2^(k-1)}, the PR 6
        # crop-span set) — drive two batch sizes sharing one bucket
        # (5, 6 -> bucket 6) plus one more bucket (3 -> 3): the
        # committed max for `grid.fuse_scans_masked` is exactly the
        # bucket count, and a bucketing regression (one variant per B)
        # shows up as an over-budget third variant.
        import jax.numpy as jnp
        from jax_mapping.ops import grid as G
        gcfg, scfg = cfg.grid, cfg.scan
        gr = G.empty_grid(gcfg)
        for nb in (3, 5, 6):
            G.fuse_scans_bucketed(
                gcfg, scfg, gr,
                jnp.ones((nb, scfg.padded_beams), jnp.float32),
                jnp.zeros((nb, 3), jnp.float32))
        # Tenant-megabatch buckets (ISSUE 14): the tenant axis rides
        # the same bucket set — drive `budget_tenant_counts` mission
        # counts at the shared micro mission shape so the committed
        # budget pins one compiled variant per BUCKET (5 and 6 share
        # the 6-bucket; a bucketing regression shows as a variant per
        # count). The full admission-ladder ceiling (one variant per
        # bucket up to TenancyConfig.max_tenants) is gated by the
        # cold-cache subprocess test in tests/test_tenancy.py against
        # the same budget entry.
        import jax
        from jax_mapping.config import micro_config
        from jax_mapping.models import fleet as FM
        from jax_mapping.tenancy import megabatch as MBT
        mcfg = micro_config()
        mworld = jnp.asarray(W.empty_arena(
            mcfg.grid.size_cells, mcfg.grid.resolution_m))
        mstate = FM.init_fleet_state(mcfg, jax.random.PRNGKey(0))
        mkey = jax.random.PRNGKey(0)
        for nt in a.budget_tenant_counts:
            b = MBT.make_tenant_batch([mstate] * nt, [mworld] * nt,
                                      [mkey] * nt)
            MBT.megabatch_step(mcfg, b, mcfg.grid.resolution_m)
        # Sliding-window world jits (ISSUE 18): one fuse at global
        # coordinates, one shift with a content-bearing leaving band
        # (extract + roll), one shift back (host-hit rehydrate =
        # scatter) — the full shift/evict/rehydrate dispatch set, each
        # pinned to ONE variant (shift amounts are traced, tile size
        # is the single static). Geometry mirrors the world tests:
        # 12-tile logical lattice, 4-tile window, so a ±2-tile shift
        # stays on-lattice.
        import dataclasses as _dc
        from jax_mapping.world.store import WorldStore
        wcfg = cfg.replace(
            grid=_dc.replace(cfg.grid, size_cells=768),
            world=_dc.replace(cfg.world, windowed=True,
                              window_tiles=4, margin_tiles=1))
        wstore = WorldStore(wcfg)
        win = G.empty_grid(wstore.cfg.grid)
        win = wstore.fuse_scan_global(
            win, jnp.full((cfg.scan.padded_beams,), 1.0, jnp.float32),
            jnp.zeros((3,), jnp.float32))
        win = wstore.shift(win, 2, 2)
        win = wstore.shift(win, -2, -2)
        win, _ = wstore.poll_prefetch(win)
        jax.block_until_ready(win)
    finally:
        st.shutdown()
    return {k: v for k, v in snapshot_cache_sizes().items() if v > 0}


# -- the budget --------------------------------------------------------------

class Budget:
    def __init__(self, entries: List[dict]):
        self.entries = list(entries)
        self.by_name = {e["name"]: e for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Budget":
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 1:
            raise ValueError(f"{path}: unsupported budget version "
                             f"{data.get('version')!r}")
        return cls(data.get("budgets", []))

    def check(self, measured: Dict[str, int]
              ) -> Tuple[List[str], List[str], List[str]]:
        """(over_budget, unknown, stale) violation descriptions."""
        over, unknown = [], []
        for name, count in sorted(measured.items()):
            e = self.by_name.get(name)
            if e is None:
                unknown.append(
                    f"{name}: compiled {count} variant(s) but has no "
                    "budget entry — run --write-budget and justify any "
                    "entry above 1 with a note")
            elif count > e["max"]:
                over.append(
                    f"{name}: {count} compiled variant(s) exceeds "
                    f"budget {e['max']} — recompile regression (bucket "
                    "the offending shape, or raise the budget WITH a "
                    "note in compile_budget.json)")
        stale = [
            f"{e['name']}: budgeted {e['max']} but never compiled in "
            "the canonical scenario — stale entry, ratchet it out"
            for e in self.entries if e["name"] not in measured]
        return over, unknown, stale

    @staticmethod
    def dump(measured: Dict[str, int], path: str,
             notes: Optional[Dict[str, str]] = None) -> None:
        entries = []
        for name in sorted(measured):
            e = {"name": name, "max": measured[name]}
            note = (notes or {}).get(name)
            if note:
                e["note"] = note
            entries.append(e)
        with open(path, "w") as f:
            json.dump({"version": 1, "budgets": entries}, f, indent=1)
            f.write("\n")


# -- CLI ---------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    p = argparse.ArgumentParser(
        prog="python -m jax_mapping.analysis.compilebudget",
        description="jit recompile-budget tracker (ratcheted like "
                    "analysis/baseline.json)")
    p.add_argument("--budget", default=None, metavar="JSON")
    p.add_argument("--ledger", action="store_true",
                   help="with --check: run the scenario under the "
                        "dispatch profiler (obs/devprof.py) and ALSO "
                        "require the static XLA cost ledger to cover "
                        "every budgeted function (FLOPs/bytes per "
                        "compiled variant, variant counts within "
                        "budget) — the ISSUE 10 attribution gate")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--measure", action="store_true",
                   help="run the canonical scenario, print counts")
    g.add_argument("--write-budget", action="store_true",
                   help="regenerate the budget file (notes preserved)")
    g.add_argument("--check", action="store_true",
                   help="gate: exit 0 clean / 1 violations / 2 error")
    args = p.parse_args(argv)
    path = args.budget or default_budget_path()

    # Budget-file preflight BEFORE the ~30 s scenario (the same
    # fail-fast contract the lint CLI keeps for its baseline): a
    # missing/corrupt budget must refuse immediately, not after a full
    # stack drive it will then discard.
    budget = None
    notes: Dict[str, str] = {}
    if args.check:
        try:
            budget = Budget.load(path)
        except (OSError, ValueError) as e:
            print(f"compilebudget: {e}", file=sys.stderr)
            return 2
    elif args.write_budget and os.path.exists(path):
        try:
            notes = {e["name"]: e["note"]
                     for e in Budget.load(path).entries if e.get("note")}
        except (OSError, ValueError) as e:
            print(f"compilebudget: {path}: {e} — refusing to "
                  "overwrite what cannot be merged", file=sys.stderr)
            return 2

    try:
        # The stack logs bring-up lines to stdout; push them to stderr
        # so --measure's stdout is exactly one JSON document.
        import contextlib
        ledger = None
        with contextlib.redirect_stdout(sys.stderr):
            if args.ledger:
                from jax_mapping.obs.ledger import run_cost_ledger
                measured, _profiler, ledger = run_cost_ledger()
            else:
                measured = measure_scenario()
    except Exception as e:              # noqa: BLE001
        print(f"compilebudget: scenario failed: {e}", file=sys.stderr)
        return 2

    if args.measure:
        print(json.dumps(measured, indent=1, sort_keys=True))
        return 0

    if args.write_budget:
        Budget.dump(measured, path, notes=notes)
        print(f"wrote {len(measured)} budget(s) to {path}")
        return 0

    over, unknown, stale = budget.check(measured)
    ledger_violations = []
    if ledger is not None:
        ledger_violations = ledger.cross_check(path)
    for line in over + unknown + stale + ledger_violations:
        print(line)
    return 1 if (over or unknown or stale or ledger_violations) else 0


if __name__ == "__main__":
    sys.exit(main())
