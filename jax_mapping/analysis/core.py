"""Checker framework: source modules, findings, baseline, driver.

Design notes:

* Checkers are whole-program passes (`run(modules) -> findings`), not
  per-file visitors — the jit registry (which functions are jitted,
  with which static argnums) and the lock graph both need the full
  module set before any site can be judged.
* Finding identity is ``(checker, path, symbol, code)`` — the stripped
  source line, NOT the line number. Baselines keyed on line numbers
  churn on every unrelated edit above the site; keying on the enclosing
  symbol plus the code text survives moves and stays unique enough in
  practice (two identical flagged lines in one function are the same
  accepted idiom).
* The baseline is a committed JSON file of accepted findings. The gate
  (tests/test_analysis_selfcheck.py) fails on any NON-baselined
  finding; unused suppressions are reported so the baseline ratchets
  down rather than silently rotting.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: Directories never analyzed (generated, vendored, caches).
SKIP_DIRS = {"__pycache__", "build", ".git"}


@dataclass(frozen=True)
class Finding:
    checker: str       #: checker id, e.g. "A1-host-sync"
    severity: str      #: "error" | "warning"
    path: str          #: posix path relative to the analysis root
    line: int          #: 1-based line of the flagged site
    symbol: str        #: dotted symbol inside the module ("" = module level)
    message: str
    code: str = ""     #: stripped source of the flagged line

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return (self.checker, self.path, self.symbol, self.code)

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"{self.checker}: {self.message}{sym}\n"
                f"    {self.code}")


class SourceModule:
    """One parsed source file: AST + line access + dotted module name."""

    def __init__(self, path: str, source: str, dotted: str):
        self.path = path                     # relative, posix separators
        self.source = source
        self.dotted = dotted                 # e.g. "jax_mapping.ops.grid"
        self.tree = ast.parse(source, filename=path)
        self._lines = source.splitlines()

    @classmethod
    def from_source(cls, source: str, path: str = "snippet.py",
                    dotted: Optional[str] = None) -> "SourceModule":
        """In-memory module — the fixture-test entry point."""
        if dotted is None:
            dotted = path[:-3].replace("/", ".") if path.endswith(".py") \
                else path
        return cls(path, source, dotted)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self._lines):
            return self._lines[lineno - 1].strip()
        return ""

    def finding(self, checker: str, severity: str, node: ast.AST,
                symbol: str, message: str) -> Finding:
        lineno = getattr(node, "lineno", 0)
        return Finding(checker=checker, severity=severity, path=self.path,
                       line=lineno, symbol=symbol, message=message,
                       code=self.line(lineno))


class Baseline:
    """Committed accepted-findings list; see `analysis/baseline.json`."""

    def __init__(self, suppressions: Optional[List[dict]] = None):
        self.suppressions = list(suppressions or [])
        self._keys = {(s["checker"], s["path"], s.get("symbol", ""),
                       s.get("code", "")) for s in self.suppressions}
        self._hits: set = set()

    @classmethod
    def load(cls, path: str) -> "Baseline":
        with open(path) as f:
            data = json.load(f)
        if data.get("version") != 1:
            raise ValueError(f"{path}: unsupported baseline version "
                             f"{data.get('version')!r}")
        return cls(data.get("suppressions", []))

    def matches(self, finding: Finding) -> bool:
        if finding.key in self._keys:
            self._hits.add(finding.key)
            return True
        return False

    def unused(self) -> List[dict]:
        """Suppressions that matched nothing this run — ratchet these out."""
        return [s for s in self.suppressions
                if (s["checker"], s["path"], s.get("symbol", ""),
                    s.get("code", "")) not in self._hits]

    @staticmethod
    def dump(findings: Iterable[Finding], path: str,
             notes: Optional[Dict[Tuple, str]] = None,
             keep: Iterable[dict] = ()) -> None:
        """Write a baseline accepting `findings` (--write-baseline).
        `notes` maps finding keys to justification strings; `keep`
        carries forward existing suppressions this run could not have
        re-observed (out-of-scope paths/checkers), so a scoped rewrite
        never silently deletes them."""
        sups = []
        seen = set()
        for s in keep:
            key = (s["checker"], s["path"], s.get("symbol", ""),
                   s.get("code", ""))
            if key not in seen:
                seen.add(key)
                sups.append(dict(s))
        for f in sorted(findings, key=lambda f: (f.path, f.line, f.checker)):
            if f.key in seen:
                continue
            seen.add(f.key)
            entry = {"checker": f.checker, "path": f.path,
                     "symbol": f.symbol, "code": f.code}
            note = (notes or {}).get(f.key)
            if note:
                entry["note"] = note
            sups.append(entry)
        sups.sort(key=lambda s: (s["path"], s["checker"],
                                 s.get("symbol", "")))
        with open(path, "w") as fh:
            json.dump({"version": 1, "suppressions": sups}, fh, indent=1)
            fh.write("\n")


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)   # non-baselined
    baselined: List[Finding] = field(default_factory=list)
    unused_suppressions: List[dict] = field(default_factory=list)
    n_files: int = 0

    @property
    def all_findings(self) -> List[Finding]:
        return sorted(self.findings + self.baselined,
                      key=lambda f: (f.path, f.line, f.checker))


# -- discovery ---------------------------------------------------------------

def _dotted_name(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("\\", "/")
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def _package_anchor(file_abs: str) -> Optional[str]:
    """Parent of the topmost package directory containing `file_abs`
    (walking up while `__init__.py` exists), or None outside any
    package. Anchoring here makes baseline keys like
    `jax_mapping/bridge/planner.py` come out identical whether the
    CLI was handed the package dir, a subdir, one file, or `.`."""
    d = os.path.dirname(file_abs)
    top = None
    while os.path.isfile(os.path.join(d, "__init__.py")):
        top = d
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return os.path.dirname(top) if top else None


def load_paths(paths: Sequence[str],
               root: Optional[str] = None) -> List[SourceModule]:
    """Collect .py files under `paths`. Each module's key path is made
    relative to `root` when given, else to the file's package anchor
    (see `_package_anchor`), else to the parent of the common path of
    `paths` — so `jax-mapping-lint jax_mapping/`,
    `jax-mapping-lint jax_mapping/bridge/planner.py` and
    `jax-mapping-lint .` all yield `jax_mapping/...` keys that match
    the committed baseline regardless of cwd."""
    abspaths = [os.path.abspath(p) for p in paths]
    common = os.path.commonpath(abspaths)
    fallback_root = os.path.dirname(common)
    files: List[str] = []
    for p in abspaths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in SKIP_DIRS]
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    modules = []
    for f in files:
        base = root if root is not None \
            else (_package_anchor(f) or fallback_root)
        rel = os.path.relpath(f, base).replace(os.sep, "/")
        with open(f, encoding="utf-8") as fh:
            src = fh.read()
        modules.append(SourceModule(rel, src, _dotted_name(rel)))
    return modules


def load_package_modules() -> List[SourceModule]:
    """The installed `jax_mapping` package — what the self-check gates."""
    import jax_mapping
    pkg_dir = os.path.dirname(os.path.abspath(jax_mapping.__file__))
    return load_paths([pkg_dir], root=os.path.dirname(pkg_dir))


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


# -- driver ------------------------------------------------------------------

def all_checkers() -> List:
    """The registered checker passes, in report order. The A family and
    the C checkers that need the jit registry share one registry build,
    the B family one class-walk per module set (`_SharedRegistry` /
    `_SharedWalk`)."""
    from jax_mapping.analysis import (device_views, jax_hazards,
                                      lock_discipline, revision_order,
                                      shape_churn, snapshot_tear)
    registry = jax_hazards._SharedRegistry()
    walk = lock_discipline._SharedWalk()
    return [jax_hazards.HostSyncChecker(registry),
            jax_hazards.JitHygieneChecker(registry),
            jax_hazards.DtypeDriftChecker(registry),
            jax_hazards.ImpureJitChecker(registry),
            lock_discipline.LockOrderChecker(walk),
            lock_discipline.CallbackUnderLockChecker(walk),
            lock_discipline.UnguardedWriteChecker(walk),
            revision_order.RevisionOrderChecker(),
            snapshot_tear.SnapshotTearChecker(),
            device_views.DeviceViewMutationChecker(registry),
            shape_churn.ShapeChurnChecker(registry)]


def analyze_modules(modules: Sequence[SourceModule],
                    baseline: Optional[Baseline] = None,
                    checkers: Optional[Sequence] = None) -> AnalysisResult:
    res = AnalysisResult(n_files=len(modules))
    active = list(checkers) if checkers is not None else all_checkers()
    for checker in active:
        for f in checker.run(list(modules)):
            if baseline is not None and baseline.matches(f):
                res.baselined.append(f)
            else:
                res.findings.append(f)
    res.findings.sort(key=lambda f: (f.path, f.line, f.checker))
    res.baselined.sort(key=lambda f: (f.path, f.line, f.checker))
    if baseline is not None:
        # A suppression is only provably stale when this run COULD have
        # re-observed it: its checker ran, and the run had full
        # cross-module context (every baselined file analyzed — the A
        # checkers build a package-wide jit registry, so a path-subset
        # run finds strictly less and would report valid entries as
        # stale). Deleted-but-still-baselined files are caught by the
        # gate's path-existence check, not here.
        ids = {c.id for c in active}
        analyzed = {m.path for m in modules}
        full_context = {s["path"] for s in baseline.suppressions} \
            <= analyzed
        if full_context:
            res.unused_suppressions = [s for s in baseline.unused()
                                       if s["checker"] in ids]
    return res


def analyze_paths(paths: Sequence[str],
                  baseline_path: Optional[str] = None,
                  checkers: Optional[Sequence] = None) -> AnalysisResult:
    baseline = Baseline.load(baseline_path) if baseline_path else None
    return analyze_modules(load_paths(paths), baseline, checkers)
