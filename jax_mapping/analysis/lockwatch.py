"""Runtime lock-order recorder — the dynamic complement to B1.

The static graph (`lock_discipline.build_lock_graph`) cannot see
cross-object edges (bus lock -> subscription lock) or the per-node
`_cb_lock` chains created by inline bus delivery. `LockWatch` closes
that gap: it swaps selected instance locks for recording proxies, keeps
a per-thread held-lock stack, and logs every "acquired B while holding
A" pair actually exercised by a live run (e.g. `launch_sim_stack` in a
test). Tests then assert the observed order is acyclic and consistent
with the static graph.

Usage:

    watch = LockWatch()
    watch.watch(stack.bus, "_lock")            # -> "Bus._lock"
    watch.watch(stack.brain, "_state_lock")    # -> "ThymioBrain._state_lock"
    ... drive the stack ...
    watch.unwatch_all()
    assert watch.cycle() is None
    assert watch.edges() <= allowed_edges

Proxies forward the full Lock/RLock surface (`acquire`, `release`,
context manager, `locked`), count reentrant acquires without
re-recording, and are safe to leave installed for a whole process —
recording is one set-add under a private mutex per acquisition.

Do NOT watch a lock that other objects captured at construction time
(e.g. `Subscription._lock`, which its `Condition`s wrap): the proxy
only intercepts attribute access, so pre-captured references would
bypass it and the record would be partial in a misleading way.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple


class _RecordingLock:
    def __init__(self, watch: "LockWatch", real, name: str):
        self._watch = watch
        self._real = real
        self.name = name

    # -- lock protocol ------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._watch._record_acquire(self.name)
        return ok

    def release(self) -> None:
        self._watch._record_release(self.name)
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        locked = getattr(self._real, "locked", None)
        return locked() if locked is not None else False

    def __repr__(self) -> str:
        return f"<RecordingLock {self.name} over {self._real!r}>"


class LockWatch:
    """Records runtime lock-acquisition order edges across threads."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], int] = {}
        self._tls = threading.local()
        self._installed: List[Tuple[object, str, object]] = []

    # -- installation -------------------------------------------------------

    def watch(self, obj: object, attr: str,
              name: Optional[str] = None) -> str:
        """Replace `obj.<attr>` with a recording proxy; returns the
        recorded lock name (default `TypeName.attr`, matching the
        static graph's `Class.attr` node names)."""
        real = getattr(obj, attr)
        if isinstance(real, _RecordingLock):
            return real.name
        lock_name = name or f"{type(obj).__name__}.{attr}"
        setattr(obj, attr, _RecordingLock(self, real, lock_name))
        self._installed.append((obj, attr, real))
        return lock_name

    def unwatch_all(self) -> None:
        for obj, attr, real in reversed(self._installed):
            setattr(obj, attr, real)
        self._installed.clear()

    # -- recording ----------------------------------------------------------

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _record_acquire(self, name: str) -> None:
        held = self._held()
        if name not in held:                  # reentrant RLock re-acquire
            with self._mu:
                for h in held:
                    key = (h, name)
                    self._edges[key] = self._edges.get(key, 0) + 1
        held.append(name)

    def _record_release(self, name: str) -> None:
        held = self._held()
        # LIFO is the norm; tolerate out-of-order release by removing
        # the most recent matching entry.
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- results ------------------------------------------------------------

    def edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def edge_counts(self) -> Dict[Tuple[str, str], int]:
        with self._mu:
            return dict(self._edges)

    def cycle(self) -> Optional[List[str]]:
        """A lock cycle in the observed order, or None. A cycle means
        two threads can deadlock given the right interleaving even if
        this run happened not to."""
        edges = self.edges()
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        WHITE, GREY, BLACK = 0, 1, 2
        color = {v: WHITE for v in graph}
        parent: Dict[str, str] = {}

        def dfs(v: str) -> Optional[List[str]]:
            color[v] = GREY
            for w in graph[v]:
                if color[w] == GREY:
                    path = [w, v]
                    u = v
                    while u != w:
                        u = parent[u]
                        path.append(u)
                    return list(reversed(path))
                if color[w] == WHITE:
                    parent[w] = v
                    found = dfs(w)
                    if found:
                        return found
            color[v] = BLACK
            return None

        for v in sorted(graph):
            if color[v] == WHITE:
                found = dfs(v)
                if found:
                    return found
        return None

    def check_against_static(self, static_edges: Set[Tuple[str, str]]
                             ) -> Set[Tuple[str, str]]:
        """Observed edges between locks the static graph KNOWS that the
        static pass missed (both endpoints appear somewhere in
        `static_edges`, the edge itself does not) — each one is a
        static-analysis blind spot worth a checker improvement."""
        known = {n for e in static_edges for n in e}
        return {e for e in self.edges()
                if e[0] in known and e[1] in known
                and e not in static_edges}
