"""C2 — snapshot tears: correlated state read across separate lock regions.

The hazard (the historical `publish_frontiers` pose/grid tear, fixed in
PR 6): a pure-reader function assembles a "snapshot" of correlated
state — robot poses, the shared grid, the map revision — but takes the
guarding lock *twice*, reading part of the snapshot in each region. A
writer scheduled between the two regions produces a pose/grid pairing
that never existed; every downstream consumer of the pair (frontier
assignment, serving, checkpoints) silently computes on it.

Which fields are "correlated" is a design fact the code cannot express
syntactically, so it comes from the committed lock-protection map
(`analysis/protection.py`). For each declared `LockGroup` the checker
examines every method of the owning class:

* **atomic sections** are (a) top-level ``with self.<lock>:`` regions
  (Condition attributes constructed over the lock alias to it) and
  (b) calls to same-class methods that transitively acquire the lock —
  the callee's internal region is a section of the CALLER's timeline
  (exactly how the historical tear hid: ``merged_grid()`` locks
  internally, so the caller looked lock-free).
* methods that **write** any group field (directly or through called
  sections) are exempt: read-compute-reinstall paths re-read the group
  to *validate* against their base snapshot (the mapper/voxel CAS
  idiom), which is the tear *defense*, not the tear.
* a finding is two sections A before B where A reads part of the group
  and B reads a group field A did not — B's read cannot be consistent
  with A's. Re-reading the *same* fields (staleness re-check) passes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule
from jax_mapping.analysis.lock_discipline import _lock_aliases
from jax_mapping.analysis.protection import (LockGroup, REPO_PROTECTION,
                                             groups_by_class)


class _MethodSummary:
    """Per-method group-field access summary, transitive over self-calls."""

    def __init__(self, cls: "A.ClassInfo", lock_attr: str,
                 fields: Set[str], aliases: Dict[str, str]):
        self.cls = cls
        self.lock_attr = lock_attr
        self.fields = fields
        self.aliases = aliases
        self._acquires: Dict[str, bool] = {}
        self._reads: Dict[str, Set[str]] = {}
        self._writes: Dict[str, Set[str]] = {}

    def _field_accesses(self, node: ast.AST) -> Tuple[Set[str], Set[str]]:
        reads: Set[str] = set()
        writes: Set[str] = set()
        for n in ast.walk(node):
            if isinstance(n, ast.Attribute):
                attr = A._self_attr(n)
                if attr in self.fields:
                    if isinstance(n.ctx, ast.Store):
                        writes.add(attr)
                    else:
                        reads.add(attr)
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        attr = A._self_attr(t.value)
                        if attr in self.fields:
                            writes.add(attr)      # self.f[...] = mutation
        return reads, writes

    def _summarize(self, name: str, seen: Set[str]) -> None:
        if name in self._reads or name in seen \
                or name not in self.cls.methods:
            self._reads.setdefault(name, set())
            self._writes.setdefault(name, set())
            self._acquires.setdefault(name, False)
            return
        seen.add(name)
        meth = self.cls.methods[name]
        reads, writes = self._field_accesses(meth)
        acquires = any(
            self.aliases.get(A._self_attr(i.context_expr)) == self.lock_attr
            for n in ast.walk(meth) if isinstance(n, ast.With)
            for i in n.items)
        for callee in A.self_calls(meth):
            if callee == name:
                continue
            self._summarize(callee, seen)
            reads |= self._reads.get(callee, set())
            writes |= self._writes.get(callee, set())
            acquires = acquires or self._acquires.get(callee, False)
        self._reads[name] = reads
        self._writes[name] = writes
        self._acquires[name] = acquires

    def reads(self, name: str) -> Set[str]:
        self._summarize(name, set())
        return self._reads.get(name, set())

    def writes(self, name: str) -> Set[str]:
        self._summarize(name, set())
        return self._writes.get(name, set())

    def acquires(self, name: str) -> bool:
        self._summarize(name, set())
        return self._acquires.get(name, False)


class SnapshotTearChecker:
    id = "C2-snapshot-tear"

    def __init__(self, protection: Optional[Sequence[LockGroup]] = None):
        self._by_class = groups_by_class(
            REPO_PROTECTION if protection is None else protection)

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in modules:
            for cls in A.collect_classes(mod):
                for grp in self._by_class.get(cls.name, ()):
                    if grp.lock_attr not in cls.lock_attrs:
                        continue
                    findings += self._check_class(mod, cls, grp)
        return findings

    def _check_class(self, mod: SourceModule, cls: "A.ClassInfo",
                     grp: LockGroup) -> List[Finding]:
        fields = set(grp.all_fields)
        aliases = _lock_aliases(cls)
        summary = _MethodSummary(cls, grp.lock_attr, fields, aliases)
        findings: List[Finding] = []
        for name, meth in cls.methods.items():
            if name == "__init__":
                continue
            sections = self._sections(meth, cls, grp, aliases, summary)
            if not sections:
                continue
            if any(w for _, _, w in sections) or \
                    self._writes_outside(meth, fields):
                continue                     # CAS/install path: exempt
            seen_reads: Set[str] = set()
            for node, reads, _w in sections:
                fresh = reads - seen_reads
                if seen_reads and fresh:
                    findings.append(mod.finding(
                        self.id, "error", node, f"{cls.name}.{name}",
                        f"snapshot tear: correlated field(s) "
                        f"{sorted(fresh)} of lock group "
                        f"{cls.name}.{grp.lock_attr} read in a SECOND "
                        f"atomic section after {sorted(seen_reads)} — "
                        "a writer between the sections pairs state no "
                        "writer ever produced; read the whole group in "
                        "ONE lock region"))
                seen_reads |= reads
        return findings

    def _sections(self, meth: ast.FunctionDef, cls: "A.ClassInfo",
                  grp: LockGroup, aliases: Dict[str, str],
                  summary: _MethodSummary
                  ) -> List[Tuple[ast.AST, Set[str], Set[str]]]:
        """Ordered atomic sections in `meth`: with-lock regions + calls
        to self-methods that acquire the group lock internally."""
        out: List[Tuple[ast.AST, Set[str], Set[str]]] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.With):
                if any(aliases.get(A._self_attr(i.context_expr))
                       == grp.lock_attr for i in node.items):
                    reads, writes = summary._field_accesses(node)
                    out.append((node, reads, writes))
                    return               # whole region is one section
                for stmt in node.body:
                    visit(stmt)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, ast.Call):
                m = A._self_attr(node.func)
                if m is not None and m in cls.methods \
                        and summary.acquires(m):
                    reads = summary.reads(m)
                    writes = summary.writes(m)
                    if reads or writes:
                        out.append((node, set(reads), set(writes)))
            for child in ast.iter_child_nodes(node):
                visit(child)

        for stmt in meth.body:
            visit(stmt)
        return out

    @staticmethod
    def _writes_outside(meth: ast.FunctionDef, fields: Set[str]) -> bool:
        """Direct group-field writes anywhere in the method body (a
        writer is a CAS/install path even when the write is outside a
        lock region — B3 already polices THAT hazard)."""
        for n in ast.walk(meth):
            if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Store):
                if A._self_attr(n) in fields:
                    return True
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    if isinstance(t, ast.Subscript) \
                            and A._self_attr(t.value) in fields:
                        return True
        return False
