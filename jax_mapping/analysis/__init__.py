"""Repo-native static analysis: JAX hazard linter + lock-discipline checker.

The framework's correctness rests on two mechanically checkable
disciplines that ordinary linters know nothing about:

* the **device boundary** — host syncs (`np.asarray`, `.item()`, ...)
  must stay out of jit-traced code and be deliberate (baselined) in
  per-tick bridge code; jit entry points must not hide recompile-storm
  or tracer-leak hazards (ROADMAP north-star: "runs as fast as the
  hardware allows");
* the **lock discipline** of the threaded bridge layer (`bus.py`,
  `node.py`, `mapper.py`, ...) — consistent acquisition order, no
  callbacks invoked under a lock, no unguarded writes to state that is
  elsewhere lock-protected.

`core` holds the checker framework (Finding, baseline, driver),
`jax_hazards` the A-family checkers, `lock_discipline` the B-family,
and the C family encodes the hazard classes review caught in PRs 4-6:
`revision_order` (C1 revision-before-content for lock-free stamped
snapshots), `snapshot_tear` (C2 correlated state across separate lock
regions, driven by the `protection` lock-protection map),
`device_views` (C3 mutation of read-only np.asarray device views) and
`shape_churn` (C4 unbucketed runtime sizes at jit boundaries).

The dynamic tier: `lockwatch` records runtime lock ORDER, `racewatch`
applies Eraser's lockset refinement to the protection-map fields on a
live stack, and `compilebudget` pins per-function jit compile counts
against the committed `compile_budget.json` ratchet. `cli` is the
`jax-mapping-lint` console entry point (also `python -m
jax_mapping.analysis`). The repo gates itself in tier-1 via
`tests/test_analysis_selfcheck.py`: the full analyzer over
`jax_mapping/` must report zero non-baselined findings.
"""

from jax_mapping.analysis.core import (  # noqa: F401
    Baseline, Finding, SourceModule, all_checkers, analyze_paths,
    analyze_modules, default_baseline_path, load_package_modules,
)
