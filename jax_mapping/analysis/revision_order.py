"""C1 — revision-before-content ordering for lock-free stamped snapshots.

The repo's cache-consistency idiom pairs a monotonic revision counter
with the content it stamps (`map_revision` + the grid, a tile store's
`revision` + its tiles, `serving_revision()` + `serving_snapshot()`).
Readers that cannot afford a lock take the pair as two separate reads,
and then the ORDER is the whole correctness argument:

* revision FIRST, content second: a writer landing between the reads
  leaves *newer content under an older stamp* — conservative; the next
  freshness peek sees a newer revision and re-reads.
* content first, revision second: the same interleaving stamps *old
  content with the new revision* — every later freshness check compares
  equal and the stale content is served as current **forever**.

This exact inversion was caught by review three times in three PRs
(the voxel `serving_snapshot`, the relocalizer's pyramid cache, the
planner's `_planning_grid` tick path) before this checker existed.

Mechanics: within one function, the checker collects lock-free reads of
*revision-named* attributes/methods (``*_revision``, ``*_rev``,
``revision``) and of *content-named* ones (``grid``/``*_grid``,
``*snapshot*``, ``states``, ``tiles``) per receiver expression
(``self``, ``self.mapper``, a local alias). If the first content read
of a receiver precedes its first revision read, the revision read is
flagged. Reads made while holding a lock are exempt — a lock-atomic
snapshot has no ordering hazard (tears across *separate* lock regions
are C2's department), and re-reading the revision after content as a
staleness *re-check* passes because the first revision read came first.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from jax_mapping.analysis import astutil as A
from jax_mapping.analysis.core import Finding, SourceModule

#: attribute / method names that read a revision stamp.
def _is_revision_name(name: str) -> bool:
    return (name == "revision" or name.endswith("_revision")
            or name.endswith("_rev"))


#: attribute / method names that read the content a revision stamps.
def _is_content_name(name: str) -> bool:
    return (name == "grid" or name.endswith("_grid")
            or "snapshot" in name
            or name in ("states", "tiles", "height_map"))


def _with_is_lock(item: ast.withitem) -> bool:
    """`with <expr>:` acquires a lock when the context expression is a
    dotted name mentioning a lock by the repo's naming convention
    (`self._lock`, `self._state_lock`, `store._refresh_lock`, ...)."""
    d = A.dotted(item.context_expr)
    if d is None and isinstance(item.context_expr, ast.Call):
        d = A.dotted(item.context_expr.func)
    return d is not None and "lock" in d.rsplit(".", 1)[-1].lower()


class RevisionOrderChecker:
    id = "C1-revision-order"

    def run(self, modules: List[SourceModule]) -> Iterable[Finding]:
        findings: List[Finding] = []
        for mod in modules:
            for func, symbol, _cls in A.walk_functions(mod.tree):
                if func.name == "__init__":
                    continue
                findings += self._scan(mod, func, symbol)
        return findings

    def _scan(self, mod: SourceModule, func: ast.FunctionDef,
              symbol: str) -> List[Finding]:
        #: receiver -> (first content read node, first revision read node)
        first_content: Dict[str, ast.AST] = {}
        first_revision: Dict[str, ast.AST] = {}
        flagged: Dict[str, Tuple[ast.AST, str]] = {}

        def receiver_of(attr_node: ast.Attribute) -> Optional[str]:
            return A.dotted(attr_node.value)

        def visit(node: ast.AST, in_lock: bool) -> None:
            if isinstance(node, ast.With):
                locked = in_lock or any(_with_is_lock(i)
                                        for i in node.items)
                for item in node.items:
                    visit(item.context_expr, in_lock)
                for stmt in node.body:
                    visit(stmt, locked)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return                       # nested defs: separate scans
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load) and not in_lock:
                recv = receiver_of(node)
                if recv is not None:
                    self._record(node, node.attr, recv, first_content,
                                 first_revision, flagged)
            for child in ast.iter_child_nodes(node):
                visit(child, in_lock)

        for stmt in func.body:
            visit(stmt, False)

        return [mod.finding(
            self.id, "error", node, symbol,
            f"`{code_name}` read AFTER its content on receiver — a "
            "writer landing between the reads stamps OLD content with "
            "the NEW revision and serves it as current forever; read "
            "the revision first (newer-content-under-older-stamp heals "
            "at the next freshness peek)")
            for node, code_name in flagged.values()]

    @staticmethod
    def _record(node: ast.Attribute, name: str, recv: str,
                first_content: Dict, first_revision: Dict,
                flagged: Dict) -> None:
        if _is_revision_name(name):
            if recv not in first_revision:
                first_revision[recv] = node
                if recv in first_content and recv not in flagged:
                    flagged[recv] = (node, name)
        elif _is_content_name(name):
            first_content.setdefault(recv, node)
