"""Runtime lockset race detector — Eraser's refinement over live locks.

`lockwatch` answers "is the lock *order* consistent"; this module
answers the prior question: "is shared state actually protected by the
lock the design says protects it". It implements the lockset algorithm
of Savage et al.'s Eraser on top of the same `_RecordingLock` proxy
`lockwatch` uses, plus per-field access instrumentation:

* **Locks** are watched exactly like `LockWatch.watch` — the proxy
  maintains a per-thread held-lockset.
* **Fields** come from the lock-protection map
  (`analysis/protection.py`): `watch_object(obj, group)` swaps the
  instance's class for a dynamically-created subclass whose
  `__getattribute__`/`__setattr__` record (field, thread, held-lockset,
  is_write) events for the declared fields. Fields the design reads
  lock-free (`lockfree_ok`) are never instrumented — Eraser would
  rightly empty their lockset and wrongly call the *convention* a bug.
* **Refinement** (per field): the candidate lockset starts as ⊤ (all
  locks) and is intersected with the held set on every access once the
  field leaves its initialization phase. The Eraser state machine
  keeps first-thread-exclusive access exempt (constructor/single-owner
  setup), starts refining on second-thread reads (`SHARED`), and
  *reports* when the candidate set empties in `SHARED_MODIFIED`
  (a write raced a second thread with no common lock).

Watching is cooperative and test-scoped: install on a live stack
(`launch_sim_stack`), drive it — including the serving fan-out and SSE
threads lockwatch does not cover — then `unwatch_all()` and read
`reports()`. The proxies add one dict op per access; poses of a watched
run must equal an unwatched one (asserted in the self-check tests).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

from jax_mapping.analysis.lockwatch import _RecordingLock
from jax_mapping.analysis.protection import LockGroup

#: Eraser states.
VIRGIN = "virgin"
EXCLUSIVE = "exclusive"              # one thread only — no refinement
SHARED = "shared"                    # 2+ threads, reads only since shared
SHARED_MODIFIED = "shared-modified"  # 2+ threads incl. a write — report

#: ⊤ — "every lock" before the first refinement.
_TOP = None


@dataclass
class FieldState:
    name: str                        # "MapperNode.states@mapper"
    state: str = VIRGIN
    first_thread: Optional[int] = None
    #: candidate lockset; None = ⊤ (not yet refined).
    candidate: Optional[FrozenSet[str]] = _TOP
    n_reads: int = 0
    n_writes: int = 0
    #: filled when the candidate set empties in SHARED_MODIFIED.
    report: Optional[str] = None
    #: last locksets seen, for the report text.
    last_write_lockset: FrozenSet[str] = frozenset()


@dataclass(frozen=True)
class RaceReport:
    field: str
    message: str


class RaceWatch:
    """Record lock-held sets + field accesses; apply Eraser refinement."""

    def __init__(self):
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._installed_locks: List[Tuple[object, str, object]] = []
        self._installed_objects: List[Tuple[object, type]] = []
        self._fields: Dict[Tuple[int, str], FieldState] = {}
        self._monitored_cache: Dict[Tuple[type, FrozenSet[str]], type] = {}

    # -- lock protocol (duck-typed for _RecordingLock) -----------------------

    def _held(self) -> List[str]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _record_acquire(self, name: str) -> None:
        self._held().append(name)

    def _record_release(self, name: str) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                return

    # -- installation --------------------------------------------------------

    def watch_lock(self, obj: object, attr: str,
                   name: Optional[str] = None) -> str:
        """Proxy `obj.<attr>` so acquisitions feed the held-lockset.
        Same contract (and same caveat about pre-captured lock
        references) as `LockWatch.watch`."""
        real = getattr(obj, attr)
        if isinstance(real, _RecordingLock):
            if real._watch is self:
                return real.name         # already ours: idempotent
            # Another watch's proxy (e.g. a LockWatch validating order
            # on the same stack): CHAIN ours over it — returning early
            # would route this lock's acquisitions only to the other
            # watch, leaving our held-set empty and every field's
            # candidate lockset spuriously intersecting to ∅.
        lock_name = name or f"{type(obj).__name__}.{attr}"
        setattr(obj, attr, _RecordingLock(self, real, lock_name))
        self._installed_locks.append((obj, attr, real))
        return lock_name

    def watch_object(self, obj: object, group: LockGroup,
                     name: Optional[str] = None) -> str:
        """Instrument `group.watchable_fields()` on `obj` AND its group
        lock. The object's class is swapped for a recording subclass;
        `unwatch_all` restores it."""
        tag = name or type(obj).__name__
        self.watch_lock(obj, group.lock_attr,
                        name=f"{group.cls}.{group.lock_attr}@{tag}")
        for extra in sorted(group.extra_locks):
            self.watch_lock(obj, extra,
                            name=f"{group.cls}.{extra}@{tag}")
        fields = frozenset(group.watchable_fields())
        cls = type(obj)
        key = (cls, fields)
        mon = self._monitored_cache.get(key)
        if mon is None:
            mon = self._make_monitored(cls, fields)
            self._monitored_cache[key] = mon
        self._installed_objects.append((obj, cls))
        # The subclass reads the watch + tag through instance slots set
        # BEFORE the swap so no recorded attribute is touched unarmed.
        object.__setattr__(obj, "_racewatch", self)
        object.__setattr__(obj, "_racewatch_tag", tag)
        obj.__class__ = mon
        return tag

    @staticmethod
    def _make_monitored(cls: type, fields: FrozenSet[str]) -> type:
        def __getattribute__(self, attr):
            value = object.__getattribute__(self, attr)
            if attr in fields:
                watch = object.__getattribute__(self, "_racewatch")
                tag = object.__getattribute__(self, "_racewatch_tag")
                watch._record_access(self, tag, attr, is_write=False)
            return value

        def __setattr__(self, attr, value):
            if attr in fields:
                watch = object.__getattribute__(self, "_racewatch")
                tag = object.__getattribute__(self, "_racewatch_tag")
                watch._record_access(self, tag, attr, is_write=True)
            object.__setattr__(self, attr, value)

        return type(f"Raced{cls.__name__}", (cls,), {
            "__getattribute__": __getattribute__,
            "__setattr__": __setattr__,
        })

    def unwatch_all(self) -> None:
        for obj, cls in reversed(self._installed_objects):
            obj.__class__ = cls
        self._installed_objects.clear()
        for obj, attr, real in reversed(self._installed_locks):
            setattr(obj, attr, real)
        self._installed_locks.clear()

    # -- the Eraser refinement ----------------------------------------------

    def _record_access(self, obj: object, tag: str, attr: str,
                       is_write: bool) -> None:
        held = frozenset(self._held())
        tid = threading.get_ident()
        key = (id(obj), attr)
        new_report = None
        with self._mu:
            st = self._fields.get(key)
            if st is None:
                st = self._fields[key] = FieldState(
                    name=f"{type(obj).__bases__[0].__name__}.{attr}@{tag}")
            if is_write:
                st.n_writes += 1
                st.last_write_lockset = held
            else:
                st.n_reads += 1
            if st.state == VIRGIN:
                st.state = EXCLUSIVE
                st.first_thread = tid
                return
            if st.state == EXCLUSIVE:
                if tid == st.first_thread:
                    return               # still single-owner: no refining
                st.state = SHARED_MODIFIED if is_write else SHARED
                # the first cross-thread access starts the candidate set
                st.candidate = held
            else:
                if st.state == SHARED and is_write:
                    st.state = SHARED_MODIFIED
                st.candidate = (held if st.candidate is _TOP
                                else st.candidate & held)
            if st.state == SHARED_MODIFIED and st.candidate is not _TOP \
                    and not st.candidate and st.report is None:
                st.report = (
                    f"{st.name}: candidate lockset EMPTY after a "
                    f"{'write' if is_write else 'read'} on thread "
                    f"{tid} holding {sorted(held) or ['<nothing>']} — "
                    "no single lock protects every access "
                    f"({st.n_reads} reads / {st.n_writes} writes "
                    "observed); the field races")
                new_report = st.name
        if new_report is not None:
            # Postmortem trigger (ISSUE 9): a race report is exactly
            # the moment the flight recorder's recent transitions
            # explain — record + dump OUTSIDE `_mu`, and BOTH on a
            # one-shot thread, never inline: _record_access fires
            # mid-attribute-access, i.e. while the racing thread may
            # still hold the watched object's own lock — and the
            # watched object may BE the global flight_recorder or the
            # Tracer attached to it (both have protection.py groups),
            # so an inline record()/dump() would re-take that
            # non-reentrant lock and self-deadlock (inline I/O under a
            # foreign caller lock would also break the B2 rule). The
            # thread sequences record before dump, so the dump's
            # snapshot still contains the report event. At most once
            # per field, so never hot.
            from jax_mapping.obs.recorder import flight_recorder

            def _postmortem(field=new_report):
                flight_recorder.record("racewatch_report", field=field)
                flight_recorder.dump(f"racewatch_{field}")

            threading.Thread(target=_postmortem,
                             name=f"racewatch-dump-{new_report}",
                             daemon=True).start()

    # -- results -------------------------------------------------------------

    def reports(self) -> List[RaceReport]:
        with self._mu:
            return [RaceReport(field=st.name, message=st.report)
                    for st in self._fields.values()
                    if st.report is not None]

    def field_states(self) -> Dict[str, FieldState]:
        """Per-field final states keyed by display name (telemetry and
        the self-check's 'the watch actually saw traffic' assertion)."""
        with self._mu:
            return {st.name: st for st in self._fields.values()}
