"""Pallas TPU kernel: batched 3D inverse sensor model over voxel patches.

The 3D hot op, built from the design note recorded in `ops/voxel.py` in
round 4: the XLA formulation pays a per-voxel `depth[vi, ui]` image gather
over the (Z, P, P) patch — the same scalarised-gather hazard the 2D path
had with `ranges[beam]` before `ops/sensor_kernel.py` (~10x the cost of
the rest of the model there). At pitch == 0 the gather FACTORS:
camera-frame cxc/czc depend only on the voxel COLUMN (y, x) — not z — so

  (1) the image column index u is ONE integer per (y, x): the whole
      column picks one W-wide image column. Done on the MXU: a one-hot
      (cols, W) matmul against the transposed image (W, H) — the one-hot
      trick the 2D kernel rejected is RIGHT here, because the output is
      H = 120 lanes wide (the 2D case starved at 8 of 128 output lanes).
      f32 `Precision.HIGHEST` makes the pick bit-exact (a one-hot row
      times the 3-term bf16 split of a depth value re-sums all 24
      mantissa bits).
  (2) the per-z image row index v is LINEAR in z down that one H-entry
      column: an in-vreg `take_along_axis` along lanes — the identical
      lookup class as the 2D kernel's 128-lane beam-table gather
      (H = 120 <= 128 fits one vreg row).

Layout: each kernel step processes a tile of C=128 voxel COLUMNS of the
flattened (y, x) patch on sublanes, with lanes holding (stage by stage)
the W-wide one-hot, the H-wide picked column, and finally the Z-wide
log-odds delta. The (Z, P, P) result is materialised as (P*P, Z) —
column-major in z — and reshaped/transposed by XLA outside the kernel.

A strip cull mirrors the 2D kernel's: a tile whose patch rows all sit
farther from the camera than `max_range_m` (the EUCLIDEAN trust horizon
bounds |dy|) produces delta == 0 everywhere and skips its body.

Semantics match `ops/voxel.classify_region` exactly (same `safe_z`
guard, round-to-nearest-even pixel indices, clipped gather with raw-index
validity masks, euclidean trust horizon, zero-depth-carves-nothing);
tests hold both to the NumPy loop oracle in `tests/test_voxel.py` and to
each other, CPU interpret mode + TPU parity behind JAX_MAPPING_TPU_TESTS
(the `tests/test_sensor_kernel.py` pattern).

Requirements (checked, ValueError otherwise — callers fall back to the
XLA path): `mount_pitch_rad == 0` (the factorization's premise),
`height_px <= 128`, `size_z_cells <= 128`, `patch_cells**2 % 128 == 0`.

Throughput target (stated in BASELINE terms): >= 640 images/s on a v5e
chip = 64 robots x the reference's 10 Hz sensor cadence
(`/root/reference/server/thymio_project/thymio_project/main.py:60`);
the CPU-only XLA number from round 4 was 23.9 images/s (BENCH_r04.json).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax_mapping.config import DepthCamConfig, VoxelConfig

Array = jax.Array

LANES = 128      # TPU vreg lane count
COLS = 128       # voxel columns (flattened y,x) per kernel step

# VMEM ceiling on the whole-batch depth table (B, nw, 128, 128) f32 that
# stays resident across a call (~65 kB * nw per image); larger batches
# split across calls exactly like sensor_kernel._MAX_B_PER_CALL.
_MAX_B_PER_CALL = 32


def kernel_supported(vox: VoxelConfig, cam: DepthCamConfig) -> bool:
    """Static config compatibility for the PATCH paths — the pitch-0
    factorization premise plus vreg-shape fits (one predicate,
    region_supported, so the patch and slab paths cannot drift)."""
    return region_supported(vox, cam, vox.patch_cells, vox.patch_cells)


def _check(vox: VoxelConfig, cam: DepthCamConfig) -> None:
    if not kernel_supported(vox, cam):
        raise ValueError(
            f"voxel kernel unsupported for this config: needs pitch==0 "
            f"(got {cam.mount_pitch_rad}), height_px<={LANES} (got "
            f"{cam.height_px}), size_z_cells<={LANES} (got "
            f"{vox.size_z_cells}), patch_cells^2 % {COLS} == 0 (got "
            f"{vox.patch_cells}); use ops.voxel.classify_patch")


def _n_wchunks(cam: DepthCamConfig) -> int:
    return -(-cam.width_px // LANES)


def depth_table(cam: DepthCamConfig, depths_b: Array) -> Array:
    """(B, H, W) depth images -> (B, nw, LANES, LANES) packed transposed
    table: `table[b, c, w, h] = depth[b, h, c*128 + w]` (zero padded).
    Row w of chunk c is image COLUMN c*128+w laid along lanes — the shape
    stage (1)'s one-hot matmul consumes."""
    B, H, W = depths_b.shape
    nw = _n_wchunks(cam)
    dT = jnp.swapaxes(depths_b, 1, 2)                       # (B, W, H)
    dT = jnp.pad(dT, ((0, 0), (0, nw * LANES - W), (0, LANES - H)))
    return dT.reshape(B, nw, LANES, LANES).astype(jnp.float32)


def _pose_table(poses_b: Array) -> Array:
    """(B, 3) [x, y, yaw] -> (B, 4) [x, y, cos yaw, sin yaw] for SMEM.
    cos/sin computed by XLA outside the kernel with the same jnp ops as
    `voxel.camera_pose` (bit-identical rotation terms)."""
    p = poses_b.astype(jnp.float32)
    return jnp.stack([p[:, 0], p[:, 1],
                      jnp.cos(p[:, 2]), jnp.sin(p[:, 2])], axis=1)


def _make_kernel(vox: VoxelConfig, cam: DepthCamConfig, accumulate: bool,
                 ny: int = None, nx: int = None):
    """Kernel over a (Z, ny, nx) region (default: the (P, P) patch).
    The sharded path passes full-width Y slabs (ny=slab_rows,
    nx=size_x_cells) — same math, different flattening."""
    ny = vox.patch_cells if ny is None else ny
    nx = vox.patch_cells if nx is None else nx
    Z = vox.size_z_cells
    H, W = cam.height_px, cam.width_px
    nw = _n_wchunks(cam)
    res = vox.resolution_m
    ox, oy, oz = vox.origin_m
    camz = float(cam.mount_height_m)
    fx, fy = float(cam.fx), float(cam.fy)
    cx_, cy_ = float(cam.cx), float(cam.cy)
    rmin = float(cam.range_min_m)
    max_r = float(vox.max_range_m)
    tol = vox.hit_tolerance_cells * res
    lo_occ, lo_free = float(vox.logodds_occ), float(vox.logodds_free)

    def kernel(table_ref, pose_ref, origin_ref, out_ref):
        t = pl.program_id(0)
        b = pl.program_id(1)

        px = pose_ref[b, 0]
        py = pose_ref[b, 1]
        cyaw = pose_ref[b, 2]
        syaw = pose_ref[b, 3]
        y0 = origin_ref[b, 0]
        x0 = origin_ref[b, 1]

        # Tile row-band cull: the euclidean trust horizon bounds |wy - py|
        # by max_range, so a tile whose patch rows all sit farther away
        # classifies nothing. One cell of slack for the half-cell centre.
        row_lo = ((t * COLS) // nx).astype(jnp.float32)
        row_hi = (((t + 1) * COLS - 1) // nx).astype(jnp.float32)
        pose_row = (py - oy) / res - 0.5 - y0.astype(jnp.float32)
        gap = jnp.maximum(
            jnp.maximum(row_lo - pose_row, pose_row - row_hi), 0.0)
        near_tile = gap * res <= max_r + res

        if accumulate:
            @pl.when(b == 0)
            def _():
                out_ref[:] = jnp.zeros_like(out_ref)

        def body():
            # Per-column geometry. Column index on sublanes; every lane
            # of a row carries the same per-column value until stage (2)
            # fans out over z on lanes.
            cc = jax.lax.broadcasted_iota(jnp.int32, (COLS, LANES), 0)
            flat = t * COLS + cc
            r_i = flat // nx
            c_i = flat - r_i * nx
            wy = ((y0 + r_i).astype(jnp.float32) + 0.5) * res + oy
            wx = ((x0 + c_i).astype(jnp.float32) + 0.5) * res + ox
            dx = wx - px
            dy = wy - py
            # Pitch-0 camera basis (voxel.camera_pose with p=0):
            # right=(sy,-cy,0), down=(0,0,-1), fwd=(cy,sy,0).
            cxc = syaw * dx - cyaw * dy           # camera x (constant in z)
            czc = cyaw * dx + syaw * dy           # camera z (constant in z)
            in_front = czc > rmin
            safe_z = jnp.where(in_front, czc, 1.0)
            u = fx * cxc / safe_z + cx_
            ui = jnp.round(u).astype(jnp.int32)
            in_u = (ui >= 0) & (ui < W)
            ui_c = jnp.clip(ui, 0, W - 1)

            # Stage (1): one-hot MXU pick of each column's image column.
            # HIGHEST precision = exact f32 pass-through of the depth
            # values (one-hot weights are exactly 1.0/0.0).
            ll = jax.lax.broadcasted_iota(jnp.int32, (COLS, LANES), 1)
            percol = jnp.zeros((COLS, LANES), jnp.float32)
            for c in range(nw):
                oh = (ui_c == c * LANES + ll).astype(jnp.float32)
                percol = percol + jax.lax.dot_general(
                    oh, table_ref[b, c], (((1,), (0,)), ((), ())),
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=jnp.float32)

            # Stage (2): per-z row sample down the picked column. v is
            # linear in z; the lookup is an in-vreg lane gather.
            wz = (ll.astype(jnp.float32) + 0.5) * res + oz
            cyc = camz - wz                        # camera y (pitch 0)
            v = fy * cyc / safe_z + cy_
            vi = jnp.round(v).astype(jnp.int32)
            in_v = (vi >= 0) & (vi < H)
            z_img = jnp.take_along_axis(percol, jnp.clip(vi, 0, H - 1),
                                        axis=1)

            near = (cxc * cxc + cyc * cyc + czc * czc) <= max_r * max_r
            valid = (in_front & in_u & in_v & near
                     & (z_img > 0.0) & (z_img >= rmin))
            carve = jnp.minimum(jnp.where(z_img > 0.0, z_img, 0.0), max_r)
            free = valid & (czc < carve - tol)
            occ = valid & (jnp.abs(czc - z_img) <= tol) & (z_img <= max_r)
            delta = jnp.where(occ, lo_occ, jnp.where(free, lo_free, 0.0))
            # Lanes beyond Z are sliced off by the (COLS, Z) store.
            return delta[:, :Z].astype(jnp.float32)

        if accumulate:
            @pl.when(near_tile)
            def _():
                out_ref[:] = out_ref[:] + body()
        else:
            @pl.when(near_tile)
            def _():
                out_ref[0] = body()

            @pl.when(jnp.logical_not(near_tile))
            def _():
                out_ref[0] = jnp.zeros_like(out_ref[0])

    return kernel


def _colmajor_to_region(vox: VoxelConfig, flat: Array,
                        ny: int, nx: int) -> Array:
    """(..., ny*nx, Z) kernel output -> (..., Z, ny, nx)."""
    Z = vox.size_z_cells
    nd = flat.ndim
    out = flat.reshape(*flat.shape[:-2], ny, nx, Z)
    return jnp.moveaxis(out, nd, nd - 2)


def _colmajor_to_patch(vox: VoxelConfig, flat: Array) -> Array:
    """(..., P*P, Z) kernel output -> (..., Z, P, P)."""
    return _colmajor_to_region(vox, flat, vox.patch_cells, vox.patch_cells)


@functools.partial(jax.jit, static_argnums=(0, 1))
def image_deltas(vox: VoxelConfig, cam: DepthCamConfig, depths_b: Array,
                 poses_b: Array, origins_yx: Array) -> Array:
    """Per-image (B, Z, P, P) log-odds patch deltas, one origin per image.

    The general-pose path: feeds the sequential exact fold in
    `fuse_depths` (scattered fleet poses). Mirrors
    `sensor_kernel.scan_deltas`.

    Args:
      depths_b: (B, H, W) metres, 0 = no return.
      poses_b: (B, 3) [x, y, yaw]; origins_yx: (B, 2) int32 [y0, x0].
    """
    _check(vox, cam)
    P, Z = vox.patch_cells, vox.size_z_cells
    B = depths_b.shape[0]
    if B == 0:
        return jnp.zeros((0, Z, P, P), jnp.float32)
    if B > _MAX_B_PER_CALL:
        return jnp.concatenate([
            image_deltas(vox, cam, depths_b[i:i + _MAX_B_PER_CALL],
                         poses_b[i:i + _MAX_B_PER_CALL],
                         origins_yx[i:i + _MAX_B_PER_CALL])
            for i in range(0, B, _MAX_B_PER_CALL)], axis=0)
    table = depth_table(cam, depths_b)
    kernel = _make_kernel(vox, cam, accumulate=False)
    ncols = P * P
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(ncols // COLS, B),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),   # whole depth table
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, COLS, Z), lambda t, b: (b, t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, ncols, Z), jnp.float32),
        interpret=interpret,
    )(table, _pose_table(poses_b),
      origins_yx.astype(jnp.int32).reshape(B, 2))
    return _colmajor_to_patch(vox, out)


@functools.partial(jax.jit, static_argnums=(0, 1))
def window_delta(vox: VoxelConfig, cam: DepthCamConfig, depths_b: Array,
                 poses_b: Array, origin_yx: Array) -> Array:
    """Sum of all B images' deltas on ONE shared (Z, P, P) patch.

    The temporal-window path (one robot's consecutive frames share a
    patch): replaces the B-step sequential fold with a single aligned
    read-modify-write, like `sensor_kernel.window_delta`. Caller is
    responsible for the shared-patch contract (`window_fits`).
    """
    _check(vox, cam)
    P = vox.patch_cells
    B = depths_b.shape[0]
    origins = jnp.broadcast_to(
        origin_yx.astype(jnp.int32).reshape(1, 2), (max(B, 1), 2))
    return _summed_delta(vox, cam, depths_b, poses_b, origins, P, P)


def _summed_delta(vox: VoxelConfig, cam: DepthCamConfig, depths_b: Array,
                  poses_b: Array, origins_b: Array, ny: int,
                  nx: int) -> Array:
    """Shared accumulate-mode body of window_delta and region_delta: the
    batch-summed (Z, ny, nx) delta at per-image origins (one pallas_call
    per <=_MAX_B_PER_CALL chunk so the two public paths cannot drift)."""
    Z = vox.size_z_cells
    B = depths_b.shape[0]
    if B == 0:
        return jnp.zeros((Z, ny, nx), jnp.float32)
    if B > _MAX_B_PER_CALL:
        total = jnp.zeros((Z, ny, nx), jnp.float32)
        for i in range(0, B, _MAX_B_PER_CALL):
            total = total + _summed_delta(
                vox, cam, depths_b[i:i + _MAX_B_PER_CALL],
                poses_b[i:i + _MAX_B_PER_CALL],
                origins_b[i:i + _MAX_B_PER_CALL], ny, nx)
        return total
    table = depth_table(cam, depths_b)
    kernel = _make_kernel(vox, cam, accumulate=True, ny=ny, nx=nx)
    ncols = ny * nx
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(ncols // COLS, B),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((COLS, Z), lambda t, b: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((ncols, Z), jnp.float32),
        interpret=interpret,
    )(table, _pose_table(poses_b), origins_b)
    return _colmajor_to_region(vox, out, ny, nx)


def region_supported(vox: VoxelConfig, cam: DepthCamConfig,
                     ny: int, nx: int) -> bool:
    """Static support check for arbitrary (ny, nx) regions (the sharded
    Y-slab path): the patch shape constraint generalises to the region's
    flattened column count."""
    return (cam.mount_pitch_rad == 0.0
            and cam.height_px <= LANES
            and vox.size_z_cells <= LANES
            and (ny * nx) % COLS == 0)


@functools.partial(jax.jit, static_argnums=(0, 1, 5, 6))
def region_delta(vox: VoxelConfig, cam: DepthCamConfig, depths_b: Array,
                 poses_b: Array, y0, ny: int, nx: int) -> Array:
    """Summed (Z, ny, nx) log-odds delta of B images over the region at
    rows y0.., cols 0.. — the kernel twin of summing
    `voxel.classify_region` over the batch. The sharded Y-slab fuse
    (`parallel/voxel_sharded.py`) calls it per device with its own
    traced y0; there is no coverage contract here (the slab keeps every
    in-trust-radius update, unlike patches).
    """
    Z = vox.size_z_cells
    if not region_supported(vox, cam, ny, nx):
        raise ValueError(
            f"voxel region kernel unsupported: pitch="
            f"{cam.mount_pitch_rad}, H={cam.height_px}, Z={Z}, "
            f"ny*nx={ny * nx} % {COLS}")
    B = depths_b.shape[0]
    origins = jnp.stack(
        [jnp.broadcast_to(jnp.asarray(y0, jnp.int32), (max(B, 1),)),
         jnp.zeros((max(B, 1),), jnp.int32)], axis=1)
    return _summed_delta(vox, cam, depths_b, poses_b, origins, ny, nx)


def window_fits(vox: VoxelConfig, poses_b: Array, origin_yx: Array) -> Array:
    """Scalar bool: every camera's max-range disc inside the shared patch
    (the `sensor_kernel.window_fits` contract in 3D)."""
    P = vox.patch_cells
    margin = vox.max_range_m / vox.resolution_m
    ox, oy, _ = vox.origin_m
    col = (poses_b[:, 0] - ox) / vox.resolution_m
    row = (poses_b[:, 1] - oy) / vox.resolution_m
    r0 = origin_yx[0].astype(jnp.float32)
    c0 = origin_yx[1].astype(jnp.float32)
    ok = ((row - margin >= r0) & (row + margin <= r0 + P)
          & (col - margin >= c0) & (col + margin <= c0 + P))
    return ok.all()


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_depths(vox: VoxelConfig, cam: DepthCamConfig, grid: Array,
                depths_b: Array, poses_b: Array) -> Array:
    """Kernel-engine batch fuse: per-image kernel deltas -> the same
    chunked sequential aligned fold as `voxel.fuse_depths` (identical
    chunking and fold order bound peak delta memory the same way; clamp
    once per call). Dispatched from `voxel.fuse_depths` on TPU;
    parity-tested against the XLA path on every backend."""
    from jax_mapping.ops import voxel as V
    V._check_patch_coverage(vox, cam)
    _check(vox, cam)
    B = depths_b.shape[0]
    if B == 0:
        return grid

    def pose_origin(pose):
        pos, _ = V.camera_pose(pose[0], pose[1], pose[2], cam)
        return V.patch_origin(vox, pos[:2])

    def chunk(g, dp):
        d, p = dp
        origins = jax.vmap(pose_origin)(p)
        deltas = image_deltas(vox, cam, d, p, origins)

        def body(gg, do):
            return V.apply_patch(vox, gg, do[0], do[1], clamp=False), None
        out, _ = jax.lax.scan(body, g, (deltas, origins))
        return out, None

    CB = min(V._FUSE_CHUNK, B)
    nc, rem = B // CB, B % CB
    out = grid
    if nc:
        cut = nc * CB
        out, _ = jax.lax.scan(
            chunk, out,
            (depths_b[:cut].reshape(nc, CB, *depths_b.shape[1:]),
             poses_b[:cut].reshape(nc, CB, 3)))
    if rem:
        out, _ = chunk(out, (depths_b[B - rem:], poses_b[B - rem:]))
    return jnp.clip(out, vox.logodds_min, vox.logodds_max)
