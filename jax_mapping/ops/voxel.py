"""3D log-odds voxel grid fused from depth images — OctoMap-style mapping,
TPU-first (BASELINE.json configs[4]: "3D voxel grid (OctoMap-style) from
simulated depth cam").

The reference maps in 2D only (slam_toolbox, slam_config.yaml:26-27); this
module generalizes the framework's dense inverse-sensor-patch idiom
(ops/grid.py) to 3D. OctoMap's CPU design — per-ray octree traversal with
pointer chasing — is exactly what a TPU cannot run; instead every voxel of
a fixed-shape local patch evaluates the inverse sensor model against the
depth image directly:

    for every voxel v in a (Z, P, P) patch around the camera:
        c            = R^T (v - cam_pos)          # camera frame, z optical
        (u, v_px)    = pinhole projection of c    # static-shape math
        z_img        = depth[v_px, u]             # one gather per voxel
        v is FREE      if c.z < min(z_img, r_max) - tol  (in frustum, valid)
        v is OCCUPIED  if |c.z - z_img| <= tol           (valid return)
        else unchanged

No ray marching, no scatter: each voxel is written exactly once per image,
so batching over images is a vmap and fleet merging is an add — the same
deterministic-accumulation property the 2D grid gets (SURVEY.md §7).

Layout: (Z, Y, X), X on TPU lanes (128-aligned patch origins), Y on
sublanes, Z as the small outer axis. Update patches span the FULL Z extent
(buildings are shallow; ranges are horizontal-ish), so patch origins stay
2D (y0, x0) and the global fold is the same aligned dynamic_update_slice
read-modify-write the 2D grid uses.

Depth-image conventions: pinhole (DepthCamConfig), optical axes (camera z
forward, x right, y down), depth = z along the OPTICAL AXIS (what real
depth sensors report), NOT euclidean ray length. A reading of exactly 0
means "no return" and carves nothing — see DepthCamConfig's docstring for
why this differs from the LD06 zero-as-outlier rule.

The Pallas kernel for the hot classify (`ops/voxel_kernel.py`, built in
round 5 from the round-4 design note) exploits the pitch==0 structure:
camera-frame cxc and czc depend only on (y, x) — NOT z — so the per-voxel
`depth[vi, ui]` gather (the XLA-TPU hazard, exactly like the 2D path's
`ranges[beam]` before its in-vreg kernel) factors into (1) a per-(y, x)
column pick from the W-wide image — a one-hot MXU matmul — and (2) per-z
samples at linear positions down one H-entry column — an in-vreg lane
gather. `fuse_depths` dispatches to it on TPU (`_use_pallas`);
parity-tested bit-exact against this module's XLA path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from jax_mapping.config import DepthCamConfig, VoxelConfig

Array = jax.Array


# ---------------------------------------------------------------------------
# Camera pose
# ---------------------------------------------------------------------------

def camera_pose(x_m, y_m, yaw_rad, cam_cfg: DepthCamConfig
                ) -> Tuple[Array, Array]:
    """Robot planar pose -> (cam_pos (3,), R_wc (3,3)) world-frame camera.

    The camera sits `mount_height_m` above the ground at the robot's x/y,
    optical axis along the robot heading tilted by `mount_pitch_rad`
    (>0 = up). R_wc columns are the camera's (x=right, y=down, z=forward)
    axes expressed in world coordinates; world points map to camera frame
    via R_wc^T (w - pos).
    """
    x_m = jnp.asarray(x_m, jnp.float32)
    y_m = jnp.asarray(y_m, jnp.float32)
    yaw = jnp.asarray(yaw_rad, jnp.float32)
    p = jnp.float32(cam_cfg.mount_pitch_rad)
    cy, sy = jnp.cos(yaw), jnp.sin(yaw)
    cp, sp = jnp.cos(p), jnp.sin(p)
    fwd = jnp.stack([cp * cy, cp * sy, sp])          # optical axis (cam z)
    right = jnp.stack([sy, -cy, jnp.zeros_like(sy)])  # cam x
    down = jnp.cross(fwd, right)                      # cam y (world -z at p=0)
    pos = jnp.stack([x_m, y_m, jnp.float32(cam_cfg.mount_height_m)])
    return pos, jnp.stack([right, down, fwd], axis=1)


# ---------------------------------------------------------------------------
# Patch origin (2D, full-Z patches) — the ops/grid.py alignment contract
# ---------------------------------------------------------------------------

def patch_origin(vox: VoxelConfig, cam_pos_xy: Array) -> Array:
    """Aligned int32 (y0, x0) of the update patch around the camera."""
    ox, oy, _ = vox.origin_m
    cx = (cam_pos_xy[0] - ox) / vox.resolution_m
    cy = (cam_pos_xy[1] - oy) / vox.resolution_m
    ax, ay = vox.align_x, vox.align_y
    x0 = jnp.round((cx - vox.patch_cells / 2) / ax).astype(jnp.int32) * ax
    y0 = jnp.round((cy - vox.patch_cells / 2) / ay).astype(jnp.int32) * ay
    x0 = jnp.clip(x0, 0, vox.size_x_cells - vox.patch_cells)
    y0 = jnp.clip(y0, 0, vox.size_y_cells - vox.patch_cells)
    return jnp.stack([y0, x0])


def empty_voxel_grid(vox: VoxelConfig, dtype=jnp.float32) -> Array:
    """Fresh all-unknown (log-odds 0) voxel grid, (Z, Y, X)."""
    return jnp.zeros((vox.size_z_cells, vox.size_y_cells, vox.size_x_cells),
                     dtype=dtype)


# ---------------------------------------------------------------------------
# Dense inverse sensor model over an arbitrary (Z, Ny, Nx) region
# ---------------------------------------------------------------------------

def classify_region(vox: VoxelConfig, cam: DepthCamConfig, depth: Array,
                    cam_pos: Array, R_wc: Array, y0, x0,
                    ny: int, nx: int) -> Array:
    """Log-odds delta for the (Z, ny, nx) voxel region at rows y0, cols x0.

    The one model evaluation both fusion paths share: the patch path calls
    it at (patch_cells, patch_cells); the sharded path (parallel/
    voxel_sharded.py) calls it on each device's Y slab directly — the model
    is pure per-voxel math + one image gather, so GSPMD/shard_map splits it
    along Y with zero collectives.

    Args:
      depth: (H, W) float32 metres, 0 = no return (carves nothing).
      cam_pos: (3,) world camera position; R_wc: (3, 3) from camera_pose.
      y0, x0: traced int32 region origin (rows, cols).
    """
    res = vox.resolution_m
    ox, oy, oz = vox.origin_m
    Z = vox.size_z_cells
    # Voxel centre world coordinates, broadcast to (Z, ny, nx) lazily.
    xs = (x0 + jnp.arange(nx, dtype=jnp.int32)).astype(jnp.float32)
    ys = (y0 + jnp.arange(ny, dtype=jnp.int32)).astype(jnp.float32)
    zs = jnp.arange(Z, dtype=jnp.float32)
    wx = (xs + 0.5) * res + ox                       # (nx,)
    wy = (ys + 0.5) * res + oy                       # (ny,)
    wz = (zs + 0.5) * res + oz                       # (Z,)
    dx = (wx - cam_pos[0])[None, None, :]            # (1, 1, nx)
    dy = (wy - cam_pos[1])[None, :, None]            # (1, ny, 1)
    dz = (wz - cam_pos[2])[:, None, None]            # (Z, 1, 1)

    # Camera-frame coordinates: c = R^T d, expanded per-component so the
    # (Z, ny, nx) cube is built from broadcasted rank-1 pieces (XLA fuses
    # these; no (Z*ny*nx, 3) matmul materialisation).
    cxc = R_wc[0, 0] * dx + R_wc[1, 0] * dy + R_wc[2, 0] * dz   # cam x
    cyc = R_wc[0, 1] * dx + R_wc[1, 1] * dy + R_wc[2, 1] * dz   # cam y
    czc = R_wc[0, 2] * dx + R_wc[1, 2] * dy + R_wc[2, 2] * dz   # cam z

    in_front = czc > cam.range_min_m
    safe_z = jnp.where(in_front, czc, 1.0)
    u = cam.fx * cxc / safe_z + cam.cx
    v = cam.fy * cyc / safe_z + cam.cy
    ui = jnp.round(u).astype(jnp.int32)
    vi = jnp.round(v).astype(jnp.int32)
    in_img = ((ui >= 0) & (ui < cam.width_px)
              & (vi >= 0) & (vi < cam.height_px))
    frustum = in_front & in_img

    z_img = depth[jnp.clip(vi, 0, cam.height_px - 1),
                  jnp.clip(ui, 0, cam.width_px - 1)]
    # Trust horizon is EUCLIDEAN distance (OctoMap's max-range-on-the-ray
    # semantics), not axial depth: an axial-only bound would let frustum-
    # corner voxels classify up to max_range/cos(diag half-FOV) ~ 1.4x
    # max_range away horizontally — outside the patch coverage contract
    # (_check_patch_coverage), where the patch path would silently drop
    # them while the sharded full-slab path kept them. The euclidean bound
    # makes the two paths bit-identical.
    max_r = jnp.float32(vox.max_range_m)
    near = (cxc * cxc + cyc * cyc + czc * czc) <= max_r * max_r
    valid = frustum & near & (z_img > 0.0) & (z_img >= cam.range_min_m)

    tol = vox.hit_tolerance_cells * res
    carve = jnp.minimum(jnp.where(z_img > 0.0, z_img, 0.0), max_r)
    free = valid & (czc < carve - tol)
    occ = valid & (jnp.abs(czc - z_img) <= tol) & (z_img <= max_r)

    delta = jnp.where(occ, vox.logodds_occ,
                      jnp.where(free, vox.logodds_free, 0.0))
    return delta.astype(jnp.float32)


def classify_patch(vox: VoxelConfig, cam: DepthCamConfig, depth: Array,
                   cam_pos: Array, R_wc: Array, origin_yx: Array) -> Array:
    """The (Z, P, P) patch delta for one depth image."""
    P = vox.patch_cells
    return classify_region(vox, cam, depth, cam_pos, R_wc,
                           origin_yx[0], origin_yx[1], P, P)


# ---------------------------------------------------------------------------
# Folding patches into the global voxel grid
# ---------------------------------------------------------------------------

def apply_patch(vox: VoxelConfig, grid: Array, delta: Array,
                origin_yx: Array, clamp: bool = True) -> Array:
    """grid[:, y0:y0+P, x0:x0+P] += delta, clamped to log-odds bounds."""
    P = vox.patch_cells
    idx = (jnp.int32(0), origin_yx[0], origin_yx[1])
    cur = jax.lax.dynamic_slice(grid, idx, (vox.size_z_cells, P, P))
    new = cur + delta
    if clamp:
        new = jnp.clip(new, vox.logodds_min, vox.logodds_max)
    return jax.lax.dynamic_update_slice(grid, new, idx)


# Images classified per fold chunk: a (B, Z, P, P) delta batch at the
# production shape (64, 384, 384) is B x 37.7 MB of HBM; chunking bounds
# peak memory the same way grid._FUSE_CHUNK does for 2D scans.
_FUSE_CHUNK = 8


def _check_patch_coverage(vox: VoxelConfig, cam: DepthCamConfig) -> None:
    """Static (trace-time) guard on the VoxelConfig coverage contract:
    patch/2 - align_x/2 must reach the trust horizon, or origin alignment
    can shift the patch far enough that valid returns land outside the
    update region and silently vanish (the bug code review caught in the
    first default config)."""
    slack_m = (vox.patch_cells / 2 - max(vox.align_x, vox.align_y) / 2) \
        * vox.resolution_m
    # The horizon is the VOXEL trust radius alone: classify_region bounds
    # its valid region by euclidean distance <= vox.max_range_m regardless
    # of the camera's range cap (a caller may feed depth values past the
    # camera spec; free-carving laterally reaches the voxel radius).
    horizon = vox.max_range_m
    if slack_m < horizon:
        raise ValueError(
            f"voxel patch coverage violated: patch/2 - align/2 = "
            f"{slack_m:.2f} m < trust horizon {horizon:.2f} m; raise "
            f"patch_cells or shrink max_range_m")


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_depth(vox: VoxelConfig, cam: DepthCamConfig, grid: Array,
               depth: Array, pose_xyyaw: Array) -> Array:
    """Fuse ONE depth image taken from a planar robot pose [x, y, yaw]."""
    _check_patch_coverage(vox, cam)
    pos, R = camera_pose(pose_xyyaw[0], pose_xyyaw[1], pose_xyyaw[2], cam)
    origin = patch_origin(vox, pos[:2])
    delta = classify_patch(vox, cam, depth, pos, R, origin)
    return apply_patch(vox, grid, delta, origin)


def _use_pallas(vox: VoxelConfig, cam: DepthCamConfig) -> bool:
    """Kernel engine on TPU (grid._use_pallas's policy, incl. the
    JAX_MAPPING_NO_PALLAS escape hatch) for supported configs;
    unsupported ones (pitched camera, oversize image/z extents) stay on
    the parity-tested XLA path below."""
    from jax_mapping.ops.grid import _use_pallas as _grid_use_pallas
    if not _grid_use_pallas():
        return False
    from jax_mapping.ops import voxel_kernel as VKK
    return VKK.kernel_supported(vox, cam)


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_depths(vox: VoxelConfig, cam: DepthCamConfig, grid: Array,
                depths_b: Array, poses_b: Array) -> Array:
    """Fuse a batch of B depth images — backend-dispatched.

    On TPU the Pallas kernel (ops/voxel_kernel.py) computes the deltas;
    elsewhere (or for kernel-unsupported configs) the XLA formulation
    runs. Identical chunking/fold/clamp semantics either way.
    """
    if _use_pallas(vox, cam):
        from jax_mapping.ops import voxel_kernel as VKK
        return VKK.fuse_depths(vox, cam, grid, depths_b, poses_b)
    return fuse_depths_xla(vox, cam, grid, depths_b, poses_b)


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_depths_xla(vox: VoxelConfig, cam: DepthCamConfig, grid: Array,
                    depths_b: Array, poses_b: Array) -> Array:
    """Fuse a batch of B depth images, chunked classify -> sequential fold.

    Classification is vmapped (fully parallel); the fold is a sequential
    scan of aligned read-modify-writes — exact under overlapping patches,
    no scatter (the 2D fuse_scans design, ops/grid.py).

    Clamp semantics: ONCE per call, not per image (the 2D
    grid.fuse_scans_window precedent — slam_toolbox's bounded relaxation
    per map update cycle). This also makes the sharded path
    (parallel/voxel_sharded.py: sum all slab deltas, clamp once)
    bit-identical: per-image clamping would diverge on voxels saturating
    mid-batch under mixed-sign updates.

    Args:
      depths_b: (B, H, W) metres; poses_b: (B, 3) [x, y, yaw].
    """
    _check_patch_coverage(vox, cam)
    B = depths_b.shape[0]
    if B == 0:
        return grid

    def classify_one(depth, pose):
        pos, R = camera_pose(pose[0], pose[1], pose[2], cam)
        origin = patch_origin(vox, pos[:2])
        return classify_patch(vox, cam, depth, pos, R, origin), origin

    def chunk(g, dp):
        d, p = dp
        deltas, origins = jax.vmap(classify_one)(d, p)

        def body(gg, do):
            return apply_patch(vox, gg, do[0], do[1], clamp=False), None
        out, _ = jax.lax.scan(body, g, (deltas, origins))
        return out, None

    CB = min(_FUSE_CHUNK, B)
    nc, rem = B // CB, B % CB
    out = grid
    if nc:
        cut = nc * CB
        out, _ = jax.lax.scan(
            chunk, out,
            (depths_b[:cut].reshape(nc, CB, *depths_b.shape[1:]),
             poses_b[:cut].reshape(nc, CB, 3)))
    if rem:
        out, _ = chunk(out, (depths_b[B - rem:], poses_b[B - rem:]))
    return jnp.clip(out, vox.logodds_min, vox.logodds_max)


# ---------------------------------------------------------------------------
# Export
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0,))
def to_occupancy(vox: VoxelConfig, grid: Array) -> Array:
    """Log-odds -> int8 {-1 unknown, 0 free, 100 occupied}, the same
    tri-state contract the 2D grid exports (grid.to_occupancy)."""
    occ = grid > vox.occ_threshold
    free = grid < vox.free_threshold
    return jnp.where(occ, jnp.int8(100),
                     jnp.where(free, jnp.int8(0), jnp.int8(-1)))


@functools.partial(jax.jit, static_argnums=(0,))
def height_map(vox: VoxelConfig, grid: Array) -> Array:
    """(Y, X) float32 metres: top surface of occupied space per column
    (-1.0 where the column holds no occupied voxel). The 2.5D projection
    that feeds a 2D planner from the 3D map."""
    occ = grid > vox.occ_threshold                    # (Z, Y, X)
    zs = jnp.arange(vox.size_z_cells, dtype=jnp.float32)
    top = jnp.max(jnp.where(occ, zs[:, None, None], -jnp.inf), axis=0)
    _, _, oz = vox.origin_m
    h = (top + 1.0) * vox.resolution_m + oz
    return jnp.where(jnp.isfinite(top), h, -1.0).astype(jnp.float32)


@functools.partial(jax.jit, static_argnums=(0, 2, 3))
def obstacle_slice(vox: VoxelConfig, grid: Array,
                   z_min_m: float, z_max_m: float) -> Array:
    """(Y, X) bool: any occupied voxel in the height band — the 3D map's
    answer to "which 2D cells block a robot of this height"."""
    _, _, oz = vox.origin_m
    zs = (jnp.arange(vox.size_z_cells, dtype=jnp.float32) + 0.5) \
        * vox.resolution_m + oz
    band = (zs >= z_min_m) & (zs <= z_max_m)
    occ = grid > vox.occ_threshold
    return jnp.any(occ & band[:, None, None], axis=0)


def occupied_voxel_centers(vox: VoxelConfig, grid) -> "np.ndarray":  # noqa: F821
    """Host-side export: (N, 3) world-metre centres of occupied voxels
    (dynamic N — deliberately not jitted; point-cloud publishing runs on
    the host like the PNG encoder, bridge/png.py)."""
    import numpy as np
    g = np.asarray(grid)
    zi, yi, xi = np.nonzero(g > vox.occ_threshold)
    ox, oy, oz = vox.origin_m
    res = vox.resolution_m
    return np.stack([(xi + 0.5) * res + ox,
                     (yi + 0.5) * res + oy,
                     (zi + 0.5) * res + oz], axis=1).astype(np.float32)
