"""Log-odds occupancy grid: the TPU-native replacement for slam_toolbox's
occupancy-grid rasterization.

The reference delegates grid building to slam_toolbox (C++ Karto), configured
at `/root/reference/server/thymio_project/config/slam_config.yaml:26-27`
(0.05 m resolution, 12 m max range), and exports ROS `nav_msgs/OccupancyGrid`
semantics {-1 unknown, 0 free, 100 occupied} which the reference's Flask
endpoint re-colors for PNG (`server/thymio_project/thymio_project/main.py:259-263`).

TPU-first design — no per-ray Bresenham marching (that is a scalar,
data-dependent CUDA/CPU idiom). Instead each scan updates a fixed-shape local
*patch* with a dense inverse sensor model evaluated per cell:

    for every cell in a P x P patch around the robot:
        r, theta = polar coords of the cell relative to the sensor
        z        = scan range at the beam covering theta   (gather)
        cell is FREE     if r < min(z, r_max) - tol
        cell is OCCUPIED if |r - z| <= tol and the beam actually hit
        else unchanged

This is embarrassingly cell-parallel (VPU-friendly, no scatter contention —
SURVEY.md §7 "hard parts": deterministic accumulation comes for free because
each cell is written exactly once per scan), maps to static shapes, and
batches over scans with `vmap`. Patches fold into the global grid with
aligned `dynamic_update_slice` read-modify-writes.

Zero ranges are outliers and treated as `invalid_range_m`
(`server/.../main.py:152`: `ranges[ranges == 0] = 10.0`). Beam angle
convention (counterclockwise LD06, `pi_hardware.launch.py:20`) is an explicit,
tested transform — see SURVEY.md Appendix B on the reference's inverted cone
indexing.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from jax_mapping.config import GridConfig, ScanConfig
from jax_mapping.ops import trig

Array = jax.Array


# ---------------------------------------------------------------------------
# World <-> grid transforms
# ---------------------------------------------------------------------------

def world_to_cell(grid: GridConfig, xy: Array) -> Array:
    """Continuous world metres -> continuous cell coordinates (col, row).

    Grid is centred on world (0, 0); cell (0, 0) corner sits at origin_m.
    """
    ox, oy = grid.origin_m
    origin = jnp.array([ox, oy], dtype=jnp.float32)
    return (xy - origin) / grid.resolution_m


def cell_to_world(grid: GridConfig, cr: Array) -> Array:
    """Continuous cell coords (col, row) -> world metres of the cell centre
    when given integer coords + 0.5."""
    ox, oy = grid.origin_m
    origin = jnp.array([ox, oy], dtype=jnp.float32)
    return cr * grid.resolution_m + origin


def empty_grid(grid: GridConfig, dtype=jnp.float32) -> Array:
    """Fresh all-unknown (log-odds 0) grid."""
    return jnp.zeros((grid.size_cells, grid.size_cells), dtype=dtype)


# ---------------------------------------------------------------------------
# Scan sanitation
# ---------------------------------------------------------------------------

def sanitize_ranges(scan_cfg: ScanConfig, ranges: Array) -> Tuple[Array, Array]:
    """Pad-aware range cleanup.

    Returns (ranges_m, hit_mask):
      * zero readings become `invalid_range_m` (reference outlier rule,
        `server/.../main.py:152`) and are not hits;
      * readings beyond range_max or below range_min are not hits (the beam
        still clears free space up to min(r, max));
      * padded tail beams (index >= n_beams) are fully ignored.
    """
    if ranges.shape[-1] != scan_cfg.padded_beams:
        raise ValueError(
            f"scan has {ranges.shape[-1]} beams, config expects padded_beams="
            f"{scan_cfg.padded_beams}; XLA gather would clamp out-of-bounds "
            f"beam indices silently and mis-fuse")
    idx = jnp.arange(ranges.shape[-1])
    in_beam = idx < scan_cfg.n_beams
    r = jnp.asarray(ranges, jnp.float32)
    is_zero = r <= 0.0
    r = jnp.where(is_zero, scan_cfg.invalid_range_m, r)
    hit = (~is_zero) & (r >= scan_cfg.range_min_m) & (r <= scan_cfg.range_max_m) & in_beam
    # Non-hit beams still carve free space out to invalid_range (capped later
    # by the grid's max_range); padded beams carve nothing.
    r = jnp.where(in_beam, r, 0.0)
    return r, hit


# ---------------------------------------------------------------------------
# Patch origin (aligned for TPU lane-friendly dynamic slices)
# ---------------------------------------------------------------------------

def patch_origin(grid: GridConfig, pose_xy: Array) -> Array:
    """Integer (row0, col0) of the update patch for a robot at pose_xy.

    Snapped to (sublane, lane)-aligned offsets so the dynamic_update_slice
    read-modify-write stays tiled; `patch_cells` must satisfy
    P/2 - align/2 >= max_range_cells for full coverage (the default 640-cell
    patch covers 12 m at 0.05 m with 128-lane alignment).
    """
    ar, ac = grid.align_rows, grid.align_cols
    cr = world_to_cell(grid, pose_xy)          # (col, row) float
    col0 = jnp.round((cr[0] - grid.patch_cells / 2) / ac).astype(jnp.int32) * ac
    row0 = jnp.round((cr[1] - grid.patch_cells / 2) / ar).astype(jnp.int32) * ar
    hi = grid.size_cells - grid.patch_cells
    return jnp.stack([jnp.clip(row0, 0, hi), jnp.clip(col0, 0, hi)])


# ---------------------------------------------------------------------------
# Dense inverse sensor model over one patch
# ---------------------------------------------------------------------------

def patch_geometry(grid: GridConfig, scan_cfg: ScanConfig, pose: Array,
                   origin_rc: Array) -> Tuple[Array, Array, Array]:
    """Per-cell (r_cell_m, beam_index, in_fov) for a patch at origin_rc.

    THE canonical encoding of the beam conventions — CCW direction
    (`pi_hardware.launch.py:20`), [0, 2pi) wrap, partial-FOV masking —
    shared by classify_patch and raster_patch (the Pallas sensor kernel
    re-derives the same math in VMEM; parity is pinned by
    tests/test_sensor_kernel.py).
    """
    P = grid.patch_cells
    res = grid.resolution_m
    rows = origin_rc[0] + jnp.arange(P, dtype=jnp.int32)
    cols = origin_rc[1] + jnp.arange(P, dtype=jnp.int32)
    ox, oy = grid.origin_m
    ys = (rows.astype(jnp.float32) + 0.5) * res + oy       # (P,)
    xs = (cols.astype(jnp.float32) + 0.5) * res + ox       # (P,)
    dx = xs[None, :] - pose[0]                              # (1,P) -> bcast (P,P)
    dy = ys[:, None] - pose[1]                              # (P,1)
    r_cell = jnp.sqrt(dx * dx + dy * dy)                    # (P,P) metres

    # Bearing of the cell in the sensor frame, wrapped to [0, 2*pi).
    # trig.atan2 (not jnp.arctan2) so beam assignment matches the Pallas
    # kernel bit-for-bit — Mosaic can't lower atan2, and the two engines
    # must not disagree on boundary cells.
    theta = trig.atan2(dy, dx) - pose[2]
    if not scan_cfg.counterclockwise:
        theta = -theta
    theta = jnp.mod(theta - scan_cfg.angle_min_rad, 2.0 * jnp.pi)

    beam_raw = jnp.round(theta / scan_cfg.angle_increment_rad).astype(jnp.int32)
    beam = jnp.mod(beam_raw, scan_cfg.n_beams)
    # For a full-circle scanner the wrap beam_raw == n_beams is beam 0; for a
    # partial FOV, bearings past the last beam must NOT alias onto real beams
    # (a cell behind a 180-degree scanner is unobserved, not free).
    full_circle = abs(scan_cfg.n_beams * scan_cfg.angle_increment_rad
                      - 2.0 * jnp.pi) < scan_cfg.angle_increment_rad / 2
    in_fov = (jnp.ones_like(beam, dtype=jnp.bool_) if full_circle
              else beam_raw <= scan_cfg.n_beams - 1)
    return r_cell, beam, in_fov

def classify_patch(grid: GridConfig, scan_cfg: ScanConfig,
                   ranges: Array, pose: Array, origin_rc: Array) -> Array:
    """Evaluate the inverse sensor model on every cell of the patch.

    Args:
      ranges: (padded_beams,) raw ranges in metres (0 == outlier).
      pose: (3,) [x_m, y_m, yaw_rad] sensor pose in world frame.
      origin_rc: (2,) int32 [row0, col0] patch origin in the global grid.

    Returns:
      (P, P) float32 log-odds delta for the patch.
    """
    res = grid.resolution_m
    r_m, hit = sanitize_ranges(scan_cfg, ranges)
    r_cell, beam, in_fov = patch_geometry(grid, scan_cfg, pose, origin_rc)
    z = r_m[beam]                                           # (P,P) gather
    beam_hit = hit[beam] & in_fov

    tol = grid.hit_tolerance_cells * res
    max_r = jnp.float32(grid.max_range_m)
    carve = jnp.minimum(jnp.where(z > 0.0, z, 0.0), max_r)
    free = (r_cell < carve - tol) & (r_cell > scan_cfg.range_min_m) & in_fov
    occ = beam_hit & (jnp.abs(r_cell - z) <= tol) & (r_cell <= max_r)

    delta = jnp.where(occ, grid.logodds_occ,
                      jnp.where(free, grid.logodds_free, 0.0))
    return delta.astype(jnp.float32)


def raster_patch(grid: GridConfig, scan_cfg: ScanConfig,
                 ranges: Array, pose: Array, origin_rc: Array) -> Array:
    """Soft scan raster on a patch: per cell max(0, 1-|r_cell - z|/res) on
    the hit band (no free carving) — XLA counterpart of the Pallas
    sensor_kernel 'raster' mode (parity-tested); the correlative matcher's
    rasterizer."""
    res = grid.resolution_m
    r_m, hit = sanitize_ranges(scan_cfg, ranges)
    r_cell, beam, in_fov = patch_geometry(grid, scan_cfg, pose, origin_rc)
    z = r_m[beam]
    keep = hit[beam] & in_fov & (r_cell <= grid.max_range_m)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(r_cell - z) / res)
    return jnp.where(keep, w, 0.0).astype(jnp.float32)


def scan_rasters(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 ranges_b: Array, poses_b: Array, origins_rc: Array) -> Array:
    """Batched soft rasters, backend-dispatched like _classify_batch."""
    if _use_pallas():
        from jax_mapping.ops import sensor_kernel as SK
        return SK.scan_rasters(grid_cfg, scan_cfg, ranges_b, poses_b,
                               origins_rc)
    return jax.vmap(
        lambda r, p, o: raster_patch(grid_cfg, scan_cfg, r, p, o)
    )(ranges_b, poses_b, origins_rc)


# ---------------------------------------------------------------------------
# Folding patches into the global grid
# ---------------------------------------------------------------------------

def apply_patch(grid_cfg: GridConfig, grid_arr: Array, delta: Array,
                origin_rc: Array, clamp: bool = True) -> Array:
    """grid[origin:origin+P, ...] += delta, clamped to log-odds bounds."""
    cur = jax.lax.dynamic_slice(grid_arr, (origin_rc[0], origin_rc[1]),
                                (grid_cfg.patch_cells, grid_cfg.patch_cells))
    new = cur + delta
    if clamp:
        new = jnp.clip(new, grid_cfg.logodds_min, grid_cfg.logodds_max)
    return jax.lax.dynamic_update_slice(grid_arr, new, (origin_rc[0], origin_rc[1]))


def _classify_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                    ranges_b: Array, poses_b: Array) -> Tuple[Array, Array]:
    """Batched inverse sensor model: (deltas, origins).

    On TPU the per-scan Pallas kernel computes the deltas (the XLA
    formulation's per-cell `ranges[beam]` gather lowers to a scalarised
    loop ~10x the cost of the rest of the model; the kernel does the
    lookup as an in-vreg gather over the packed beam table). Elsewhere the
    vmapped XLA path runs; the two are parity-tested in
    tests/test_sensor_kernel.py.
    """
    origins = jax.vmap(lambda p: patch_origin(grid_cfg, p[:2]))(poses_b)
    if _use_pallas():
        from jax_mapping.ops import sensor_kernel as SK
        deltas = SK.scan_deltas(grid_cfg, scan_cfg, ranges_b, poses_b,
                                origins)
    else:
        deltas = jax.vmap(
            lambda r, p, o: classify_patch(grid_cfg, scan_cfg, r, p, o)
        )(ranges_b, poses_b, origins)
    return deltas, origins


def _use_pallas() -> bool:
    """Pallas engine on TPU unless JAX_MAPPING_NO_PALLAS=1 (escape hatch:
    keeps every pipeline runnable on a toolchain whose Mosaic build rejects
    the kernel — the XLA paths are parity-tested equivalents)."""
    import os
    return (jax.default_backend() == "tpu"
            and os.environ.get("JAX_MAPPING_NO_PALLAS") != "1")


def _fold(grid_cfg: GridConfig, grid_arr: Array, deltas: Array,
          origins: Array, clamp: bool) -> Array:
    """Sequentially apply patches (exact under overlap; no scatter)."""
    def body(g, do):
        delta, origin = do
        return apply_patch(grid_cfg, g, delta, origin, clamp=clamp), None

    out, _ = jax.lax.scan(body, grid_arr, (deltas, origins))
    return out


# Scans classified per fold chunk. Two ceilings bind the batch axis:
# Mosaic's scoped SMEM grows with the Pallas grid's step count (B > 512
# over-runs the 1 MB budget at the full-size 640-patch config — measured
# on v5e), and the (B, P, P) deltas array is B x 1.6 MB of HBM (the
# 1024-scan loop-repair refuse would materialise 1.7 GB at once; the
# fused streaming engine bounds it at _STREAM_CHUNK x 1.6 MB instead).
_FUSE_CHUNK = 256


def _batch_bucket(n: int) -> int:
    """Smallest of {2^k} ∪ {3·2^(k-1)} >= n — the scan-batch bucket
    (the PR 6 crop-span set: the 1.5x midpoints halve bucket overshoot,
    so padding never exceeds a third of the batch — a fixed 3-robot
    ring re-fuse of 192 rows buckets to exactly 192, not 256)."""
    if n <= 2:
        return max(n, 1)
    p = 1 << (n - 1).bit_length()           # next pow2
    mid = 3 * (p // 4)                       # the midpoint below it
    return mid if mid >= n else p


def _pad_batch_to(bucket: int, ranges_b: Array, poses_b: Array,
                  mask_b: Optional[Array]):
    """Pad a scan batch to `bucket` rows with mask=0 entries: padded
    ranges are zeros, padded poses COPY the last real row (keeps the
    padded patch origins on real data — clip(cur + 0) there is exact on
    any in-bounds grid), and the returned mask zeroes the pad rows out
    of the classified deltas, so padding is exact by the same argument
    the masked fold already rests on."""
    B = ranges_b.shape[0]
    m = (jnp.ones(B, jnp.bool_) if mask_b is None
         else mask_b.astype(jnp.bool_))
    pad = bucket - B
    if pad <= 0:
        return ranges_b, poses_b, m
    return (
        jnp.concatenate(
            [ranges_b, jnp.zeros((pad, ranges_b.shape[1]),
                                 ranges_b.dtype)]),
        jnp.concatenate(
            [poses_b, jnp.broadcast_to(poses_b[B - 1:B],
                                       (pad, poses_b.shape[1]))]),
        jnp.concatenate([m, jnp.zeros(pad, jnp.bool_)]),
    )


def _classify_fold(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                   grid_arr: Array, ranges_b: Array, poses_b: Array,
                   mask_b: Array, clamp: bool) -> Array:
    """Chunked classify->fold over the batch: peak memory and Pallas grid
    size are bounded by `_FUSE_CHUNK` regardless of B; results are exact
    (the fold is sequential either way). With mask_b, scan b contributes
    iff mask_b[b] (multiplied on the classified deltas: zeroing ranges
    instead would still carve free space — a zero range means "outlier,
    carve to 10 m", server/.../main.py:152); mask_b=None skips the
    multiply on the unmasked hot paths.

    `GridConfig.fused_fusion` swaps the chunk body for the streaming
    engine (`ops/fuse_kernel.stream_fold`): classify and fold in the
    same scan body, no (B, P, P) deltas in HBM — bit-identical output
    (tests/test_fuse_kernel.py). False = this pre-fused chain exactly.

    The remainder tail is padded to its `_batch_bucket` with mask=0
    rows (exact — masked deltas are multiplied out, the PR 6 crop-span
    idiom), so callers passing unbucketed B > _FUSE_CHUNK batches
    compile one variant per BUCKET, not per distinct remainder size."""
    B = ranges_b.shape[0]
    if B == 0:
        return grid_arr

    def chunk(g, rpm):
        r, p, m = rpm
        if grid_cfg.fused_fusion:
            from jax_mapping.ops import fuse_kernel as FK
            return FK.stream_fold(grid_cfg, scan_cfg, g, r, p, m,
                                  clamp), None
        deltas, origins = _classify_batch(grid_cfg, scan_cfg, r, p)
        if m is not None:
            deltas = deltas * m[:, None, None].astype(deltas.dtype)
        return _fold(grid_cfg, g, deltas, origins, clamp=clamp), None

    # Full chunks ride one lax.scan; the remainder is a smaller final
    # call at its bucket (padding all the way up to _FUSE_CHUNK would
    # cost full kernel work per dummy scan — zero ranges are outliers
    # that carve to max range, hence the mask, and a 257-scan batch
    # should not pay 255 masked classifies).
    CB = min(_FUSE_CHUNK, B)
    nc, rem = B // CB, B % CB
    out = grid_arr
    if nc:
        cut = nc * CB
        out, _ = jax.lax.scan(
            chunk, out,
            (ranges_b[:cut].reshape(nc, CB, -1),
             poses_b[:cut].reshape(nc, CB, 3),
             None if mask_b is None else mask_b[:cut].reshape(nc, CB)))
    if rem:
        bucket = min(_batch_bucket(rem), CB)
        r, p, m = _pad_batch_to(
            bucket, ranges_b[B - rem:], poses_b[B - rem:],
            None if mask_b is None else mask_b[B - rem:])
        if bucket == rem and mask_b is None:
            m = None        # no pad rows: keep the unmasked hot path
        out, _ = chunk(out, (r, p, m))
    return out


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_scan(grid_cfg: GridConfig, scan_cfg: ScanConfig,
              grid_arr: Array, ranges: Array, pose: Array) -> Array:
    """Fuse a single scan (the minimum end-to-end kernel)."""
    deltas, origins = _classify_batch(grid_cfg, scan_cfg, ranges[None],
                                      pose[None])
    return apply_patch(grid_cfg, grid_arr, deltas[0], origins[0])


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_scans(grid_cfg: GridConfig, scan_cfg: ScanConfig,
               grid_arr: Array, ranges_b: Array, poses_b: Array) -> Array:
    """Fuse a batch of B scans into the grid.

    Classification is batched (vmap — fully parallel); the fold is a
    sequential `scan` of aligned read-modify-writes, which keeps overlapping
    patches exact (SURVEY.md §7 "scatter contention" without the scatter).

    Args:
      ranges_b: (B, padded_beams) metres.
      poses_b:  (B, 3) [x, y, yaw].
    """
    return _classify_fold(grid_cfg, scan_cfg, grid_arr, ranges_b, poses_b,
                          None, clamp=True)


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_scans_masked(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                      grid_arr: Array, ranges_b: Array, poses_b: Array,
                      mask_b: Array) -> Array:
    """`fuse_scans` where scan b contributes iff mask_b[b].

    The fleet step's key-scan gate (slam_config.yaml:37-38): sub-gate
    robots' scans must add NO evidence — zeroing their ranges would still
    carve free space (a zero range means "outlier, carve to 10 m",
    server/.../main.py:152), so the mask multiplies the classified deltas
    instead.
    """
    return _classify_fold(grid_cfg, scan_cfg, grid_arr, ranges_b, poses_b,
                          mask_b.astype(jnp.bool_), clamp=True)


def fuse_scans_bucketed(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                        grid_arr: Array, ranges_b: Array, poses_b: Array,
                        mask_b: Optional[Array] = None) -> Array:
    """`fuse_scans_masked` with the scan-batch dimension bucketed.

    Host-side wrapper (bucketing must happen OUTSIDE the jit boundary —
    inside it the trace still keys on the caller's B): pads the batch to
    its `_batch_bucket` ({2^k} ∪ {3·2^(k-1)} — padding never exceeds a
    third of the batch) with mask=0 rows (exact — masked deltas are
    multiplied out, the PR 6 crop-span idiom) and dispatches
    `fuse_scans_masked`, so callers with churning queue lengths compile
    one variant per BUCKET instead of one per distinct B. The committed
    `analysis/compile_budget.json` pins the bucket variant count."""
    B = ranges_b.shape[0]
    if B == 0:
        return grid_arr
    ranges_b = jnp.asarray(ranges_b)
    poses_b = jnp.asarray(poses_b)
    r, p, m = _pad_batch_to(_batch_bucket(B), ranges_b, poses_b,
                            None if mask_b is None
                            else jnp.asarray(mask_b))
    return fuse_scans_masked(grid_cfg, scan_cfg, grid_arr, r, p, m)


@functools.partial(jax.jit, static_argnums=(0, 1))
def scan_deltas_full(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                     ranges_b: Array, poses_b: Array) -> Array:
    """Batch of scans -> one full-size log-odds delta grid (no clamp).

    Used by the multi-robot merge path: per-robot deltas are `psum`-merged
    across the fleet mesh axis before a single clamped apply (parallel/fleet).
    """
    zero = jnp.zeros((grid_cfg.size_cells, grid_cfg.size_cells), jnp.float32)
    return _classify_fold(grid_cfg, scan_cfg, zero, ranges_b, poses_b,
                          None, clamp=False)


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_scans_window(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                      grid_arr: Array, ranges_b: Array,
                      poses_b: Array) -> Array:
    """Fuse a temporal scan window (one robot's consecutive scans) fast.

    All B scans share one patch whose origin is snapped from the mean pose;
    the Pallas kernel (ops/sensor_kernel.py) sums their deltas in VMEM and
    the grid sees a single aligned read-modify-write. This is the throughput
    path: HBM traffic is independent of B. Requires the window to fit the
    patch (default config: poses within ~4 m of their mean —
    `sensor_kernel.window_fits`); scans from scattered poses should use
    `fuse_scans` instead.

    Clamp semantics differ from the sequential fold only *within* a batch:
    the clamp applies once per window rather than once per scan (the same
    bounded-relaxation slam_toolbox applies per map update cycle,
    `slam_config.yaml:25`).

    `GridConfig.fused_fusion` routes through the fused engines
    (`ops/fuse_kernel.window_fused`): on TPU the Mosaic fused-apply
    kernel keeps each grid strip VMEM-resident across the batch
    (bit-identical to this classic composition); elsewhere the
    streaming accumulate never materialises more than a sub-chunk of
    deltas (bit-identical up to the documented cross-scan-sum
    reassociation for windows over `fuse_kernel._STREAM_CHUNK` scans).
    False = the chain below, bit-exactly.
    """
    mean_xy = poses_b[:, :2].mean(axis=0)
    origin = patch_origin(grid_cfg, mean_xy)
    if grid_cfg.fused_fusion:
        from jax_mapping.ops import fuse_kernel as FK
        return FK.window_fused(grid_cfg, scan_cfg, grid_arr, ranges_b,
                               poses_b, origin)
    if _use_pallas():
        from jax_mapping.ops import sensor_kernel as SK
        delta = SK.window_delta(grid_cfg, scan_cfg, ranges_b, poses_b,
                                origin)
    else:
        delta = jax.vmap(
            lambda r, p: classify_patch(grid_cfg, scan_cfg, r, p, origin)
        )(ranges_b, poses_b).sum(axis=0)
    return apply_patch(grid_cfg, grid_arr, delta, origin, clamp=True)


@functools.partial(jax.jit, static_argnums=(0, 1))
def fuse_scans_window_checked(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                              grid_arr: Array, ranges_b: Array,
                              poses_b: Array) -> Array:
    """`fuse_scans_window` that can NOT silently lose scan evidence.

    Checks the shared-patch contract (`sensor_kernel.window_fits`) on
    device and falls back to the exact per-scan fold (`fuse_scans`) for
    windows whose poses spread beyond the patch. Callers on the hot path
    that can guarantee the contract statically (e.g. bench.py's closed
    trajectory) should call `fuse_scans_window` directly; everyone else —
    the bridge mapper in particular — uses this.
    """
    from jax_mapping.ops import sensor_kernel as SK
    mean_xy = poses_b[:, :2].mean(axis=0)
    origin = patch_origin(grid_cfg, mean_xy)
    return jax.lax.cond(
        SK.window_fits(grid_cfg, poses_b, origin),
        lambda args: fuse_scans_window(grid_cfg, scan_cfg, *args),
        lambda args: fuse_scans(grid_cfg, scan_cfg, *args),
        (grid_arr, ranges_b, poses_b))


@jax.jit
def decay_grid(grid_arr: Array, factor: Array, cap: Array) -> Array:
    """One map-healing pass for dynamic worlds (DecayConfig semantics):
    every cell's log-odds shrinks toward 0 (unknown) by `factor` and is
    clamped to ±`cap` — stale evidence fades, and no cell is ever so
    entrenched that re-observation can't flip it within ~cap/|free|
    contradicting scans. Both knobs traced (one compile regardless of
    config values); the caller owns revision bookkeeping."""
    f = jnp.float32(factor)
    c = jnp.float32(cap)
    return jnp.clip(grid_arr * f, -c, c)


def merge_delta(grid_cfg: GridConfig, grid_arr: Array, delta_full: Array) -> Array:
    """Apply a full-size delta (e.g. the psum of a fleet's deltas)."""
    return jnp.clip(grid_arr + delta_full, grid_cfg.logodds_min,
                    grid_cfg.logodds_max)


# ---------------------------------------------------------------------------
# Coarse view (loop-closure wide search)
# ---------------------------------------------------------------------------

def coarse_grid_config(grid_cfg: GridConfig, factor: int) -> GridConfig:
    """A GridConfig viewing the same world at `factor`x coarser resolution.

    Same patch cell count — a coarse patch covers factor x the area, which
    is what lets the correlative matcher sweep slam_toolbox's 8 m loop
    search window (`slam_config.yaml:56-58`) with the identical dense-conv
    machinery it uses for the 0.5 m online window.
    """
    import dataclasses
    if grid_cfg.size_cells % factor:
        raise ValueError(f"size_cells={grid_cfg.size_cells} not divisible "
                         f"by coarse factor {factor}")
    size = grid_cfg.size_cells // factor
    return dataclasses.replace(
        grid_cfg,
        size_cells=size,
        resolution_m=grid_cfg.resolution_m * factor,
        patch_cells=min(grid_cfg.patch_cells, size),
    )


@functools.partial(jax.jit, static_argnums=(1,))
def downsample_max(grid_arr: Array, factor: int) -> Array:
    """Log-odds grid -> factor x coarser by block max.

    Max keeps every occupied cell visible at coarse scale (free space may
    vanish under a wall — conservative for a matcher that is attracted to
    occupied mass only, `scan_match.likelihood_field`).
    """
    n0, n1 = grid_arr.shape
    return grid_arr.reshape(n0 // factor, factor,
                            n1 // factor, factor).max(axis=(1, 3))


# ---------------------------------------------------------------------------
# Export: ROS OccupancyGrid semantics
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0,))
def to_occupancy(grid_cfg: GridConfig, grid_arr: Array) -> Array:
    """Log-odds -> int8 {-1 unknown, 0 free, 100 occupied}.

    The nav_msgs/OccupancyGrid contract the reference's map consumer reads
    (`server/.../main.py:259-263` maps 0->255 free, 100->0 occupied,
    else 127 unknown for PNG).
    """
    occ = grid_arr > grid_cfg.occ_threshold
    free = grid_arr < grid_cfg.free_threshold
    return jnp.where(occ, jnp.int8(100),
                     jnp.where(free, jnp.int8(0), jnp.int8(-1)))


# ---------------------------------------------------------------------------
# Serving: tiled delta distribution (jax_mapping/serving/tiles.py)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0,))
def to_gray(grid_cfg: GridConfig, grid_arr: Array) -> Array:
    """Log-odds -> uint8 grayscale in GRID orientation (row 0 = min-y):
    127 unknown, 255 free, 0 occupied — the /map-image palette WITHOUT
    the flipud (tiles compose in grid coordinates; the client flips once
    for display). Stays on device so tile hashing and the pyramid reduce
    without a host round trip."""
    occ = grid_arr > grid_cfg.occ_threshold
    free = grid_arr < grid_cfg.free_threshold
    return jnp.where(occ, jnp.uint8(0),
                     jnp.where(free, jnp.uint8(255), jnp.uint8(127)))


@jax.jit
def downsample_gray(img: Array) -> Array:
    """Uint8 occupancy-gray image -> 2x coarser by block PRIORITY:
    occupied (0) > free (255) > unknown (127). Plain block-max or -min
    on the gray values would let unknown shadow free (or free shadow
    occupied); ranking by priority keeps every wall AND every explored
    cell visible at overview scale."""
    rank = jnp.where(img == 0, jnp.uint8(0),
                     jnp.where(img == 255, jnp.uint8(1), jnp.uint8(2)))
    n0, n1 = img.shape
    blk = rank.reshape(n0 // 2, 2, n1 // 2, 2).min(axis=(1, 3))
    lut = jnp.asarray([0, 255, 127], jnp.uint8)
    return lut[blk]


@functools.partial(jax.jit, static_argnums=(1,))
def tile_hashes(arr: Array, tile_cells: int) -> Array:
    """(H, W) array -> (H//t, W//t, 2) uint32 per-tile content hashes,
    computed in ONE on-device reduction (both edges must divide).

    The serving tile store re-encodes only tiles whose hash changed —
    the 4096^2 grid never crosses to the host just to learn that 15 of
    16 tiles are byte-identical to what every client already holds. Two
    independent multiplicative-weight lanes (Knuth/Murmur-style odd
    constants over the within-tile cell index, uint32 wraparound) give a
    64-bit identity per tile; float grids hash their exact bit patterns
    (bitcast), so no epsilon can alias two different tiles."""
    h, w = arr.shape
    if h % tile_cells or w % tile_cells:
        raise ValueError(f"array shape ({h}, {w}) not divisible by "
                         f"tile_cells={tile_cells}")
    th, tw = h // tile_cells, w // tile_cells
    if jnp.issubdtype(arr.dtype, jnp.floating):
        v = jax.lax.bitcast_convert_type(arr, jnp.uint32)
    else:
        v = arr.astype(jnp.uint32)
    idx = jnp.arange(tile_cells * tile_cells,
                     dtype=jnp.uint32).reshape(tile_cells, tile_cells)
    w1 = idx * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)
    w2 = (idx ^ jnp.uint32(0x85EBCA6B)) * jnp.uint32(2246822519) \
        + jnp.uint32(1)
    tv = v.reshape(th, tile_cells, tw, tile_cells).transpose(0, 2, 1, 3)
    h1 = (tv * w1).sum(axis=(2, 3), dtype=jnp.uint32)
    h2 = (tv * w2).sum(axis=(2, 3), dtype=jnp.uint32)
    return jnp.stack([h1, h2], axis=-1)


def occupancy_to_png_array(occ_int8) -> "np.ndarray":  # noqa: F821
    """int8 occupancy -> uint8 grayscale image array, reference PNG semantics:
    127 unknown, 255 free, 0 occupied, flipud for image coords
    (`server/.../main.py:256-266`). Host-side numpy; the device hands off the
    int8 grid once, then this is pure PIL-ready bytes."""
    import numpy as np
    data = np.asarray(occ_int8, dtype=np.int8)
    img = np.full(data.shape, 127, dtype=np.uint8)
    img[data == 0] = 255
    img[data == 100] = 0
    return np.flipud(img)
