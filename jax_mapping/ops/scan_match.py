"""Correlative scan matching on device: the TPU-native replacement for
slam_toolbox's Karto scan matcher.

Capability contract from the reference's matcher configuration
(`/root/reference/server/thymio_project/config/slam_config.yaml:51-66`):
translation window +-0.5 m (fine step 0.01 m), coarse angular window
+-0.349 rad @ 0.0349, fine angular resolution 0.00349, smear deviation 0.1,
and a [0,1] "response" score used for acceptance/loop gating
(`slam_config.yaml:46-48`).

TPU-first design: instead of Karto's pointer-chasing lookup tables, the
matcher is two dense passes over static shapes —

  1. build a smooth *likelihood field* from the local grid patch with a
     separable Gaussian blur of the occupied mask (conv -> MXU/VPU, smooth
     enough for sub-cell refinement);
  2. score every (dtheta, dy, dx) candidate jointly: rotate the scan's
     point cloud per candidate angle (one einsum), then gather the field at
     every translated point — a (n_angles, n_shifts, n_points) gather batch,
     reduced to a response tensor and argmax'd.

Coarse pass at grid resolution over the full window, fine pass with
bilinear sub-cell sampling around the coarse winner. Everything jits; no
data-dependent shapes (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import GridConfig, MatcherConfig, ScanConfig
from jax_mapping.ops import grid as G

Array = jax.Array


class MatchResult(NamedTuple):
    pose: Array          # (3,) refined [x, y, yaw]
    response: Array      # () fine-stage response in [0, 1]
    coarse_response: Array  # () coarse-stage response in [0, 1]
    accepted: Array      # () bool: response >= matcher.min_response


# ---------------------------------------------------------------------------
# Scan -> point cloud
# ---------------------------------------------------------------------------

def scan_points(scan_cfg: ScanConfig, ranges: Array) -> tuple[Array, Array]:
    """Ranges -> (padded_beams, 2) points in the sensor frame + valid mask.

    Only genuine hits become points (zero/outlier/padded beams are masked),
    mirroring what a matcher may legitimately align against.
    """
    r_m, hit = G.sanitize_ranges(scan_cfg, ranges)
    idx = jnp.arange(scan_cfg.padded_beams, dtype=jnp.float32)
    ang = scan_cfg.angle_min_rad + idx * scan_cfg.angle_increment_rad
    if not scan_cfg.counterclockwise:
        ang = -ang
    pts = jnp.stack([r_m * jnp.cos(ang), r_m * jnp.sin(ang)], axis=-1)
    return pts, hit


# ---------------------------------------------------------------------------
# Likelihood field
# ---------------------------------------------------------------------------

def likelihood_field(grid_cfg: GridConfig, m_cfg: MatcherConfig,
                     patch: Array) -> Array:
    """Occupied-cell mask -> smooth [0,1] field via separable Gaussian blur.

    Unknown cells contribute nothing (slam_toolbox semantics: only mapped
    obstacles attract the matcher), the blur supplies the smear
    (slam_config.yaml:53) and a gradient for sub-cell refinement.
    """
    occ = (patch > grid_cfg.occ_threshold).astype(jnp.float32)
    sigma = float(max(m_cfg.smear_cells, 1))
    radius = int(3 * sigma)
    # max_{cells} exp(-(di^2+dj^2)/2s^2) separates exactly into two weighted
    # max passes because the per-axis decays are non-negative:
    #   max_{di,dj} kv(di) kh(dj) occ(i-di, j-dj)
    #     = max_dj kh(dj) [ max_di kv(di) occ(i-di, j) ].
    # (A summed Gaussian blur saturates on walls and flattens the response
    # surface — max-smear keeps a unique peak per obstacle.)
    def max_blur(x: Array, axis: int) -> Array:
        pad = [(0, 0), (0, 0)]
        pad[axis] = (radius, radius)
        xp = jnp.pad(x, pad)
        n = x.shape[axis]
        out = jnp.zeros_like(x)
        for off in range(-radius, radius + 1):
            w = jnp.float32(jnp.exp(-0.5 * (off / sigma) ** 2))
            sl = jax.lax.slice_in_dim(xp, off + radius, off + radius + n,
                                      axis=axis)
            out = jnp.maximum(out, w * sl)
        return out

    return max_blur(max_blur(occ, 0), 1)


def bilinear_sample(field: Array, rc: Array) -> Array:
    """Sample field at float (row, col) coords (..., 2), edge-clamped."""
    H, W = field.shape
    r = jnp.clip(rc[..., 0], 0.0, H - 1.001)
    c = jnp.clip(rc[..., 1], 0.0, W - 1.001)
    r0 = jnp.floor(r).astype(jnp.int32)
    c0 = jnp.floor(c).astype(jnp.int32)
    fr = r - r0
    fc = c - c0
    v00 = field[r0, c0]
    v01 = field[r0, c0 + 1]
    v10 = field[r0 + 1, c0]
    v11 = field[r0 + 1, c0 + 1]
    return ((1 - fr) * (1 - fc) * v00 + (1 - fr) * fc * v01
            + fr * (1 - fc) * v10 + fr * fc * v11)


# ---------------------------------------------------------------------------
# Correlative search
# ---------------------------------------------------------------------------

def _angle_grid(half: float, step: float) -> jnp.ndarray:
    n = int(round(half / step))
    return jnp.arange(-n, n + 1, dtype=jnp.float32) * step


def _shift_grid(half_m: float, step_m: float) -> jnp.ndarray:
    n = int(round(half_m / step_m))
    s = jnp.arange(-n, n + 1, dtype=jnp.float32) * step_m
    dy, dx = jnp.meshgrid(s, s, indexing="ij")
    return jnp.stack([dy.ravel(), dx.ravel()], axis=-1)   # (S, 2) metres


def _score_candidates(field: Array, origin_rc: Array, grid_cfg: GridConfig,
                      pts_world: Array, valid: Array, dthetas: Array,
                      shifts_m: Array, centre_xy: Array) -> Array:
    """Response[(a, s)] = mean_valid field(R(dtheta)·(p - c) + c + shift).

    pts_world: (N,2) scan points already placed at the guess pose.
    Rotation is about the sensor centre, matching a yaw perturbation.
    """
    res = grid_cfg.resolution_m
    rel = pts_world - centre_xy                               # (N,2)
    ca, sa = jnp.cos(dthetas), jnp.sin(dthetas)               # (A,)
    rot = jnp.stack([jnp.stack([ca, -sa], -1),
                     jnp.stack([sa, ca], -1)], -2)            # (A,2,2)
    pts_a = jnp.einsum("aij,nj->ani", rot, rel) + centre_xy   # (A,N,2)
    # world -> patch-local continuous cell coords (row, col)
    ox, oy = grid_cfg.origin_m
    col = (pts_a[..., 0] - ox) / res - origin_rc[1].astype(jnp.float32) - 0.5
    row = (pts_a[..., 1] - oy) / res - origin_rc[0].astype(jnp.float32) - 0.5
    rc = jnp.stack([row, col], axis=-1)                       # (A,N,2)
    shift_rc = shifts_m / res        # (S, 2) [dy, dx] metres -> cells
    samples = bilinear_sample(
        field, rc[:, None, :, :] + shift_rc[None, :, None, :])  # (A,S,N)
    w = valid.astype(jnp.float32)
    return jnp.einsum("asn,n->as", samples, w) / jnp.maximum(w.sum(), 1.0)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match(grid_cfg: GridConfig, scan_cfg: ScanConfig, m_cfg: MatcherConfig,
          grid_arr: Array, ranges: Array, guess_pose: Array) -> MatchResult:
    """Coarse-to-fine correlative match of one scan against the map.

    Returns the refined pose; `accepted` mirrors the reference's response
    gating (callers fall back to the odometry guess when not accepted).
    """
    origin = G.patch_origin(grid_cfg, guess_pose[:2])
    patch = jax.lax.dynamic_slice(
        grid_arr, (origin[0], origin[1]),
        (grid_cfg.patch_cells, grid_cfg.patch_cells))
    field = likelihood_field(grid_cfg, m_cfg, patch)

    pts_s, valid = scan_points(scan_cfg, ranges)
    ca, sa = jnp.cos(guess_pose[2]), jnp.sin(guess_pose[2])
    rotg = jnp.array([[ca, -sa], [sa, ca]])
    pts_world = pts_s @ rotg.T + guess_pose[:2]
    centre = guess_pose[:2]

    # --- coarse pass: full windows at grid resolution -------------------
    dth_c = _angle_grid(m_cfg.coarse_angle_half_rad, m_cfg.coarse_angle_step_rad)
    shifts_c = _shift_grid(m_cfg.search_half_extent_m, m_cfg.coarse_step_m)
    resp_c = _score_candidates(field, origin, grid_cfg, pts_world, valid,
                               dth_c, shifts_c, centre)
    best_c = jnp.argmax(resp_c)
    ai_c, si_c = jnp.unravel_index(best_c, resp_c.shape)
    coarse_resp = resp_c[ai_c, si_c]
    dth0 = dth_c[ai_c]
    shift0 = shifts_c[si_c]

    # --- fine pass: sub-cell window around the coarse winner ------------
    dth_f = dth0 + _angle_grid(m_cfg.coarse_angle_step_rad, m_cfg.fine_angle_step_rad)
    shifts_f = shift0 + _shift_grid(m_cfg.coarse_step_m, m_cfg.fine_step_m)
    resp_f = _score_candidates(field, origin, grid_cfg, pts_world, valid,
                               dth_f, shifts_f, centre)
    best_f = jnp.argmax(resp_f)
    ai_f, si_f = jnp.unravel_index(best_f, resp_f.shape)
    fine_resp = resp_f[ai_f, si_f]

    pose = jnp.stack([
        guess_pose[0] + shifts_f[si_f, 1],
        guess_pose[1] + shifts_f[si_f, 0],
        guess_pose[2] + dth_f[ai_f],
    ])
    return MatchResult(pose=pose, response=fine_resp,
                       coarse_response=coarse_resp,
                       accepted=fine_resp >= m_cfg.min_response)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                m_cfg: MatcherConfig, grid_arr: Array, ranges_b: Array,
                guesses_b: Array) -> MatchResult:
    """vmap the matcher over a batch of scans against one shared map."""
    return jax.vmap(lambda r, p: match(grid_cfg, scan_cfg, m_cfg,
                                       grid_arr, r, p))(ranges_b, guesses_b)
