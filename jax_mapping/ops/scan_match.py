"""Correlative scan matching on device: the TPU-native replacement for
slam_toolbox's Karto scan matcher.

Capability contract from the reference's matcher configuration
(`/root/reference/server/thymio_project/config/slam_config.yaml:51-66`):
translation window +-0.5 m (fine step 0.01 m), coarse angular window
+-0.349 rad @ 0.0349, fine angular resolution 0.00349, smear deviation 0.1,
and a [0,1] "response" score used for acceptance/loop gating
(`slam_config.yaml:46-48`).

TPU-first design: instead of Karto's pointer-chasing lookup tables (or a
gather-based point scorer — ~20M scalarised lookups per match on TPU), the
matcher is dense passes over static shapes with zero gathers:

  1. build a smooth *likelihood field* from the local grid patch with a
     separable max-Gaussian smear of the occupied mask;
  2. rasterize the scan at every candidate angle with the dense sensor
     kernel (ops/sensor_kernel.py 'raster' mode — candidate poses are just
     batch rows), and score ALL translation shifts of all angles as one
     cross-correlation conv on the MXU;
  3. refine sub-cell by rasterizing at fine_step_m pose offsets — the
     dense rasterizer evaluates continuous poses exactly, so sub-cell
     sensitivity needs no bilinear gather.

Coarse pass over the full window at grid resolution, fine angle pass, then
sub-cell translation pass. Everything jits; no data-dependent shapes
(SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import GridConfig, MatcherConfig, ScanConfig
from jax_mapping.ops import grid as G

Array = jax.Array


class MatchResult(NamedTuple):
    """pose + acceptance + response + covariance of one correlative match.

    `cov` is the diagonal (var_x m^2, var_y m^2, var_theta rad^2) from
    softmax-weighted second moments of the COARSE response surface —
    the only stage that spans the whole search window, so a corridor's
    metres-long ridge registers (the fine surface covers just +-1 coarse
    step). It is the correlation-surface covariance Karto/slam_toolbox
    publish with their poses (Olson 2009's formulation): a sharp single
    peak reports tight variance (floored at the coarse quantisation), a
    ridge reports wide variance along the ridge axis.
    """
    pose: Array          # (3,) refined [x, y, yaw]
    response: Array      # () fine-stage response in [0, 1]
    coarse_response: Array  # () coarse-stage response in [0, 1]
    accepted: Array      # () bool: response >= matcher.min_response
    cov: Array           # (3,) diag [var_x m^2, var_y m^2, var_th rad^2]


# ---------------------------------------------------------------------------
# Scan -> point cloud
# ---------------------------------------------------------------------------

def scan_points(scan_cfg: ScanConfig, ranges: Array) -> tuple[Array, Array]:
    """Ranges -> (padded_beams, 2) points in the sensor frame + valid mask.

    Only genuine hits become points (zero/outlier/padded beams are masked).
    Public geometry utility (point-cloud export / visualisation); the
    matcher itself scores dense rasters, not points.
    """
    r_m, hit = G.sanitize_ranges(scan_cfg, ranges)
    idx = jnp.arange(scan_cfg.padded_beams, dtype=jnp.float32)
    ang = scan_cfg.angle_min_rad + idx * scan_cfg.angle_increment_rad
    if not scan_cfg.counterclockwise:
        ang = -ang
    pts = jnp.stack([r_m * jnp.cos(ang), r_m * jnp.sin(ang)], axis=-1)
    return pts, hit


# ---------------------------------------------------------------------------
# Likelihood field
# ---------------------------------------------------------------------------

def likelihood_field(grid_cfg: GridConfig, m_cfg: MatcherConfig,
                     patch: Array) -> Array:
    """Occupied-cell mask -> smooth [0,1] field via separable Gaussian blur.

    Unknown cells contribute nothing (slam_toolbox semantics: only mapped
    obstacles attract the matcher), the blur supplies the smear
    (slam_config.yaml:53) and a gradient for sub-cell refinement.
    """
    occ = (patch > grid_cfg.occ_threshold).astype(jnp.float32)
    sigma = float(max(m_cfg.smear_cells, 1))
    radius = int(3 * sigma)
    # max_{cells} exp(-(di^2+dj^2)/2s^2) separates exactly into two weighted
    # max passes because the per-axis decays are non-negative:
    #   max_{di,dj} kv(di) kh(dj) occ(i-di, j-dj)
    #     = max_dj kh(dj) [ max_di kv(di) occ(i-di, j) ].
    # (A summed Gaussian blur saturates on walls and flattens the response
    # surface — max-smear keeps a unique peak per obstacle.)
    def max_blur(x: Array, axis: int) -> Array:
        pad = [(0, 0), (0, 0)]
        pad[axis] = (radius, radius)
        xp = jnp.pad(x, pad)
        n = x.shape[axis]
        out = jnp.zeros_like(x)
        for off in range(-radius, radius + 1):
            w = jnp.float32(jnp.exp(-0.5 * (off / sigma) ** 2))
            sl = jax.lax.slice_in_dim(xp, off + radius, off + radius + n,
                                      axis=axis)
            out = jnp.maximum(out, w * sl)
        return out

    return max_blur(max_blur(occ, 0), 1)




# ---------------------------------------------------------------------------
# Correlative search
# ---------------------------------------------------------------------------

def _angle_grid(half: float, step: float) -> jnp.ndarray:
    n = int(round(half / step))
    return jnp.arange(-n, n + 1, dtype=jnp.float32) * step


def _pen_dist(m_cfg: MatcherConfig, d2_m2: Array) -> Array:
    """Karto's distance variance penalty (slam_config.yaml:61): ranking
    multiplier for candidates offset d from the odometric prior."""
    return jnp.maximum(m_cfg.min_distance_penalty,
                       1.0 - 0.2 * d2_m2 / m_cfg.distance_variance_penalty_m2)


def _pen_angle(m_cfg: MatcherConfig, dth_rad: Array) -> Array:
    """Karto's angle variance penalty (slam_config.yaml:62)."""
    return jnp.maximum(
        m_cfg.min_angle_penalty,
        1.0 - 0.2 * dth_rad * dth_rad / m_cfg.angle_variance_penalty_rad2)




def _raster_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig, ranges: Array,
                  poses: Array, origin_rc: Array) -> tuple[Array, Array]:
    """(A, P, P) soft rasters of one scan at A candidate poses + masses."""
    A = poses.shape[0]
    ranges_b = jnp.broadcast_to(ranges, (A,) + ranges.shape)
    origins = jnp.broadcast_to(origin_rc, (A, 2))
    rasters = G.scan_rasters(grid_cfg, scan_cfg, ranges_b, poses, origins)
    mass = jnp.maximum(rasters.sum(axis=(1, 2)), 1e-6)
    return rasters, mass


def _conv_scores(field: Array, rasters: Array, mass_ref: Array,
                 n_steps: int, stride: int = 1,
                 compute_dtype=jnp.float32) -> Array:
    """resp[a, sy, sx] = <raster_a, field shifted by ((sy-n)*stride,
    (sx-n)*stride) cells> / mass_ref — the whole correlative window as ONE
    cross-correlation on the MXU (XLA conv kernels are not flipped, so the
    conv IS the correlation). `stride` realises MatcherConfig.coarse_step_m
    in cells.

    mass_ref is one SHARED scalar denominator for every candidate of a
    match (the fullest raster's in-patch mass): normalising each candidate
    by its own mass would hand candidates whose hit band is clipped by the
    patch edge a smaller denominator and a quietly inflated score. With a
    shared denominator, clipping can only lower a response — conservative.

    Lowering: phrased as a 1D conv whose CHANNEL axis is the patch rows
    and whose batch axis is the y-shift (one sliced window of the padded
    field per sy). The natural 2D form — C_in=1 input against (A, 1, P, P)
    kernels — makes XLA stage the whole P^2 contraction through an
    implicit im2col at C=1 and ran 3.7x slower at the production 640-patch
    shape (7.5 -> 2.0 ms coarse, 2.0 -> 0.24 ms fine, measured on v5e);
    with rows as channels the contraction is a clean (A, P*P) x (P*P, nx)
    matmul per sy on the MXU. out[sy, a, sx] = sum_{r,c}
    fpad[sy*stride + r, sx*stride + c] * raster[a, r, c] — identical
    (unflipped-kernel) correlation semantics either way.
    """
    pad = n_steps * stride
    A, P, _ = rasters.shape
    fpad = jnp.pad(field, pad).astype(compute_dtype)
    ny = 2 * n_steps + 1
    windows = jax.vmap(lambda so: jax.lax.dynamic_slice(
        fpad, (so, 0), (P, P + 2 * pad)))(
            jnp.arange(ny) * stride)                # (ny, P, P+2p)
    out = jax.lax.conv_general_dilated(
        windows, rasters.astype(compute_dtype), window_strides=(stride,),
        padding="VALID", dimension_numbers=("NCW", "OIW", "NCW"),
        preferred_element_type=jnp.float32)         # (ny, A, nx)
    return jnp.transpose(out, (1, 0, 2)) / mass_ref


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match(grid_cfg: GridConfig, scan_cfg: ScanConfig, m_cfg: MatcherConfig,
          grid_arr: Array, ranges: Array, guess_pose: Array) -> MatchResult:
    """Coarse-to-fine correlative match of one scan against the map.

    Three dense passes, no gathers (a gather-based scorer pays ~20M
    scalarised lookups per match on TPU):

      1. coarse: rasters at every coarse angle x every integer cell shift
         in the window, scored jointly as one conv on the MXU;
      2. fine angles: rasters at fine angular steps around the winner,
         conv over +-1 cell;
      3. sub-cell: rasters at `fine_step_m` translation offsets of the
         winning angle (the dense rasterizer evaluates continuous poses
         exactly — sub-cell shifts move the hit band through the cells),
         scored at zero shift.

    Returns the refined pose; `accepted` mirrors the reference's response
    gating (callers fall back to the odometry guess when not accepted).
    """
    res = grid_cfg.resolution_m
    origin = G.patch_origin(grid_cfg, guess_pose[:2])
    patch = jax.lax.dynamic_slice(
        grid_arr, (origin[0], origin[1]),
        (grid_cfg.patch_cells, grid_cfg.patch_cells))
    field = likelihood_field(grid_cfg, m_cfg, patch)

    # --- coarse pass: all angles x all strided-cell shifts --------------
    stride = max(1, int(round(m_cfg.coarse_step_m / res)))
    n_steps = max(1, int(round(m_cfg.search_half_extent_m / (stride * res))))
    dth_c = _angle_grid(m_cfg.coarse_angle_half_rad,
                        m_cfg.coarse_angle_step_rad)
    A_c = dth_c.shape[0]
    poses_c = jnp.concatenate([
        jnp.broadcast_to(guess_pose[:2], (A_c, 2)),
        (guess_pose[2] + dth_c)[:, None]], axis=1)
    rasters_c, mass_c = _raster_batch(grid_cfg, scan_cfg, ranges, poses_c,
                                      origin)
    # One denominator for the whole match (see _conv_scores): the fullest
    # candidate raster's mass. Rotations preserve band mass up to clipping,
    # so this is the scan's unclipped in-patch mass for any candidate.
    mass_ref = jnp.maximum(jnp.max(mass_c), 1e-6)
    # bf16 only where it pays: XLA CPU has no fast bf16 conv path (a tiny
    # bf16 conv ran orders of magnitude slower than f32 — measured), so
    # off-TPU the flag is ignored and everything stays f32. The process
    # default backend is the best trace-time signal available under jit
    # (input avals carry no device); arrays explicitly committed to CPU on
    # a TPU host still trace bf16 — set coarse_bf16=False for that
    # debugging pattern.
    coarse_dtype = (jnp.bfloat16
                    if m_cfg.coarse_bf16 and jax.default_backend() == "tpu"
                    else jnp.float32)
    resp_c = _conv_scores(field, rasters_c, mass_ref, n_steps, stride,
                          compute_dtype=coarse_dtype)
    # Rank by variance-penalized response (prior-proximity tie-break,
    # yaml:61-62); gate on the winner's RAW response (Karto semantics).
    step_m = stride * res
    offs = jnp.arange(-n_steps, n_steps + 1, dtype=jnp.float32) * step_m
    d2_c = offs[None, :] ** 2 + offs[:, None] ** 2          # (2n+1, 2n+1)
    pen_c = _pen_dist(m_cfg, d2_c)[None] * \
        _pen_angle(m_cfg, dth_c)[:, None, None]
    best_c = jnp.argmax(resp_c * pen_c)
    ai_c, sy_c, sx_c = jnp.unravel_index(best_c, resp_c.shape)
    coarse_resp = resp_c[ai_c, sy_c, sx_c]
    dth0 = dth_c[ai_c]
    # Shift in metres ((sy, sx) strided steps; row = y, col = x).
    shift0 = jnp.stack([(sx_c - n_steps).astype(jnp.float32) * step_m,
                        (sy_c - n_steps).astype(jnp.float32) * step_m])

    # --- fine angles around the winner, +- one coarse step --------------
    dth_f = dth0 + _angle_grid(m_cfg.coarse_angle_step_rad,
                               m_cfg.fine_angle_step_rad)
    A_f = dth_f.shape[0]
    poses_f = jnp.concatenate([
        jnp.broadcast_to(guess_pose[:2] + shift0, (A_f, 2)),
        (guess_pose[2] + dth_f)[:, None]], axis=1)
    rasters_f, _mass_f = _raster_batch(grid_cfg, scan_cfg, ranges, poses_f,
                                       origin)
    resp_f = _conv_scores(field, rasters_f, mass_ref, stride)
    offs_f = jnp.arange(-stride, stride + 1, dtype=jnp.float32) * res
    d2_f = (shift0[0] + offs_f[None, :]) ** 2 \
        + (shift0[1] + offs_f[:, None]) ** 2
    pen_f = _pen_dist(m_cfg, d2_f)[None] * \
        _pen_angle(m_cfg, dth_f)[:, None, None]
    best_f = jnp.argmax(resp_f * pen_f)
    ai_f, sy_f, sx_f = jnp.unravel_index(best_f, resp_f.shape)
    dth1 = dth_f[ai_f]
    shift1 = shift0 + jnp.stack([(sx_f - stride).astype(jnp.float32) * res,
                                 (sy_f - stride).astype(jnp.float32) * res])

    # --- sub-cell translation at the winning angle ----------------------
    k = max(1, int(round(0.5 * res / m_cfg.fine_step_m)) + 1)
    d1 = jnp.arange(-k, k + 1, dtype=jnp.float32) * m_cfg.fine_step_m
    ddx, ddy = jnp.meshgrid(d1, d1, indexing="xy")
    deltas = jnp.stack([ddx.ravel(), ddy.ravel()], axis=-1)   # (S, 2) m
    S = deltas.shape[0]
    poses_s = jnp.concatenate([
        guess_pose[:2] + shift1 + deltas,
        jnp.full((S, 1), guess_pose[2] + dth1)], axis=1)
    rasters_s, _mass_s = _raster_batch(grid_cfg, scan_cfg, ranges, poses_s,
                                       origin)
    resp_s = jnp.einsum("bhw,hw->b", rasters_s, field) / mass_ref
    d2_s = jnp.sum((shift1[None, :] + deltas) ** 2, axis=-1)
    si = jnp.argmax(resp_s * _pen_dist(m_cfg, d2_s))
    fine_resp = resp_s[si]

    pose = jnp.stack([
        guess_pose[0] + shift1[0] + deltas[si, 0],
        guess_pose[1] + shift1[1] + deltas[si, 1],
        guess_pose[2] + dth1,
    ])

    # --- correlation-surface covariance (MatchResult.cov docstring) -----
    # Computed over the COARSE surface: it spans the whole search window
    # (the fine surface covers only +-1 coarse step, far too narrow to
    # see a corridor's metres-long ridge). Softmax weights; temperature
    # in response units — small enough that only the peak's basin
    # contributes, large enough that a flat ridge keeps mass spread.
    T = jnp.float32(0.05)
    surf = resp_c[ai_c].astype(jnp.float32)  # (2n+1, 2n+1) xy, step_m
    w_t = jnp.exp((surf - surf.max()) / T)
    wx = w_t.sum(axis=0)                     # collapse y -> x axis
    wy = w_t.sum(axis=1)
    mx = (wx * offs).sum() / wx.sum()
    my = (wy * offs).sum() / wy.sum()
    var_x = (wx * (offs - mx) ** 2).sum() / wx.sum()
    var_y = (wy * (offs - my) ** 2).sum() / wy.sum()
    resp_a = resp_c.max(axis=(1, 2)).astype(jnp.float32)  # per coarse angle
    w_a = jnp.exp((resp_a - resp_a.max()) / T)
    ma = (w_a * dth_c).sum() / w_a.sum()
    var_th = (w_a * (dth_c - ma) ** 2).sum() / w_a.sum()
    # Never report tighter than the stage's own quantisation — and the
    # stage HERE is the coarse one for all three axes (the theta surface
    # is sampled at coarse_angle_step_rad; flooring it at the fine step
    # would publish ~100x overconfident yaw variance).
    cov = jnp.stack([
        jnp.maximum(var_x, (step_m / 2) ** 2 / 3),
        jnp.maximum(var_y, (step_m / 2) ** 2 / 3),
        jnp.maximum(var_th,
                    (m_cfg.coarse_angle_step_rad / 2) ** 2 / 3)])

    return MatchResult(pose=pose, response=fine_resp,
                       coarse_response=coarse_resp,
                       accepted=fine_resp >= m_cfg.min_response,
                       cov=cov)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                m_cfg: MatcherConfig, grid_arr: Array, ranges_b: Array,
                guesses_b: Array) -> MatchResult:
    """vmap the matcher over a batch of scans against one shared map."""
    return jax.vmap(lambda r, p: match(grid_cfg, scan_cfg, m_cfg,
                                       grid_arr, r, p))(ranges_b, guesses_b)
