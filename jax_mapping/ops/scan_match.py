"""Correlative scan matching on device: the TPU-native replacement for
slam_toolbox's Karto scan matcher.

Capability contract from the reference's matcher configuration
(`/root/reference/server/thymio_project/config/slam_config.yaml:51-66`):
translation window +-0.5 m (fine step 0.01 m), coarse angular window
+-0.349 rad @ 0.0349, fine angular resolution 0.00349, smear deviation 0.1,
and a [0,1] "response" score used for acceptance/loop gating
(`slam_config.yaml:46-48`).

TPU-first design: instead of Karto's pointer-chasing lookup tables (or a
gather-based point scorer — ~20M scalarised lookups per match on TPU), the
matcher is dense passes over static shapes with zero gathers:

  1. build a smooth *likelihood field* from the local grid patch with a
     separable max-Gaussian smear of the occupied mask;
  2. rasterize the scan at every candidate angle with the dense sensor
     kernel (ops/sensor_kernel.py 'raster' mode — candidate poses are just
     batch rows), and score ALL translation shifts of all angles as one
     cross-correlation conv on the MXU;
  3. refine sub-cell by rasterizing at fine_step_m pose offsets — the
     dense rasterizer evaluates continuous poses exactly, so sub-cell
     sensitivity needs no bilinear gather.

Coarse pass over the full window at grid resolution, fine angle pass, then
sub-cell translation pass. Everything jits; no data-dependent shapes
(SURVEY.md §7 hard parts).

Branch-and-bound coarse stage (`MatcherConfig.pruned`, the default): the
exhaustive coarse sweep scores EVERY (angle, shift) candidate even though
almost all of them are nowhere near the winner. The pruned path is the
classic coarse-to-fine branch-and-bound acceleration of correlative
matching (the FPGA 2D-LiDAR-SLAM formulation; Cartographer's real-time
loop closure uses the same bound): precompute a multi-resolution
max-pyramid of the likelihood field where level-l cell x holds
max_{0<=d<2^l} field[x + stride*d] per axis — so a level-l score is an
ADMISSIBLE upper bound on every leaf score in its 2^l x 2^l shift block —
score the whole window at the top level in one strided MXU conv, keep the
top-K candidate branches per level, and descend to exact leaf scores at
level 0. Identical argmax to the f32 exhaustive sweep whenever the true
winner's ancestors stay inside the top-K frontier (property-tested across
random worlds; on TPU the exhaustive path's own `coarse_bf16` rounding
can flip near-tie coarse winners relative to f32 — the pruned path
always scores f32, so the parity contract is against the f32 sweep);
`pruned=False` is the bit-exact exhaustive path. The
whole refinement runs in ONE jitted dispatch — no host syncs between
levels — and the host-driven cached entry points (`pyramid_coarse_scores`
/ `pyramid_refine`, fed by `ops/pyramid.PyramidCache`) donate the coarse
score buffer into the refinement dispatch.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import GridConfig, MatcherConfig, ScanConfig
from jax_mapping.ops import grid as G

Array = jax.Array


class MatchResult(NamedTuple):
    """pose + acceptance + response + covariance of one correlative match.

    `cov` is the diagonal (var_x m^2, var_y m^2, var_theta rad^2) from
    softmax-weighted second moments of the COARSE response surface —
    the only stage that spans the whole search window, so a corridor's
    metres-long ridge registers (the fine surface covers just +-1 coarse
    step). It is the correlation-surface covariance Karto/slam_toolbox
    publish with their poses (Olson 2009's formulation): a sharp single
    peak reports tight variance (floored at the coarse quantisation), a
    ridge reports wide variance along the ridge axis. On the pruned path
    the x/y surface is the winner-angle level-1 block surface (admissible
    upper bounds at 2-leaf granularity — a ridge stays a ridge) and the
    floor widens to the block size; theta reads the top-level per-angle
    bound maxima.
    """
    pose: Array          # (3,) refined [x, y, yaw]
    response: Array      # () fine-stage response in [0, 1]
    coarse_response: Array  # () coarse-stage response in [0, 1]
    accepted: Array      # () bool: response >= matcher.min_response
    cov: Array           # (3,) diag [var_x m^2, var_y m^2, var_th rad^2]
    # Coarse-stage work accounting (SlamDiag / bench gauges): candidate
    # evaluations actually scored, and the fraction of the exhaustive
    # A x (2n+1)^2 sweep that branch-and-bound pruned away (0.0 on the
    # exhaustive path). Both are trace-time constants per config.
    n_candidates: Array  # () int32
    prune_ratio: Array   # () float32 in [0, 1)


# ---------------------------------------------------------------------------
# Scan -> point cloud
# ---------------------------------------------------------------------------

def scan_points(scan_cfg: ScanConfig, ranges: Array) -> tuple[Array, Array]:
    """Ranges -> (padded_beams, 2) points in the sensor frame + valid mask.

    Only genuine hits become points (zero/outlier/padded beams are masked).
    Public geometry utility (point-cloud export / visualisation); the
    matcher itself scores dense rasters, not points.
    """
    r_m, hit = G.sanitize_ranges(scan_cfg, ranges)
    idx = jnp.arange(scan_cfg.padded_beams, dtype=jnp.float32)
    ang = scan_cfg.angle_min_rad + idx * scan_cfg.angle_increment_rad
    if not scan_cfg.counterclockwise:
        ang = -ang
    pts = jnp.stack([r_m * jnp.cos(ang), r_m * jnp.sin(ang)], axis=-1)
    return pts, hit


# ---------------------------------------------------------------------------
# Likelihood field
# ---------------------------------------------------------------------------

def likelihood_field(grid_cfg: GridConfig, m_cfg: MatcherConfig,
                     patch: Array) -> Array:
    """Occupied-cell mask -> smooth [0,1] field via separable Gaussian blur.

    Unknown cells contribute nothing (slam_toolbox semantics: only mapped
    obstacles attract the matcher), the blur supplies the smear
    (slam_config.yaml:53) and a gradient for sub-cell refinement.
    """
    occ = (patch > grid_cfg.occ_threshold).astype(jnp.float32)
    sigma = float(max(m_cfg.smear_cells, 1))
    radius = int(3 * sigma)
    # max_{cells} exp(-(di^2+dj^2)/2s^2) separates exactly into two weighted
    # max passes because the per-axis decays are non-negative:
    #   max_{di,dj} kv(di) kh(dj) occ(i-di, j-dj)
    #     = max_dj kh(dj) [ max_di kv(di) occ(i-di, j) ].
    # (A summed Gaussian blur saturates on walls and flattens the response
    # surface — max-smear keeps a unique peak per obstacle.)
    def max_blur(x: Array, axis: int) -> Array:
        pad = [(0, 0), (0, 0)]
        pad[axis] = (radius, radius)
        xp = jnp.pad(x, pad)
        n = x.shape[axis]
        out = jnp.zeros_like(x)
        for off in range(-radius, radius + 1):
            w = jnp.float32(jnp.exp(-0.5 * (off / sigma) ** 2))
            sl = jax.lax.slice_in_dim(xp, off + radius, off + radius + n,
                                      axis=axis)
            out = jnp.maximum(out, w * sl)
        return out

    return max_blur(max_blur(occ, 0), 1)




# ---------------------------------------------------------------------------
# Correlative search
# ---------------------------------------------------------------------------

def _angle_grid(half: float, step: float) -> jnp.ndarray:
    n = int(round(half / step))
    return jnp.arange(-n, n + 1, dtype=jnp.float32) * step


def _pen_dist(m_cfg: MatcherConfig, d2_m2: Array) -> Array:
    """Karto's distance variance penalty (slam_config.yaml:61): ranking
    multiplier for candidates offset d from the odometric prior."""
    return jnp.maximum(m_cfg.min_distance_penalty,
                       1.0 - 0.2 * d2_m2 / m_cfg.distance_variance_penalty_m2)


def _pen_angle(m_cfg: MatcherConfig, dth_rad: Array) -> Array:
    """Karto's angle variance penalty (slam_config.yaml:62)."""
    return jnp.maximum(
        m_cfg.min_angle_penalty,
        1.0 - 0.2 * dth_rad * dth_rad / m_cfg.angle_variance_penalty_rad2)




def _raster_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig, ranges: Array,
                  poses: Array, origin_rc: Array) -> tuple[Array, Array]:
    """(A, P, P) soft rasters of one scan at A candidate poses + masses."""
    A = poses.shape[0]
    ranges_b = jnp.broadcast_to(ranges, (A,) + ranges.shape)
    origins = jnp.broadcast_to(origin_rc, (A, 2))
    rasters = G.scan_rasters(grid_cfg, scan_cfg, ranges_b, poses, origins)
    mass = jnp.maximum(rasters.sum(axis=(1, 2)), 1e-6)
    return rasters, mass


def _conv_scores(field: Array, rasters: Array, mass_ref: Array,
                 n_steps: int, stride: int = 1,
                 compute_dtype=jnp.float32) -> Array:
    """resp[a, sy, sx] = <raster_a, field shifted by ((sy-n)*stride,
    (sx-n)*stride) cells> / mass_ref — the whole correlative window as ONE
    cross-correlation on the MXU (XLA conv kernels are not flipped, so the
    conv IS the correlation). `stride` realises MatcherConfig.coarse_step_m
    in cells.

    mass_ref is one SHARED scalar denominator for every candidate of a
    match (the fullest raster's in-patch mass): normalising each candidate
    by its own mass would hand candidates whose hit band is clipped by the
    patch edge a smaller denominator and a quietly inflated score. With a
    shared denominator, clipping can only lower a response — conservative.
    """
    pad = n_steps * stride
    fpad = jnp.pad(field, pad).astype(compute_dtype)
    return _conv_scores_grid(fpad, rasters, mass_ref, 2 * n_steps + 1,
                             stride)


def _conv_scores_grid(fpad: Array, rasters: Array, mass_ref: Array,
                      n_out: int, stride: int) -> Array:
    """Strided-window correlation core over an ALREADY-padded field:
    resp[a, my, mx] = <raster_a, fpad[my*stride : my*stride+P,
    mx*stride : mx*stride+P]> / mass_ref. `_conv_scores` realises the
    classic symmetric window with it; the branch-and-bound top level
    calls it directly on the pyramid's coarsest array with
    stride = base_stride * 2^L (same padding, far fewer windows).

    Lowering: phrased as a 1D conv whose CHANNEL axis is the patch rows
    and whose batch axis is the y-shift (one sliced window of the padded
    field per my). The natural 2D form — C_in=1 input against (A, 1, P, P)
    kernels — makes XLA stage the whole P^2 contraction through an
    implicit im2col at C=1 and ran 3.7x slower at the production 640-patch
    shape (7.5 -> 2.0 ms coarse, 2.0 -> 0.24 ms fine, measured on v5e);
    with rows as channels the contraction is a clean (A, P*P) x (P*P, nx)
    matmul per my on the MXU. out[my, a, mx] = sum_{r,c}
    fpad[my*stride + r, mx*stride + c] * raster[a, r, c] — identical
    (unflipped-kernel) correlation semantics either way.
    """
    A, P, _ = rasters.shape
    compute_dtype = fpad.dtype
    windows = jax.vmap(lambda so: jax.lax.dynamic_slice(
        fpad, (so, 0), (P, fpad.shape[1])))(
            jnp.arange(n_out) * stride)             # (n_out, P, P+2p)
    out = jax.lax.conv_general_dilated(
        windows, rasters.astype(compute_dtype), window_strides=(stride,),
        padding="VALID", dimension_numbers=("NCW", "OIW", "NCW"),
        preferred_element_type=jnp.float32)         # (n_out, A, n_out)
    return jnp.transpose(out, (1, 0, 2)) / mass_ref


# ---------------------------------------------------------------------------
# Branch-and-bound coarse stage (MatcherConfig.pruned)
# ---------------------------------------------------------------------------

def window_params(grid_cfg: GridConfig,
                  m_cfg: MatcherConfig) -> tuple[int, int]:
    """(stride_cells, n_steps): the coarse window's leaf grid — shifts at
    `stride` cells, leaf index j in [-n_steps, n_steps]. ONE derivation
    for the exhaustive sweep, the pruned matcher, and the pyramid
    builders (a drifted copy would silently mis-key the cache)."""
    stride = max(1, int(round(m_cfg.coarse_step_m / grid_cfg.resolution_m)))
    n_steps = max(1, int(round(m_cfg.search_half_extent_m
                               / (stride * grid_cfg.resolution_m))))
    return stride, n_steps


def bnb_num_levels(m_cfg: MatcherConfig, n_steps: int) -> int:
    """Pyramid depth above level 0 for a (2*n_steps+1)-leaf window:
    `bnb_levels` when set, else the deepest level whose top grid still
    holds >= 3 nodes per axis (fewer and the top pass stops pruning;
    capped at 6 — beyond that the window would be absurd). 0 means the
    window is too small to prune — callers fall back to the exhaustive
    sweep, which at that size costs the same."""
    nw = 2 * n_steps + 1
    lv = m_cfg.bnb_levels
    if lv <= 0:
        lv = 0
        while lv < 6 and -(-nw // (2 ** (lv + 1))) >= 3:
            lv += 1
    while lv > 0 and -(-nw // (2 ** lv)) < 2:
        lv -= 1                  # explicit override deeper than the window
    return lv


def _block_reduce(x: Array, q: int, op: str) -> Array:
    """q x q block max/sum downsample, zero-padding ragged edges (safe
    both ways: the field is non-negative, so padding cannot LOWER a max
    bound, and zero raster cells add nothing to a sum)."""
    if q == 1:
        return x
    h, w = x.shape[-2], x.shape[-1]
    ph, pw = (-h) % q, (-w) % q
    if ph or pw:
        cfg = [(0, 0)] * (x.ndim - 2) + [(0, ph), (0, pw)]
        x = jnp.pad(x, cfg)
    shp = x.shape[:-2] + ((h + ph) // q, q, (w + pw) // q, q)
    blk = x.reshape(shp)
    return blk.max(axis=(-3, -1)) if op == "max" else \
        blk.sum(axis=(-3, -1))


def build_levels(field: Array, n_steps: int, stride: int,
                 n_levels: int) -> tuple[Array, ...]:
    """Likelihood field -> admissible multi-resolution max-pyramid.

    Internally, full-resolution sliding maxima are built first:

        F_0[x] = pad(field)[x]           (pad = n_steps * stride)
        F_l[x] = max_{0 <= d < 2^l} pad(field)[x + stride * d]
                 (per axis; positions past the array read as 0)

    so a level-l score upper-bounds EVERY leaf score in its 2^l x 2^l
    shift block. The RETURNED tuple is (F_0, D_1, ..., D_L) where
    D_l = blockmax_{2^l}(F_l) — each level 2^l x COARSER per axis. The
    dual coarsening (max-pooled field scored against SUM-pooled rasters,
    `_raster_sums`) keeps the bound admissible while a level-l candidate
    evaluation touches (P/2^l)^2 cells instead of P^2 — the
    multi-resolution map pyramid of the FPGA 2D-LiDAR-SLAM formulation:

        sum_r raster[r] * field[r + s]
          <= sum_R (sum_{r in R} raster[r]) * max_{r in R} F_l[r + s0]
           = sum_R rastersum_l[R] * D_l[R + s0/2^l]

    for any leaf shift s in the level-l block starting at s0 (s0 and
    every level-l candidate offset are multiples of 2^l by
    construction). Zero-fill past the edge only covers shift positions
    outside the search window (masked invalid during refinement), and
    the field is non-negative, so it cannot inflate a valid bound."""
    pad = n_steps * stride
    full = jnp.pad(field, pad)
    levels = [full]
    for lv in range(1, n_levels + 1):
        s = stride * (2 ** (lv - 1))
        prev = full
        rows = jnp.concatenate(
            [prev[s:, :], jnp.zeros((s, prev.shape[1]), prev.dtype)],
            axis=0)
        m = jnp.maximum(prev, rows)
        cols = jnp.concatenate(
            [m[:, s:], jnp.zeros((m.shape[0], s), m.dtype)], axis=1)
        full = jnp.maximum(m, cols)
        levels.append(_block_reduce(full, 2 ** lv, "max"))
    return tuple(levels)


def _raster_sums(rasters: Array, n_levels: int) -> list:
    """Per-level 2^l x 2^l block-SUM pools of the raster batch — the
    dual of the field max-pyramid (build_levels docstring). Index 0 is
    the full-resolution batch."""
    return [rasters] + [_block_reduce(rasters, 2 ** lv, "sum")
                        for lv in range(1, n_levels + 1)]


def _axis_min_off(i0: Array, lv: int, n_steps: int) -> Array:
    """Per-axis minimum |leaf offset| (in leaf steps) over a level-lv
    node starting at leaf index i0 — the admissible distance for the
    node's distance-penalty upper bound (the leaf closest to the
    odometric prior). At lv=0 this is the exact per-leaf offset."""
    nw = 2 * n_steps + 1
    i1 = jnp.minimum(i0 + (2 ** lv) - 1, nw - 1)
    lo = i0 - n_steps
    hi = i1 - n_steps
    return jnp.where((lo <= 0) & (hi >= 0), 0,
                     jnp.minimum(jnp.abs(lo), jnp.abs(hi)))


def _bnb_scores(lvl: Array, rasters: Array, a_idx: Array, oy: Array,
                ox: Array, mass_ref: Array) -> Array:
    """Candidate-batch scores <raster[a_k], lvl[oy_k : oy_k+P,
    ox_k : ox_k+P]> / mass_ref in ONE dispatchable op: a lax.map over
    fixed-size chunks, each chunk a vmapped slice-gather + einsum — peak
    memory is chunk x P^2 regardless of K, and nothing in the loop
    touches the host."""
    P = rasters.shape[1]
    K = a_idx.shape[0]
    C = 8 if K % 8 == 0 else 4        # child batches are multiples of 4

    def chunk(args):
        a, y, x = args
        sl = jax.vmap(lambda yy, xx: jax.lax.dynamic_slice(
            lvl, (yy, xx), (P, P)))(y, x)
        ra = jnp.take(rasters, a, axis=0)
        return jnp.einsum("kij,kij->k", sl, ra)

    out = jax.lax.map(chunk, (a_idx.reshape(-1, C), oy.reshape(-1, C),
                              ox.reshape(-1, C)))
    return out.reshape(-1) / mass_ref


def _bnb_winner(m_cfg: MatcherConfig, levels: tuple, resp_top: Array,
                rasters_c: Array, mass_ref: Array, dth_c: Array,
                n_steps: int, stride: int, step_m: float,
                n_levels: int
                ) -> tuple[Array, Array, Array, Array, int]:
    """Branch-and-bound descent from the top-level score surface to the
    exact leaf winner: (angle index, leaf iy, leaf ix, the winner's
    exact leaf response, n_scored).

    Candidates are (angle, leaf-block) nodes ranked by their admissible
    upper bound x the penalty upper bound (`_axis_min_off`); each round
    expands the kept top-K into its 4 children one level down and
    re-ranks. Level-0 scores are exact, so the final selection replicates
    the exhaustive sweep's penalty-weighted argmax — including its
    first-flat-index tie-break over (angle, sy, sx). Static shapes
    throughout; the whole descent lives inside one jit (no host syncs)."""
    A = dth_c.shape[0]
    nw = 2 * n_steps + 1
    M = resp_top.shape[1]
    pen_a = _pen_angle(m_cfg, dth_c)                        # (A,)
    iy0 = jnp.arange(M, dtype=jnp.int32) * (2 ** n_levels)
    mo = _axis_min_off(iy0, n_levels, n_steps).astype(jnp.float32) * step_m
    pen_d = _pen_dist(m_cfg, mo[:, None] ** 2 + mo[None, :] ** 2)  # (M, M)
    rank = resp_top * pen_d[None] * pen_a[:, None, None]
    K = min(m_cfg.bnb_topk, A * M * M)
    _, flat = jax.lax.top_k(rank.reshape(-1), K)
    a = (flat // (M * M)).astype(jnp.int32)
    rem = flat % (M * M)
    iy = (rem // M).astype(jnp.int32) * (2 ** n_levels)
    ix = (rem % M).astype(jnp.int32) * (2 ** n_levels)
    n_scored = A * M * M

    rsums = _raster_sums(rasters_c, n_levels - 1)
    for lv in range(n_levels - 1, -1, -1):
        off = 2 ** lv
        ca = jnp.tile(a, 4)
        ciy = jnp.tile(iy, 4) + jnp.repeat(
            jnp.asarray([0, 0, off, off], jnp.int32), K)
        cix = jnp.tile(ix, 4) + jnp.repeat(
            jnp.asarray([0, off, 0, off], jnp.int32), K)
        valid = (ciy < nw) & (cix < nw)
        # Level lv >= 1 scores on the 2^lv-downsampled dual pyramid
        # (1/4^lv the cells per candidate); level 0 scores exact leaves
        # at full resolution. Valid candidates' offsets are multiples of
        # 2^lv by construction; invalid ones may slice out of bounds,
        # where dynamic_slice clamps and the -1 mask discards them.
        scores = _bnb_scores(levels[lv], rsums[lv], ca,
                             (ciy // off) * stride,
                             (cix // off) * stride, mass_ref)
        my = _axis_min_off(ciy, lv, n_steps).astype(jnp.float32) * step_m
        mx = _axis_min_off(cix, lv, n_steps).astype(jnp.float32) * step_m
        pen = _pen_dist(m_cfg, my * my + mx * mx) * pen_a[ca]
        rank = jnp.where(valid, scores * pen, jnp.float32(-1.0))
        n_scored += 4 * K
        if lv > 0:
            # Funnel: full breadth while candidates are cheap
            # (downsampled), `bnb_leaf_topk` into the full-resolution
            # leaf round whose evaluations dominate memory traffic.
            K = min(m_cfg.bnb_leaf_topk if lv == 1 else m_cfg.bnb_topk,
                    4 * K)
            _, idx = jax.lax.top_k(rank, K)
            a, iy, ix = ca[idx], ciy[idx], cix[idx]
        else:
            # Exact leaves: penalty-weighted argmax with the exhaustive
            # sweep's first-flat-index tie-break over (a, sy, sx).
            best = rank.max()
            flat_leaf = ca * (nw * nw) + ciy * nw + cix
            sel = jnp.where(rank == best, flat_leaf,
                            jnp.int32(A * nw * nw))
            w = jnp.argmin(sel)
            a, iy, ix, resp = ca[w], ciy[w], cix[w], scores[w]
    return a, iy, ix, resp, n_scored


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match(grid_cfg: GridConfig, scan_cfg: ScanConfig, m_cfg: MatcherConfig,
          grid_arr: Array, ranges: Array, guess_pose: Array) -> MatchResult:
    """Coarse-to-fine correlative match of one scan against the map.

    Three dense passes, no gathers (a gather-based scorer pays ~20M
    scalarised lookups per match on TPU):

      1. coarse: rasters at every coarse angle x every integer cell shift
         in the window, scored jointly as one conv on the MXU;
      2. fine angles: rasters at fine angular steps around the winner,
         conv over +-1 cell;
      3. sub-cell: rasters at `fine_step_m` translation offsets of the
         winning angle (the dense rasterizer evaluates continuous poses
         exactly — sub-cell shifts move the hit band through the cells),
         scored at zero shift.

    Returns the refined pose; `accepted` mirrors the reference's response
    gating (callers fall back to the odometry guess when not accepted).

    `m_cfg.pruned` (the default) runs the branch-and-bound coarse stage
    instead of the exhaustive sweep — same argmax contract, a small
    fraction of the candidate evaluations (module docstring); windows too
    small to prune fall through to the exhaustive path, and
    `pruned=False` is the bit-exact pre-pruning pipeline.
    """
    origin = G.patch_origin(grid_cfg, guess_pose[:2])
    patch = jax.lax.dynamic_slice(
        grid_arr, (origin[0], origin[1]),
        (grid_cfg.patch_cells, grid_cfg.patch_cells))
    field = likelihood_field(grid_cfg, m_cfg, patch)
    stride, n_steps = window_params(grid_cfg, m_cfg)
    n_levels = bnb_num_levels(m_cfg, n_steps) if m_cfg.pruned else 0
    if n_levels > 0:
        levels = build_levels(field, n_steps, stride, n_levels)
        return _match_bnb(grid_cfg, scan_cfg, m_cfg, levels, origin,
                          ranges, guess_pose, n_levels)
    return _match_exhaustive(grid_cfg, scan_cfg, m_cfg, field, origin,
                             ranges, guess_pose)


def _match_exhaustive(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                      m_cfg: MatcherConfig, field: Array, origin: Array,
                      ranges: Array, guess_pose: Array) -> MatchResult:
    """The pre-pruning three-pass pipeline, bit-for-bit (the
    `MatcherConfig.pruned=False` contract and the parity oracle for the
    branch-and-bound path)."""
    res = grid_cfg.resolution_m
    # --- coarse pass: all angles x all strided-cell shifts --------------
    stride, n_steps = window_params(grid_cfg, m_cfg)
    dth_c = _angle_grid(m_cfg.coarse_angle_half_rad,
                        m_cfg.coarse_angle_step_rad)
    A_c = dth_c.shape[0]
    poses_c = jnp.concatenate([
        jnp.broadcast_to(guess_pose[:2], (A_c, 2)),
        (guess_pose[2] + dth_c)[:, None]], axis=1)
    rasters_c, mass_c = _raster_batch(grid_cfg, scan_cfg, ranges, poses_c,
                                      origin)
    # One denominator for the whole match (see _conv_scores): the fullest
    # candidate raster's mass. Rotations preserve band mass up to clipping,
    # so this is the scan's unclipped in-patch mass for any candidate.
    mass_ref = jnp.maximum(jnp.max(mass_c), 1e-6)
    # bf16 only where it pays: XLA CPU has no fast bf16 conv path (a tiny
    # bf16 conv ran orders of magnitude slower than f32 — measured), so
    # off-TPU the flag is ignored and everything stays f32. The process
    # default backend is the best trace-time signal available under jit
    # (input avals carry no device); arrays explicitly committed to CPU on
    # a TPU host still trace bf16 — set coarse_bf16=False for that
    # debugging pattern.
    coarse_dtype = (jnp.bfloat16
                    if m_cfg.coarse_bf16 and jax.default_backend() == "tpu"
                    else jnp.float32)
    resp_c = _conv_scores(field, rasters_c, mass_ref, n_steps, stride,
                          compute_dtype=coarse_dtype)
    # Rank by variance-penalized response (prior-proximity tie-break,
    # yaml:61-62); gate on the winner's RAW response (Karto semantics).
    step_m = stride * res
    offs = jnp.arange(-n_steps, n_steps + 1, dtype=jnp.float32) * step_m
    d2_c = offs[None, :] ** 2 + offs[:, None] ** 2          # (2n+1, 2n+1)
    pen_c = _pen_dist(m_cfg, d2_c)[None] * \
        _pen_angle(m_cfg, dth_c)[:, None, None]
    best_c = jnp.argmax(resp_c * pen_c)
    ai_c, sy_c, sx_c = jnp.unravel_index(best_c, resp_c.shape)
    coarse_resp = resp_c[ai_c, sy_c, sx_c]
    dth0 = dth_c[ai_c]
    # Shift in metres ((sy, sx) strided steps; row = y, col = x).
    shift0 = jnp.stack([(sx_c - n_steps).astype(jnp.float32) * step_m,
                        (sy_c - n_steps).astype(jnp.float32) * step_m])

    pose, fine_resp = _fine_stages(grid_cfg, scan_cfg, m_cfg, field,
                                   origin, ranges, guess_pose, mass_ref,
                                   dth0, shift0)

    # --- correlation-surface covariance (MatchResult.cov docstring) -----
    # Computed over the COARSE surface: it spans the whole search window
    # (the fine surface covers only +-1 coarse step, far too narrow to
    # see a corridor's metres-long ridge). Softmax weights; temperature
    # in response units — small enough that only the peak's basin
    # contributes, large enough that a flat ridge keeps mass spread.
    T = jnp.float32(0.05)
    surf = resp_c[ai_c].astype(jnp.float32)  # (2n+1, 2n+1) xy, step_m
    w_t = jnp.exp((surf - surf.max()) / T)
    wx = w_t.sum(axis=0)                     # collapse y -> x axis
    wy = w_t.sum(axis=1)
    mx = (wx * offs).sum() / wx.sum()
    my = (wy * offs).sum() / wy.sum()
    var_x = (wx * (offs - mx) ** 2).sum() / wx.sum()
    var_y = (wy * (offs - my) ** 2).sum() / wy.sum()
    resp_a = resp_c.max(axis=(1, 2)).astype(jnp.float32)  # per coarse angle
    w_a = jnp.exp((resp_a - resp_a.max()) / T)
    ma = (w_a * dth_c).sum() / w_a.sum()
    var_th = (w_a * (dth_c - ma) ** 2).sum() / w_a.sum()
    # Never report tighter than the stage's own quantisation — and the
    # stage HERE is the coarse one for all three axes (the theta surface
    # is sampled at coarse_angle_step_rad; flooring it at the fine step
    # would publish ~100x overconfident yaw variance).
    cov = jnp.stack([
        jnp.maximum(var_x, (step_m / 2) ** 2 / 3),
        jnp.maximum(var_y, (step_m / 2) ** 2 / 3),
        jnp.maximum(var_th,
                    (m_cfg.coarse_angle_step_rad / 2) ** 2 / 3)])

    return MatchResult(pose=pose, response=fine_resp,
                       coarse_response=coarse_resp,
                       accepted=fine_resp >= m_cfg.min_response,
                       cov=cov,
                       n_candidates=jnp.int32(A_c * (2 * n_steps + 1) ** 2),
                       prune_ratio=jnp.float32(0.0))


def _fine_stages(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 m_cfg: MatcherConfig, field: Array, origin: Array,
                 ranges: Array, guess_pose: Array, mass_ref: Array,
                 dth0: Array, shift0: Array) -> tuple[Array, Array]:
    """Fine-angle + sub-cell refinement around a coarse winner — shared
    verbatim by the exhaustive and branch-and-bound paths, so a matching
    coarse winner implies a bit-identical refined pose."""
    res = grid_cfg.resolution_m
    stride, _n_steps = window_params(grid_cfg, m_cfg)

    # --- fine angles around the winner, +- one coarse step --------------
    dth_f = dth0 + _angle_grid(m_cfg.coarse_angle_step_rad,
                               m_cfg.fine_angle_step_rad)
    A_f = dth_f.shape[0]
    poses_f = jnp.concatenate([
        jnp.broadcast_to(guess_pose[:2] + shift0, (A_f, 2)),
        (guess_pose[2] + dth_f)[:, None]], axis=1)
    rasters_f, _mass_f = _raster_batch(grid_cfg, scan_cfg, ranges, poses_f,
                                       origin)
    resp_f = _conv_scores(field, rasters_f, mass_ref, stride)
    offs_f = jnp.arange(-stride, stride + 1, dtype=jnp.float32) * res
    d2_f = (shift0[0] + offs_f[None, :]) ** 2 \
        + (shift0[1] + offs_f[:, None]) ** 2
    pen_f = _pen_dist(m_cfg, d2_f)[None] * \
        _pen_angle(m_cfg, dth_f)[:, None, None]
    best_f = jnp.argmax(resp_f * pen_f)
    ai_f, sy_f, sx_f = jnp.unravel_index(best_f, resp_f.shape)
    dth1 = dth_f[ai_f]
    shift1 = shift0 + jnp.stack([(sx_f - stride).astype(jnp.float32) * res,
                                 (sy_f - stride).astype(jnp.float32) * res])

    # --- sub-cell translation at the winning angle ----------------------
    k = max(1, int(round(0.5 * res / m_cfg.fine_step_m)) + 1)
    d1 = jnp.arange(-k, k + 1, dtype=jnp.float32) * m_cfg.fine_step_m
    ddx, ddy = jnp.meshgrid(d1, d1, indexing="xy")
    deltas = jnp.stack([ddx.ravel(), ddy.ravel()], axis=-1)   # (S, 2) m
    S = deltas.shape[0]
    poses_s = jnp.concatenate([
        guess_pose[:2] + shift1 + deltas,
        jnp.full((S, 1), guess_pose[2] + dth1)], axis=1)
    rasters_s, _mass_s = _raster_batch(grid_cfg, scan_cfg, ranges, poses_s,
                                       origin)
    resp_s = jnp.einsum("bhw,hw->b", rasters_s, field) / mass_ref
    d2_s = jnp.sum((shift1[None, :] + deltas) ** 2, axis=-1)
    si = jnp.argmax(resp_s * _pen_dist(m_cfg, d2_s))
    fine_resp = resp_s[si]

    pose = jnp.stack([
        guess_pose[0] + shift1[0] + deltas[si, 0],
        guess_pose[1] + shift1[1] + deltas[si, 1],
        guess_pose[2] + dth1,
    ])
    return pose, fine_resp


def _bnb_setup(grid_cfg: GridConfig, scan_cfg: ScanConfig,
               m_cfg: MatcherConfig, origin: Array, ranges: Array,
               guess_pose: Array) -> tuple[Array, Array, Array]:
    """(dth_c, rasters_c, mass_ref): the same coarse-angle raster batch
    and shared mass denominator the exhaustive sweep builds."""
    dth_c = _angle_grid(m_cfg.coarse_angle_half_rad,
                        m_cfg.coarse_angle_step_rad)
    A_c = dth_c.shape[0]
    poses_c = jnp.concatenate([
        jnp.broadcast_to(guess_pose[:2], (A_c, 2)),
        (guess_pose[2] + dth_c)[:, None]], axis=1)
    rasters_c, mass_c = _raster_batch(grid_cfg, scan_cfg, ranges, poses_c,
                                      origin)
    mass_ref = jnp.maximum(jnp.max(mass_c), 1e-6)
    return dth_c, rasters_c, mass_ref


def _bnb_top(levels: tuple, rasters_c: Array, mass_ref: Array,
             n_steps: int, stride: int, n_levels: int) -> Array:
    """Top-level upper-bound surface: every (angle, 2^L-block) node of
    the window scored as ONE strided MXU conv over the coarsest DUAL
    pyramid level — ceil((2n+1)/2^L)^2 windows of (P/2^L)^2-cell
    sum-pooled rasters instead of (2n+1)^2 windows of P^2 cells. Always
    f32: a bf16 round-DOWN of an upper bound would break admissibility
    (MatcherConfig.coarse_bf16 stays an exhaustive-path knob). Window
    stride is `stride` in downsampled units: a 2^L-block step is
    stride * 2^L full-resolution cells."""
    nw = 2 * n_steps + 1
    M = -(-nw // (2 ** n_levels))
    rsum = _block_reduce(rasters_c, 2 ** n_levels, "sum")
    # Ragged-edge ceil padding can leave the conv with a column or two
    # of extra x-windows past the last node; keep the exact M x M grid.
    return _conv_scores_grid(levels[n_levels], rsum, mass_ref, M,
                             stride)[:, :, :M]


def _match_bnb(grid_cfg: GridConfig, scan_cfg: ScanConfig,
               m_cfg: MatcherConfig, levels: tuple, origin: Array,
               ranges: Array, guess_pose: Array,
               n_levels: int) -> MatchResult:
    """Branch-and-bound coarse stage + the shared fine stages."""
    dth_c, rasters_c, mass_ref = _bnb_setup(grid_cfg, scan_cfg, m_cfg,
                                            origin, ranges, guess_pose)
    stride, n_steps = window_params(grid_cfg, m_cfg)
    resp_top = _bnb_top(levels, rasters_c, mass_ref, n_steps, stride,
                        n_levels)
    return _bnb_finish(grid_cfg, scan_cfg, m_cfg, levels, resp_top,
                       rasters_c, mass_ref, dth_c, origin, ranges,
                       guess_pose, n_levels)


def _bnb_finish(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                m_cfg: MatcherConfig, levels: tuple, resp_top: Array,
                rasters_c: Array, mass_ref: Array, dth_c: Array,
                origin: Array, ranges: Array, guess_pose: Array,
                n_levels: int) -> MatchResult:
    """Descend to the leaf winner, then refine and report like the
    exhaustive path. `coarse_response` is the winner's EXACT leaf score
    (the level-0 descent already computed it). The covariance surface is
    the winner-ANGLE's level-1 dual-pyramid surface — the whole search
    window at 2-leaf block granularity, Olson's correlation-surface
    covariance over admissible upper bounds: a ridge stays a ridge and a
    peak stays a peak, at 1/4 the cells of the full-resolution surface
    (re-scoring the full surface for one angle cost more than the whole
    descent); the quantisation floor widens to the block size
    accordingly. Theta variance reads the top-level per-angle maxima —
    admissible upper bounds of the exhaustive per-angle maxima, same
    softmax shape."""
    res = grid_cfg.resolution_m
    stride, n_steps = window_params(grid_cfg, m_cfg)
    nw = 2 * n_steps + 1
    step_m = stride * res
    A_c = dth_c.shape[0]
    pad = n_steps * stride

    ai_c, iy_b, ix_b, coarse_resp, n_scored = _bnb_winner(
        m_cfg, levels, resp_top, rasters_c, mass_ref, dth_c, n_steps,
        stride, step_m, n_levels)
    field = levels[0][pad:-pad, pad:-pad]
    dth0 = dth_c[ai_c]
    shift0 = jnp.stack([(ix_b - n_steps).astype(jnp.float32) * step_m,
                        (iy_b - n_steps).astype(jnp.float32) * step_m])

    pose, fine_resp = _fine_stages(grid_cfg, scan_cfg, m_cfg, field,
                                   origin, ranges, guess_pose, mass_ref,
                                   dth0, shift0)

    # Covariance: x/y softmax moments over the winner-angle level-1
    # block surface (2-leaf granularity), theta over the top-level
    # per-angle maxima.
    T = jnp.float32(0.05)
    Mb = -(-nw // 2)                         # level-1 blocks per axis
    r1 = _block_reduce(jnp.take(rasters_c, ai_c[None], axis=0), 2, "sum")
    surf = _conv_scores_grid(levels[1], r1, mass_ref, Mb,
                             stride)[0, :, :Mb].astype(jnp.float32)
    n_scored += Mb * Mb
    # Block-centre offsets: block m covers leaves {2m, 2m+1}.
    offs = (jnp.arange(Mb, dtype=jnp.float32) * 2.0 + 0.5
            - n_steps) * step_m
    w_t = jnp.exp((surf - surf.max()) / T)
    wx = w_t.sum(axis=0)
    wy = w_t.sum(axis=1)
    mx = (wx * offs).sum() / wx.sum()
    my = (wy * offs).sum() / wy.sum()
    var_x = (wx * (offs - mx) ** 2).sum() / wx.sum()
    var_y = (wy * (offs - my) ** 2).sum() / wy.sum()
    resp_a = resp_top.max(axis=(1, 2)).astype(jnp.float32)
    w_a = jnp.exp((resp_a - resp_a.max()) / T)
    ma = (w_a * dth_c).sum() / w_a.sum()
    var_th = (w_a * (dth_c - ma) ** 2).sum() / w_a.sum()
    cov = jnp.stack([
        jnp.maximum(var_x, step_m ** 2 / 3),
        jnp.maximum(var_y, step_m ** 2 / 3),
        jnp.maximum(var_th,
                    (m_cfg.coarse_angle_step_rad / 2) ** 2 / 3)])

    total = A_c * nw * nw
    return MatchResult(pose=pose, response=fine_resp,
                       coarse_response=coarse_resp,
                       accepted=fine_resp >= m_cfg.min_response,
                       cov=cov,
                       n_candidates=jnp.int32(n_scored),
                       prune_ratio=jnp.float32(
                           max(0.0, 1.0 - n_scored / total)))


# ---------------------------------------------------------------------------
# Host-driven cached entry points (ops/pyramid.PyramidCache)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def pyramid_coarse_scores(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                          m_cfg: MatcherConfig, n_levels: int,
                          levels: tuple, origin: Array, ranges: Array,
                          guess_pose: Array
                          ) -> tuple[Array, Array, Array]:
    """Stage 1 of the cached pruned match: rasterize + top-level bound
    surface against a PREBUILT pyramid. Returns (resp_top, rasters_c,
    mass_ref) — device-resident intermediates `pyramid_refine` consumes
    (and donates) without a host round trip."""
    dth_c, rasters_c, mass_ref = _bnb_setup(grid_cfg, scan_cfg, m_cfg,
                                            origin, ranges, guess_pose)
    del dth_c
    stride, n_steps = window_params(grid_cfg, m_cfg)
    resp_top = _bnb_top(levels, rasters_c, mass_ref, n_steps, stride,
                        n_levels)
    return resp_top, rasters_c, mass_ref


def _pyramid_refine_impl(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                         m_cfg: MatcherConfig, n_levels: int,
                         resp_top: Array, levels: tuple, origin: Array,
                         ranges: Array, rasters_c: Array, mass_ref: Array,
                         guess_pose: Array) -> MatchResult:
    dth_c = _angle_grid(m_cfg.coarse_angle_half_rad,
                        m_cfg.coarse_angle_step_rad)
    return _bnb_finish(grid_cfg, scan_cfg, m_cfg, levels, resp_top,
                       rasters_c, mass_ref, dth_c, origin, ranges,
                       guess_pose, n_levels)


@functools.lru_cache(maxsize=None)
def _pyramid_refine_jit():
    """jit of `_pyramid_refine_impl`, donating the coarse score buffer
    and the raster batch (dead after the call; XLA reuses their backing
    for the candidate batches). Donation is a TPU/GPU capability — the
    CPU runtime ignores it with a warning per compile, so off-accelerator
    the args are simply not donated (identical results). Built lazily:
    probing the backend at import time could hang package import on a
    wedged TPU tunnel (the conftest re-exec hazard)."""
    donate = (4, 8) if jax.default_backend() in ("tpu", "gpu") else ()
    return jax.jit(_pyramid_refine_impl, static_argnums=(0, 1, 2, 3),
                   donate_argnums=donate)


def pyramid_refine(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                   m_cfg: MatcherConfig, n_levels: int, resp_top: Array,
                   levels: tuple, origin: Array, ranges: Array,
                   rasters_c: Array, mass_ref: Array,
                   guess_pose: Array) -> MatchResult:
    """Stage 2: the whole branch-and-bound descent + fine stages as ONE
    jitted dispatch — no host syncs between levels; on accelerators the
    coarse score buffer and raster batch are donated
    (`_pyramid_refine_jit`)."""
    return _pyramid_refine_jit()(grid_cfg, scan_cfg, m_cfg, n_levels,
                                 resp_top, levels, origin, ranges,
                                 rasters_c, mass_ref, guess_pose)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def match_with_pyramid(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                       m_cfg: MatcherConfig, n_levels: int, levels: tuple,
                       origin: Array, ranges: Array,
                       guess_pose: Array) -> MatchResult:
    """Single-dispatch pruned match against a prebuilt pyramid (the
    convenience form of the coarse/refine split; parity-tested against
    `match`)."""
    return _match_bnb(grid_cfg, scan_cfg, m_cfg, levels, origin, ranges,
                      guess_pose, n_levels)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def match_batch(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                m_cfg: MatcherConfig, grid_arr: Array, ranges_b: Array,
                guesses_b: Array) -> MatchResult:
    """vmap the matcher over a batch of scans against one shared map."""
    return jax.vmap(lambda r, p: match(grid_cfg, scan_cfg, m_cfg,
                                       grid_arr, r, p))(ranges_b, guesses_b)
