"""Fused scan-to-log-odds fusion: one pass from ranges to hashed tiles.

Fusion is the per-tick floor every robot pays, and the pre-fused path is
a chain of separate device passes (visible in the PR 10 dispatch
profiler): `grid._classify_batch` materialises a (B, P, P) deltas array
in HBM, a sequential `lax.scan` of dynamic_slice/dynamic_update_slice
read-modify-writes folds it, and a THIRD full-grid pass
(`grid.tile_hashes`) plus host-side dirty marking tells
serving/frontier/pyramid caches what changed. The ray-casting-free
formulation (PAPERS.md: arxiv 2307.08493, "Occupancy Grid Mapping
without Ray-Casting") is per-cell evidence with no beam walk — exactly
the shape that fuses raster + log-odds update + tile accounting into one
pass, with FPGA-SLAM's stage-overlap mindset (arxiv 2006.01050).

Two parity-tested engines behind the `grid._use_pallas()` dispatch
convention, gated by `GridConfig.fused_fusion` (False = the pre-fused
chain bit-exactly):

* **Streaming XLA engine** (every backend; what tier-1 measures):
  classify and fold ride the same `lax.scan` body in `_STREAM_CHUNK`
  sub-batches — at most (_STREAM_CHUNK, P, P) of deltas is ever live,
  never the full (B, P, P) HBM array, and the whole fuse -> touched
  tiles -> bounded tile hash pipeline is ONE dispatch (the classic
  chain pays fuse + to_gray + full-grid tile_hashes). Bit-identical to
  the classic chain on the scattered/masked paths (the per-scan op
  order is unchanged — only the fusion structure moved); the
  shared-patch window path reassociates the cross-scan delta sum once
  per sub-chunk boundary (windows of <= _STREAM_CHUNK scans, i.e.
  every default `batch_scans` window and the regress-gate `fuse_tiny`
  workload, are bit-identical; larger windows differ by last-ulp — the
  documented `sensor_kernel.window_delta` chunk-split caveat).
* **Pallas TPU engine** (`sensor_kernel._make_kernel(fused_apply=True)`
  Mosaic kernel, following the beam-table/chunking conventions incl.
  the `_MAX_B_PER_CALL` SMEM ceiling): each grid strip stays
  VMEM-resident across the whole scan batch — in-vreg beam-table
  gather, per-scan log-odds accumulate, and the clamped fold into the
  resident patch on the last scan: one HBM round-trip per strip instead
  of window-delta write + read + patch read + write. Bit-identical to
  the classic Pallas window composition (same b-order accumulation,
  same single `patch + acc` addition).

Touched-tile contract: the fused entry points report which serving
tiles their patches may have touched ON DEVICE — exact
`grid.patch_origin` extents, not the host marker's half-extent
padding — and `fuse_scans_window_touched` finishes with an incremental
`tile_hashes` restricted to the touched-tile region in the SAME
dispatch, so the separate full-grid hash pass and the host dirty-mark
bookkeeping collapse into consuming the kernel's output. Semantics
stay validated-superset: the tile store's own hash diff (on the gray
surface) remains the re-encode criterion; a log-odds-identical tile is
gray-identical by construction.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax_mapping.config import GridConfig, ScanConfig
from jax_mapping.ops import grid as G
from jax_mapping.ops import sensor_kernel as SK

Array = jax.Array

#: Scans classified per streaming sub-batch. Measured on the 2-core
#: CPU builder at the production 640-patch config: a pure per-scan
#: stream (classify one, fold one) serialises the classify work
#: XLA:CPU vectorises across a batch and runs ~1.4x slower than the
#: classic chain, and finer sub-batches (8/16/32) still pay a 13-30%
#: interleave tax — so the XLA engine streams at 64: batches up to 64
#: (every mapper window, single scans, the tiny ring repair) keep the
#: classic classify-then-fold structure EXACTLY (bit-identical, same
#: speed), while larger batches bound the transient deltas at
#: 64 x 1.6 MB = 105 MB instead of the classic chain's
#: _FUSE_CHUNK x 1.6 MB = 420 MB HBM materialisation (1.7 GB unchunked
#: at the 1024-scan loop repair) for a measured ~5-19% interleave cost.
#: Fine-grained interleaving is the TPU engine's job — there the fused
#: Mosaic kernel keeps strips VMEM-resident across the whole batch.
_STREAM_CHUNK = 64

#: Extra tile-box slack (grid cells) for intra-window robot motion when
#: deriving touched tiles from step ENDPOINT poses (the mapper's dirty
#: marking): the window-fits contract bounds how far a window's interior
#: poses stray from its endpoints — the same 8-cell slack the host
#: marker `MapperNode._mark_dirty_patch` always carried.
_ENDPOINT_SLACK_CELLS = 8


# ---------------------------------------------------------------------------
# Streaming XLA engine
# ---------------------------------------------------------------------------

def stream_fold(grid_cfg: GridConfig, scan_cfg: ScanConfig, grid_arr: Array,
                ranges_b: Array, poses_b: Array, mask_b: Optional[Array],
                clamp: bool) -> Array:
    """Delta-free streaming classify->fold over one chunk (traced; the
    fused twin of `grid._classify_fold`'s classic chunk body).

    Classification runs in `_STREAM_CHUNK` sub-batches through the same
    engine-dispatched `grid._classify_batch` the classic chain uses;
    each sub-batch folds immediately, so the (B, P, P) deltas array the
    classic chain materialises in HBM never exists. Per-scan op order is
    identical to classic — bit-identical output (property-tested)."""
    B = ranges_b.shape[0]
    if B == 0:
        return grid_arr

    def fold_chunk(g, r, p, m):
        deltas, origins = G._classify_batch(grid_cfg, scan_cfg, r, p)
        if m is not None:
            deltas = deltas * m[:, None, None].astype(deltas.dtype)

        def body(g2, do):
            delta, origin = do
            return G.apply_patch(grid_cfg, g2, delta, origin,
                                 clamp=clamp), None

        g3, _ = jax.lax.scan(body, g, (deltas, origins))
        return g3

    c = min(_STREAM_CHUNK, B)
    nc, rem = B // c, B % c
    out = grid_arr
    if nc == 1:
        # One sub-chunk: no outer scan layer — the extra while-loop
        # nesting costs ~25% of slam_step's XLA compile for nothing
        # (this IS the classic classify-then-fold structure, which is
        # also what makes the <= _STREAM_CHUNK paths bit-identical).
        out = fold_chunk(out, ranges_b[:c], poses_b[:c],
                         None if mask_b is None else mask_b[:c])
    elif nc:
        cut = nc * c

        def outer(g, rpm):
            r, p, m = rpm
            return fold_chunk(g, r, p, m), None

        out, _ = jax.lax.scan(
            outer, out,
            (ranges_b[:cut].reshape(nc, c, -1),
             poses_b[:cut].reshape(nc, c, 3),
             None if mask_b is None else mask_b[:cut].reshape(nc, c)))
    if rem:
        out = fold_chunk(out, ranges_b[B - rem:], poses_b[B - rem:],
                         None if mask_b is None else mask_b[B - rem:])
    return out


def window_accumulate_xla(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                          ranges_b: Array, poses_b: Array,
                          origin_rc: Array) -> Array:
    """Streaming shared-patch window delta (XLA engine): sum of all B
    scans' deltas on one patch, accumulated per `_STREAM_CHUNK`
    sub-batch so at most (c, P, P) is ever live. For B <= _STREAM_CHUNK
    this IS the classic vmap+sum bit-for-bit; beyond that the cross-scan
    sum reassociates at sub-chunk boundaries (last-ulp, the
    `window_delta` chunk-split caveat)."""
    P = grid_cfg.patch_cells
    B = ranges_b.shape[0]
    if B == 0:
        return jnp.zeros((P, P), jnp.float32)

    def chunk_delta(r, p):
        return jax.vmap(
            lambda rr, pp: G.classify_patch(grid_cfg, scan_cfg, rr, pp,
                                            origin_rc)
        )(r, p).sum(axis=0)

    c = min(_STREAM_CHUNK, B)
    nc, rem = B // c, B % c
    if nc == 1 and rem == 0:
        return chunk_delta(ranges_b, poses_b)
    acc = jnp.zeros((P, P), jnp.float32)
    if nc == 1:
        acc = acc + chunk_delta(ranges_b[:c], poses_b[:c])
        nc = 0                      # rem handled below; no outer scan
    if nc:
        cut = nc * c

        def outer(a, rp):
            r, p = rp
            return a + chunk_delta(r, p), None

        acc, _ = jax.lax.scan(outer, acc,
                              (ranges_b[:cut].reshape(nc, c, -1),
                               poses_b[:cut].reshape(nc, c, 3)))
    if rem:
        acc = acc + chunk_delta(ranges_b[B - rem:], poses_b[B - rem:])
    return acc


# ---------------------------------------------------------------------------
# Pallas TPU engine: grid strips VMEM-resident across the scan batch
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1))
def _window_apply_pallas(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                         patch: Array, ranges_b: Array, poses_b: Array,
                         origin_rc: Array) -> Array:
    """clip(patch + sum_b delta_b) in ONE kernel: per (S, LANES) strip,
    accumulate every scan's delta in the resident output register file
    and fold the current patch in (clamped) on the last scan — the
    window delta never round-trips HBM. B <= `SK._MAX_B_PER_CALL`
    (callers chunk; the scoped-SMEM ceiling is the sensor kernel's)."""
    SK._check_shapes(grid_cfg, scan_cfg)
    P = grid_cfg.patch_cells
    S = SK._step_rows(grid_cfg)
    B = ranges_b.shape[0]
    nchunk = scan_cfg.padded_beams // SK.LANES
    table = SK._beam_table(grid_cfg, scan_cfg, ranges_b)
    origin = jnp.broadcast_to(
        origin_rc.astype(jnp.int32).reshape(1, 2), (B, 2))
    kernel = SK._make_kernel(grid_cfg, scan_cfg, S, accumulate=True,
                             fused_apply=True)
    rows_tot = P * P // SK.LANES
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(rows_tot // S, B),
        in_specs=[
            pl.BlockSpec((1, nchunk, SK.LANES), lambda t, b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((S, SK.LANES), lambda t, b: (t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((S, SK.LANES), lambda t, b: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_tot, SK.LANES), jnp.float32),
        interpret=interpret,
    )(table, poses_b.astype(jnp.float32), origin,
      patch.reshape(rows_tot, SK.LANES))
    return out.reshape(P, P)


def window_fused(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 grid_arr: Array, ranges_b: Array, poses_b: Array,
                 origin_rc: Array) -> Array:
    """Fused shared-patch window fuse (traced): engine-dispatched like
    `grid._classify_batch`. Every pose must satisfy the shared-patch
    contract (`sensor_kernel.window_fits`) — same as the classic path."""
    P = grid_cfg.patch_cells
    B = ranges_b.shape[0]
    if B == 0:
        return G.apply_patch(grid_cfg, grid_arr,
                             jnp.zeros((P, P), jnp.float32), origin_rc,
                             clamp=True)
    if G._use_pallas():
        if B <= SK._MAX_B_PER_CALL:
            cur = jax.lax.dynamic_slice(
                grid_arr, (origin_rc[0], origin_rc[1]), (P, P))
            new = _window_apply_pallas(grid_cfg, scan_cfg, cur, ranges_b,
                                       poses_b, origin_rc)
            return jax.lax.dynamic_update_slice(
                grid_arr, new, (origin_rc[0], origin_rc[1]))
        # Over the SMEM ceiling: chunked kernel subtotals + one apply —
        # the classic composition bit-for-bit, still one dispatch.
        delta = SK.window_delta(grid_cfg, scan_cfg, ranges_b, poses_b,
                                origin_rc)
    else:
        delta = window_accumulate_xla(grid_cfg, scan_cfg, ranges_b,
                                      poses_b, origin_rc)
    return G.apply_patch(grid_cfg, grid_arr, delta, origin_rc, clamp=True)


# ---------------------------------------------------------------------------
# Touched-tile accounting (device-computed; serving tile units)
# ---------------------------------------------------------------------------

def patch_span_tiles(grid_cfg: GridConfig, tile_cells: int) -> int:
    """Serving tiles per axis that one fusion patch can intersect: the
    patch spans `patch_cells` from a tile-UNaligned origin, so
    ceil(P/t) + 1 tiles bound it (clamped to the tile grid)."""
    if grid_cfg.size_cells % tile_cells:
        raise ValueError(
            f"tile_cells={tile_cells} does not divide grid.size_cells="
            f"{grid_cfg.size_cells}")
    span = -(-grid_cfg.patch_cells // tile_cells) + 1
    return min(span, grid_cfg.size_cells // tile_cells)


@functools.partial(jax.jit, static_argnums=(0, 1))
def touched_tile_box(grid_cfg: GridConfig, tile_cells: int,
                     poses_xy: Array, pad_cells: Array) -> Array:
    """(4,) int32 [tr0, tr1, tc0, tc1] INCLUSIVE serving-tile bounds
    covering every fusion patch a step at these poses touched — the
    device-computed feed for the mapper's dirty-tile mask
    (`MapperNode._mark_dirty_box`). Uses the exact `grid.patch_origin`
    snapping the fusion itself used (the host marker approximated it
    with half-extent + alignment padding), padded by `pad_cells` —
    callers pass the step's intra-window TRAVEL bound (window-interior
    poses lie within the odometric path length of the endpoints, so the
    box is a true superset even for windows the shared-patch check sent
    down the per-scan-patch fallback) — plus the fixed endpoint slack
    AND the origin-alignment quantum: `patch_origin` rounds to
    align_cols (128 at production), so a pose just past an endpoint can
    snap its patch a full alignment step beyond the endpoints' own
    snapped origins — the same snap the host marker's align/2 padding
    absorbed, needed in full here because both compared values are
    snapped. `_tile_rev` consumers (pyramid cache, incremental
    frontier) rely on the superset; the tile store's hash diff stays
    its own criterion.

    poses_xy: (N, 2) world metres — the step's pose endpoints.
    pad_cells: () int32 — extra slack in grid cells (traced: one
    compiled variant regardless of travel).
    """
    P = grid_cfg.patch_cells
    nt = grid_cfg.size_cells // tile_cells
    origins = jax.vmap(
        lambda xy: G.patch_origin(grid_cfg, xy))(poses_xy)   # (N, 2) r,c
    pad = (_ENDPOINT_SLACK_CELLS + pad_cells
           + max(grid_cfg.align_rows, grid_cfg.align_cols))
    lo = jnp.clip(origins.min(axis=0) - pad, 0,
                  grid_cfg.size_cells - 1)
    hi = jnp.clip(origins.max(axis=0) + P - 1 + pad, 0,
                  grid_cfg.size_cells - 1)
    t0 = lo // tile_cells
    t1 = hi // tile_cells
    return jnp.stack([t0[0], jnp.minimum(t1[0], nt - 1),
                      t0[1], jnp.minimum(t1[1], nt - 1)]).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Fused entry points: ranges -> grid (+ touched tiles, + hashed tiles)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def fuse_scans_window_touched(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                              tile_cells: int, grid_arr: Array,
                              ranges_b: Array, poses_b: Array
                              ) -> Tuple[Array, Array, Array]:
    """One dispatch from raw ranges to hashed tiles (the ISSUE 11
    headline): fuse a shared-patch scan window AND hash exactly the
    tile region the patch touched.

    Returns (new_grid, tile_rc, hashes): `tile_rc` is the (2,) int32
    [tile_row, tile_col] origin of the touched K x K tile region
    (K = `patch_span_tiles`), `hashes` its (K, K, 2) uint32 per-tile
    content hashes (`grid.tile_hashes` lanes) over the NEW grid — the
    bounded incremental replacement for the classic chain's separate
    full-grid hash dispatch. Window semantics (shared patch from the
    mean pose, clamp once per window) match `grid.fuse_scans_window`;
    honors `GridConfig.fused_fusion` so parity tests can pin the classic
    chain through the same output surface.
    """
    mean_xy = poses_b[:, :2].mean(axis=0)
    origin = G.patch_origin(grid_cfg, mean_xy)
    if grid_cfg.fused_fusion:
        new = window_fused(grid_cfg, scan_cfg, grid_arr, ranges_b,
                           poses_b, origin)
    else:
        new = G.fuse_scans_window(grid_cfg, scan_cfg, grid_arr, ranges_b,
                                  poses_b)
    K = patch_span_tiles(grid_cfg, tile_cells)
    nt = grid_cfg.size_cells // tile_cells
    tile_rc = jnp.minimum(origin // tile_cells,
                          nt - K).astype(jnp.int32)
    region = jax.lax.dynamic_slice(
        new, (tile_rc[0] * tile_cells, tile_rc[1] * tile_cells),
        (K * tile_cells, K * tile_cells))
    return new, tile_rc, G.tile_hashes(region, tile_cells)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def fuse_scans_touched(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                       tile_cells: int, grid_arr: Array, ranges_b: Array,
                       poses_b: Array, mask_b: Optional[Array] = None
                       ) -> Tuple[Array, Array]:
    """Scattered-pose fused fold with a touched-tile side output: the
    grid exactly as `fuse_scans`/`fuse_scans_masked` produce it, plus
    the (nt, nt) bool mask of serving tiles any CONTRIBUTING scan's
    patch intersected (masked-out scans mark nothing), computed in the
    same dispatch from the per-patch origins the fold itself used.

    The scattered half of the touched-tile contract. No bridge caller
    yet: the mapper's scattered installs run inside `slam_step`'s jit
    (no host consumer for a side output there) and its closure re-fuse
    marks all tiles anyway — this is the entry the sharded fleet step's
    halo exchange (ROADMAP item 3) consumes, where per-patch tile
    extents decide which neighbor slabs must move."""
    m = None if mask_b is None else mask_b.astype(jnp.bool_)
    if grid_cfg.fused_fusion:
        out = stream_fold(grid_cfg, scan_cfg, grid_arr, ranges_b, poses_b,
                          m, clamp=True)
    elif m is None:
        out = G.fuse_scans(grid_cfg, scan_cfg, grid_arr, ranges_b,
                           poses_b)
    else:
        out = G.fuse_scans_masked(grid_cfg, scan_cfg, grid_arr, ranges_b,
                                  poses_b, m)
    K = patch_span_tiles(grid_cfg, tile_cells)
    nt = grid_cfg.size_cells // tile_cells
    origins = jax.vmap(
        lambda p: G.patch_origin(grid_cfg, p[:2]))(poses_b)
    contributing = (jnp.ones(ranges_b.shape[0], jnp.bool_)
                    if m is None else m)

    def mark(acc, om):
        o, keep = om
        rc = jnp.minimum(o // tile_cells, nt - K)
        marked = jax.lax.dynamic_update_slice(
            acc, jnp.ones((K, K), jnp.bool_), (rc[0], rc[1]))
        return jnp.where(keep, marked, acc), None

    touched, _ = jax.lax.scan(mark, jnp.zeros((nt, nt), jnp.bool_),
                              (origins, contributing))
    return out, touched
