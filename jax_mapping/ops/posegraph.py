"""Pose-graph SLAM back-end on device: fixed-shape graph, Gauss-Newton solve.

The reference gets loop closure from slam_toolbox's Karto pose graph + SPA
solver, gated by `/root/reference/server/thymio_project/config/slam_config.yaml:43-48`
(loop search 3 m, chain >= 10, response gates 0.35/0.45). That C++ graph is
unbounded and pointer-based; the TPU-native design is a *fixed-capacity* ring
of poses and edges (static shapes, SURVEY.md §7 "loop-closure corrections
mutate history"), with the linear algebra done densely on the MXU:

  * the Jacobian is materialised as one dense (3E x 3N) matrix via a single
    scatter of per-edge 3x6 blocks,
  * the normal equations H = J^T W J are one matmul,
  * the damped solve is a Cholesky factorisation,
  * invalid pose/edge slots carry zero weight, so capacity padding is free.

Map repair after a closure is not an incremental patch dance like Karto's:
the whole occupancy grid is simply re-fused from the optimised trajectory
and the stored scan ring (`ops.grid.fuse_scans`) — cheap on TPU, exact by
construction.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import LoopClosureConfig
from jax_mapping.ops.odometry import pose_between, pose_compose, wrap_angle

Array = jax.Array


class PoseGraph(NamedTuple):
    """Fixed-capacity pose graph; all shapes static."""
    poses: Array        # (N, 3) world poses
    pose_valid: Array   # (N,) bool
    n_poses: Array      # () int32 next free slot
    edge_ij: Array      # (E, 2) int32 endpoints
    edge_meas: Array    # (E, 3) relative pose of j in i's frame
    edge_weight: Array  # (E, 3) information diag [wx, wy, wth]
    edge_valid: Array   # (E,) bool
    n_edges: Array      # () int32


def empty_graph(cfg: LoopClosureConfig) -> PoseGraph:
    N, E = cfg.max_poses, cfg.max_edges
    return PoseGraph(
        poses=jnp.zeros((N, 3), jnp.float32),
        pose_valid=jnp.zeros((N,), bool),
        n_poses=jnp.int32(0),
        edge_ij=jnp.zeros((E, 2), jnp.int32),
        edge_meas=jnp.zeros((E, 3), jnp.float32),
        edge_weight=jnp.zeros((E, 3), jnp.float32),
        edge_valid=jnp.zeros((E,), bool),
        n_edges=jnp.int32(0),
    )


def add_pose(g: PoseGraph, pose: Array) -> PoseGraph:
    """Append a pose at the next slot (no-op when full)."""
    i = g.n_poses
    ok = i < g.poses.shape[0]
    poses = jnp.where(ok, g.poses.at[i].set(pose), g.poses)
    valid = g.pose_valid.at[i].set(ok | g.pose_valid[i])
    return g._replace(poses=poses, pose_valid=valid,
                      n_poses=i + ok.astype(jnp.int32))


def add_edge(g: PoseGraph, i: Array, j: Array, meas: Array,
             weight: Array) -> PoseGraph:
    """Append a relative-pose constraint (no-op when full)."""
    e = g.n_edges
    ok = e < g.edge_ij.shape[0]
    ij = jnp.stack([jnp.asarray(i, jnp.int32), jnp.asarray(j, jnp.int32)])
    return g._replace(
        edge_ij=jnp.where(ok, g.edge_ij.at[e].set(ij), g.edge_ij),
        edge_meas=jnp.where(ok, g.edge_meas.at[e].set(meas), g.edge_meas),
        edge_weight=jnp.where(ok, g.edge_weight.at[e].set(weight),
                              g.edge_weight),
        edge_valid=g.edge_valid.at[e].set(ok | g.edge_valid[e]),
        n_edges=e + ok.astype(jnp.int32),
    )


def add_pose_if(g: PoseGraph, pose: Array, enabled: Array) -> PoseGraph:
    """`add_pose` gated by a traced bool — the vmapped fleet path's
    per-robot key-scan gate (every robot computes, masked robots no-op)."""
    i = g.n_poses
    ok = enabled & (i < g.poses.shape[0])
    poses = jnp.where(ok, g.poses.at[i].set(pose), g.poses)
    valid = g.pose_valid.at[i].set(ok | g.pose_valid[i])
    return g._replace(poses=poses, pose_valid=valid,
                      n_poses=i + ok.astype(jnp.int32))


def add_edge_if(g: PoseGraph, i: Array, j: Array, meas: Array,
                weight: Array, enabled: Array) -> PoseGraph:
    """`add_edge` gated by a traced bool (see add_pose_if)."""
    e = g.n_edges
    ok = enabled & (e < g.edge_ij.shape[0])
    ij = jnp.stack([jnp.asarray(i, jnp.int32), jnp.asarray(j, jnp.int32)])
    return g._replace(
        edge_ij=jnp.where(ok, g.edge_ij.at[e].set(ij), g.edge_ij),
        edge_meas=jnp.where(ok, g.edge_meas.at[e].set(meas), g.edge_meas),
        edge_weight=jnp.where(ok, g.edge_weight.at[e].set(weight),
                              g.edge_weight),
        edge_valid=g.edge_valid.at[e].set(ok | g.edge_valid[e]),
        n_edges=e + ok.astype(jnp.int32),
    )


def odometry_edge(g: PoseGraph, i: Array, j: Array,
                  weight_t: float = 50.0, weight_th: float = 100.0) -> PoseGraph:
    """Constrain j to its current relative pose from i (dead-reckoning link)."""
    meas = pose_between(g.poses[i], g.poses[j])
    w = jnp.array([weight_t, weight_t, weight_th], jnp.float32)
    return add_edge(g, i, j, meas, w)


def anchor_tip(g: PoseGraph, pose: Array, weight_t: float = 200.0,
               weight_th: float = 400.0) -> PoseGraph:
    """External-assertion anchor on the graph tip — the rendezvous-merge
    alignment edge (scenarios/rendezvous.py): constrain the newest pose
    toward an externally VERIFIED `pose` by re-measuring the
    (tip-1 → tip) hop against it at loop-closure-grade weights (the
    models/fleet cross-robot anchor idiom, factored into a reusable
    op). `optimize` then pulls the tip onto the verified pose with the
    rest of the chain following elastically. The weights clear the
    `thin_keyframes` strong-edge threshold, so the anchor survives ring
    thinning like any loop edge. Host-orchestrated cold path (concrete
    index); no-op on graphs with < 2 poses — nothing to hang the edge
    on."""
    q = int(g.n_poses)
    if q < 2:
        return g
    meas = pose_between(g.poses[q - 2], jnp.asarray(pose, jnp.float32))
    w = jnp.array([weight_t, weight_t, weight_th], jnp.float32)
    return add_edge(g, q - 2, q - 1, meas, w)


# ---------------------------------------------------------------------------
# Keyframe thinning: unbounded trajectories in a fixed-capacity ring
# ---------------------------------------------------------------------------

def thin_keyframes(g: PoseGraph, scan_ring: Array,
                   odo_weight_t: float = 50.0, odo_weight_th: float = 100.0
                   ) -> tuple[PoseGraph, Array]:
    """Halve keyframe density: keep even-indexed poses/scans, freeing half
    the ring for new key-scans.

    slam_toolbox's Karto graph is unbounded (slam_config.yaml:43-48); a
    fixed-shape device graph cannot be, and before this op a saturated
    ring froze map repair forever (round-3 verdict weak #5). Thinning on
    saturation gives the long-run behaviour of a keyframe SLAM: spacing
    between retained keyframes doubles each time the ring fills, so an
    arbitrarily long trajectory stays repairable at logarithmically
    coarsening history resolution (consecutive key-scans overlap heavily —
    the gate fires every 0.1 m — so dropping alternate ones loses little
    map support).

    Edge handling:
      * the odometry chain (j == i+1) is REBUILT between consecutive kept
        poses, re-measured from the current (optimised) estimates — their
        information has already been absorbed into those estimates;
      * long-range (loop) edges are KEPT: endpoints remap to the even
        keyframe at-or-before them (i -> i//2 after the drop), and the
        measurement is adjusted by the currently-estimated hop between
        the original and surviving endpoint, preserving the measured
        middle: meas' = (i'⊖i) ⊕ meas ⊕ (j⊖j')^; hops are one keyframe
        (~0.1 m) so the adjustment error is the local odometry error.

    Returns (thinned graph, thinned ring). Works on full or partial
    graphs; callers invoke it when n_poses reaches capacity.
    """
    N = g.poses.shape[0]
    E = g.edge_ij.shape[0]
    n2 = (g.n_poses + 1) // 2

    idx = jnp.arange(N)
    src = jnp.minimum(2 * idx, N - 1)
    keep_slot = idx < n2
    poses2 = g.poses[src]
    valid2 = g.pose_valid[src] & keep_slot
    ring2 = scan_ring[src]

    # --- odometry chain between consecutive kept poses ----------------
    m = jnp.arange(E)
    chain_on = m < jnp.maximum(n2 - 1, 0)
    ci = jnp.minimum(m, N - 1)
    cj = jnp.minimum(m + 1, N - 1)
    chain_meas = jax.vmap(
        lambda a, b: pose_between(poses2[a], poses2[b]))(ci, cj)
    w_odo = jnp.array([odo_weight_t, odo_weight_t, odo_weight_th],
                      jnp.float32)

    edge_ij = jnp.stack([ci, cj], axis=1) * chain_on[:, None]
    edge_meas = chain_meas * chain_on[:, None]
    edge_weight = jnp.broadcast_to(w_odo, (E, 3)) * chain_on[:, None]

    # --- surviving long-range edges, remapped + adjusted ---------------
    # "Loop" = anything whose information must outlive the thin: index
    # gap > 1 (a real loop edge), OR a gap-1 edge carrying MORE than
    # odometry information — the fleet path's cross-robot anchor edges
    # ((q-1) -> q at loop weights, models/fleet._verify_and_optimize)
    # would otherwise be silently downgraded to a weak re-measured
    # odometry edge. Anchors whose endpoints collapse onto one kept
    # index still drop (nothing to constrain); the optimised poses have
    # already absorbed them.
    ij = g.edge_ij
    gap = ij[:, 1] - ij[:, 0]
    strong = g.edge_weight[:, 2] > odo_weight_th
    is_loop = g.edge_valid & ((gap > 1) | ((gap == 1) & strong))
    i_new, j_new = ij[:, 0] // 2, ij[:, 1] // 2
    i_kept, j_kept = 2 * i_new, 2 * j_new          # even at-or-before
    # meas' = (T_i'^-1 T_i) ⊕ meas ⊕ (T_j^-1 T_j')
    adj = jax.vmap(lambda ik, io, mm, jo, jk: pose_compose(
        pose_between(g.poses[ik], g.poses[io]),
        pose_compose(mm, pose_between(g.poses[jo], g.poses[jk]))))(
        i_kept, ij[:, 0], g.edge_meas, ij[:, 1], j_kept)
    adj = adj.at[:, 2].set(wrap_angle(adj[:, 2]))
    # Remapped self-edges (i//2 == j//2) carry no information — drop.
    is_loop = is_loop & (j_new > i_new)

    base = jnp.maximum(n2 - 1, 0)
    tgt = base + jnp.cumsum(is_loop) - 1
    tgt = jnp.where(is_loop, tgt, E)               # E == out of bounds
    edge_ij = edge_ij.at[tgt].set(
        jnp.stack([i_new, j_new], axis=1), mode="drop")
    edge_meas = edge_meas.at[tgt].set(adj, mode="drop")
    edge_weight = edge_weight.at[tgt].set(g.edge_weight, mode="drop")

    n_edges2 = base + is_loop.sum()
    n_edges2 = jnp.minimum(n_edges2, E)
    edge_valid2 = m < n_edges2

    g2 = PoseGraph(poses=poses2, pose_valid=valid2,
                   n_poses=n2.astype(jnp.int32),
                   edge_ij=edge_ij.astype(jnp.int32), edge_meas=edge_meas,
                   edge_weight=edge_weight, edge_valid=edge_valid2,
                   n_edges=n_edges2.astype(jnp.int32))
    return g2, ring2


# ---------------------------------------------------------------------------
# Loop-closure candidate gating (slam_config.yaml:44-45 semantics)
# ---------------------------------------------------------------------------

def loop_candidate(cfg: LoopClosureConfig, g: PoseGraph,
                   query: Array) -> tuple[Array, Array]:
    """For pose index `query`, the nearest old pose within search_radius_m
    whose index is at least min_chain_size behind AND whose chain to the
    query actually LEFT the search radius in between. Returns (index, found).

    The departure requirement is Karto's "near-linked scan" exclusion
    (slam_toolbox loop search, `slam_config.yaml:43-48`): without it the
    trailing chain of just-added poses is always the nearest "loop" and a
    robot driving along closes fake loops onto its own tail. A genuine
    loop must go away and come back.
    """
    idx = jnp.arange(g.poses.shape[0])
    d = jnp.linalg.norm(g.poses[:, :2] - g.poses[query, :2], axis=-1)
    old_enough = idx <= query - cfg.min_chain_size
    in_chain = g.pose_valid & (idx <= query)
    # departed[i] = max_{i <= j <= query} d[j] > radius: the trajectory
    # between candidate i and the query left the search disc (suffix max
    # via reversed cummax).
    dm = jnp.where(in_chain, d, -jnp.inf)
    suffix_max = jax.lax.cummax(dm[::-1])[::-1]
    departed = suffix_max > cfg.search_radius_m
    ok = g.pose_valid & old_enough & (d <= cfg.search_radius_m) & departed
    d_masked = jnp.where(ok, d, jnp.inf)
    best = jnp.argmin(d_masked)
    return best.astype(jnp.int32), ok.any()


# ---------------------------------------------------------------------------
# Gauss-Newton optimisation (dense, MXU-shaped)
# ---------------------------------------------------------------------------

def _edge_residual_jac(poses: Array, ij: Array, meas: Array):
    """Residual (3,) and two 3x3 Jacobian blocks for one edge."""
    pi, pj = poses[ij[0]], poses[ij[1]]
    ci, si = jnp.cos(pi[2]), jnp.sin(pi[2])
    Rt = jnp.array([[ci, si], [-si, ci]])             # R(th_i)^T
    dt = pj[:2] - pi[:2]
    r_t = Rt @ dt - meas[:2]
    r_th = wrap_angle(pj[2] - pi[2] - meas[2])
    r = jnp.concatenate([r_t, jnp.array([r_th])])
    dRt = jnp.array([[-si, ci], [-ci, -si]])          # d(R^T)/d th_i
    Ji = jnp.zeros((3, 3)).at[:2, :2].set(-Rt) \
        .at[:2, 2].set(dRt @ dt).at[2, 2].set(-1.0)
    Jj = jnp.zeros((3, 3)).at[:2, :2].set(Rt).at[2, 2].set(1.0)
    return r, Ji, Jj


def _assemble(g: PoseGraph):
    """All residuals/Jacobians -> dense J (3E, 3N), r (3E,), w (3E,)."""
    E = g.edge_ij.shape[0]
    N = g.poses.shape[0]
    r, Ji, Jj = jax.vmap(
        lambda ij, m: _edge_residual_jac(g.poses, ij, m)
    )(g.edge_ij, g.edge_meas)                          # (E,3), (E,3,3) x2

    w = (g.edge_weight * g.edge_valid[:, None]).reshape(-1)  # (3E,)
    r = (r * g.edge_valid[:, None]).reshape(-1)              # (3E,)

    rows = (3 * jnp.arange(E)[:, None, None]
            + jnp.arange(3)[None, :, None])                  # (E,3,1)
    rows = jnp.broadcast_to(rows, (E, 3, 3))
    cols_i = (3 * g.edge_ij[:, 0, None, None]
              + jnp.arange(3)[None, None, :])
    cols_i = jnp.broadcast_to(cols_i, (E, 3, 3))
    cols_j = (3 * g.edge_ij[:, 1, None, None]
              + jnp.arange(3)[None, None, :])
    cols_j = jnp.broadcast_to(cols_j, (E, 3, 3))

    J = jnp.zeros((3 * E, 3 * N), jnp.float32)
    J = J.at[rows.reshape(-1), cols_i.reshape(-1)].add(Ji.reshape(-1))
    J = J.at[rows.reshape(-1), cols_j.reshape(-1)].add(Jj.reshape(-1))
    return J, r, w


@functools.partial(jax.jit, static_argnums=(0,))
def optimize(cfg: LoopClosureConfig, g: PoseGraph) -> PoseGraph:
    """Damped Gauss-Newton over the whole graph; pose 0 is gauge-fixed by a
    strong prior. Fixed iteration count keeps everything jit-compatible."""
    N = g.poses.shape[0]

    def gn_iter(graph: PoseGraph, _):
        J, r, w = _assemble(graph)
        H = J.T @ (w[:, None] * J)                    # (3N, 3N) — MXU
        b = J.T @ (w * r)
        # Gauge prior on pose 0 + Levenberg damping.
        gauge = jnp.concatenate([jnp.full(3, 1e6), jnp.zeros(3 * N - 3)])
        H = H + jnp.diag(gauge) + cfg.damping * jnp.eye(3 * N, dtype=H.dtype)
        delta = jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(H), -b).reshape(N, 3)
        delta = delta * graph.pose_valid[:, None]
        poses = graph.poses + delta
        poses = poses.at[:, 2].set(wrap_angle(poses[:, 2]))
        return graph._replace(poses=poses), None

    out, _ = jax.lax.scan(gn_iter, g, None, length=cfg.gn_iters)
    return out


def graph_error(g: PoseGraph) -> Array:
    """Total weighted squared residual (for tests/telemetry)."""
    r, _, _ = jax.vmap(
        lambda ij, m: _edge_residual_jac(g.poses, ij, m)
    )(g.edge_ij, g.edge_meas)
    w = g.edge_weight * g.edge_valid[:, None]
    return jnp.sum(w * r * r)
