"""Pallas TPU kernel: batched dense inverse sensor model over a shared patch.

This is the hot op of the whole framework — the capability slam_toolbox's
C++ rasterizer provides (`/root/reference/server/thymio_project/config/
slam_config.yaml:26-27`), rebuilt as a TPU kernel. The XLA formulation in
`ops/grid.py` evaluates the same model but pays for a per-cell gather
``ranges[beam]`` (measured ~10x the cost of all the geometry math combined:
XLA lowers the small-table gather to a scalarised loop). Here the lookup is
an in-VMEM one-hot contraction on the MXU, so the (cells x beams) one-hot
never touches HBM:

    grid = (patch_tiles, B_scans)            # scan axis innermost
    per step: geometry for a (TILE_R x P) strip of scan b's patch (VPU),
              z/carve/hit lookup = onehot(beam) @ table[b]  (MXU, VMEM),
              delta accumulated INTO the output tile across all B scans.

The output tile is revisited across the innermost scan axis, so the
accumulator stays resident in VMEM and each patch tile is written to HBM
exactly once per batch — total HBM traffic per batch is one (P, P) float32
patch plus the (B, BEAMS) tables, independent of B's contribution to
compute. Scans in a batch share one patch origin (a temporal scan window
from one robot: the reference's LD06 delivers ~10 scans/sec while the robot
moves ~1 cm/scan, `server/.../main.py:60`), which also replaces the
sequential per-scan fold of the general path with a single aligned
read-modify-write.

Semantics match `ops/grid.classify_patch` (same sanitize rules: zero range
-> invalid 10 m carve, `server/.../main.py:152`; padded beams inert; CCW
beam convention `pi_hardware.launch.py:20`) — tests hold the two to a
NumPy oracle.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax_mapping.config import GridConfig, ScanConfig
from jax_mapping.ops import trig

Array = jax.Array

# Rows of the patch strip each grid step computes. Mosaic requires the
# output block's sublane dim to be a multiple of 8. The one-hot
# intermediate is (TILE_R * P, BEAMS) bfloat16 in VMEM: 8 * 640 * 512 * 2B
# ~= 5.2 MB for the full-size config — inside the ~16 MB VMEM budget with
# the output tile and table alongside.
TILE_R = 8
_TABLE_COLS = 8          # [carve, z, hit, 0...] padded to a lane-friendly 8


def _bf16x3(x: Array):
    """Exact f32 -> (hi, mid, lo) bf16 triple: hi + mid + lo == x.

    The MXU multiplies f32 operands by truncating them to bf16 at default
    precision (measured: max err = bf16 ulp), which perturbs table VALUES
    coming out of the one-hot contraction and flips hit-band comparisons.
    Splitting each value into three bf16 components (8 significand bits
    each, 24 total = f32) keeps the contraction single-pass per column
    while the f32 accumulator reconstructs the exact value — the one-hot
    side is 0/1, exact in bf16, so one pass per component is all needed.

    The split masks mantissa bits instead of round-tripping f32->bf16->f32:
    XLA's excess-precision pass elides the convert pair on TPU (measured:
    residuals collapse to zero and the table degrades to single-bf16), and
    a bitmask is not a convert so it survives. Truncation toward zero makes
    each component's sub-word exact, so hi + mid + lo == x bit-for-bit.
    """
    def trunc(v):
        bits = jax.lax.bitcast_convert_type(v, jnp.uint32)
        part = jax.lax.bitcast_convert_type(
            bits & jnp.uint32(0xFFFF0000), jnp.float32)
        # part's low mantissa bits are zero -> bf16 conversion is exact.
        return part.astype(jnp.bfloat16), v - part
    hi, r1 = trunc(x)
    mid, r2 = trunc(r1)
    lo, _ = trunc(r2)
    return hi, mid, lo


def _beam_table(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                ranges_b: Array) -> Array:
    """(B, BEAMS) raw ranges -> (B, BEAMS, 8) bf16 lookup table.

    Columns: 0-2 = carve distance (free-space limit) bf16x3, 3-5 = hit
    range z bf16x3, 6 = hit flag. Sanitize semantics identical to
    grid.sanitize_ranges.
    """
    from jax_mapping.ops.grid import sanitize_ranges
    r_m, hit = jax.vmap(lambda r: sanitize_ranges(scan_cfg, r))(ranges_b)
    carve = jnp.minimum(jnp.where(r_m > 0.0, r_m, 0.0),
                        jnp.float32(grid_cfg.max_range_m))
    cols = [*_bf16x3(carve), *_bf16x3(r_m), hit.astype(jnp.bfloat16)]
    zeros = jnp.zeros_like(carve, dtype=jnp.bfloat16)
    table = jnp.stack(cols + [zeros] * (_TABLE_COLS - len(cols)), axis=-1)
    return table


def _make_kernel(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 accumulate: bool = True, mode: str = "delta"):
    """mode='delta': log-odds inverse sensor model. mode='raster': soft
    scan raster — per cell a triangular weight max(0, 1-|r_cell - z|/res)
    on the hit band (no free-space carving), the correlative matcher's
    continuous-pose rasterizer (ops/scan_match.py)."""
    P = grid_cfg.patch_cells
    beams = scan_cfg.padded_beams
    res = grid_cfg.resolution_m
    ox, oy = grid_cfg.origin_m
    inc = scan_cfg.angle_increment_rad
    n_beams = scan_cfg.n_beams
    two_pi = 2.0 * math.pi
    full_circle = abs(n_beams * inc - two_pi) < inc / 2
    tol = grid_cfg.hit_tolerance_cells * res
    ccw = scan_cfg.counterclockwise

    def kernel(table_ref, pose_ref, origin_ref, out_ref):
        # pose/origin ride whole-array in SMEM (Mosaic rejects sub-row
        # blocks over a (B, 3) array: block last-two dims must tile to
        # (8, 128) or equal the array's); the kernel picks its scan's row
        # with the grid index instead of a BlockSpec.
        b = pl.program_id(1)
        t = pl.program_id(0)

        px = pose_ref[b, 0]
        py = pose_ref[b, 1]
        yaw = pose_ref[b, 2]
        row0 = origin_ref[b, 0]
        col0 = origin_ref[b, 1]

        # Cell-centre world coords for this (TILE_R, P) strip.
        # Mosaic only lowers integer iota; cast after.
        rr = jax.lax.broadcasted_iota(jnp.int32, (TILE_R, P), 0).astype(
            jnp.float32)
        cc = jax.lax.broadcasted_iota(jnp.int32, (TILE_R, P), 1).astype(
            jnp.float32)
        gr = (row0 + t * TILE_R).astype(jnp.float32) + rr
        gc = col0.astype(jnp.float32) + cc
        y = (gr + 0.5) * res + oy
        x = (gc + 0.5) * res + ox
        dx = x - px
        dy = y - py
        r_cell = jnp.sqrt(dx * dx + dy * dy)

        theta = trig.atan2(dy, dx) - yaw
        if not ccw:
            theta = -theta
        theta = theta - scan_cfg.angle_min_rad
        theta = theta - two_pi * jnp.floor(theta / two_pi)   # wrap [0, 2pi)
        beam_raw = jnp.round(theta / inc).astype(jnp.int32)
        beam = jax.lax.rem(beam_raw, n_beams)
        in_fov = (jnp.ones_like(r_cell, dtype=jnp.bool_) if full_circle
                  else beam_raw <= n_beams - 1)

        # z / carve / hit lookup as an MXU contraction; the one-hot only
        # ever exists in VMEM. bf16 operands, f32 accumulate: the one-hot
        # is exact in bf16 and the table columns are bf16x3 components, so
        # the reconstructed values are exact f32 (see _bf16x3).
        bi = jax.lax.broadcasted_iota(jnp.int32, (TILE_R, P, beams), 2)
        oh = (beam[:, :, None] == bi).astype(jnp.bfloat16)
        looked = jax.lax.dot_general(
            oh.reshape(TILE_R * P, beams), table_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).reshape(TILE_R, P, _TABLE_COLS)
        carve = looked[:, :, 0] + looked[:, :, 1] + looked[:, :, 2]
        z = looked[:, :, 3] + looked[:, :, 4] + looked[:, :, 5]
        beam_hit = (looked[:, :, 6] > 0.5) & in_fov

        if mode == "delta":
            free = ((r_cell < carve - tol)
                    & (r_cell > scan_cfg.range_min_m) & in_fov)
            occ = (beam_hit & (jnp.abs(r_cell - z) <= tol)
                   & (r_cell <= grid_cfg.max_range_m))
            delta = jnp.where(occ, grid_cfg.logodds_occ,
                              jnp.where(free, grid_cfg.logodds_free, 0.0))
        else:
            w = jnp.maximum(0.0, 1.0 - jnp.abs(r_cell - z) / res)
            keep = beam_hit & (r_cell <= grid_cfg.max_range_m)
            delta = jnp.where(keep, w, 0.0)
        delta = delta.astype(jnp.float32)

        if accumulate:
            @pl.when(b == 0)
            def _():
                out_ref[:] = delta

            @pl.when(b != 0)
            def _():
                out_ref[:] = out_ref[:] + delta
        else:
            out_ref[0] = delta

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 1))
def window_delta(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 ranges_b: Array, poses_b: Array, origin_rc: Array) -> Array:
    """Sum of all B scans' log-odds deltas on one shared (P, P) patch.

    Args:
      ranges_b: (B, padded_beams) raw ranges (0 = outlier).
      poses_b:  (B, 3) world [x, y, yaw].
      origin_rc: (2,) int32 patch origin [row0, col0] (aligned; see
        grid.patch_origin). Every pose must lie within
        patch/2 - max_range_cells of the patch centre (`window_fits`).
    """
    P = grid_cfg.patch_cells
    if P % TILE_R:
        raise ValueError(f"patch_cells={P} not divisible by TILE_R={TILE_R}")
    B = ranges_b.shape[0]
    if B == 0:
        # A grid of size 0 would never run the b==0 init step and return
        # the output buffer uninitialised; an empty window adds nothing.
        return jnp.zeros((P, P), jnp.float32)
    table = _beam_table(grid_cfg, scan_cfg, ranges_b)
    origin = jnp.broadcast_to(
        origin_rc.astype(jnp.int32).reshape(1, 2), (B, 2))
    kernel = _make_kernel(grid_cfg, scan_cfg)
    interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        kernel,
        grid=(P // TILE_R, B),
        in_specs=[
            pl.BlockSpec((1, scan_cfg.padded_beams, _TABLE_COLS),
                         lambda t, b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((TILE_R, P), lambda t, b: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((P, P), jnp.float32),
        interpret=interpret,
    )(table, poses_b.astype(jnp.float32), origin)


@functools.partial(jax.jit, static_argnums=(0, 1))
def scan_deltas(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                ranges_b: Array, poses_b: Array, origins_rc: Array) -> Array:
    """Per-scan (B, P, P) log-odds deltas, one patch origin per scan.

    The general-pose counterpart of `window_delta` (same kernel body, no
    cross-scan accumulation): feeds the sequential exact fold in
    `grid.fuse_scans` when poses are scattered. On TPU this replaces the
    XLA classify path whose per-cell `ranges[beam]` gather dominates its
    runtime.
    """
    return _per_scan_call(grid_cfg, scan_cfg, ranges_b, poses_b, origins_rc,
                          mode="delta")


@functools.partial(jax.jit, static_argnums=(0, 1))
def scan_rasters(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 ranges_b: Array, poses_b: Array, origins_rc: Array) -> Array:
    """Soft (B, P, P) scan rasters at continuous candidate poses.

    The correlative matcher's rasterizer: candidate rotations/sub-cell
    translations of one scan are just different `poses_b` rows — the dense
    per-cell evaluation shifts the hit band continuously, which is what
    gives the matcher sub-cell sensitivity without any gather.
    """
    return _per_scan_call(grid_cfg, scan_cfg, ranges_b, poses_b, origins_rc,
                          mode="raster")


@functools.partial(jax.jit, static_argnums=(0, 1, 5))
def _per_scan_call(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                   ranges_b: Array, poses_b: Array, origins_rc: Array,
                   mode: str) -> Array:
    P = grid_cfg.patch_cells
    if P % TILE_R:
        raise ValueError(f"patch_cells={P} not divisible by TILE_R={TILE_R}")
    B = ranges_b.shape[0]
    if B == 0:
        return jnp.zeros((0, P, P), jnp.float32)
    table = _beam_table(grid_cfg, scan_cfg, ranges_b)
    origins = origins_rc.astype(jnp.int32).reshape(B, 2)
    kernel = _make_kernel(grid_cfg, scan_cfg, accumulate=False, mode=mode)
    interpret = jax.default_backend() != "tpu"
    return pl.pallas_call(
        kernel,
        grid=(P // TILE_R, B),
        in_specs=[
            pl.BlockSpec((1, scan_cfg.padded_beams, _TABLE_COLS),
                         lambda t, b: (b, 0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE_R, P), lambda t, b: (b, t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, P, P), jnp.float32),
        interpret=interpret,
    )(table, poses_b.astype(jnp.float32), origins)


def window_fits(grid_cfg: GridConfig, poses_b: Array,
                origin_rc: Array) -> Array:
    """Scalar bool: does every pose's max-range disc fit in the patch?

    The window kernel silently drops updates outside the shared patch; a
    caller batching scans from a fast-moving robot should check (or chunk
    by) this — or use `grid.fuse_scans_window_checked`, which falls back
    to the exact per-scan fold on device. Slack for the default config:
    (640/2 - 240) * 0.05 = 4 m from the patch CENTRE, but patch-origin
    alignment (grid.patch_origin) can offset the centre by up to
    align_cols/2 cells, leaving a worst-case guaranteed slack of
    (640/2 - 128/2 - 240) * 0.05 = 0.8 m around the mean pose.
    """
    P = grid_cfg.patch_cells
    margin = grid_cfg.max_range_cells
    cr = (poses_b[:, :2] - jnp.array(grid_cfg.origin_m)) / grid_cfg.resolution_m
    col = cr[:, 0]
    row = cr[:, 1]
    r0 = origin_rc[0].astype(jnp.float32)
    c0 = origin_rc[1].astype(jnp.float32)
    ok = ((row - margin >= r0) & (row + margin <= r0 + P)
          & (col - margin >= c0) & (col + margin <= c0 + P))
    return ok.all()
