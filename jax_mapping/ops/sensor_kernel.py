"""Pallas TPU kernel: batched dense inverse sensor model over a shared patch.

This is the hot op of the whole framework — the capability slam_toolbox's
C++ rasterizer provides (`/root/reference/server/thymio_project/config/
slam_config.yaml:26-27`), rebuilt as a TPU kernel. The XLA formulation in
`ops/grid.py` evaluates the same model but pays for a per-cell gather
``ranges[beam]`` that XLA lowers to a scalarised loop (~10x the cost of the
rest of the model). Here the lookup is a *vector-register gather*:

    Mosaic lowers `take_along_axis` along lanes when the gather stays
    inside one 128-lane vreg. The 512-beam table is packed as 4 chunks of
    128 lanes; each cell's lookup is 4 in-vreg gathers + selects on the
    chunk id — ~10 VPU ops/cell, no MXU, no HBM traffic for the table.

The patch strip a grid step computes is laid out as (S, 128) sublane-rows
of the flattened patch (the natural vreg shape), not (rows, P): the gather
wants 128-lane tiles, and the flat layout makes every step's block dense.
The output array is (P*P/128, 128), reshaped to (P, P) by XLA outside the
kernel.

Two exact compute culls keep the work proportional to what a scan can see:
  * strip cull — a strip entirely farther from the pose than max_range
    produces delta == 0 everywhere, so the whole body is skipped
    (`pl.when`); for a centred pose this skips ~25% of (strip, scan) steps.
  * the window accumulator is initialised once per tile (b == 0) and only
    touched by scans that pass the cull.

Performance (v5e single chip, 256-scan window into the 640^2 patch of the
4096^2 grid): ~5.9 ms/window = ~43,700 scans/sec (BENCH_LOCAL_r03.json) —
~60x the one-hot-matmul formulation this replaced (the one-hot burned VPU
on (cells x beams) compares and starved the MXU at 8 of 128 output
lanes).

Scans in a batch share one patch origin in `window_delta` (a temporal scan
window from one robot: the reference's LD06 delivers ~10 scans/sec while
the robot moves ~1 cm/scan, `server/.../main.py:60`), which replaces the
sequential per-scan fold of the general path with a single aligned
read-modify-write of the grid.

Semantics match `ops/grid.classify_patch` exactly (same sanitize rules:
zero range -> invalid 10 m carve, `server/.../main.py:152`; padded beams
inert; CCW beam convention `pi_hardware.launch.py:20`; the shared
`trig.atan2` keeps beam assignment bit-identical across engines) — tests
hold both to a NumPy oracle, and the TPU parity test runs on hardware.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax_mapping.config import GridConfig, ScanConfig
from jax_mapping.ops import trig

Array = jax.Array

LANES = 128          # TPU vreg lane count; the in-vreg gather width
_TARGET_S = 80       # preferred sublane-rows per grid step (16 patch rows)

# Max scans per pallas_call: Mosaic's scoped SMEM allocation grows with the
# grid's total step count (~12.8 B/step at the full-size config) and the
# 1 MB SMEM budget over-runs somewhere between B=512 and B=1024 (measured
# on v5e; grid = (40, B) at the 640-patch config). Larger batches are
# split across calls: per-scan outputs concatenate (bitwise identical);
# window_delta adds chunk subtotals, which reassociates the cross-scan
# float sum (last-ulp differences vs one sequential accumulation).
_MAX_B_PER_CALL = 512


def _step_rows(grid_cfg: GridConfig) -> int:
    """Sublane-rows of the flattened patch one grid step computes.

    Largest multiple of 8 that divides P*P/LANES and is <= _TARGET_S
    (measured fastest at 80 for the full-size config; 40 and 160 are
    within ~20%).
    """
    P = grid_cfg.patch_cells
    rows_tot = P * P // LANES
    s = min(_TARGET_S, rows_tot)
    # s*LANES % P == 0: the strip cull's band math assumes each step
    # covers whole patch rows; a fractional-row step would drift the
    # band and silently cull in-range cells.
    while s > 8 and (rows_tot % s or s % 8 or (s * LANES) % P):
        s -= 8
    if rows_tot % s or (s * LANES) % P:
        raise ValueError(
            f"patch_cells={P} incompatible with LANES={LANES} stepping")
    return s


def _check_shapes(grid_cfg: GridConfig, scan_cfg: ScanConfig) -> None:
    if grid_cfg.patch_cells % LANES:
        raise ValueError(
            f"patch_cells={grid_cfg.patch_cells} must be a multiple of "
            f"{LANES} (vreg lane count)")
    if scan_cfg.padded_beams % LANES:
        raise ValueError(
            f"padded_beams={scan_cfg.padded_beams} must be a multiple of "
            f"{LANES} (table chunk width)")


def _beam_table(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                ranges_b: Array) -> Array:
    """(B, BEAMS) raw ranges -> (B, NCHUNK, 128) f32 packed table.

    ONE signed value per beam: enc = z (the hit range) for hits, -carve
    (negated free-space limit) for misses. Sanitized hit ranges are
    >= range_min > 0, so the sign is the hit flag, and a hit beam's carve
    is derivable as min(z, max_range) — exactly the value the two-row
    table used to store — so halving the table costs nothing: the kernel
    recovers (carve, z, hit) from one in-vreg lookup instead of two
    (the lookup was ~20% of the per-cell op budget). Sanitize semantics
    identical to grid.sanitize_ranges.
    """
    from jax_mapping.ops.grid import sanitize_ranges
    nchunk = scan_cfg.padded_beams // LANES
    B = ranges_b.shape[0]
    r_m, hit = jax.vmap(lambda r: sanitize_ranges(scan_cfg, r))(ranges_b)
    carve = jnp.minimum(jnp.where(r_m > 0.0, r_m, 0.0),
                        jnp.float32(grid_cfg.max_range_m))
    enc = jnp.where(hit, r_m, -carve)
    return enc.reshape(B, nchunk, LANES).astype(jnp.float32)


def _make_kernel(grid_cfg: GridConfig, scan_cfg: ScanConfig, step_rows: int,
                 accumulate: bool = True, mode: str = "delta",
                 fused_apply: bool = False):
    """mode='delta': log-odds inverse sensor model. mode='raster': soft
    scan raster — per cell a triangular weight max(0, 1-|r_cell - z|/res)
    on the hit band (no free-space carving), the correlative matcher's
    continuous-pose rasterizer (ops/scan_match.py).

    fused_apply (requires accumulate): the ISSUE 11 fused-fusion finale —
    the kernel takes the CURRENT grid patch as an extra input (same
    (S, LANES) strip blocking as the output) and, on the batch's last
    scan, folds the accumulated window delta into it with the log-odds
    clamp: out = clip(patch + sum_b delta_b). The strip never makes a
    second HBM round-trip through a separate apply dispatch, and the
    single `patch + acc` addition is bit-identical to the classic
    `apply_patch(grid, window_delta(...))` composition."""
    P = grid_cfg.patch_cells
    nchunk = scan_cfg.padded_beams // LANES
    res = grid_cfg.resolution_m
    ox, oy = grid_cfg.origin_m
    inc = scan_cfg.angle_increment_rad
    n_beams = scan_cfg.n_beams
    two_pi = 2.0 * math.pi
    full_circle = abs(n_beams * inc - two_pi) < inc / 2
    tol = grid_cfg.hit_tolerance_cells * res
    ccw = scan_cfg.counterclockwise
    S = step_rows
    patch_rows_per_step = S * LANES // P
    if fused_apply and not accumulate:
        raise ValueError("fused_apply needs the accumulating kernel form")

    def kernel(table_ref, pose_ref, origin_ref, *refs):
        patch_ref = refs[0] if fused_apply else None
        out_ref = refs[-1]
        # pose/origin ride whole-array in SMEM; the kernel picks its
        # scan's row with the grid index instead of a BlockSpec (Mosaic
        # rejects sub-row blocks over a (B, 3) array).
        t = pl.program_id(0)
        b = pl.program_id(1)

        px = pose_ref[b, 0]
        py = pose_ref[b, 1]
        yaw = pose_ref[b, 2]
        row0 = origin_ref[b, 0]
        col0 = origin_ref[b, 1]

        # Strip-level range cull: if every patch row of this step's band
        # is farther from the pose than max_range, every cell's delta is
        # 0 and the whole body can be skipped. Exact, not approximate:
        # free needs r_cell < carve - tol <= max_range and occ needs
        # r_cell <= max_range, and the vertical row gap lower-bounds
        # r_cell. One extra cell of slack for the half-cell centre offset.
        pose_row = (py - oy) / res - 0.5 - row0.astype(jnp.float32)
        top = (t * patch_rows_per_step).astype(jnp.float32)
        bot = top + (patch_rows_per_step - 1)
        gap = jnp.maximum(jnp.maximum(top - pose_row, pose_row - bot), 0.0)
        near = gap * res <= grid_cfg.max_range_m + res

        if accumulate:
            @pl.when(b == 0)
            def _():
                out_ref[:] = jnp.zeros_like(out_ref)

        def body():
            # Cell-centre world coords for this (S, LANES) strip of the
            # flattened patch. Mosaic only lowers integer iota; cast after.
            ss = jax.lax.broadcasted_iota(jnp.int32, (S, LANES), 0)
            ll = jax.lax.broadcasted_iota(jnp.int32, (S, LANES), 1)
            flat = (t * S + ss) * LANES + ll
            r_i = flat // P
            c_i = flat - r_i * P
            y = ((row0 + r_i).astype(jnp.float32) + 0.5) * res + oy
            x = ((col0 + c_i).astype(jnp.float32) + 0.5) * res + ox
            dx = x - px
            dy = y - py
            r_cell = jnp.sqrt(dx * dx + dy * dy)

            theta = trig.atan2(dy, dx) - yaw
            if not ccw:
                theta = -theta
            theta = theta - scan_cfg.angle_min_rad
            theta = theta - two_pi * jnp.floor(theta / two_pi)  # [0, 2pi)
            beam_raw = jnp.round(theta / inc).astype(jnp.int32)
            beam = jax.lax.rem(beam_raw, n_beams)
            in_fov = (jnp.ones_like(r_cell, dtype=jnp.bool_) if full_circle
                      else beam_raw <= n_beams - 1)
            lo = beam & (LANES - 1)
            hi = beam // LANES     # same lowering as a shift for 2^n LANES

            # 4 in-vreg gathers + chunk-id selects = table[beam]; one
            # signed lookup carries (carve, z, hit) — see _beam_table.
            enc = jnp.zeros((S, LANES), jnp.float32)
            for c in range(nchunk):
                row = jnp.broadcast_to(
                    table_ref[0, c].reshape(1, LANES), (S, LANES))
                got = jnp.take_along_axis(row, lo, axis=1)
                enc = got if nchunk == 1 else jnp.where(hi == c, got, enc)

            z = enc
            carve = jnp.where(enc > 0.0,
                              jnp.minimum(enc,
                                          jnp.float32(grid_cfg.max_range_m)),
                              -enc)
            beam_hit = (enc > 0.0) & in_fov

            if mode == "delta":
                free = ((r_cell < carve - tol)
                        & (r_cell > scan_cfg.range_min_m) & in_fov)
                occ = (beam_hit & (jnp.abs(r_cell - z) <= tol)
                       & (r_cell <= grid_cfg.max_range_m))
                delta = jnp.where(occ, grid_cfg.logodds_occ,
                                  jnp.where(free, grid_cfg.logodds_free, 0.0))
            else:
                w = jnp.maximum(0.0, 1.0 - jnp.abs(r_cell - z) / res)
                keep = beam_hit & (r_cell <= grid_cfg.max_range_m)
                delta = jnp.where(keep, w, 0.0)
            return delta.astype(jnp.float32)

        if accumulate:
            @pl.when(near)
            def _():
                out_ref[:] = out_ref[:] + body()

            if fused_apply:
                # Last scan of the batch: fold the accumulated window
                # delta into the resident grid strip, clamped — the
                # whens trace in program order, so the final scan's own
                # delta (the `near` block above) lands first.
                @pl.when(b == pl.num_programs(1) - 1)
                def _():
                    out_ref[:] = jnp.clip(
                        patch_ref[:] + out_ref[:],
                        grid_cfg.logodds_min, grid_cfg.logodds_max)
        else:
            @pl.when(near)
            def _():
                out_ref[0] = body()

            @pl.when(jnp.logical_not(near))
            def _():
                out_ref[0] = jnp.zeros_like(out_ref[0])

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 1))
def window_delta(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 ranges_b: Array, poses_b: Array, origin_rc: Array) -> Array:
    """Sum of all B scans' log-odds deltas on one shared (P, P) patch.

    Args:
      ranges_b: (B, padded_beams) raw ranges (0 = outlier).
      poses_b:  (B, 3) world [x, y, yaw].
      origin_rc: (2,) int32 patch origin [row0, col0] (aligned; see
        grid.patch_origin). Every pose must lie within
        patch/2 - max_range_cells of the patch centre (`window_fits`).
    """
    _check_shapes(grid_cfg, scan_cfg)
    P = grid_cfg.patch_cells
    S = _step_rows(grid_cfg)
    B = ranges_b.shape[0]
    if B == 0:
        # A grid of size 0 would never run the b==0 init step and return
        # the output buffer uninitialised; an empty window adds nothing.
        return jnp.zeros((P, P), jnp.float32)
    if B > _MAX_B_PER_CALL:
        total = jnp.zeros((P, P), jnp.float32)
        for i in range(0, B, _MAX_B_PER_CALL):
            total = total + window_delta(
                grid_cfg, scan_cfg, ranges_b[i:i + _MAX_B_PER_CALL],
                poses_b[i:i + _MAX_B_PER_CALL], origin_rc)
        return total
    nchunk = scan_cfg.padded_beams // LANES
    table = _beam_table(grid_cfg, scan_cfg, ranges_b)
    origin = jnp.broadcast_to(
        origin_rc.astype(jnp.int32).reshape(1, 2), (B, 2))
    kernel = _make_kernel(grid_cfg, scan_cfg, S)
    rows_tot = P * P // LANES
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(rows_tot // S, B),
        in_specs=[
            pl.BlockSpec((1, nchunk, LANES), lambda t, b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((S, LANES), lambda t, b: (t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows_tot, LANES), jnp.float32),
        interpret=interpret,
    )(table, poses_b.astype(jnp.float32), origin)
    return out.reshape(P, P)


@functools.partial(jax.jit, static_argnums=(0, 1))
def scan_deltas(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                ranges_b: Array, poses_b: Array, origins_rc: Array) -> Array:
    """Per-scan (B, P, P) log-odds deltas, one patch origin per scan.

    The general-pose counterpart of `window_delta` (same kernel body, no
    cross-scan accumulation): feeds the sequential exact fold in
    `grid.fuse_scans` when poses are scattered.
    """
    return _per_scan_call(grid_cfg, scan_cfg, ranges_b, poses_b, origins_rc,
                          mode="delta")


@functools.partial(jax.jit, static_argnums=(0, 1))
def scan_rasters(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                 ranges_b: Array, poses_b: Array, origins_rc: Array) -> Array:
    """Soft (B, P, P) scan rasters at continuous candidate poses.

    The correlative matcher's rasterizer: candidate rotations/sub-cell
    translations of one scan are just different `poses_b` rows — the dense
    per-cell evaluation shifts the hit band continuously, which is what
    gives the matcher sub-cell sensitivity without any gather.
    """
    return _per_scan_call(grid_cfg, scan_cfg, ranges_b, poses_b, origins_rc,
                          mode="raster")


@functools.partial(jax.jit, static_argnums=(0, 1, 5))
def _per_scan_call(grid_cfg: GridConfig, scan_cfg: ScanConfig,
                   ranges_b: Array, poses_b: Array, origins_rc: Array,
                   mode: str) -> Array:
    _check_shapes(grid_cfg, scan_cfg)
    P = grid_cfg.patch_cells
    S = _step_rows(grid_cfg)
    B = ranges_b.shape[0]
    if B == 0:
        return jnp.zeros((0, P, P), jnp.float32)
    if B > _MAX_B_PER_CALL:
        return jnp.concatenate([
            _per_scan_call(grid_cfg, scan_cfg,
                           ranges_b[i:i + _MAX_B_PER_CALL],
                           poses_b[i:i + _MAX_B_PER_CALL],
                           origins_rc[i:i + _MAX_B_PER_CALL], mode)
            for i in range(0, B, _MAX_B_PER_CALL)], axis=0)
    nchunk = scan_cfg.padded_beams // LANES
    table = _beam_table(grid_cfg, scan_cfg, ranges_b)
    origins = origins_rc.astype(jnp.int32).reshape(B, 2)
    kernel = _make_kernel(grid_cfg, scan_cfg, S, accumulate=False, mode=mode)
    rows_tot = P * P // LANES
    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(rows_tot // S, B),
        in_specs=[
            pl.BlockSpec((1, nchunk, LANES), lambda t, b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, S, LANES), lambda t, b: (b, t, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, rows_tot, LANES), jnp.float32),
        interpret=interpret,
    )(table, poses_b.astype(jnp.float32), origins)
    return out.reshape(B, P, P)


def window_fits(grid_cfg: GridConfig, poses_b: Array,
                origin_rc: Array) -> Array:
    """Scalar bool: does every pose's max-range disc fit in the patch?

    The window kernel silently drops updates outside the shared patch; a
    caller batching scans from a fast-moving robot should check (or chunk
    by) this — or use `grid.fuse_scans_window_checked`, which falls back
    to the exact per-scan fold on device. Slack for the default config:
    (640/2 - 240) * 0.05 = 4 m from the patch CENTRE, but patch-origin
    alignment (grid.patch_origin) can offset the centre by up to
    align_cols/2 cells, leaving a worst-case guaranteed slack of
    (640/2 - 128/2 - 240) * 0.05 = 0.8 m around the mean pose.
    """
    P = grid_cfg.patch_cells
    margin = grid_cfg.max_range_cells
    cr = (poses_b[:, :2] - jnp.array(grid_cfg.origin_m)) / grid_cfg.resolution_m
    col = cr[:, 0]
    row = cr[:, 1]
    r0 = origin_rc[0].astype(jnp.float32)
    c0 = origin_rc[1].astype(jnp.float32)
    ok = ((row - margin >= r0) & (row + margin <= r0 + P)
          & (col - margin >= c0) & (col + margin <= c0 + P))
    return ok.all()
