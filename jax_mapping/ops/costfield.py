"""Batched obstacle-aware cost-to-go fields: multigrid min-plus on TPU.

The frontier auction needs geodesic-ish travel costs from every robot to
every frontier cluster (`ops/frontier.py`). The round-2 formulation ran a
full-diameter min-plus dilation per robot at the clustering resolution —
`bfs_iters` x 2 sweeps x 8 XLA shift ops over the whole grid, 173 ms at 64
robots (VERDICT r2). Two structural fixes:

  * **Multigrid**: solve the field at the coarsest level (where the map
    diameter is only ~n/4 cells, so full convergence is cheap), then
    upsample as an upper-bound initialiser and run a few refinement sweeps
    per finer level. Min-plus relaxation converges monotonically downward,
    so the initialiser must never underestimate: coarse passability pools
    conservatively (any blocked child blocks the parent), which makes every
    coarse path a valid fine path, and the upsample adds a +2c slack for
    discretisation. Costs remain upper bounds at every iteration count —
    a robot never underpays for a far cluster, which is the safe direction
    for assignment. Narrow corridors (< 2 coarse cells wide) stay
    overestimated unless the refinement budget reaches them; the exact
    single-level path (`frontier.cost_to_go`) remains for callers that
    need it.
  * **Pallas relaxation kernel**: the fields for a chunk of robots live in
    VMEM across ALL iterations of a level — HBM sees one read of the
    blocked mask and one write of the finished fields, instead of 16
    materialised full-grid arrays per sweep. Off-TPU the same kernel runs
    in interpret mode (tests), and `JAX_MAPPING_NO_PALLAS=1` selects a
    pure-XLA twin (`_relax_level_xla`, parity-tested).

Units: distances are in cells of the level the call runs at; the caller
scales to physical units. Blocked cells and unreachable cells hold _BIG.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jax.Array

# Python floats (not jnp scalars): the Pallas kernel body closes over
# these, and traced-array constants cannot be captured by a kernel.
_BIG = 1e9
_SQ2 = 1.41421356

# VMEM budget for one chunk of per-robot fields (bytes). Mosaic stack-
# allocates the relaxation body's shift temporaries alongside the block:
# the measured scoped peak is ~17x the field block (a 1 MB block hit
# 17.42 M scoped vs the 16 M VMEM limit on v5e), so the block must stay
# near 512 KB for the whole allocation to fit with margin.
_FIELD_VMEM_BYTES = 512 * 1024


def _chunk_robots(n: int, n_robots: int) -> int:
    """Fields per Pallas grid step; the caller pads n_robots up to a
    multiple (a prime robot count must not collapse the chunk to 1)."""
    return max(1, min(_FIELD_VMEM_BYTES // (n * n * 4), n_robots))


def _relax_once(d: Array, blocked: Array) -> Array:
    """One 8-neighbour min-plus sweep on (..., n, n); jnp ops only so the
    same body lowers inside the Pallas kernel and traces as plain XLA."""
    n = d.shape[-1]

    def sh(x, dr, dc):
        # Static-slice shift with _BIG fill, along the last two axes.
        if dr:
            fill = jnp.full_like(x[..., :1, :], _BIG)
            x = (jnp.concatenate([fill, x[..., :-1, :]], axis=-2) if dr > 0
                 else jnp.concatenate([x[..., 1:, :], fill], axis=-2))
        if dc:
            fill = jnp.full_like(x[..., :, :1], _BIG)
            x = (jnp.concatenate([fill, x[..., :, :-1]], axis=-1) if dc > 0
                 else jnp.concatenate([x[..., :, 1:], fill], axis=-1))
        return x

    best = d
    for dr, dc, w in ((1, 0, 1.0), (-1, 0, 1.0), (0, 1, 1.0), (0, -1, 1.0),
                      (1, 1, _SQ2), (1, -1, _SQ2),
                      (-1, 1, _SQ2), (-1, -1, _SQ2)):
        best = jnp.minimum(best, sh(d, dr, dc) + w)
    return jnp.where(blocked, _BIG, best)


def _relax_level_xla(blocked: Array, init: Array, iters: int) -> Array:
    """(C, n, n) init -> relaxed fields after `iters` doubled sweeps."""
    blk = blocked[None, :, :]
    return jax.lax.fori_loop(
        0, iters, lambda _, d: _relax_once(_relax_once(d, blk), blk), init)


def _relax_level_pallas(blocked: Array, init: Array, iters: int) -> Array:
    """Pallas twin of `_relax_level_xla`: fields stay in VMEM across all
    iterations; robots are chunked to fit the VMEM budget."""
    R, n, _ = init.shape
    C = _chunk_robots(n, R)
    pad = (-R) % C
    if pad:
        init = jnp.concatenate(
            [init, jnp.full((pad, n, n), _BIG, init.dtype)], axis=0)
    Rp = R + pad

    def kernel(blocked_ref, init_ref, out_ref):
        blk = blocked_ref[:] > 0.5
        d = jax.lax.fori_loop(
            0, iters,
            lambda _, dm: _relax_once(_relax_once(dm, blk), blk),
            init_ref[:])
        out_ref[:] = d

    interpret = jax.default_backend() != "tpu"
    out = pl.pallas_call(
        kernel,
        grid=(Rp // C,),
        in_specs=[
            pl.BlockSpec((n, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((C, n, n), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((C, n, n), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Rp, n, n), jnp.float32),
        interpret=interpret,
    )(blocked.astype(jnp.float32), init)
    return out[:R] if pad else out


def _use_pallas() -> bool:
    """Shared engine toggle (grid._use_pallas): Pallas on TPU unless
    JAX_MAPPING_NO_PALLAS=1; the XLA twin elsewhere (interpret-mode
    Pallas is far slower than XLA on CPU — tests exercise the kernel
    explicitly via _relax_level_pallas). JAX_MAPPING_COSTFIELD_XLA=1
    disables THIS kernel alone (bench probes it separately: a Mosaic
    rejection here must not also take down the proven fusion kernel)."""
    import os
    if os.environ.get("JAX_MAPPING_COSTFIELD_XLA") == "1":
        return False
    from jax_mapping.ops.grid import _use_pallas as _gp
    return _gp()


def _relax_level(blocked: Array, init: Array, iters: int) -> Array:
    n = init.shape[-1]
    # A single field larger than the budget cannot be chunked down
    # (_chunk_robots floors at 1 whole field) — the Mosaic stack for the
    # shift temporaries would over-run VMEM exactly the way the budget
    # exists to prevent, so such levels run the XLA twin instead.
    if _use_pallas() and n * n * 4 <= _FIELD_VMEM_BYTES:
        return _relax_level_pallas(blocked, init, iters)
    return _relax_level_xla(blocked, init, iters)


def _pool_blocked(blocked: Array) -> Array:
    """2x conservative pooling: a parent is blocked if ANY child is.

    Guarantees every coarse path exists at fine resolution, which is what
    makes the upsampled coarse solution an upper bound. reduce_window max
    on i8 rather than strided reshape-any — the reshape form lowered ~60x
    slower on TPU at the production shapes (see frontier.coarsen)."""
    return jax.lax.reduce_window(blocked.astype(jnp.int8), jnp.int8(0),
                                 jax.lax.max, (2, 2), (2, 2), "VALID") > 0


def _seed(init: Array, robot_rc: Array, blocked: Array,
          neighbours: bool) -> Array:
    """Seed each robot's own field at one level.

    The seed cell gets 0 only where it is OPEN at this level: the
    relaxation re-applies the shared blocked mask every sweep, so a 0 in
    a blocked cell cannot propagate — and it must NOT be made to (opening
    a blocked cell that straddles a wall at coarse resolution would let
    distance flow through the wall; that is true even within the robot's
    own field, so neither the shared mask nor a per-field mask may be
    punched open).

    `neighbours=True` (finest level only): a wall-hugging robot whose
    fine cell is conservatively blocked instead seeds its OPEN 8-neighbour
    cells with their one-step costs — at the level whose cells the robot
    physically occupies this is exact, while at coarser levels a
    neighbouring cell can sit across the wall. The cost of this
    conservatism: a wall-hugger forfeits the multigrid head start, so its
    field only covers 2*refine_iters cells around it (an overestimate
    beyond — the safe direction for the auction; far frontiers go to
    robots in open space)."""
    R = init.shape[0]
    n = init.shape[-1]
    rr = jnp.clip(robot_rc[:, 0], 0, n - 1)
    cc = jnp.clip(robot_rc[:, 1], 0, n - 1)
    ar = jnp.arange(R)
    seed_open = ~blocked[rr, cc]
    init = init.at[ar, rr, cc].min(jnp.where(seed_open, 0.0, _BIG))
    if neighbours:
        for dr, dc, w in ((1, 0, 1.0), (-1, 0, 1.0), (0, 1, 1.0),
                          (0, -1, 1.0), (1, 1, _SQ2), (1, -1, _SQ2),
                          (-1, 1, _SQ2), (-1, -1, _SQ2)):
            r2 = rr + dr
            c2 = cc + dc
            inb = (r2 >= 0) & (r2 < n) & (c2 >= 0) & (c2 < n)
            r2c = jnp.clip(r2, 0, n - 1)
            c2c = jnp.clip(c2, 0, n - 1)
            val = jnp.where(inb & ~blocked[r2c, c2c], jnp.float32(w),
                            jnp.float32(_BIG))
            init = init.at[ar, r2c, c2c].min(val)
    return init


@functools.partial(jax.jit, static_argnums=(2, 3))
def cost_fields(blocked: Array, robot_rc: Array, levels: int = 3,
                refine_iters: int = 8) -> Array:
    """(n, n) blocked mask + (R, 2) robot cells -> (R, n, n) cost fields.

    Multigrid: `levels` resolutions, full-convergence relaxation at the
    coarsest (diameter-bounded), `refine_iters` doubled sweeps per finer
    level from the upsampled upper-bound initialiser. Distances in cells
    of the FINEST level; robots' own cells are forced open (see
    frontier.cost_to_go for why).
    """
    n = blocked.shape[0]
    R = robot_rc.shape[0]
    # Each pooling halves the grid, so n must be divisible by
    # 2^(levels-1); clamp instead of crashing at trace time for grids
    # with limited 2-divisibility (e.g. n=62 supports only 2 levels).
    max_levels = 1
    while n % (1 << max_levels) == 0 and (n >> max_levels) >= 8:
        max_levels += 1
    levels = max(1, min(levels, 6, max_levels))

    blocked_pyr = [blocked]
    for _ in range(levels - 1):
        blocked_pyr.append(_pool_blocked(blocked_pyr[-1]))

    rc_pyr = [robot_rc // (1 << lv) for lv in range(levels)]

    # Coarsest level: full-diameter convergence. The doubled sweep moves
    # the wavefront 2 cells per iteration; the diameter of an n_c x n_c
    # grid along an 8-connected path is <= n_c (worst-case serpentine maps
    # need more, but those are exactly what the exact path is for).
    n_c = n >> (levels - 1)
    blk_c = blocked_pyr[-1]
    init = _seed(jnp.full((R, n_c, n_c), _BIG), rc_pyr[-1], blk_c,
                 neighbours=(levels == 1))
    d = _relax_level(blk_c, init, iters=max(1, n_c // 2))

    for lv in range(levels - 2, -1, -1):
        # Upsample: x2 in cells (so distances double), +2 cells slack for
        # the corner a coarse step can cut inside a 2x2 block. Stays an
        # upper bound; refinement only tightens.
        d = jnp.repeat(jnp.repeat(d, 2, axis=1), 2, axis=2)
        d = jnp.where(d >= _BIG, _BIG, d * 2.0 + 2.0)
        blk = blocked_pyr[lv]
        d = jnp.where(blk[None], _BIG, d)
        d = _seed(d, rc_pyr[lv], blk, neighbours=(lv == 0))
        d = _relax_level(blk, d, iters=refine_iters)

    # The relaxation re-applies the mask every sweep, so a robot whose
    # cell is conservatively blocked ends with _BIG at its own seed;
    # report 0 there (its true distance to itself) like the exact path.
    rr = jnp.clip(robot_rc[:, 0], 0, n - 1)
    cc = jnp.clip(robot_rc[:, 1], 0, n - 1)
    return d.at[jnp.arange(R), rr, cc].set(0.0)


@functools.partial(jax.jit, static_argnums=(3,))
def warm_cost_fields(blocked: Array, robot_rc: Array, prev_fields: Array,
                     iters: int) -> Array:
    """`cost_fields` warm-started from a previous solve's fields.

    Init: each robot's previous field plus its own previous-field value
    at the robot's NEW cell — an upper bound by the triangle inequality
    (d_new(x) <= d(new, old) + d_old(x), and prev[new_cell] upper-bounds
    d(new, old)), so the monotone min-plus relaxation only tightens.
    VALIDITY IS THE CALLER'S CONTRACT: prev_fields must have been
    computed on a blocked mask that is a SUPERSET of `blocked` (cells
    may open, never close) — relaxation never raises a value, so an
    underestimate through a newly-blocked cell could never heal. A
    robot whose new cell the previous field called unreachable (_BIG
    offset) degenerates to a fresh seed-only field: still an upper
    bound, covering 2*iters cells around the robot.

    `iters` doubled sweeps tighten a 2*iters-cell wavefront around each
    seed; far cells keep the per-robot offset (~the robot's travel since
    the previous solve) — a near-uniform per-robot surcharge, which the
    greedy auction's per-robot argmin is insensitive to.
    """
    R = prev_fields.shape[0]
    n = blocked.shape[0]
    rr = jnp.clip(robot_rc[:, 0], 0, n - 1)
    cc = jnp.clip(robot_rc[:, 1], 0, n - 1)
    ar = jnp.arange(R)
    off = prev_fields[ar, rr, cc]                     # (R,)
    init = jnp.minimum(prev_fields + off[:, None, None], _BIG)
    init = jnp.where(blocked[None], _BIG, init)
    init = _seed(init, robot_rc, blocked, neighbours=True)
    d = _relax_level(blocked, init, iters)
    return d.at[ar, rr, cc].set(0.0)
