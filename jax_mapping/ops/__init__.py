"""Pure-JAX device kernels: grid fusion, scan matching, frontiers, pose graph."""
