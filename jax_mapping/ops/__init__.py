"""Pure-JAX device kernels: grid fusion, scan matching (exhaustive +
branch-and-bound pruned paths with the revision-keyed pyramid cache),
frontiers, pose graph."""
