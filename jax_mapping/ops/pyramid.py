"""Revision-keyed likelihood-pyramid cache for the pruned scan matcher.

The branch-and-bound matcher (ops/scan_match, `MatcherConfig.pruned`)
descends a max-pyramid of the likelihood field. Inside a jitted SLAM step
the pyramid is rebuilt in-graph — cheap next to the sweep it replaces —
but the HOST-driven repeated-match workloads (the recovery relocalizer
hammering the same map region every tick, loop-verification sweeps from
bench harnesses) rebuild the identical pyramid over and over against a
map that did not change underneath them.

`PyramidCache` keys a built pyramid on (region key, region revision):
the region key names WHERE the pyramid reads (a patch origin on a given
grid view), the revision says WHEN that area last changed. The mapper
supplies revisions from its serving-side dirty-tile bookkeeping
(`MapperNode.region_revision`: the monotonic `map_revision` recorded
per serving tile at mark time), so a fusion on the far side of the map
does NOT invalidate a relocalizing robot's pyramid — only mutations
whose patch extents touched the region do. A `None` revision means "no
revision source" (serving disabled, standalone tests): the entry is
rebuilt every time rather than ever serving stale data.

Entries are whole pyramids (tuples of device arrays): re-pooling happens
at region granularity — the likelihood smear crosses tile borders, so a
sub-region re-pool would need halo bookkeeping the hash-diff already
makes unnecessary (a clean region is reused wholesale; a dirty one is
one jitted rebuild).
"""

from __future__ import annotations

import functools
import threading
from collections import OrderedDict
from typing import Callable, Optional, Tuple

import jax

from jax_mapping.config import GridConfig, MatcherConfig
from jax_mapping.ops import grid as G
from jax_mapping.ops import scan_match as M

Array = jax.Array


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def build_match_pyramid(grid_cfg: GridConfig, m_cfg: MatcherConfig,
                        n_levels: int, grid_arr: Array,
                        origin_rc: Array) -> Tuple[Array, ...]:
    """Grid view + patch origin -> the pruned matcher's pyramid, one
    jitted dispatch: patch slice, likelihood field, max-pyramid levels
    (ops/scan_match.build_levels). The cached counterpart of the
    in-graph build `match` does per call."""
    patch = jax.lax.dynamic_slice(
        grid_arr, (origin_rc[0], origin_rc[1]),
        (grid_cfg.patch_cells, grid_cfg.patch_cells))
    field = M.likelihood_field(grid_cfg, m_cfg, patch)
    stride, n_steps = M.window_params(grid_cfg, m_cfg)
    return M.build_levels(field, n_steps, stride, n_levels)


def patch_origin_host(grid_cfg: GridConfig, xy) -> Tuple[int, int]:
    """`ops/grid.patch_origin` fetched to host ints — the cache-key form
    (origins are alignment-snapped, so nearby guesses share keys)."""
    import numpy as np
    o = np.asarray(G.patch_origin(grid_cfg, jax.numpy.asarray(
        np.asarray(xy, np.float32))))
    return int(o[0]), int(o[1])


class PyramidCache:
    """Bounded LRU of built pyramids keyed on (region, revision).

    Thread-safety: lookups and installs serialize on a leaf lock; the
    BUILD runs outside it (a device dispatch under a host lock is the
    exact stall the B2 lint exists to catch). Two threads racing the
    same cold key both build — harmless (last install wins; the cache is
    an optimisation, never a correctness surface).
    """

    def __init__(self, max_entries: int = 8):
        self._lock = threading.Lock()
        #: key -> (revision, pyramid levels tuple), LRU order.
        self._entries: "OrderedDict[tuple, tuple]" = OrderedDict()
        self.max_entries = max_entries
        self.n_hits = 0
        self.n_misses = 0
        self.n_invalidations = 0

    def get(self, key: tuple, revision: Optional[int],
            build: Callable[[], Tuple[Array, ...]]) -> Tuple[Array, ...]:
        """The cached pyramid for `key` at `revision`, building on miss.

        A hit requires the stored revision to EQUAL the requested one —
        a dirty region (newer revision) rebuilds, and a clean region
        (same revision) is reused no matter how far the global
        `map_revision` advanced elsewhere. `revision=None` always
        rebuilds and never stores (no revision source = no way to know
        the entry is still current)."""
        stale = False
        if revision is not None:
            with self._lock:
                ent = self._entries.get(key)
                if ent is not None:
                    if ent[0] == revision:
                        self._entries.move_to_end(key)
                        self.n_hits += 1
                        return ent[1]
                    stale = True
        levels = build()
        with self._lock:
            self.n_misses += 1
            if stale:
                self.n_invalidations += 1
            if revision is not None:
                self._entries[key] = (revision, levels)
                self._entries.move_to_end(key)
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
        return levels

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def snapshot(self) -> dict:
        with self._lock:
            total = self.n_hits + self.n_misses
            return {
                "n_entries": len(self._entries),
                "n_hits": self.n_hits,
                "n_misses": self.n_misses,
                "n_invalidations": self.n_invalidations,
                "hit_rate": (self.n_hits / total) if total else 0.0,
            }
