"""Incremental revision-keyed exploration pipeline (the publish hot path).

`MapperNode.publish_frontiers` historically recomputed the whole frontier
pipeline — coarsen, mask, label propagation, cost-to-go, auction — from
the full grid every publish cycle: 16M cells re-pooled and a fleet of
full-extent cost fields re-relaxed to move, typically, a couple of
robots by a few centimetres (BENCH_r05: `frontier_p50_ms_64robots` =
4418 ms against the <5 ms north star). The per-tile `map_revision`
bookkeeping built for serving and the pruned matcher (`_tile_rev`,
`region_revision`, `PyramidCache`) already knows exactly which tiles
changed — this module applies ROG-Map's incremental-update idiom
(PAPERS.md, arxiv 2302.14819) to exploration:

  * **Tile-keyed coarse-mask cache** — `coarsen` is a tile-local block
    pool, so per-tile coarse free/occupied/unknown masks are cached in
    persistent device buffers and only tiles whose revision advanced
    since the last publish re-pool (`_refresh_tiles`, one jitted scatter
    over a power-of-two-bucketed dirty set; dense dirt falls back to one
    full-grid re-pool).
  * **Active-region cropping** — label propagation, summarisation and
    cost-to-go run on the bounding box of observed (non-unknown) tiles
    ∪ robot cells, padded and bucketed to a small set of power-of-two
    spans (bounded recompile churn). Obstacles exist only in observed
    space, so an optimal detour leaves the observed bbox by at most one
    cell — with pad >= 2 BFS cells the crop preserves every optimal
    path (see FrontierConfig.crop_pad).
  * **Warm-started cost fields** — the previous publish's fields seed
    the next relaxation (`costfield.warm_cost_fields`; upper-bound-safe
    only while no blocked cell appeared in the crop, enforced here via
    per-tile occupancy-growth flags from the refresh).
  * **Publish skip** — when no tile revision advanced and no robot
    moved past `pose_skip_m` (nor changed BFS cell), the cached result
    is returned for republish through the bridge's reassign/blacklist
    post-passes.

Parity contract (tests/test_frontier_incremental.py): coarse masks,
cluster sizes and component structure are EXACTLY the full recompute's
(tile pooling is local; row-major index tie-breaks survive cropping);
targets are bit-identical whenever the representative cells match;
cost-field values match the full solve wherever the relaxation budget
converges both (exact-BFS mode with a covering iteration bound is
provably identical), and assignment/target identity is property-tested
across randomized dirty-tile sequences, pose walks and revision
interleavings. `FrontierConfig.incremental=False` bypasses this module
entirely (bit-exact pre-incremental publishes).

Thread-safety: ONE writer (the mapper tick thread) calls `compute`;
`status()` reads are lock-free stale-by-one snapshots, the repo's
/status counter convention.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig
from jax_mapping.ops import frontier as F

Array = jax.Array

#: Smallest crop span (first-level coarse cells): keeps the bucket set
#: tiny and every span divisible by the clustering/multigrid pooling
#: factors (powers of two up to this floor are never needed).
_MIN_SPAN = 32

#: Dirty-tile fraction above which one full-grid re-pool beats the
#: per-tile scatter loop (a closure storm marks everything; a sequential
#: per-tile loop over the whole grid would be strictly slower than the
#: single fused reduce_window it replaced).
_DENSE_DIRTY_FRAC = 0.25


class IncrementalPublish(NamedTuple):
    """Host-side publish payload + provenance of one `compute` call."""

    targets: np.ndarray      # (K, 2) world-metre goal points
    sizes: np.ndarray        # (K,) fine frontier cells per cluster
    assignment: np.ndarray   # (R,) cluster per robot, -1 none
    costs: np.ndarray        # (R, K) travel costs (first-level coarse cells)
    revision: int            # map_revision the result was computed at
    recomputed: bool         # False = cache served (publish skip)
    crop_rc: tuple           # (row0, col0, span) first-level coarse cells


def _next_pow2(x: int) -> int:
    return 1 << max(0, int(x - 1).bit_length())


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _refresh_tiles(fcfg: FrontierConfig, grid_cfg: GridConfig,
                   tile_cells: int, logodds: Array, free: Array, occ: Array,
                   unknown: Array, stale, tile_rc: Array, valid: Array):
    """Re-coarsen the (bucket-padded) dirty tiles into the persistent
    coarse-mask buffers; one jitted dispatch per bucket size.

    tile_rc: (M, 2) int32 tile indices (padding rows point at tile 0 and
    carry valid=False — they write back the tile's current content, an
    identity update). Returns the updated masks plus a per-tile
    `observed` flag (any non-unknown coarse cell — the crop-bbox
    input). Field-carry validity is NOT judged from per-tile flags: the
    BFS blocked mask depends on the frontier mask as well as occupancy,
    so `_field_mode` compares the actual crop blocked masks instead.

    `stale` is the persistent HEALED/STALE coarse mask (decay-aware
    scoring, ROADMAP item 7c): with `fcfg.decay_aware` it re-pools
    from the raw log-odds tile-locally exactly like the other masks —
    `stale_mask` is a tile-local block pool, so per-tile refresh is
    exact — and None otherwise (nothing computed, nothing carried).
    """
    tcc = tile_cells // fcfg.downsample

    def body(m, carry):
        free, occ, unknown, stale, obs = carry
        tr = tile_rc[m]
        of = (tr[0] * tile_cells, tr[1] * tile_cells)
        oc = (tr[0] * tcc, tr[1] * tcc)
        patch = jax.lax.dynamic_slice(logodds, of, (tile_cells, tile_cells))
        f, o, u = F.coarsen(fcfg, grid_cfg, patch)
        cf = jax.lax.dynamic_slice(free, oc, (tcc, tcc))
        co = jax.lax.dynamic_slice(occ, oc, (tcc, tcc))
        cu = jax.lax.dynamic_slice(unknown, oc, (tcc, tcc))
        v = valid[m]
        f = jnp.where(v, f, cf)
        o = jnp.where(v, o, co)
        u = jnp.where(v, u, cu)
        free = jax.lax.dynamic_update_slice(free, f, oc)
        occ = jax.lax.dynamic_update_slice(occ, o, oc)
        unknown = jax.lax.dynamic_update_slice(unknown, u, oc)
        if fcfg.decay_aware:
            st = F.stale_mask(fcfg, grid_cfg, patch)
            cs = jax.lax.dynamic_slice(stale, oc, (tcc, tcc))
            st = jnp.where(v, st, cs)
            stale = jax.lax.dynamic_update_slice(stale, st, oc)
        obs = obs.at[m].set(v & (~u).any())
        return free, occ, unknown, stale, obs

    obs = jnp.zeros(valid.shape, bool)
    return jax.lax.fori_loop(0, tile_rc.shape[0], body,
                             (free, occ, unknown, stale, obs))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _refresh_full(fcfg: FrontierConfig, grid_cfg: GridConfig,
                  tile_cells: int, logodds: Array, stale):
    """Dense-dirt fallback: one full-grid coarsen + per-tile observed
    flags (occupancy growth is not tracked here — the caller treats a
    full refresh as warm-start-invalidating, the conservative stance).
    With `fcfg.decay_aware` the stale mask re-pools full-grid too;
    otherwise the None input passes through untouched."""
    free, occ, unknown = F.coarsen(fcfg, grid_cfg, logodds)
    if fcfg.decay_aware:
        stale = F.stale_mask(fcfg, grid_cfg, logodds)
    obs = F._pool_any(~unknown, tile_cells // fcfg.downsample)
    return free, occ, unknown, stale, obs


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _crop_blocked(fcfg: FrontierConfig, grid_cfg: GridConfig, span: int,
                  free: Array, unknown: Array, origin_rc: Array):
    """The crop's BFS-resolution blocked mask — the EXACT quantity the
    carried cost fields depend on (besides seeds). Computed stand-alone
    so `_field_mode` can compare it against the mask the fields were
    solved on: blocked is NOT a function of occupancy alone
    (`bfs_passability` keeps frontier-containing clustering blocks
    traversable, so consuming a wall-adjacent frontier cell flips its
    block to blocked with no occupancy change — per-tile occ flags
    cannot see that)."""
    f = jax.lax.dynamic_slice(free, (origin_rc[0], origin_rc[1]),
                              (span, span))
    u = jax.lax.dynamic_slice(unknown, (origin_rc[0], origin_rc[1]),
                              (span, span))
    mask = F.frontier_mask(f, u)
    bfs_passable, _ = F.bfs_passability(fcfg, grid_cfg, f, u, mask)
    return ~bfs_passable


def _crop_stale(fcfg: FrontierConfig, stale, origin_rc: Array,
                span: int):
    """The stale-mask crop for decay-aware scoring (None when the knob
    is off — `compute_frontiers_from_masks` then skips the discount
    with a bit-identical trace)."""
    if not fcfg.decay_aware or stale is None:
        return None
    return jax.lax.dynamic_slice(stale, (origin_rc[0], origin_rc[1]),
                                 (span, span))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _compute_crop(fcfg: FrontierConfig, grid_cfg: GridConfig, span: int,
                  free: Array, unknown: Array, stale, origin_rc: Array,
                  poses: Array):
    f = jax.lax.dynamic_slice(free, (origin_rc[0], origin_rc[1]),
                              (span, span))
    u = jax.lax.dynamic_slice(unknown, (origin_rc[0], origin_rc[1]),
                              (span, span))
    return F.compute_frontiers_from_masks(
        fcfg, grid_cfg, f, u, poses, origin_rc=origin_rc,
        return_fields=True,
        stale=_crop_stale(fcfg, stale, origin_rc, span))


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def _compute_crop_warm(fcfg: FrontierConfig, grid_cfg: GridConfig,
                       span: int, warm_iters: int, free: Array,
                       unknown: Array, stale, origin_rc: Array,
                       poses: Array, prev_fields: Array):
    f = jax.lax.dynamic_slice(free, (origin_rc[0], origin_rc[1]),
                              (span, span))
    u = jax.lax.dynamic_slice(unknown, (origin_rc[0], origin_rc[1]),
                              (span, span))
    return F.compute_frontiers_from_masks(
        fcfg, grid_cfg, f, u, poses, origin_rc=origin_rc,
        warm_fields=prev_fields, warm_iters=warm_iters,
        return_fields=True,
        stale=_crop_stale(fcfg, stale, origin_rc, span))


class IncrementalFrontierPipeline:
    """Revision-keyed incremental frontier recompute for one mapper.

    Construction validates the geometry the incremental path depends on
    (tile/pooling divisibility, power-of-two pooling factors) and raises
    ValueError otherwise — the bridge catches it and falls back to the
    full recompute, loudly, once.
    """

    def __init__(self, fcfg: FrontierConfig, grid_cfg: GridConfig,
                 tile_cells: int):
        d = fcfg.downsample
        c = fcfg.cluster_downsample
        n_full = grid_cfg.size_cells
        if n_full % tile_cells:
            raise ValueError(f"tile_cells={tile_cells} does not divide "
                             f"grid size {n_full}")
        if tile_cells % d:
            raise ValueError(f"downsample={d} does not divide "
                             f"tile_cells={tile_cells}")
        if c & (c - 1) or d & (d - 1):
            raise ValueError("incremental frontier pipeline needs "
                             f"power-of-two pooling factors, got "
                             f"downsample={d} cluster_downsample={c}")
        self.fcfg = fcfg
        self.grid_cfg = grid_cfg
        self.tile_cells = tile_cells
        self._n = n_full // d                    # coarse grid edge
        self._tcc = tile_cells // d              # coarse cells per tile
        self._nt = n_full // tile_cells
        # Crop origins snap to the clustering x multigrid pooling period
        # so cropped pooling blocks align with the full grid's.
        self._snap = c * (1 << (fcfg.mg_levels - 1))
        if self._n % self._snap:
            raise ValueError(f"coarse grid {self._n} not divisible by "
                             f"crop alignment {self._snap}")
        # Persistent coarse-mask cache (device): an empty grid is all
        # unknown — matching coarsen() of a zero log-odds grid, so tiles
        # never marked dirty are already correct.
        self._free = jnp.zeros((self._n, self._n), bool)
        self._occ = jnp.zeros((self._n, self._n), bool)
        self._unknown = jnp.ones((self._n, self._n), bool)
        # Decay-aware scoring (ROADMAP item 7c): the HEALED/STALE mask
        # is carried tile-incrementally like the other coarse masks —
        # `stale_mask` is a tile-local block pool of the raw log-odds,
        # and a decay pass bumps every tile's revision, so staleness
        # can never go out of date against the tile cache. None when
        # the knob is off: nothing computed, bit-identical pre-7c
        # traces.
        self._stale = (jnp.zeros((self._n, self._n), bool)
                       if fcfg.decay_aware else None)
        self._seen_rev = np.full((self._nt, self._nt), -1, np.int64)
        self._tile_observed = np.zeros((self._nt, self._nt), bool)
        self._extra_key = None
        # Previous-publish carry.
        self._last: Optional[IncrementalPublish] = None
        self._last_poses: Optional[np.ndarray] = None
        self._last_cells: Optional[np.ndarray] = None
        self._prev_fields = None                 # device (R, nb, nb) or None
        self._prev_crop: Optional[tuple] = None
        #: BFS cells the carried fields were last actually RELAXED at
        #: (reuse passes them through unchanged, so this deliberately
        #: does not advance on reuse).
        self._field_cells: Optional[np.ndarray] = None
        #: Crop BFS blocked mask the carried fields were solved on
        #: (device; returned fused from the crop compute).
        self._prev_blocked = None
        # Observability (single tick-thread writer; lock-free readers).
        self.n_recomputes = 0
        self.n_skips = 0
        self.n_tiles_refreshed = 0               # tile-cache misses
        self.n_tiles_clean = 0                   # tile-cache hits
        self.n_warm_starts = 0
        self.n_field_reuses = 0
        self.n_full_refreshes = 0
        self.last_recompute_ms: Optional[float] = None
        self.last_crop: Optional[tuple] = None
        self.last_device_result = None           # crop-shaped (tests/debug)
        #: Static shapes compiled so far — the bounded-recompile-churn
        #: guarantee the crop-bucketing test pins down.
        self.compiled_shapes: set = set()

    # -- host-side geometry helpers --------------------------------------

    def _robot_cells(self, poses: np.ndarray) -> np.ndarray:
        """Robot (row, col) in first-level coarse cells, clipped."""
        res = self.grid_cfg.resolution_m * self.fcfg.downsample
        ox, oy = self.grid_cfg.origin_m
        rows = np.clip(((poses[:, 1] - oy) / res).astype(np.int64),
                       0, self._n - 1)
        cols = np.clip(((poses[:, 0] - ox) / res).astype(np.int64),
                       0, self._n - 1)
        return np.stack([rows, cols], axis=1)

    def _bucket_span(self, needed: int) -> int:
        """Smallest allowed span >= needed. Allowed spans are 2^k and
        3*2^(k-1) (both divisible by the pooling period when they clear
        the floor) — the 1.5x midpoints halve the worst-case bucket
        overshoot (a 260-cell bbox must not pay a 512^2 relax), while
        the set stays logarithmic (the bounded-recompile guarantee)."""
        n = self._n
        floor = max(_MIN_SPAN, self._snap)
        span = n
        p = floor
        while p <= n:
            for s in (p, p + p // 2):
                if s >= needed and s <= n and s % self._snap == 0 \
                        and s < span:
                    span = s
            p *= 2
        return span

    def _crop(self, cells: np.ndarray) -> tuple:
        """(row0, col0, span): observed-tiles bbox ∪ robot cells, padded
        by crop_pad, origin snapped to the pooling period, span bucketed
        (>= _MIN_SPAN, <= full grid)."""
        n = self._n
        tcc = self._tcc
        obs = np.argwhere(self._tile_observed)
        lo = cells.min(axis=0)
        hi = cells.max(axis=0) + 1
        if obs.size:
            lo = np.minimum(lo, obs.min(axis=0) * tcc)
            hi = np.maximum(hi, (obs.max(axis=0) + 1) * tcc)
        pad = self.fcfg.crop_pad
        lo = np.maximum(lo - pad, 0)
        hi = np.minimum(hi + pad, n)
        snap = self._snap
        lo = (lo // snap) * snap
        span = self._bucket_span(int((hi - lo).max()))
        r0 = int(min(lo[0], n - span))
        c0 = int(min(lo[1], n - span))
        return r0, c0, span

    # -- the pipeline ------------------------------------------------------

    def compute(self, logodds, poses: np.ndarray, tile_rev: np.ndarray,
                revision: int, extra_key=None) -> IncrementalPublish:
        """One publish cycle: refresh dirty tiles, recompute on the
        active-region crop (warm-started when valid), or skip outright.

        logodds: the (consistent-snapshot) full-resolution grid the
        publish runs on. tile_rev: the mapper's per-tile last-dirty
        revision snapshot, same consistent section. extra_key: any
        non-tile-tracked ingredient of `logodds` (the planner's voxel
        overlay key); a change invalidates every tile.
        """
        fcfg, g = self.fcfg, self.grid_cfg
        if extra_key != self._extra_key:
            self._seen_rev[:] = -1
            self._extra_key = extra_key
            self._prev_fields = None
        dirty = tile_rev > self._seen_rev
        ndirty = int(dirty.sum())
        cells = self._robot_cells(poses)

        if ndirty == 0 and self._last is not None \
                and self._last_poses is not None \
                and len(poses) == len(self._last_poses):
            moved = float(np.abs(poses[:, :2]
                                 - self._last_poses[:, :2]).max())
            if moved < fcfg.pose_skip_m \
                    and bool((cells == self._last_cells).all()):
                self.n_skips += 1
                return self._last._replace(recomputed=False)

        t0 = time.perf_counter()
        if ndirty:
            logodds = jnp.asarray(logodds)
            if ndirty >= max(1, int(dirty.size * _DENSE_DIRTY_FRAC)):
                (self._free, self._occ, self._unknown, self._stale,
                 obs) = _refresh_full(
                    fcfg, g, self.tile_cells, logodds, self._stale)
                # np.array (copy): np.asarray of a device array is a
                # read-only view, and the sparse path writes into this.
                self._tile_observed = np.array(obs)
                self.n_full_refreshes += 1
                self.compiled_shapes.add(("refresh", "full"))
            else:
                idx = np.argwhere(dirty).astype(np.int32)
                m_b = _next_pow2(ndirty)
                pad = m_b - ndirty
                if pad:
                    idx = np.concatenate(
                        [idx, np.zeros((pad, 2), np.int32)], axis=0)
                valid = np.arange(m_b) < ndirty
                (self._free, self._occ, self._unknown, self._stale,
                 obs_f) = _refresh_tiles(
                     fcfg, g, self.tile_cells, logodds, self._free,
                     self._occ, self._unknown, self._stale,
                     jnp.asarray(idx), jnp.asarray(valid))
                self._tile_observed[dirty] = np.asarray(obs_f)[:ndirty]
                self.compiled_shapes.add(("refresh", m_b))
            self._seen_rev = np.where(dirty, tile_rev, self._seen_rev)
            self.n_tiles_refreshed += ndirty
        self.n_tiles_clean += int(dirty.size) - ndirty

        crop = self._crop(cells)
        r0, c0, span = crop
        origin = jnp.asarray([r0, c0], jnp.int32)
        mode, cur_blocked = self._field_mode(ndirty, crop, cells, origin)
        poses_d = jnp.asarray(poses.astype(np.float32))
        if mode is not None:
            # Fields are per-robot independent, so only robots whose
            # BFS cell moved need relaxing: their rows warm-start
            # (offset init, fcfg.warm_extra_iters sweeps around the
            # new seed) against the already-validated blocked mask and
            # are patched into the carried stack; everyone else's row
            # is EXACT as-is. The crop compute then runs in pure-reuse
            # form (0 sweeps: re-mask + re-seed is the identity on a
            # valid field). With a 64-robot fleet jiggling
            # centimetres, this turns the common "one robot crossed a
            # cell border" publish from a full-fleet relax into a
            # 1-row one.
            carried = self._prev_fields
            if mode == "warm":
                c = fcfg.cluster_downsample
                moved = np.nonzero(
                    (cells // c != self._field_cells).any(axis=1))[0]
                m_b = _next_pow2(max(1, len(moved)))
                pad_idx = np.zeros(m_b, np.int64)
                pad_idx[:len(moved)] = moved
                # Padding repeats robot 0: its row relaxes to its own
                # (still valid) field — a harmless rewrite.
                origin_bfs = np.array([r0 // c, c0 // c])
                sub_rc = jnp.asarray(
                    (cells[pad_idx] // c - origin_bfs).astype(np.int32))
                from jax_mapping.ops import costfield as CF
                sub = CF.warm_cost_fields(
                    cur_blocked, sub_rc, carried[jnp.asarray(pad_idx)],
                    fcfg.warm_extra_iters)
                carried = carried.at[jnp.asarray(pad_idx)].set(sub)
                self.compiled_shapes.add(("warmsub", m_b, span))
            fr, fields, blocked_out = _compute_crop_warm(
                fcfg, g, span, 0, self._free, self._unknown,
                self._stale, origin, poses_d, carried)
            self.n_warm_starts += 1
            if mode == "reuse":
                self.n_field_reuses += 1
            self.compiled_shapes.add(("crop", span, 0))
        else:
            fr, fields, blocked_out = _compute_crop(
                fcfg, g, span, self._free, self._unknown, self._stale,
                origin, poses_d)
            self.compiled_shapes.add(("crop", span, "cold"))
        if mode != "reuse":
            self._field_cells = cells // fcfg.cluster_downsample
        # The mask the stored fields are valid against comes back fused
        # from the crop compute — no second dispatch on the store side.
        self._prev_blocked = blocked_out if fields is not None else None
        out = IncrementalPublish(
            targets=np.asarray(fr.targets),
            sizes=np.asarray(fr.sizes),
            assignment=np.asarray(fr.assignment),
            costs=np.asarray(fr.costs),
            revision=int(revision), recomputed=True, crop_rc=crop)
        self._prev_fields = fields
        self._prev_crop = crop
        self._last = out
        self._last_poses = np.array(poses, np.float32, copy=True)
        self._last_cells = cells
        self.last_device_result = fr             # crop-shaped (tests/debug)
        self.n_recomputes += 1
        dt = time.perf_counter() - t0
        self.last_recompute_ms = round(dt * 1e3, 3)
        # Report through the ONE stage mechanism (ISSUE 10 satellite):
        # the `frontier.recompute` stage renders as the /metrics
        # summary + fixed log-bucket histogram families, replacing the
        # hand-built `jax_mapping_frontier_recompute_ms` gauge —
        # last_recompute_ms above stays the /status one-glance number.
        from jax_mapping.utils import global_metrics
        global_metrics.stages.observe("frontier.recompute", dt)
        self.last_crop = crop
        return out

    def _field_mode(self, ndirty: int, crop: tuple, cells: np.ndarray,
                    origin: Array):
        """(mode, crop_blocked_or_None): how this publish's cost fields
        come to be. mode: None = cold
        multigrid; 'warm' = offset-warm-started relaxation (valid while
        no blocked cell APPEARED in the crop — the upper-bound contract
        of costfield.warm_cost_fields — and the warm budget's 2-cells-
        per-sweep wavefront covers every robot's move); 'reuse' = the
        carried fields are EXACT (identical blocked mask, every robot
        still in its BFS cell): 0 sweeps.

        Validity compares the crop's actual BFS blocked mask against
        the one the fields were solved on (`_prev_blocked`) — blocked
        depends on the frontier mask too, not just occupancy
        (bfs_passability keeps frontier blocks traversable), so a
        consumed frontier cell can GROW blocked with zero occupancy
        change; per-tile occupancy flags would miss it and the monotone
        relaxation could then never heal the stale underestimate. Also
        The decision needs the crop's blocked mask BEFORE the crop
        compute runs (it selects which compiled path runs), so dirty
        publishes pay one small standalone `_crop_blocked` dispatch
        here; the mask the fields are ultimately stored against comes
        back fused from the crop compute itself (`return_fields`), so
        nothing is computed twice on the store side."""
        fcfg = self.fcfg
        if not (fcfg.warm_start and fcfg.obstacle_aware
                and not fcfg.exact_bfs and self._prev_fields is not None
                and self._prev_crop == crop
                and self._prev_blocked is not None
                and self._field_cells is not None
                and len(cells) == len(self._field_cells)):
            return None, None
        if ndirty == 0:
            # No mask refresh happened, so blocked is prev verbatim.
            blocked = self._prev_blocked
            grew, same = False, True
        else:
            blocked = _crop_blocked(self.fcfg, self.grid_cfg, crop[2],
                                    self._free, self._unknown, origin)
            grew = bool((blocked & ~self._prev_blocked).any())
            same = not grew and not bool(
                (blocked ^ self._prev_blocked).any())
        if grew:
            return None, None
        bfs_cells = cells // fcfg.cluster_downsample
        move = int(np.abs(bfs_cells - self._field_cells).max()) \
            if len(bfs_cells) else 0
        if same and move == 0:
            return "reuse", blocked
        if move <= max(0, 2 * fcfg.warm_extra_iters - 2):
            return "warm", blocked
        return None, None

    # -- exports -----------------------------------------------------------

    def coarse_masks(self):
        """(free, occupied, unknown) persistent device buffers — parity
        tests compare them against a full-grid coarsen."""
        return self._free, self._occ, self._unknown

    def stale(self):
        """The carried HEALED/STALE coarse mask (decay-aware scoring),
        or None when `decay_aware` is off — parity tests compare it
        against a full-grid `frontier.stale_mask`."""
        return self._stale

    def status(self) -> dict:
        """Lock-free observability snapshot (/status `frontier` object)."""
        hits, misses = self.n_tiles_clean, self.n_tiles_refreshed
        total = hits + misses
        return {
            "n_recomputes": self.n_recomputes,
            "n_skips": self.n_skips,
            "cache_hits": hits,
            "cache_misses": misses,
            "cache_hit_rate": (hits / total) if total else 0.0,
            "n_warm_starts": self.n_warm_starts,
            "n_field_reuses": self.n_field_reuses,
            "n_full_refreshes": self.n_full_refreshes,
            "last_recompute_ms": self.last_recompute_ms,
            "crop": (list(self.last_crop)
                     if self.last_crop is not None else None),
            "crop_cells": (self.last_crop[2] ** 2
                           if self.last_crop is not None else 0),
            "n_compiled_shapes": len(self.compiled_shapes),
        }
