"""Polynomial atan2 usable inside Pallas TPU kernels.

Mosaic (the Pallas TPU compiler) has no lowering for `atan2`, so the
inverse-sensor kernel computes each cell's bearing with a polynomial
instead. The XLA classify path (`ops/grid.py`) uses the SAME function so
the Pallas and XLA formulations of the sensor model agree bit-for-bit on
beam assignment — a cell exactly on a beam boundary must not flip beams
depending on which engine fused it.

Accuracy: max error ~3.4e-7 rad in float32 (the degree-8 core fit of
atan(a)/a in s = a^2 on Chebyshev nodes over a in [0, 1] is 9.8e-8; the
octant-reduction subtractions add f32 rounding on top). The LD06's beam
pitch is 2*pi/512 ~= 1.2e-2 rad
(`/root/reference/pi/src/.../launch/pi_hardware.launch.py:20` publishes
full-circle scans), so the approximation error is ~5 orders of magnitude
below the rounding quantum used for beam assignment.
"""

from __future__ import annotations

import jax.numpy as jnp

_HALF_PI = 1.5707963267948966
_PI = 3.141592653589793

# atan(a)/a ~= sum c_i * (a^2)^i on a in [0, 1]; float32 max err 9.8e-8.
_C = (1.0, -0.33333138, 0.19993694, -0.14211106, 0.10667487,
      -0.075569004, 0.043278243, -0.01641319, 0.002932762)


def atan2(y, x):
    """Elementwise atan2(y, x) -> (-pi, pi], polynomial core.

    Matches jnp.arctan2 conventions for signs and the x == y == 0 case
    (returns 0.0) to within the polynomial error.
    """
    ax = jnp.abs(x)
    ay = jnp.abs(y)
    mx = jnp.maximum(ax, ay)
    mn = jnp.minimum(ax, ay)
    a = mn / jnp.maximum(mx, jnp.float32(1e-30))
    s = a * a
    p = jnp.float32(_C[-1])
    for c in _C[-2::-1]:
        p = p * s + jnp.float32(c)
    r = p * a
    r = jnp.where(ay > ax, _HALF_PI - r, r)
    r = jnp.where(x < 0.0, _PI - r, r)
    return jnp.where(y < 0.0, -r, r)
