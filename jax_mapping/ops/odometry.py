"""Differential-drive odometry as JAX kernels.

Re-implements the reference's dead-reckoning math — differential drive with
2nd-order Runge-Kutta midpoint integration
(`/root/reference/server/thymio_project/thymio_project/main.py:104-115`,
report.pdf §III.D eqs. (3)-(6)) — as pure functions: a single step, a
`lax.scan` trajectory integrator, and a batched fleet version. Wheel speeds
arrive in raw Thymio units; the 16-bit sign fix
(`server/.../main.py:101-102`) lives in `config.sign_extend_16bit` and is
applied at the ingest edge, not here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax_mapping.config import RobotConfig

Array = jax.Array


def wheel_velocities(robot: RobotConfig, left_units: Array,
                     right_units: Array) -> tuple[Array, Array]:
    """Raw speed units -> (v_lin m/s, v_ang rad/s)."""
    vl = left_units * robot.speed_coeff_m_per_unit_s
    vr = right_units * robot.speed_coeff_m_per_unit_s
    v_lin = (vr + vl) / 2.0
    v_ang = (vr - vl) / robot.wheel_base_m
    return v_lin, v_ang


def rk2_step(robot: RobotConfig, pose: Array, left_units: Array,
             right_units: Array, dt: Array) -> Array:
    """One RK2-midpoint odometry update. pose = [x, y, yaw]."""
    v_lin, v_ang = wheel_velocities(robot, left_units, right_units)
    delta_th = v_ang * dt
    mid = pose[2] + delta_th / 2.0
    return jnp.stack([
        pose[0] + v_lin * jnp.cos(mid) * dt,
        pose[1] + v_lin * jnp.sin(mid) * dt,
        pose[2] + delta_th,
    ])


@functools.partial(jax.jit, static_argnums=(0,))
def integrate(robot: RobotConfig, pose0: Array, left_units: Array,
              right_units: Array, dts: Array) -> Array:
    """Integrate a whole wheel-speed log -> (T, 3) trajectory of poses
    *after* each step. `lax.scan` keeps the sequential dependence on-device
    with static shapes."""
    def body(pose, lrdt):
        l, r, dt = lrdt
        nxt = rk2_step(robot, pose, l, r, dt)
        return nxt, nxt

    _, traj = jax.lax.scan(body, pose0, (left_units, right_units, dts))
    return traj


@functools.partial(jax.jit, static_argnums=(0,))
def integrate_fleet(robot: RobotConfig, poses0: Array, left_units: Array,
                    right_units: Array, dts: Array) -> Array:
    """vmap over a robot axis: (R,3), (R,T), (R,T), (R,T) -> (R,T,3)."""
    return jax.vmap(lambda p, l, r, d: integrate(robot, p, l, r, d))(
        poses0, left_units, right_units, dts)


def twist_to_wheel_units(robot: RobotConfig, v_lin_mps: Array,
                         v_ang_radps: Array) -> tuple[Array, Array]:
    """Inverse kinematics for the teleop path (`geometry_msgs/Twist` ->
    motor targets; capability of the reference's joystick teleop config,
    `server/install/.../config/joystick.yaml`)."""
    vr = v_lin_mps + v_ang_radps * robot.wheel_base_m / 2.0
    vl = v_lin_mps - v_ang_radps * robot.wheel_base_m / 2.0
    k = robot.speed_coeff_m_per_unit_s
    return vl / k, vr / k


def pose_compose(a: Array, b: Array) -> Array:
    """SE(2) composition a ⊕ b (b expressed in a's frame)."""
    ca, sa = jnp.cos(a[..., 2]), jnp.sin(a[..., 2])
    return jnp.stack([
        a[..., 0] + ca * b[..., 0] - sa * b[..., 1],
        a[..., 1] + sa * b[..., 0] + ca * b[..., 1],
        a[..., 2] + b[..., 2],
    ], axis=-1)


def pose_between(a: Array, b: Array) -> Array:
    """SE(2) relative pose a ⊖ b: the transform taking a to b, in a's frame."""
    ca, sa = jnp.cos(a[..., 2]), jnp.sin(a[..., 2])
    dx = b[..., 0] - a[..., 0]
    dy = b[..., 1] - a[..., 1]
    return jnp.stack([
        ca * dx + sa * dy,
        -sa * dx + ca * dy,
        wrap_angle(b[..., 2] - a[..., 2]),
    ], axis=-1)


def wrap_angle(theta: Array) -> Array:
    """Wrap to (-pi, pi]."""
    return jnp.arctan2(jnp.sin(theta), jnp.cos(theta))
