"""Wavefront frontier detection, clustering, and fleet assignment on device.

The reference explores *reactively* — a 3-layer subsumption navigator
(`/root/reference/server/thymio_project/thymio_project/main.py:119-196`) —
and its report lists map-based frontier exploration as future work
(report.pdf §VI.2). This module supplies that capability as fixed-shape
array programs (the BASELINE.json north star: p50 frontier recompute < 5 ms
at 64 robots):

  * frontier mask: free cells 4-adjacent to unknown — pure shifts;
  * clustering: connected components by iterated 8-neighbour label
    propagation (bounded iterations, no data-dependent recursion);
  * cluster summarisation into a static number of slots via one-hot
    matmuls (MXU) instead of host-side dictionaries;
  * assignment: per-robot cost = distance to cluster centroid through a
    multi-source BFS cost-to-go field (obstacle-aware), greedily auctioned
    on device with `lax.scan` over robots.

All work runs at a downsampled resolution (cfg.downsample) — the same
work-bounding idea slam_toolbox applies with its correlative windows
(SURVEY.md §5 "long-context" analog).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig

Array = jax.Array

_BIG = jnp.float32(1e9)

# Ceiling on the dense (n*n, K) one-hot membership matrices _summarize
# builds (fp32 bytes); past it the segment/gather formulation runs instead.
_SUMMARIZE_DENSE_BYTES = 32 * 1024 * 1024


class FrontierResult(NamedTuple):
    mask: Array            # (n, n) bool frontier cells (coarse resolution)
    # Cluster label per cell, -1 = none. Labels are linear indices into the
    # grid the connected-component pass ran on: the (n, n) array itself when
    # cluster_downsample == 1, the (n/c, n/c) clustering grid otherwise —
    # use them only as opaque component ids in that case.
    labels: Array          # (n, n) int32
    slots: Array           # (n, n) int32 top-K slot per cell (-1 none)
    centroids: Array       # (K, 2) float32 world-metre centroids
    targets: Array         # (K, 2) float32 world-metre goal points: a real
    #                        frontier cell of the cluster (centroids of
    #                        concave clusters can land on walls)
    sizes: Array           # (K,) int32 cells per cluster (0 = empty slot)
    assignment: Array      # (R,) int32 cluster index per robot (-1 = none)
    costs: Array           # (R, K) float32 robot->cluster travel cost (cells)


# ---------------------------------------------------------------------------
# Downsample + frontier mask
# ---------------------------------------------------------------------------

def coarsen(cfg: FrontierConfig, grid_cfg: GridConfig, logodds: Array):
    """Full-res log-odds -> coarse (free, occupied, unknown) masks.

    A coarse cell is occupied if ANY child is occupied (conservative for
    planning), free if any child is free and none occupied, else unknown.
    Works on the full grid or a row slab (spatially sharded caller).

    Any-child pooling is phrased as max/min reduce_window pools of the
    log-odds BEFORE thresholding (any(x > t) == max(x) > t): XLA's TPU
    reduce_window runs at HBM bandwidth, while the reshape(n/d, d, n/d, d)
    .any((1, 3)) formulation's strided middle axes lowered ~67x slower at
    the 4096^2 production shape (10.0 ms -> 0.15 ms measured on v5e).
    """
    d = cfg.downsample
    _check_pool_divisible(logodds, d)
    mx = jax.lax.reduce_window(logodds, -jnp.inf, jax.lax.max,
                               (d, d), (d, d), "VALID")
    mn = jax.lax.reduce_window(logodds, jnp.inf, jax.lax.min,
                               (d, d), (d, d), "VALID")
    any_occ = mx > grid_cfg.occ_threshold
    any_free = mn < grid_cfg.free_threshold
    free = any_free & ~any_occ
    unknown = ~any_occ & ~any_free
    return free, any_occ, unknown


def _shift(x: Array, dr: int, dc: int, fill=False) -> Array:
    """Shift a 2D array by ONE step per axis (dr, dc in {-1, 0, +1}),
    filling vacated cells.

    Concatenate-based (not dynamic_update_slice) so the SAME helper lowers
    inside Mosaic/Pallas kernel bodies and as plain XLA — this is the one
    shift implementation every frontier path shares. Single-step only: the
    concat formulation moves one row/col regardless of |d|, so larger
    offsets are rejected loudly rather than silently under-shifting
    (ADVICE r3)."""
    if abs(dr) > 1 or abs(dc) > 1:
        raise ValueError(f"_shift is single-step only, got ({dr}, {dc})")
    if dr:
        f = jnp.full_like(x[:1, :], fill)
        x = (jnp.concatenate([f, x[:-1, :]], axis=0) if dr > 0
             else jnp.concatenate([x[1:, :], f], axis=0))
    if dc:
        f = jnp.full_like(x[:, :1], fill)
        x = (jnp.concatenate([f, x[:, :-1]], axis=1) if dc > 0
             else jnp.concatenate([x[:, 1:], f], axis=1))
    return x


def frontier_mask(free: Array, unknown: Array) -> Array:
    """Free cells with a 4-neighbour unknown cell: the classic frontier."""
    near_unknown = (_shift(unknown, 1, 0) | _shift(unknown, -1, 0)
                    | _shift(unknown, 0, 1) | _shift(unknown, 0, -1))
    return free & near_unknown


#: Evidence floor below which a cell counts as genuinely unobserved for
#: decay-aware scoring: log-odds decay (ops/grid.decay_grid) shrinks
#: values multiplicatively toward 0 but never reaches it, so any
#: |log-odds| above this on an unknown-classified cell means "was
#: observed, evidence faded" — a healed/stale region.
_STALE_EPS = 1e-4


def stale_mask(cfg: FrontierConfig, grid_cfg: GridConfig,
               logodds: Array) -> Array:
    """Coarse (n, n) bool mask of HEALED/STALE cells: classified
    unknown by `coarsen` (evidence below both thresholds) yet carrying
    residual non-zero log-odds — exactly what map decay leaves behind
    in regions the world may have changed. Fresh unknown space (never
    observed, exact 0.0 everywhere) never flags, so the decay-aware
    discount cannot perturb plain exploration."""
    d = cfg.downsample
    _check_pool_divisible(logodds, d)
    mx = jax.lax.reduce_window(logodds, -jnp.inf, jax.lax.max,
                               (d, d), (d, d), "VALID")
    mn = jax.lax.reduce_window(logodds, jnp.inf, jax.lax.min,
                               (d, d), (d, d), "VALID")
    amax = jax.lax.reduce_window(jnp.abs(logodds), -jnp.inf, jax.lax.max,
                                 (d, d), (d, d), "VALID")
    unknown = ~(mx > grid_cfg.occ_threshold) \
        & ~(mn < grid_cfg.free_threshold)
    return unknown & (amax > _STALE_EPS)


# ---------------------------------------------------------------------------
# Connected-component clustering by label propagation
# ---------------------------------------------------------------------------

# VMEM ceiling for the label-propagation kernel's (n, n) int32 block; the
# Mosaic stack for the 8-shift sweep temporaries multiplies the block by
# ~17x (measured on the structurally identical costfield relaxation), so
# 512 KB keeps the scoped peak well under the 16 MB VMEM limit. Bigger
# grids run the XLA loop.
_LABEL_VMEM_BYTES = 512 * 1024


def _use_pallas_labels(n: int) -> bool:
    import os
    if os.environ.get("JAX_MAPPING_FRONTIER_XLA") == "1":
        return False
    from jax_mapping.ops.grid import _use_pallas as _gp
    return _gp() and n * n * 4 <= _LABEL_VMEM_BYTES


def _neighbor_max_sweep(lab: Array, m: Array) -> Array:
    """One 8-neighbour max propagation sweep; jnp ops only so the same
    body lowers inside the Pallas kernel and traces as plain XLA."""
    best = lab
    for dr in (-1, 0, 1):
        for dc in (-1, 0, 1):
            if dr == 0 and dc == 0:
                continue
            best = jnp.maximum(best, _shift(lab, dr, dc, fill=-1))
    return jnp.where(m, best, -1)


def _label_prop_pallas(mask: Array, seed: Array, iters: int) -> Array:
    """All 2*iters+1 sweeps with the labels resident in VMEM: the XLA
    fori_loop's per-sweep slice/update ops lower to hundreds of small
    un-fused kernels (5.6 ms at the 256^2 production clustering grid on
    v5e); one Pallas dispatch runs the whole propagation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    n = mask.shape[0]

    def kernel(mask_ref, seed_ref, out_ref):
        m = mask_ref[:] > 0
        lab = _neighbor_max_sweep(seed_ref[:], m)
        out_ref[:] = jax.lax.fori_loop(
            0, iters,
            lambda _, l: _neighbor_max_sweep(_neighbor_max_sweep(l, m), m),
            lab)

    return pl.pallas_call(
        kernel,
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM),
                  pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.int32),
        interpret=jax.default_backend() != "tpu",
    )(mask.astype(jnp.int32), seed)


def label_components(cfg: FrontierConfig, mask: Array) -> Array:
    """8-connected components: every frontier cell takes the max linear index
    reachable within its component. Fixed trip count (two sweeps per
    iteration so the bound is half the component diameter):
    data-independent latency, no per-iteration convergence predicate to
    serialise on (SURVEY.md §7: frontier BFS is data-dependent -> fixed-bound
    loop). On TPU the propagation runs as one Pallas kernel when the grid
    fits the VMEM budget; the XLA loop is the parity-tested fallback."""
    n = mask.shape[0]
    seed = jnp.where(mask,
                     jnp.arange(n * n, dtype=jnp.int32).reshape(n, n),
                     jnp.int32(-1))
    if _use_pallas_labels(n):
        return _label_prop_pallas(mask, seed, cfg.label_prop_iters)

    return jax.lax.fori_loop(
        0, cfg.label_prop_iters,
        lambda _, lab: _neighbor_max_sweep(_neighbor_max_sweep(lab, mask),
                                           mask),
        _neighbor_max_sweep(seed, mask))


def summarize_clusters(cfg: FrontierConfig, grid_cfg: GridConfig,
                       labels: Array, origin_rc: Array | None = None
                       ) -> tuple[Array, Array, Array, Array]:
    """Compress arbitrary labels into K static slots (top-K by size).

    Returns (centroids_world (K,2), targets_world (K,2), sizes (K,),
    slot_of_cell (n,n) int32). `targets` is a representative cell that IS
    part of the cluster — a concave cluster's centroid can fall on a wall,
    which would make it unreachable for the BFS cost and a bad goal point.
    Segment reductions keep this dense; slots beyond the true cluster count
    have size 0 and centroid/target at _BIG.
    """
    out = _summarize(cfg, grid_cfg, labels, weights=None, scale=1,
                     origin_rc=origin_rc)
    return out[:4]


def _summarize(cfg: FrontierConfig, grid_cfg: GridConfig, labels: Array,
               weights, scale: int, origin_rc=None):
    """Slot summarisation at an arbitrary clustering resolution.

    weights: optional (n, n) per-cell fine-frontier-cell counts (hierarchical
    path) — sizes and centroids weight by it so they stay in fine-cell units.
    scale: clustering cells per first-level coarse cell (cluster_downsample).
    origin_rc: optional traced (2,) int32 offset of this labels grid's
    [0, 0] within the full coarse grid, in FIRST-LEVEL coarse cells (the
    active-region crop, ops/frontier_incremental.py); must be a multiple
    of `scale`. Cell coordinates become GLOBAL before any world-metre
    conversion so cropped targets/centroids land exactly where the
    full-grid formula puts them; slot selection, tie-breaks and the
    returned rep_rc stay in LOCAL cells (row-major order is preserved
    under cropping, so every index tie-break picks the same cell).
    None compiles the identical pre-crop graph.
    Returns (centroids, targets, sizes, slot_of_cell, rep_rc).
    """
    n = labels.shape[0]
    K = cfg.max_clusters
    flat = labels.reshape(-1)
    present = flat >= 0
    w = (present.astype(jnp.int32) if weights is None
         else jnp.where(present, weights.reshape(-1), 0))

    # Unique labels -> the K largest clusters, via a bincount-free trick:
    # a cluster's label is the max linear index in it, so cells whose own
    # linear index equals their label are cluster representatives.
    lin = jnp.arange(n * n, dtype=jnp.int32)
    is_rep = present & (flat == lin)
    # Cluster size per representative: weighted count of cells sharing its
    # label. segment_sum over labels (clamped for the -1s); indexing the
    # result by `lin` is the identity, so no gather.
    sizes_by_cell = jax.ops.segment_sum(
        w, jnp.clip(flat, 0), num_segments=n * n)
    rep_sizes = jnp.where(is_rep, sizes_by_cell, 0)
    rep_sizes = jnp.where(rep_sizes >= cfg.min_cluster_cells, rep_sizes, 0)

    # Top-K representative linear indices by size.
    top_sizes, top_idx = jax.lax.top_k(rep_sizes, K)       # (K,)
    slot_valid = top_sizes > 0

    rows = (lin // n).astype(jnp.float32)
    cols = (lin % n).astype(jnp.float32)
    if origin_rc is not None:
        # Global clustering-cell coordinates: integer offsets are exact
        # in f32 below 2^24 cells, so the summed terms match the
        # full-grid path's values (only the reduction order differs).
        rows = rows + (origin_rc[0] // scale).astype(jnp.float32)
        cols = cols + (origin_rc[1] // scale).astype(jnp.float32)
    # Dense-vs-segment engine choice: the (n*n, K) one-hot membership
    # matrices are ~16 MB at the 256^2 production clustering shape but
    # 268 MB at n=1024 (the cluster_downsample=1 exact path) — gate on
    # their size and keep the O(n*n) segment/gather formulation beyond it.
    # One flag for both slot-level blocks below (the second dereferences
    # `member`, which only the dense branch defines).
    use_dense = n * n * K * 4 <= _SUMMARIZE_DENSE_BYTES
    if use_dense:
        # Everything slot-level works on the dense (n*n, K) membership
        # one-hot instead of segment/gather ops: TPU scatters and
        # 65 K-entry table gathers dominated this function (~2.8 of
        # 3.4 ms at the 256^2 production shape on v5e), while the one-hot
        # compares fuse and the weighted sums ride the MXU. A cell
        # matches at most one top_idx (its component's unique
        # representative), so argmax/sum over K are exact.
        member = (flat[:, None] == top_idx[None, :]) & slot_valid[None, :]
        slot_of_cell = jnp.where(
            member.any(axis=1),
            jnp.argmax(member, axis=1).astype(jnp.int32), -1)

        # Centroids: weighted per-slot sums as one (3, n*n) @ (n*n, K)
        # matmul. HIGHEST precision: the default TPU matmul rounds
        # operands to bf16, whose 8-bit mantissa would shift weighted
        # centroid sums (wf*rows reaches ~4k in the hierarchical path)
        # by up to a few coarse cells vs the exact fp32 segment_sum this
        # replaced.
        wf = w.astype(jnp.float32)
        mem_f = member.astype(jnp.float32)
        sums = jnp.dot(jnp.stack([wf, wf * rows, wf * cols], 0), mem_f,
                       precision=jax.lax.Precision.HIGHEST)   # (3, K)
        cnt, sr, sc = sums[0], sums[1], sums[2]
    else:
        slot_of_label = jnp.full((n * n,), -1, jnp.int32)
        slot_of_label = slot_of_label.at[top_idx].set(
            jnp.where(slot_valid, jnp.arange(K, dtype=jnp.int32), -1))
        slot_of_cell = jnp.where(present,
                                 slot_of_label[jnp.clip(flat, 0)], -1)
        sel = slot_of_cell >= 0
        seg = jnp.clip(slot_of_cell, 0)
        wf = jnp.where(sel, w.astype(jnp.float32), 0.0)
        cnt = jax.ops.segment_sum(wf, seg, num_segments=K)
        sr = jax.ops.segment_sum(wf * rows, seg, num_segments=K)
        sc = jax.ops.segment_sum(wf * cols, seg, num_segments=K)
    cnt_safe = jnp.maximum(cnt, 1.0)
    c_row = sr / cnt_safe
    c_col = sc / cnt_safe

    d = cfg.downsample
    res = grid_cfg.resolution_m * d * scale
    ox, oy = grid_cfg.origin_m
    cx = (c_col + 0.5) * res + ox
    cy = (c_row + 0.5) * res + oy
    centroids = jnp.where(slot_valid[:, None],
                          jnp.stack([cx, cy], -1), _BIG)

    # Representative cell per slot: the member closest to the centroid —
    # always a real frontier cell. d2 holds small integers-ish
    # (< 2*n^2 < 2^24), exact in float32.
    if use_dense:
        d2 = (rows[:, None] - c_row[None, :]) ** 2 \
            + (cols[:, None] - c_col[None, :]) ** 2              # (n*n, K)
        min_d2 = jnp.min(jnp.where(member, d2, jnp.inf), axis=0)  # (K,)
        is_best = member & (d2 <= min_d2[None, :] + 0.5)
        rep_lin = jnp.min(jnp.where(is_best, lin[:, None], n * n),
                          axis=0).astype(jnp.int32)               # (K,)
    else:
        d2 = (rows - c_row[jnp.clip(slot_of_cell, 0)]) ** 2 \
            + (cols - c_col[jnp.clip(slot_of_cell, 0)]) ** 2
        min_d2 = jax.ops.segment_min(jnp.where(sel, d2, jnp.inf), seg,
                                     num_segments=K)
        is_best = sel & (d2 <= min_d2[seg] + 0.5)
        rep_lin = jax.ops.segment_min(jnp.where(is_best, lin, n * n), seg,
                                      num_segments=K)
    has_rep = rep_lin < n * n
    rep_lin = jnp.clip(rep_lin, 0, n * n - 1)
    rep_row = (rep_lin // n).astype(jnp.int32)
    rep_col = (rep_lin % n).astype(jnp.int32)
    rep_row_g, rep_col_g = rep_row, rep_col
    if origin_rc is not None:
        rep_row_g = rep_row + origin_rc[0] // scale
        rep_col_g = rep_col + origin_rc[1] // scale
    tx = (rep_col_g.astype(jnp.float32) + 0.5) * res + ox
    ty = (rep_row_g.astype(jnp.float32) + 0.5) * res + oy
    targets = jnp.where(slot_valid[:, None] & has_rep[:, None],
                        jnp.stack([tx, ty], -1), _BIG)
    rep_rc = jnp.stack([rep_row, rep_col], -1)
    return centroids, targets, top_sizes.astype(jnp.int32), \
        slot_of_cell.reshape(n, n), rep_rc


def _check_pool_divisible(x: Array, c: int) -> None:
    if x.shape[0] % c or x.shape[1] % c:
        raise ValueError(f"shape {x.shape} not divisible by pool factor {c}")


def _pool_any(x: Array, c: int) -> Array:
    # reduce_window max on i8 (bool windows are unsupported on TPU); same
    # strided-reshape avoidance as coarsen().
    _check_pool_divisible(x, c)
    return jax.lax.reduce_window(x.astype(jnp.int8), jnp.int8(0),
                                 jax.lax.max, (c, c), (c, c), "VALID") > 0


def _pool_sum(x: Array, c: int) -> Array:
    _check_pool_divisible(x, c)
    return jax.lax.reduce_window(x.astype(jnp.int32), jnp.int32(0),
                                 jax.lax.add, (c, c), (c, c), "VALID")


def _upsample(x: Array, c: int) -> Array:
    return jnp.repeat(jnp.repeat(x, c, axis=0), c, axis=1)


def _cluster_hierarchical(cfg: FrontierConfig, grid_cfg: GridConfig,
                          mask: Array, origin_rc=None):
    """Latency-path clustering: connected components and slot summarisation
    at `cluster_downsample`x coarser resolution, sizes/centroids weighted by
    the fine frontier-cell counts, targets refined back to a real fine
    frontier cell. Merges frontier components that pass within
    cluster_downsample coarse cells of each other — the work-bounding trade
    the <5 ms @ 64 robots latency budget buys (BASELINE.md)."""
    import dataclasses
    c = cfg.cluster_downsample
    n = mask.shape[0]
    mask2 = _pool_any(mask, c)
    w2 = _pool_sum(mask, c)
    # Iteration bounds are expressed in first-level coarse cells; this grid
    # is c x smaller, so the same physical diameter needs 1/c the sweeps.
    cfg_c = dataclasses.replace(cfg, label_prop_iters=max(
        1, -(-cfg.label_prop_iters // c)))
    labels2 = label_components(cfg_c, mask2)
    centroids, targets2, sizes, slots2, rep_rc = _summarize(
        cfg, grid_cfg, labels2, weights=w2, scale=c, origin_rc=origin_rc)

    # Refine each slot's target from the rep coarse cell's centre to an
    # actual fine frontier cell inside it (a coarse cell centre can sit on
    # a wall even when the c x c block holds frontier cells).
    res1 = grid_cfg.resolution_m * cfg.downsample
    ox, oy = grid_cfg.origin_m

    def refine(rc, fallback):
        win = jax.lax.dynamic_slice(mask, (rc[0] * c, rc[1] * c), (c, c))
        idx = jnp.argmax(win.reshape(-1))
        any_fine = win.reshape(-1).any()
        fr = rc[0] * c + idx // c
        fc = rc[1] * c + idx % c
        if origin_rc is not None:
            # rc is crop-local (it slices the crop mask above); the
            # world-metre conversion needs the global cell.
            fr = fr + origin_rc[0]
            fc = fc + origin_rc[1]
        fine = jnp.stack([(fc.astype(jnp.float32) + 0.5) * res1 + ox,
                          (fr.astype(jnp.float32) + 0.5) * res1 + oy])
        return jnp.where(any_fine, fine, fallback)

    targets = jax.vmap(refine)(rep_rc, targets2)
    targets = jnp.where((sizes > 0)[:, None], targets, _BIG)

    labels = jnp.where(mask, _upsample(labels2, c), -1)
    slots = jnp.where(mask, _upsample(slots2, c), -1)
    return labels, slots, centroids, targets, sizes, rep_rc, mask2


# ---------------------------------------------------------------------------
# Obstacle-aware cost-to-go (multi-source BFS as min-plus dilation)
# ---------------------------------------------------------------------------

def cost_to_go(cfg: FrontierConfig, passable: Array, seeds_rc: Array,
               seed_valid: Array) -> Array:
    """Distance field (in coarse cells) from a robot's cell through passable
    space, by bounded min-plus dilation with early exit. seeds_rc: (S, 2).
    """
    n = passable.shape[0]
    dist = jnp.full((n, n), _BIG)
    rr = jnp.clip(seeds_rc[:, 0], 0, n - 1)
    cc = jnp.clip(seeds_rc[:, 1], 0, n - 1)
    dist = dist.at[rr, cc].min(jnp.where(seed_valid, 0.0, _BIG))
    blocked = ~passable
    # A robot hugging a wall can land in a conservatively-occupied coarse
    # cell; its seed must stay traversable or the whole field becomes _BIG
    # and the robot silently loses all frontier assignments.
    blocked = blocked.at[rr, cc].set(jnp.where(seed_valid, False, blocked[rr, cc]))

    sq2 = jnp.float32(1.41421356)

    def relax(dm):
        best = dm
        for dr, dc, w in ((1, 0, 1.0), (-1, 0, 1.0), (0, 1, 1.0), (0, -1, 1.0),
                          (1, 1, sq2), (1, -1, sq2), (-1, 1, sq2), (-1, -1, sq2)):
            best = jnp.minimum(best, _shift(dm, dr, dc, fill=_BIG) + w)
        return jnp.where(blocked, _BIG, best)

    # Fixed trips, doubled sweep — same latency rationale as
    # label_components.
    return jax.lax.fori_loop(
        0, cfg.bfs_iters, lambda _, dm: relax(relax(dm)),
        relax(jnp.where(blocked, _BIG, dist)))


# ---------------------------------------------------------------------------
# Fleet assignment
# ---------------------------------------------------------------------------

def assign_frontiers(costs: Array) -> Array:
    """Greedy auction: robots claim their cheapest cluster; a cluster serves
    one robot until every (valid) cluster is taken, then re-opens (more
    robots than frontiers -> sharing). costs: (R, K) with _BIG invalid.
    Returns (R,) int32 cluster per robot, -1 if no reachable cluster."""
    R, K = costs.shape

    def claim(taken, r):
        c = jnp.where(taken, costs[r] + 1e6, costs[r])   # prefer untaken
        best = jnp.argmin(c)
        ok = c[best] < _BIG
        taken = taken.at[best].set(taken[best] | ok)
        return taken, jnp.where(ok, best.astype(jnp.int32), -1)

    _, out = jax.lax.scan(claim, jnp.zeros(K, bool), jnp.arange(R))
    return out


# ---------------------------------------------------------------------------
# Full pipeline
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(0, 1))
def compute_frontiers(cfg: FrontierConfig, grid_cfg: GridConfig,
                      logodds: Array, robot_poses: Array) -> FrontierResult:
    """logodds (N,N) + robot poses (R,3) -> frontiers, clusters, assignment.

    With `cfg.decay_aware` the stale mask is derived here from the raw
    log-odds (the masks alone cannot tell healed from fresh unknown)
    and threaded into the assignment's cost discount; off (default)
    compiles the identical pre-existing graph."""
    free, _occ, unknown = coarsen(cfg, grid_cfg, logodds)
    stale = (stale_mask(cfg, grid_cfg, logodds)
             if cfg.decay_aware else None)
    return compute_frontiers_from_masks(cfg, grid_cfg, free, unknown,
                                        robot_poses, stale=stale)


#: 3x3 neighbourhood offsets (row-major) for greedy field descent —
#: index k of a 3x3 patch argmin maps to this displacement.
D8 = jnp.array([[-1, -1], [-1, 0], [-1, 1],
                [0, -1], [0, 0], [0, 1],
                [1, -1], [1, 0], [1, 1]], jnp.int32)


def descent_step(padded_field: Array, rc: Array, n: int) -> Array:
    """One greedy min-plus descent step: move to the argmin of the 3x3
    neighbourhood of `rc` in a field padded by 1 with _BIG. ONE
    definition shared by the host planner's path extraction
    (ops/planner.descend_field) and the fleet model's waypoint descent
    (assigned_waypoints_from_masks) — tie-breaking and clipping must
    never drift between them. At the field's minimum (the goal) the
    centre cell wins and the descent self-pads."""
    patch = jax.lax.dynamic_slice(padded_field, (rc[0], rc[1]), (3, 3))
    return jnp.clip(rc + D8[jnp.argmin(patch)], 0, n - 1)


def bfs_passability(cfg: FrontierConfig, grid_cfg: GridConfig,
                    free: Array, unknown: Array, mask: Array
                    ) -> tuple[Array, float]:
    """(bfs_passable, bfs_res): the passability grid and cell size the
    obstacle-aware BFS runs at. ONE definition shared by the assignment
    costs (compute_frontiers_from_masks) and the planned-steering
    waypoints (assigned_waypoints_from_masks): a waypoint descent is only
    correct while its passability matches what the assignment considered
    traversable.

    At cluster_downsample > 1, passability pools CONSERVATIVELY (a
    coarse cell is blocked if ANY child is blocked — same stance as
    coarsen()'s occupancy): pooling with any() instead would erase walls
    thinner than c cells and let obstacle-aware costs tunnel straight
    through them. Frontier cells stay traversable so targets in
    wall-adjacent blocks remain reachable (and seeds are unblocked
    inside cost_to_go / cost_fields)."""
    passable = free | mask | unknown   # robots may push into unknown space
    res = grid_cfg.resolution_m * cfg.downsample
    c = cfg.cluster_downsample
    if c == 1:
        return passable, res
    return ~_pool_any(~passable, c) | _pool_any(mask, c), res * c


def compute_frontiers_from_masks(cfg: FrontierConfig, grid_cfg: GridConfig,
                                 free: Array, unknown: Array,
                                 robot_poses: Array, origin_rc=None,
                                 warm_fields=None,
                                 warm_iters: int | None = None,
                                 return_fields: bool = False,
                                 stale=None):
    """Mask-level entry point: lets a spatially-sharded caller coarsen its
    own grid slab locally and all_gather only the coarse masks.

    origin_rc: optional traced (2,) int32 — the masks are an
    active-region CROP whose [0, 0] sits at this first-level-coarse-cell
    offset of the full grid (ops/frontier_incremental.py). Must be a
    multiple of cluster_downsample so the crop's pooling blocks align
    with the full grid's. World-metre outputs (targets/centroids) come
    out in global coordinates; mask/labels/slots stay crop-shaped.
    warm_fields: optional (R, n_bfs, n_bfs) previous cost fields — the
    multigrid solve is replaced by an offset warm-started relaxation
    (costfield.warm_cost_fields; caller guarantees upper-bound validity:
    no blocked cell appeared since the fields were computed).
    warm_iters: static doubled-sweep budget for that relaxation (None =
    cfg.warm_extra_iters); 0 is the EXACT-reuse fast path — valid when
    the caller knows the blocked mask and every seed cell are unchanged
    since the fields were solved, where the "relaxation" degenerates to
    re-masking + re-seeding the carried fields.
    return_fields: also return the (R, n_bfs, n_bfs) cost fields (None
    in euclidean/exact modes) and the BFS blocked mask, for the next
    publish's warm start and its validity check.
    stale: optional (n, n) bool HEALED/STALE mask at first-level coarse
    resolution (`stale_mask`); with `cfg.decay_aware` on, each slot's
    cost is discounted by `stale_bonus` × the stale fraction of the
    target's 3×3 clustering-cell neighbourhood — healed regions win
    cost ties and are re-verified first. None (or the knob off) skips
    the discount entirely.
    All defaults reproduce the historical single-result behavior with a
    bit-identical trace."""
    mask = frontier_mask(free, unknown)
    c = cfg.cluster_downsample
    d = cfg.downsample
    res = grid_cfg.resolution_m * d
    ox, oy = grid_cfg.origin_m
    # BFS runs at the clustering resolution (shared definition:
    # bfs_passability); costs reported in first-level coarse cells for
    # unit consistency with c == 1.
    bfs_passable, bfs_res = bfs_passability(cfg, grid_cfg, free, unknown,
                                            mask)
    if c == 1:
        labels = label_components(cfg, mask)
        centroids, targets, sizes, slots = summarize_clusters(
            cfg, grid_cfg, labels, origin_rc=origin_rc)
        tgt_r = ((targets[:, 1] - oy) / res).astype(jnp.int32)
        tgt_c = ((targets[:, 0] - ox) / res).astype(jnp.int32)
        if origin_rc is not None:
            tgt_r = tgt_r - origin_rc[0]
            tgt_c = tgt_c - origin_rc[1]
        tgt_r = jnp.clip(tgt_r, 0, free.shape[0] - 1)
        tgt_c = jnp.clip(tgt_c, 0, free.shape[0] - 1)
        bfs_scale = 1.0
    else:
        labels, slots, centroids, targets, sizes, rep_rc, _mask2 = \
            _cluster_hierarchical(cfg, grid_cfg, mask, origin_rc=origin_rc)
        tgt_r, tgt_c = rep_rc[:, 0], rep_rc[:, 1]
        bfs_scale = float(c)

    def to_bfs_rc(y, x):
        rr = (y / bfs_res).astype(jnp.int32)
        cc = (x / bfs_res).astype(jnp.int32)
        if origin_rc is not None:
            rr = rr - origin_rc[0] // c
            cc = cc - origin_rc[1] // c
        return rr, cc

    fields = None
    if cfg.obstacle_aware:
        if cfg.exact_bfs:
            import dataclasses
            bfs_cfg = (cfg if c == 1 else dataclasses.replace(
                cfg, bfs_iters=max(1, -(-cfg.bfs_iters // c))))

            def robot_costs(pose):
                rr, cc = to_bfs_rc(pose[1] - oy, pose[0] - ox)
                rc = jnp.stack([rr, cc])[None, :]
                dist = cost_to_go(bfs_cfg, bfs_passable, rc,
                                  jnp.array([True]))
                return dist[tgt_r, tgt_c] * bfs_scale

            costs = jax.vmap(robot_costs)(robot_poses)    # (R, K)
        else:
            # Multigrid batched fields (ops/costfield.py): one Pallas
            # relaxation per level with every robot's field resident in
            # VMEM — the <5 ms @ 64 robots path with obstacles kept.
            from jax_mapping.ops import costfield as CF
            rr, cc = to_bfs_rc(robot_poses[:, 1] - oy,
                               robot_poses[:, 0] - ox)
            robot_rc = jnp.stack([rr, cc], axis=1)
            if warm_fields is not None:
                fields = CF.warm_cost_fields(
                    ~bfs_passable, robot_rc, warm_fields,
                    cfg.warm_extra_iters if warm_iters is None
                    else warm_iters)
            else:
                fields = CF.cost_fields(~bfs_passable, robot_rc,
                                        cfg.mg_levels, cfg.mg_refine_iters)
            costs = fields[:, tgt_r, tgt_c] * bfs_scale   # (R, K)
        costs = jnp.minimum(costs, _BIG)
    else:
        # Euclidean distance in coarse cells (latency mode).
        diff = targets[None, :, :] - robot_poses[:, None, :2]
        costs = jnp.linalg.norm(diff, axis=-1) / res
        costs = jnp.where(jnp.isfinite(costs), costs, _BIG)
        costs = jnp.minimum(costs, _BIG)
    if cfg.decay_aware and stale is not None:
        # Decay-aware re-verification priority: discount each slot's
        # cost by the stale fraction around its target. Multiplicative
        # (not subtractive) so the discount can never push a reachable
        # cost negative or promote an unreachable (_BIG) slot past the
        # validity masking below.
        sb = (_pool_sum(stale, c).astype(jnp.float32) / float(c * c)
              if c > 1 else stale.astype(jnp.float32))
        padded_sb = jnp.pad(sb, 1)

        def _stale_frac(r, col):
            return jnp.mean(jax.lax.dynamic_slice(padded_sb,
                                                  (r, col), (3, 3)))

        frac = jax.vmap(_stale_frac)(tgt_r, tgt_c)         # (K,)
        scale = (1.0 - jnp.float32(cfg.stale_bonus)
                 * jnp.clip(frac, 0.0, 1.0))[None, :]
        # Only finite costs discount: a scaled _BIG would smuggle an
        # unreachable slot past the auction's `< _BIG` validity gate.
        costs = jnp.where(costs < _BIG, costs * scale, costs)
    costs = jnp.where((sizes > 0)[None, :], costs, _BIG)
    assignment = assign_frontiers(costs)
    result = FrontierResult(mask=mask, labels=labels, slots=slots,
                            centroids=centroids, targets=targets,
                            sizes=sizes, assignment=assignment, costs=costs)
    if return_fields:
        return result, fields, ~bfs_passable
    return result


# ---------------------------------------------------------------------------
# Planned steering waypoints (FrontierConfig.planned_goals)
# ---------------------------------------------------------------------------

def assigned_waypoints_from_masks(cfg: FrontierConfig, grid_cfg: GridConfig,
                                  free: Array, unknown: Array,
                                  robot_poses: Array, targets: Array,
                                  assignment: Array
                                  ) -> tuple[Array, Array]:
    """Per-robot planned steering waypoints toward assigned targets.

    The straight-line seek (`models/explorer.frontier_policy`) drives
    INTO walls between a robot and its frontier and leaves escape to the
    reactive shield; this computes, per robot, a multigrid cost field
    seeded at the robot's ASSIGNED target cell (`ops/costfield` — the
    same engine the assignment costs use, seeded at targets instead of
    robots) and descends it greedily from the robot's cell for
    `cfg.waypoint_lookahead` coarse steps: the waypoint leads around
    obstacles along the min-plus shortest path.

    Cost: one more `cost_fields` pass, roughly DOUBLING the
    obstacle-aware frontier cost — which is why `planned_goals` defaults
    off (the <5 ms p50 @ 64 robots budget was set without it).

    Returns (waypoints_xy (R, 2) f32, valid (R,) bool); callers keep the
    raw target where invalid (unassigned, unreachable, or already inside
    the target cell).
    """
    from jax_mapping.ops import costfield as CF

    mask = frontier_mask(free, unknown)
    ox, oy = grid_cfg.origin_m
    # The SAME passability the assignment costs used (shared helper —
    # a waypoint must never route through a cell the assignment
    # considered blocked, or vice versa).
    bfs_passable, bfs_res = bfs_passability(cfg, grid_cfg, free, unknown,
                                            mask)
    n2 = bfs_passable.shape[0]

    def to_rc(xy):
        return jnp.stack(
            [jnp.clip(((xy[:, 1] - oy) / bfs_res).astype(jnp.int32),
                      0, n2 - 1),
             jnp.clip(((xy[:, 0] - ox) / bfs_res).astype(jnp.int32),
                      0, n2 - 1)], axis=1)

    t_xy = targets[jnp.clip(assignment, 0)]
    seeds_rc = to_rc(t_xy)
    robot_rc = to_rc(robot_poses[:, :2])

    fields = CF.cost_fields(~bfs_passable, seeds_rc, cfg.mg_levels,
                            cfg.mg_refine_iters)        # (R, n2, n2)
    padded = jnp.pad(fields, ((0, 0), (1, 1), (1, 1)),
                     constant_values=_BIG)

    def descend(field_pad, rc0):
        rc = jax.lax.fori_loop(
            0, cfg.waypoint_lookahead,
            lambda _, rc: descent_step(field_pad, rc, n2), rc0)
        start_min = jnp.min(jax.lax.dynamic_slice(
            field_pad, (rc0[0], rc0[1]), (3, 3)))
        return rc, start_min

    rc2, start_min = jax.vmap(descend)(padded, robot_rc)
    wp_xy = jnp.stack(
        [(rc2[:, 1].astype(jnp.float32) + 0.5) * bfs_res + ox,
         (rc2[:, 0].astype(jnp.float32) + 0.5) * bfs_res + oy], axis=1)
    moved = jnp.any(rc2 != robot_rc, axis=1)
    valid = (assignment >= 0) & (start_min < _BIG) & moved
    return wp_xy, valid


def assigned_waypoints(cfg: FrontierConfig, grid_cfg: GridConfig,
                       logodds: Array, robot_poses: Array, targets: Array,
                       assignment: Array) -> tuple[Array, Array]:
    """`assigned_waypoints_from_masks` from a raw log-odds grid (the
    unsharded fleet model's entry; XLA CSEs the repeated coarsen with
    compute_frontiers' inside one jit)."""
    free, _occ, unknown = coarsen(cfg, grid_cfg, logodds)
    return assigned_waypoints_from_masks(cfg, grid_cfg, free, unknown,
                                         robot_poses, targets, assignment)
