"""Goal-seeded global path planning on the occupancy grid.

The Nav2-shaped capability behind the reference's unconsumed RViz SetGoal
tool (`server/rviz_config.rviz:193-198`; Nav2 itself was "future work",
report.pdf §VI.2): given the live log-odds map and a `/goal_pose`, produce
a path the robot can follow AROUND obstacles, where the round-4 brain
could only steer straight at the goal under the reactive shield.

TPU-first design — everything is fixed-shape and jit-compiled:

* The distance field is the frontier machinery's obstacle-aware min-plus
  BFS (`ops/frontier.cost_to_go`) seeded at the GOAL cell instead of the
  robot, over the same conservative coarsened passability the frontier
  costs use (free | frontier | unknown — a planner that refuses to cross
  unknown space could never reach an exploration target).
* Path extraction is greedy descent on that field: from the robot's cell,
  `lax.scan` over a static step bound, each step moving to the argmin of
  the 3x3 neighbourhood. Min-plus fields are monotone along shortest
  paths, so descent terminates at the goal (the unique local minimum of
  its connected component) without any data-dependent control flow.
* Outputs are static-shape: an (L, 2) world-frame path with a validity
  mask (the `/plan` message), a single lookahead waypoint for the brain's
  steering target, and a reachability flag.

The descent runs on the first-level coarse grid (size/downsample, default
1024^2 at 0.2 m) — planning does not need the 0.05 m rasterization detail,
and the coarse field is what already fits the <5 ms frontier budget.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from jax_mapping.config import FrontierConfig, GridConfig, PlannerConfig
from jax_mapping.ops import frontier as F

Array = jax.Array


class PlanResult(NamedTuple):
    path_xy: Array       # (max_path_len, 2) f32 world coords, goal-padded
    path_valid: Array    # (max_path_len,) bool — prefix mask of real cells
    n_steps: Array       # () i32 — valid prefix length
    reachable: Array     # () bool — the field reached the robot's cell
    waypoint_xy: Array   # (2,) f32 — lookahead steering target
    arrived: Array       # () bool — robot's cell IS the goal cell


def _world_to_cell(grid_cfg: GridConfig, res: float, xy: Array,
                   n: int) -> Array:
    """World (x, y) -> coarse (row, col), clipped into the grid."""
    ox, oy = grid_cfg.origin_m
    rc = jnp.stack([(xy[1] - oy) / res, (xy[0] - ox) / res])
    return jnp.clip(rc.astype(jnp.int32), 0, n - 1)


def _cell_to_world(grid_cfg: GridConfig, res: float, rc: Array) -> Array:
    """Coarse (row, col) cell centre -> world (x, y)."""
    ox, oy = grid_cfg.origin_m
    return jnp.stack([(rc[..., 1].astype(jnp.float32) + 0.5) * res + ox,
                      (rc[..., 0].astype(jnp.float32) + 0.5) * res + oy],
                     axis=-1)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def goal_field(pcfg: PlannerConfig, fcfg: FrontierConfig,
               grid_cfg: GridConfig, logodds: Array,
               goal_xy: Array) -> Array:
    """The goal-seeded cost-to-go field alone (coarse cells to reach the
    goal). Separated from the descent so a caller planning for MANY
    robots that share one goal (frontier auction sharing, ops/frontier
    assign_frontiers) computes the field — the dominant cost — once per
    goal and descends per robot."""
    free, _occ, unknown = F.coarsen(fcfg, grid_cfg, logodds)
    mask = F.frontier_mask(free, unknown)
    # Same passability stance as the frontier costs (compute_frontiers_
    # from_masks): robots may push into unknown space.
    passable = free | mask | unknown
    n = passable.shape[0]
    res = grid_cfg.resolution_m * fcfg.downsample
    goal_rc = _world_to_cell(grid_cfg, res, goal_xy, n)
    # cost_to_go unblocks its seed, so a goal in a conservatively-occupied
    # coarse cell (hugging a wall) still radiates.
    bfs_cfg = dataclasses.replace(fcfg, bfs_iters=pcfg.bfs_iters)
    return F.cost_to_go(bfs_cfg, passable, goal_rc[None, :],
                        jnp.array([True]))


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def descend_field(pcfg: PlannerConfig, fcfg: FrontierConfig,
                  grid_cfg: GridConfig, dist: Array, goal_xy: Array,
                  start_xy: Array) -> PlanResult:
    """Greedy descent of a `goal_field` from `start_xy` (see
    plan_to_goal, which fuses both for the single-robot case)."""
    n = dist.shape[0]
    res = grid_cfg.resolution_m * fcfg.downsample
    goal_rc = _world_to_cell(grid_cfg, res, goal_xy, n)
    start_rc = _world_to_cell(grid_cfg, res, start_xy, n)

    big = jnp.float32(F._BIG)
    padded = jnp.pad(dist, 1, constant_values=F._BIG)

    # The robot itself can sit in a conservatively-blocked coarse cell;
    # judge reachability (and take the first step) from the best cell of
    # its 3x3 neighbourhood, exactly the seed-unblocking concession
    # cost_to_go makes for frontier seeds.
    def patch_at(rc):
        return jax.lax.dynamic_slice(padded, (rc[0], rc[1]), (3, 3))

    start_patch = patch_at(start_rc)
    reachable = jnp.min(start_patch) < big
    arrived = jnp.all(start_rc == goal_rc)

    def step(rc, _):
        # Shared step (frontier.descent_step): once at the goal (field
        # == 0, the component's unique minimum) argmin holds the centre
        # cell and the path self-pads.
        nxt = F.descent_step(padded, rc, n)
        return nxt, nxt

    _, cells = jax.lax.scan(step, start_rc, None,
                            length=pcfg.max_path_len)
    at_goal = jnp.all(cells == goal_rc[None, :], axis=1)
    # Valid prefix: every cell up to and including the FIRST goal arrival
    # (the descent self-pads at the goal afterwards). A goal beyond the
    # descent horizon keeps the whole prefix — a partial path toward a far
    # goal still steers the robot the right way until the next replan.
    reached_by = jnp.cumsum(at_goal.astype(jnp.int32)) > 0
    prev_reached = jnp.concatenate([jnp.zeros(1, bool), reached_by[:-1]])
    valid = (jnp.logical_not(prev_reached) & reachable
             & jnp.logical_not(arrived))
    n_steps = valid.sum().astype(jnp.int32)

    path_xy = _cell_to_world(grid_cfg, res, cells)
    goal_f = goal_xy.astype(jnp.float32)
    path_xy = jnp.where(valid[:, None], path_xy, goal_f[None, :])

    # Lookahead waypoint: the path cell lookahead_cells along (or the last
    # valid cell when the goal is nearer than the lookahead).
    wp_idx = jnp.clip(jnp.minimum(pcfg.lookahead_cells, n_steps) - 1,
                      0, pcfg.max_path_len - 1)
    waypoint = jnp.where(reachable & (n_steps > 0), path_xy[wp_idx], goal_f)

    return PlanResult(path_xy=path_xy, path_valid=valid, n_steps=n_steps,
                      reachable=reachable, waypoint_xy=waypoint,
                      arrived=arrived)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def overlay_voxel_obstacles(pcfg: PlannerConfig, grid_cfg: GridConfig,
                            vox_cfg, logodds: Array,
                            voxel_grid: Array) -> Array:
    """The 2D log-odds grid with the 3D map's obstacle slice stamped in
    as occupied — what the planner should search when a depth camera
    maps obstacles the LiDAR plane misses (overhangs, low clutter).

    The slice (`ops.voxel.obstacle_slice`, any occupied voxel in
    [voxel_z_min_m, voxel_z_max_m]) embeds at the static cell offset the
    two grids' origins imply; same-resolution only, like the rosmap
    embed. Occupied cells take max(current, occ_threshold + 1) so a 3D
    obstacle always blocks the coarsened passability without erasing
    stronger 2D evidence; everything else is untouched (the overlay is
    for PLANNING — the published /map stays pure 2D).
    """
    from jax_mapping.ops import voxel as VX

    if abs(vox_cfg.resolution_m - grid_cfg.resolution_m) > 1e-9:
        raise ValueError(
            f"voxel resolution {vox_cfg.resolution_m} != grid "
            f"{grid_cfg.resolution_m}; 3D-aware planning requires equal "
            "cell sizes")
    obs = VX.obstacle_slice(vox_cfg, voxel_grid, pcfg.voxel_z_min_m,
                            pcfg.voxel_z_max_m)          # (Y, X) bool
    vox_o = vox_cfg.origin_m
    res = grid_cfg.resolution_m
    r0 = int(round((vox_o[1] - grid_cfg.origin_m[1]) / res))
    c0 = int(round((vox_o[0] - grid_cfg.origin_m[0]) / res))
    n = grid_cfg.size_cells
    ny, nx = obs.shape
    # Clip the voxel extent into the grid (static slices — offsets are
    # config-derived Python ints).
    gr0, gc0 = max(0, r0), max(0, c0)
    gr1, gc1 = min(n, r0 + ny), min(n, c0 + nx)
    if gr1 <= gr0 or gc1 <= gc0:
        return logodds                       # disjoint extents
    sub = obs[gr0 - r0:gr1 - r0, gc0 - c0:gc1 - c0]
    region = logodds[gr0:gr1, gc0:gc1]
    occ_lo = jnp.float32(grid_cfg.occ_threshold + 1.0)
    region2 = jnp.where(sub, jnp.maximum(region, occ_lo), region)
    return logodds.at[gr0:gr1, gc0:gc1].set(region2)


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def plan_to_goal(pcfg: PlannerConfig, fcfg: FrontierConfig,
                 grid_cfg: GridConfig, logodds: Array, goal_xy: Array,
                 start_xy: Array) -> PlanResult:
    """Plan a coarse-grid path from `start_xy` to `goal_xy` on the map.

    One fused jit: coarsen -> goal-seeded cost-to-go -> greedy descent
    (goal_field + descend_field inlined together). Unreachable goals
    (sealed off, or beyond the bfs_iters radius) come back
    `reachable=False` with an empty path; the caller keeps round-4
    behavior (straight-line seek under the shield) in that case.
    """
    dist = goal_field(pcfg, fcfg, grid_cfg, logodds, goal_xy)
    return descend_field(pcfg, fcfg, grid_cfg, dist, goal_xy, start_xy)
