"""End-to-end demo CLI: simulated fleet explores and maps a world.

The framework-native equivalent of the reference's operator workflow —
`ros2 launch thymio_project pc_server.launch.py` + `curl :5000/start` +
watching RViz (`/root/reference/README.md`, SURVEY.md §3.1) — as one
command:

    python -m jax_mapping.demo --steps 200 --robots 2 --out map.png

Boots the full node graph (sim world, driver, brain, mapper, HTTP API)
against a generated arena, starts exploration, steps the stack
faster-than-realtime, and writes the occupancy map as a grayscale PNG with
the reference's `/map-image` semantics (127 unknown / 255 free /
0 occupied, `server/.../main.py:259-266`). `--serve` keeps the HTTP API up
afterwards for interactive `curl /status`, `/map-image`, `/start`, `/stop`.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m jax_mapping.demo",
        description="Run the simulated exploration + mapping stack.")
    p.add_argument("--steps", type=int, default=150,
                   help="sensor ticks to run (default 150)")
    p.add_argument("--robots", type=int, default=1,
                   help="fleet size (default 1)")
    p.add_argument("--world", choices=["arena", "rooms"], default="rooms",
                   help="generated world layout")
    p.add_argument("--world-cells", type=int, default=192,
                   help="world edge length in cells")
    p.add_argument("--config", type=str, default=None,
                   help="SlamConfig JSON file (default: tiny_config)")
    p.add_argument("--out", type=str, default=None,
                   help="write final map PNG here")
    p.add_argument("--http-port", type=int, default=None,
                   help="serve the HTTP API on this port (0 = pick free)")
    p.add_argument("--serve", action="store_true",
                   help="keep serving HTTP after stepping (Ctrl-C to exit)")
    p.add_argument("--record", type=str, default=None, metavar="BAG",
                   help="record /scan + /odom to a rosbag-style trace "
                        "(io.trace) during the run")
    p.add_argument("--replay", type=str, default=None, metavar="BAG",
                   help="map from a recorded trace instead of simulating "
                        "(no sim, no brain: scans + odometry come from "
                        "the bag — the reference's rosbag workflow)")
    p.add_argument("--resume", type=str, default=None, metavar="CKPT",
                   help="resume the SLAM state from a checkpoint written "
                        "by --save-final or the HTTP /save endpoint")
    p.add_argument("--localization", action="store_true",
                   help="freeze the map (SlamConfig.mode=localization, "
                        "slam_config.yaml:20's other mode): scans match "
                        "for pose tracking but never fuse — localize on "
                        "a known map, usually with --map-prior")
    p.add_argument("--map-prior", type=str, default=None, metavar="YAML",
                   help="seed the mapper with a ROS map_server map "
                        "(map.yaml + map.pgm, e.g. a map_saver_cli or "
                        "POST /save-map artifact) before stepping — "
                        "localization-on-a-known-map bootstrapping")
    p.add_argument("--save-final", type=str, default=None, metavar="CKPT",
                   help="write the final SLAM state as a resumable "
                        "checkpoint")
    p.add_argument("--drop-prob", type=float, default=0.0,
                   help="Best-Effort link loss injection (report.pdf §V.A)")
    p.add_argument("--depth-cam", action="store_true",
                   help="also run the 3D pipeline: simulated depth camera "
                        "per robot fused into a shared voxel grid "
                        "(BASELINE configs[4]); adds voxel counts to the "
                        "summary and the /voxel-image HTTP route")
    p.add_argument("--voxel-out", type=str, default=None, metavar="PNG",
                   help="write the final 3D height map as a grayscale PNG "
                        "(requires --depth-cam)")
    p.add_argument("--seed", type=int, default=0)
    return p


def _occupancy(stack):
    import numpy as np

    from jax_mapping.ops import grid as G
    return np.asarray(G.to_occupancy(stack.cfg.grid, stack.mapper.merged_grid()))


def _write_png(path: str, occ) -> None:
    from jax_mapping.bridge.png import encode_gray
    from jax_mapping.ops.grid import occupancy_to_png_array
    with open(path, "wb") as f:
        f.write(encode_gray(occupancy_to_png_array(occ)))
    print(f"map written to {path}", file=sys.stderr)


def _replay_main(args, cfg) -> int:
    """Map from a recorded /scan + /odom trace: no sim, no brain — the
    reference's rosbag workflow (SURVEY.md §7 item 7), mapper only. Bags
    carrying depth topics (recorded with --depth-cam) also rebuild the 3D
    voxel map."""
    import numpy as np

    from jax_mapping.bridge.brain import robot_ns
    from jax_mapping.bridge.bus import Bus
    from jax_mapping.bridge.mapper import MapperNode
    from jax_mapping.io.trace import TraceReplayer
    from jax_mapping.ops import grid as G

    bus = Bus()
    mapper = MapperNode(cfg, bus, n_robots=args.robots)

    rep = TraceReplayer(args.replay)
    # Cross-check the bag's topics against this robot count's namespaces:
    # a bag recorded with --robots 2 replayed at the default 1 would
    # publish every message to topics nothing subscribes to and "succeed"
    # with an all-unknown map. EVERY expected namespace must appear
    # (ADVICE r3): a partial overlap — robots 2 replayed at 4 — would
    # pass a mere-intersection check while leaving robots 2-3 silently
    # unfed, which is exactly the failure mode this guard documents.
    expected = set()
    for i in range(args.robots):
        ns = robot_ns(i, args.robots)
        expected |= {f"{ns}scan", f"{ns}odom"}
    bag_topics = {rec["topic"] for rec in rep.index}
    if not expected <= bag_topics:
        missing = sorted(expected - bag_topics)
        print(f"error: bag topics {sorted(bag_topics)} do not cover the "
              f"expected {sorted(expected)} (missing {missing}) — was the "
              "bag recorded with a different --robots?", file=sys.stderr)
        return 2
    from jax_mapping.config import configs_equivalent
    if rep.config_json is not None and \
            not configs_equivalent(rep.config_json, cfg.to_json()):
        print("error: bag was recorded under a different config; pass the "
              "matching --config (the bag stores the recording config)",
              file=sys.stderr)
        return 2
    # Bags recorded with --depth-cam carry depth topics: rebuild the 3D
    # voxel map alongside the 2D one.
    voxel = None
    if any(t.endswith("depth") for t in bag_topics):
        from jax_mapping.bridge.voxel_mapper import VoxelMapperNode
        voxel = VoxelMapperNode(cfg, bus, n_robots=args.robots,
                                mapper=mapper)
    elif args.voxel_out:
        print("error: --voxel-out given but the bag has no depth topics "
              "(was it recorded without --depth-cam?)", file=sys.stderr)
        return 2

    pubs = {}
    n = 0
    # Interleave publishing with mapper ticks: the odometry pairing
    # history is bounded (mapper drops old entries), so a bag must not be
    # dumped wholesale ahead of processing.
    for stamp, topic, msg in rep.messages():
        if topic not in pubs:
            pubs[topic] = bus.publisher(topic)
        pubs[topic].publish(msg)
        n += 1
        if n % 40 == 0:
            mapper.tick()
            if voxel is not None:
                voxel.tick()
    for _ in range(4):
        mapper.tick()
    if voxel is not None:
        voxel.tick()

    occ = np.asarray(G.to_occupancy(cfg.grid, mapper.merged_grid()))
    summary = {
        "replayed": n,
        "bag": args.replay,
        "robots": args.robots,
        "cells_free": int((occ == 0).sum()),
        "cells_occupied": int((occ == 100).sum()),
        "scans_fused": int(mapper.n_scans_fused),
        "scans_dropped_unpaired": int(mapper.n_scans_dropped_unpaired),
    }
    if voxel is not None:
        from jax_mapping.ops import voxel as VX
        occ3 = np.asarray(VX.to_occupancy(cfg.voxel, voxel.voxel_grid()))
        summary["voxels_occupied"] = int((occ3 == 100).sum())
        summary["voxels_free"] = int((occ3 == 0).sum())
        summary["depth_images_fused"] = int(voxel.n_images_fused)
    print(json.dumps(summary, indent=2))
    if args.out:
        _write_png(args.out, occ)
    if args.voxel_out and voxel is not None:
        from jax_mapping.bridge.png import encode_gray
        with open(args.voxel_out, "wb") as f:
            f.write(encode_gray(voxel.height_map_image()))
        print(f"voxel height map written to {args.voxel_out}",
              file=sys.stderr)
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    args.robots = max(1, args.robots)

    # The operator guard (VERDICT r3 weak #1): under this image's ambient
    # env a wedged TPU tunnel hangs backend init forever; probe first and
    # restart on virtual CPU if so. The re-enter argv is built explicitly
    # so a programmatic main(argv) caller's sys.argv is never replayed.
    from jax_mapping.utils.backend_guard import ensure_responsive_backend
    ensure_responsive_backend(
        "jax_mapping.demo",
        argv=["-m", "jax_mapping.demo"]
             + (list(argv) if argv is not None else sys.argv[1:]))

    import numpy as np

    from jax_mapping.bridge.launch import launch_sim_stack
    from jax_mapping.config import SlamConfig, tiny_config
    from jax_mapping.sim import world as W

    if args.config:
        with open(args.config) as f:
            cfg = SlamConfig.from_json(f.read())
    else:
        cfg = tiny_config(n_robots=args.robots)
    if args.localization:
        cfg = cfg.replace(mode="localization")

    if args.voxel_out and not args.depth_cam and not args.replay:
        print("error: --voxel-out requires --depth-cam (or --replay of a "
              "bag recorded with it)", file=sys.stderr)
        return 2

    if args.replay:
        clash = [f for f in ("record", "save_final", "resume", "serve",
                             "depth_cam")
                 if getattr(args, f)]
        if clash:
            flags = ", ".join("--" + f.replace("_", "-") for f in clash)
            print(f"error: --replay cannot be combined with {flags}",
                  file=sys.stderr)
            return 2
        return _replay_main(args, cfg)

    if args.world == "arena":
        world = W.empty_arena(args.world_cells, cfg.grid.resolution_m)
    else:
        world = W.rooms_world(args.world_cells, cfg.grid.resolution_m,
                              seed=args.seed)

    port = args.http_port if args.http_port is not None else (
        0 if args.serve else None)
    stack = launch_sim_stack(cfg, world, n_robots=args.robots,
                             http_port=port, drop_prob=args.drop_prob,
                             seed=args.seed, depth_cam=args.depth_cam)
    recorder = None
    try:
        if args.record:
            from jax_mapping.bridge.brain import robot_ns
            from jax_mapping.io.trace import TraceRecorder
            topics = []
            for i in range(args.robots):
                ns = robot_ns(i, args.robots)
                topics += [f"{ns}scan", f"{ns}odom"]
                if args.depth_cam:
                    topics.append(f"{ns}depth")
            recorder = TraceRecorder(stack.bus, topics)

        if args.map_prior:
            if args.resume:
                # restore_states would install the checkpoint's grid over
                # the just-seeded prior — refusing beats silently telling
                # the user the prior is active when it is not.
                print("demo: --map-prior and --resume both set a map; "
                      "pick one (a checkpoint already contains its grid)")
                return 2
            from jax_mapping.io import rosmap
            try:
                n_occ = rosmap.seed_mapper(stack.mapper, args.map_prior,
                                           cfg.grid)
            except rosmap.SEED_ERRORS as e:
                # Same polite-refusal contract as --resume: bad input is
                # an rc=2 message, not a traceback.
                print(f"demo: cannot seed --map-prior "
                      f"{args.map_prior}: {e}")
                return 2
            print(f"demo: seeded map prior from {args.map_prior} "
                  f"({n_occ} occupied cells)")

        if args.resume:
            from jax_mapping.io.checkpoint import load_checkpoint
            from jax_mapping.models import slam as S
            template = [S.init_state(cfg) for _ in stack.mapper.states]
            try:
                states, ckpt_cfg = load_checkpoint(args.resume, template)
            except FileNotFoundError:
                print(f"error: no checkpoint at {args.resume}",
                      file=sys.stderr)
                return 2
            except ValueError as e:
                # Wrong robot count / config shape drift raises before the
                # config comparison below can explain it politely.
                print(f"error: cannot resume from {args.resume}: {e}",
                      file=sys.stderr)
                return 2
            from jax_mapping.config import configs_equivalent
            if ckpt_cfg is not None and \
                    not configs_equivalent(ckpt_cfg, cfg.to_json()):
                print("error: checkpoint config differs from the running "
                      "config; pass the matching --config", file=sys.stderr)
                return 2
            from jax_mapping.io.checkpoint import load_prior_sidecar
            from jax_mapping.ops import grid as _G
            try:
                ckpt_prior = load_prior_sidecar(
                    args.resume, _G.empty_grid(cfg.grid),
                    running_config_json=cfg.to_json())
            except ValueError as e:
                print(f"error: cannot resume map prior: {e}",
                      file=sys.stderr)
                return 2
            # Anchor at the relaunched sim's ACTUAL spawn poses: the map
            # is inherited, but robots respawned — fusing at the stale
            # checkpoint poses would draw the spawn surroundings into the
            # wrong part of the map (mapper.restore_states docstring).
            stack.mapper.restore_states(states,
                                        anchor_poses=stack.brain.poses,
                                        map_prior=ckpt_prior)
            print(f"resumed {len(states)} robot state(s) from "
                  f"{args.resume}", file=sys.stderr)
            if stack.voxel_mapper is not None:
                from jax_mapping.io.checkpoint import load_voxel_sidecar
                try:
                    vgrid = load_voxel_sidecar(
                        args.resume, stack.voxel_mapper.snapshot_grid(),
                        running_config_json=cfg.to_json())
                except ValueError as e:
                    print(f"error: cannot resume 3D map: {e}",
                          file=sys.stderr)
                    return 2
                if vgrid is not None:
                    stack.voxel_mapper.restore_grid(vgrid)
                    print("resumed 3D voxel map from the checkpoint "
                          "sidecar", file=sys.stderr)

        stack.brain.start_exploring()
        t0 = time.time()
        report_every = max(1, args.steps // 5)
        for step in range(args.steps):
            stack.run_steps(1)
            if (step + 1) % report_every == 0:
                occ = _occupancy(stack)
                n_free = int((occ == 0).sum())
                n_occ = int((occ == 100).sum())
                print(f"step {step + 1}/{args.steps}: "
                      f"{n_free} free / {n_occ} occupied cells mapped",
                      file=sys.stderr)
        wall = time.time() - t0

        occ = _occupancy(stack)
        summary = {
            "steps": args.steps,
            "robots": args.robots,
            "wall_s": round(wall, 2),
            "steps_per_sec": round(args.steps / max(wall, 1e-9), 1),
            "cells_free": int((occ == 0).sum()),
            "cells_occupied": int((occ == 100).sum()),
            "brain": stack.brain.status(),
        }
        if args.depth_cam and stack.voxel_mapper is not None:
            from jax_mapping.ops import voxel as VX
            occ3 = np.asarray(VX.to_occupancy(
                cfg.voxel, stack.voxel_mapper.voxel_grid()))
            summary["voxels_occupied"] = int((occ3 == 100).sum())
            summary["voxels_free"] = int((occ3 == 0).sum())
            summary["depth_images_fused"] = int(
                stack.voxel_mapper.n_images_fused)
        if stack.api is not None:
            summary["http"] = f"http://127.0.0.1:{stack.api.port}"
        print(json.dumps(summary, indent=2))

        if args.record and recorder is not None:
            recorder.stop()
            n_rec = recorder.save(args.record, config_json=cfg.to_json())
            print(f"recorded {n_rec} messages to {args.record}",
                  file=sys.stderr)

        if args.out:
            _write_png(args.out, occ)

        if args.voxel_out and stack.voxel_mapper is not None:
            from jax_mapping.bridge.png import encode_gray
            with open(args.voxel_out, "wb") as f:
                f.write(encode_gray(stack.voxel_mapper.height_map_image()))
            print(f"voxel height map written to {args.voxel_out}",
                  file=sys.stderr)

        if args.save_final:
            from jax_mapping.io.checkpoint import save_checkpoint
            save_checkpoint(args.save_final, stack.mapper.snapshot_states(),
                            config_json=cfg.to_json())
            print(f"checkpoint written to {args.save_final}",
                  file=sys.stderr)
            prior = stack.mapper.map_prior()
            from jax_mapping.io.checkpoint import (clear_prior_sidecar,
                                                   save_prior_sidecar)
            if prior is not None:
                pp = save_prior_sidecar(args.save_final, prior,
                                        config_json=cfg.to_json())
                print(f"map-prior sidecar written to {pp}",
                      file=sys.stderr)
            else:
                # Remove a stale sidecar from an earlier save under this
                # name — it would resurrect the old prior on resume.
                # (Sentinel-checked: never deletes a non-sidecar file.)
                clear_prior_sidecar(args.save_final)
            if stack.voxel_mapper is not None:
                from jax_mapping.io.checkpoint import (
                    save_keyframe_sidecar, save_voxel_sidecar)
                try:
                    vp = save_voxel_sidecar(
                        args.save_final,
                        stack.voxel_mapper.snapshot_grid(),
                        config_json=cfg.to_json())
                    # Keyframe ring too: demo --resume re-anchors (fresh
                    # chains) and ignores it, but HTTP /load of the same
                    # file restores post-load closure repair from it.
                    save_keyframe_sidecar(
                        args.save_final,
                        stack.voxel_mapper.snapshot_keyframes(),
                        config_json=cfg.to_json())
                    print(f"3D voxel checkpoint written to {vp}",
                          file=sys.stderr)
                except ValueError as e:
                    print(f"error: 3D checkpoint not written: {e}",
                          file=sys.stderr)

        if args.serve and stack.api is not None:
            print(f"serving on http://127.0.0.1:{stack.api.port} — Ctrl-C "
                  f"to exit", file=sys.stderr)
            try:
                while True:
                    time.sleep(1.0)
            except KeyboardInterrupt:
                pass
        return 0
    finally:
        stack.shutdown()


if __name__ == "__main__":
    sys.exit(main())
