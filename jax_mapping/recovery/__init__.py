"""Estimator guardrails: divergence watchdog, relocalization, anti-stuck.

The subsystem ISSUE 3 adds above PR 2's process-level resilience: the
resilience/ layer notices when a node or sensor DIES; this layer notices
when the ESTIMATOR goes wrong while everything keeps running — the
reference's "Failure detection / recovery" gap and Occupancy-SLAM's core
argument that pose error, not process death, is what destroys occupancy
maps (PAPERS.md).

* `watchdog`    — EstimatorWatchdog: per-robot health score with
                  hysteresis over the SlamDiag stream; declares the
                  ESTIMATOR_DIVERGED rung in FleetHealth's ladder.
* `relocalize`  — wide-window relocalization against the shared map
                  (the loop-closure sweep machinery, repurposed) with
                  consecutive-consistency verification before re-entry.
* `antistuck`   — AntiStuckLadder + FrontierBlacklist: displacement-vs-
                  commanded-motion stuck detection feeding escalating
                  recoveries (rotate rescan -> backup -> frontier
                  blacklist with TTL -> goal reassignment).
* `manager`     — RecoveryManager, the one handle launch wires through
                  brain/mapper/HTTP (the FleetHealth pattern).

Everything is host-side, deterministic, and gated on
`RecoveryConfig.enabled` — disabled, the stack behaves exactly as
before this subsystem existed.
"""

from jax_mapping.recovery.antistuck import (  # noqa: F401
    MONITOR, ROTATE, BACKUP, RUNGS, AntiStuckLadder, FrontierBlacklist,
)
from jax_mapping.recovery.manager import RecoveryManager  # noqa: F401
from jax_mapping.recovery.relocalize import (  # noqa: F401
    Relocalizer, relocalize_match,
)
from jax_mapping.recovery.watchdog import (  # noqa: F401
    DIVERGED, HEALTHY, EstimatorWatchdog,
)
