"""Divergence watchdog: per-robot estimator health from the SlamDiag stream.

The per-step `SlamDiag` (models/slam.py) already carries everything an
estimator-health monitor needs — match acceptance, match response, the
pre-fusion window agreement, the correlation-surface covariance — but
until now the mapper only counted the worst of it (low-agreement
telemetry). This watchdog folds the stream into one per-robot score with
hysteresis, so a robot whose scan-matcher quietly diverges (wheel-slip
odometry bias, a miscalibrated lidar, ghost returns) is DECLARED lost
instead of silently fusing garbage into the fleet's shared map.

Score: an EWMA of per-observation "badness"

    bad = agreement_weight * min(1, (1 - agreement) / deficit_scale)
        + match_weight     * (1 - matched)     [key steps only]
        + cov_weight       * min(1, cov_trace / cov_scale_m2)

observed at FULL SCAN CADENCE: key steps carry the SlamDiag's pre-fusion
agreement plus match/covariance telemetry; sub-gate steps sample
`models.slam.scan_agreement` (a ghosting sensor fires every scan, not
every 0.1 m of travel — key-step-only observation would leave a short
fault window invisible). The agreement deficit normalizes by
`agreement_deficit_scale`: healthy scans sit at 1.0 with ~0.05 jitter,
adversarial scans measure 0.25-0.4 below — the scale maps that gap onto
[0, 1] so the threshold has margin on both sides. The match term is
charged only after `min_keyscans` KEY observations (with an empty map
the matcher legitimately rejects — bootstrap must not read as
divergence). Rejected low-agreement steps feed a full-badness
observation: repeated garbage is exactly the streak the score exists to
catch.

Hysteresis: `diverge_persist_steps` consecutive observations at or above
`diverge_threshold` declare DIVERGED. There is NO score-based exit: a
quarantined robot produces no fresh diag (its steps are buffered, not
run), so re-admission happens only through a verified relocalization
re-anchor (`readmit`) — the asymmetry is the point, one lucky match must
not end a quarantine.

Threading: a LEAF lock like FleetHealth (methods never call out while
holding it); fed by the mapper's tick thread, read by HTTP exporters.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from jax_mapping.config import RecoveryConfig

#: Watchdog states (per robot).
HEALTHY = "healthy"
DIVERGED = "diverged"


class EstimatorWatchdog:
    """Fold SlamDiag observations into per-robot divergence state."""

    def __init__(self, cfg: RecoveryConfig, n_robots: int):
        self.cfg = cfg
        self.n_robots = n_robots
        self._lock = threading.Lock()
        self._score = [0.0] * n_robots
        self._streak = [0] * n_robots          # consecutive over-threshold
        self._n_obs = [0] * n_robots
        self._n_key_obs = [0] * n_robots       # match-term grace clock
        self._state = [HEALTHY] * n_robots
        #: (n_obs at transition, robot, old, new) — the assertion surface
        #: for guardrail tests, mirroring FleetHealth.transitions.
        self.transitions: List[tuple] = []
        self.n_diverge_events = 0
        self.n_readmits = 0

    def observe(self, robot: int, key: bool, matched: bool,
                agreement: float,
                cov_trace: Optional[float] = None) -> bool:
        """One per-scan observation; returns True when this observation
        DECLARES divergence (the caller then quarantines + notifies
        FleetHealth). `key` says a match actually ran this step (the
        match term is only meaningful there); cov_trace None = no
        accepted match (the covariance carries no information; the
        match term already charges for the rejection)."""
        c = self.cfg
        deficit = 1.0 - min(1.0, max(0.0, agreement))
        scale = max(c.agreement_deficit_scale, 1e-6)
        bad = c.agreement_weight * min(1.0, deficit / scale)
        with self._lock:
            self._n_obs[robot] += 1
            if key:
                self._n_key_obs[robot] += 1
                if not matched \
                        and self._n_key_obs[robot] > c.min_keyscans:
                    bad += c.match_weight
                if matched and cov_trace is not None \
                        and c.cov_scale_m2 > 0.0:
                    bad += c.cov_weight * min(1.0,
                                              cov_trace / c.cov_scale_m2)
            self._score[robot] = (c.score_decay * self._score[robot]
                                  + (1.0 - c.score_decay) * bad)
            if self._state[robot] == DIVERGED:
                return False
            if self._score[robot] >= c.diverge_threshold:
                self._streak[robot] += 1
            else:
                self._streak[robot] = 0
            if self._streak[robot] >= c.diverge_persist_steps:
                self._state[robot] = DIVERGED
                self.n_diverge_events += 1
                self.transitions.append(
                    (self._n_obs[robot], robot, HEALTHY, DIVERGED))
                return True
            return False

    def observe_rejected(self, robot: int) -> bool:
        """A step the mapper rejected outright (the low-agreement
        do-no-harm floor): maximum badness — the evidence was garbage by
        the mapper's own judgement."""
        return self.observe(robot, key=True, matched=False,
                            agreement=0.0)

    def readmit(self, robot: int) -> None:
        """Verified re-anchor: back to HEALTHY with a clean score (the
        old score described the pre-relocalization chain)."""
        readmitted = False
        with self._lock:
            if self._state[robot] == DIVERGED:
                self.n_readmits += 1
                readmitted = True
                self.transitions.append(
                    (self._n_obs[robot], robot, DIVERGED, HEALTHY))
            self._state[robot] = HEALTHY
            self._score[robot] = 0.0
            self._streak[robot] = 0
        if readmitted:
            # Recorded AFTER the lock releases (leaf-lock discipline):
            # the DIVERGED->HEALTHY edge closes the story the
            # divergence dump opened — a postmortem reads declaration,
            # relocalization and readmit as one stream.
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("watchdog_readmit", robot=robot)

    # -- readers -------------------------------------------------------------

    def is_diverged(self, robot: int) -> bool:
        with self._lock:
            return self._state[robot] == DIVERGED

    def states(self) -> List[str]:
        with self._lock:
            return list(self._state)

    def scores(self) -> List[float]:
        with self._lock:
            return list(self._score)

    def snapshot(self) -> dict:
        """The /status export."""
        with self._lock:
            return {
                "states": list(self._state),
                "scores": [round(s, 4) for s in self._score],
                "n_observations": list(self._n_obs),
                "n_diverge_events": self.n_diverge_events,
                "n_readmits": self.n_readmits,
            }
