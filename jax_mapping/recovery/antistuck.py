"""Anti-stuck recovery ladder: detect wedged/oscillating explorers, escalate.

The reference's subsumption navigator can wedge forever: an IR pivot in
a tight corner flips left/right each tick, the lidar swerve orbits a
concave wall — commanded motion, zero displacement, mission clock
burning (the report's untested "robustness" §V.C). The detector is
exactly that signature: over a sliding window of control ticks the
robot was COMMANDED motion for most of them yet its odometric
displacement reached only a small fraction of the distance those
commands should have produced (commanded wheel speed x speed_coeff x
dt, summed over the window). The COMMANDED-RELATIVE floor matters: an
absolute one would misread a slow-but-healthy platform as stuck — a
cruising Thymio covers just ~0.036 m per 12 control ticks.

Division of labor with the watchdog: wheels SPINNING IN PLACE (high-
centered, slipping) are invisible here by construction — the encoders
feed the phantom motion straight into odometry, so displacement tracks
the commands. That fault surfaces as ESTIMATOR DIVERGENCE instead (the
map stops agreeing with the odometric pose chain), which is the
divergence watchdog's case (recovery/watchdog.py). This ladder owns
the complementary signature: the policy commands motion and odometry
CONFIRMS none happened.

On detection the ladder escalates through recoveries, each a bounded
open-loop maneuver the brain executes INSTEAD of the policy output
(below the manual-teleop override, above the policy; never during an IR
emergency — the shield stays the last word on contact safety):

    rung 0  rotate-in-place rescan (fresh geometry for the matcher and
            the frontier auction; breaks swerve-oscillation symmetry)
    rung 1  backup (reverse out of the wedge)
    rung 2  blacklist the robot's current frontier goal for
            `blacklist_ttl_ticks` and force reassignment (the goal
            itself is unreachable-in-practice); a manual nav goal is
            cancelled instead (the operator's goal is the thing the
            robot cannot reach)

A re-detection within `escalation_memory_ticks` of finishing a rung
escalates to the next; a clean stretch resets to rung 0. All clocks are
CONTROL TICKS (the repo's deterministic TTL doctrine).

Threading: leaf locks, fed by the brain's tick thread; the blacklist is
additionally read by the mapper's frontier post-pass and ticked by the
brain (one monotone clock, so faster-than-realtime runs escalate
identically).
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from jax_mapping.config import RecoveryConfig

#: Ladder states (per robot).
MONITOR = "monitor"
ROTATE = "rotate"
BACKUP = "backup"

#: Rung order; rung index 2 is the blacklist escalation (no maneuver —
#: it fires once and drops back to MONITOR).
RUNGS = ("rotate", "backup", "blacklist")


class FrontierBlacklist:
    """(robot, target) entries with TTL, on the brain's control-tick
    clock. The mapper's frontier post-pass strips assignments that land
    within `tol_m` of a live entry for that robot and hands them to a
    healthy robot (mapper._reassign_dead's machinery)."""

    def __init__(self, cfg: RecoveryConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        #: (robot, x, y, expire_tick)
        self._entries: List[tuple] = []
        self._now = 0
        self.n_blacklisted = 0

    def note_tick(self, tick: int) -> None:
        with self._lock:
            self._now = max(self._now, tick)
            self._entries = [e for e in self._entries
                             if e[3] > self._now]

    def add(self, robot: int, xy: Tuple[float, float],
            dedup_tol_m: float = 0.05) -> None:
        with self._lock:
            exp = self._now + self.cfg.blacklist_ttl_ticks
            for k, (r, x, y, _e) in enumerate(self._entries):
                if r == robot and math.hypot(xy[0] - x,
                                             xy[1] - y) <= dedup_tol_m:
                    # Same goal re-blacklisted (e.g. the auction has no
                    # alternative frontier to redirect to): refresh the
                    # TTL instead of stacking duplicates.
                    self._entries[k] = (r, x, y, exp)
                    return
            self._entries.append((robot, float(xy[0]), float(xy[1]), exp))
            self.n_blacklisted += 1

    def is_blacklisted(self, robot: int, xy, tol_m: float) -> bool:
        with self._lock:
            for r, x, y, exp in self._entries:
                if r == robot and exp > self._now \
                        and math.hypot(xy[0] - x, xy[1] - y) <= tol_m:
                    return True
            return False

    def entries(self) -> List[tuple]:
        with self._lock:
            return list(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "n_blacklisted": self.n_blacklisted,
                "live_entries": [
                    {"robot": r, "x": round(x, 3), "y": round(y, 3),
                     "expires_tick": exp}
                    for r, x, y, exp in self._entries
                    if exp > self._now],
            }


class AntiStuckLadder:
    """Sliding-window stuck detector + escalating recovery executor."""

    def __init__(self, cfg: RecoveryConfig, n_robots: int,
                 rotation_units: int = 50, cruise_units: int = 100,
                 m_per_unit_tick: float = 3.027e-5):
        self.cfg = cfg
        self.n_robots = n_robots
        #: Maneuver magnitudes, from RobotConfig at wiring time (launch)
        #: so recoveries move at the platform's own speeds.
        self._rotation_units = int(rotation_units)
        self._cruise_units = int(cruise_units)
        #: Metres one wheel unit commands in one control tick
        #: (speed_coeff_m_per_unit_s / control_rate_hz) — converts the
        #: window's commanded wheel speeds into the displacement they
        #: SHOULD have produced. Default: the Thymio at 10 Hz.
        self._m_per_unit_tick = float(m_per_unit_tick)
        # Re-entrant: step() holds it across the per-robot loop and the
        # rung helpers re-acquire for their own writes (the bridge
        # Node._cb_lock pattern), so the lock discipline is explicit at
        # every mutation site.
        self._lock = threading.RLock()
        #: Per-robot window of (pose_xy, commanded) samples, newest last.
        self._window: List[List[tuple]] = [[] for _ in range(n_robots)]
        self._mode = [MONITOR] * n_robots
        self._mode_ticks_left = [0] * n_robots
        #: Next rung to run on re-detection (escalation level).
        self._rung = [0] * n_robots
        #: Tick the last recovery finished (escalation-memory clock).
        self._last_recovery_end = [-10**9] * n_robots
        #: (tick, robot, event) log — the ladder's assertion surface.
        self.events: List[tuple] = []
        self.n_stuck_detections = 0
        self.n_recoveries: Dict[str, int] = {r: 0 for r in RUNGS}

    # -- the per-tick hook (brain.update_loop) ------------------------------

    def step(self, tick: int, poses: np.ndarray, targets: np.ndarray,
             active: np.ndarray) -> Tuple[Dict[int, tuple], List[int]]:
        """One control tick for the whole fleet.

        poses (R, 3) odometry estimates; targets (R, 2) the wheel
        targets the policy just computed; active (R,) bool — robots
        eligible for detection/recovery (exploring, not coasting, not
        under manual drive, not in an IR emergency).

        Returns (overrides, blacklist_requests): overrides maps robot ->
        (left, right) wheel targets replacing the policy output this
        tick; blacklist_requests lists robots whose current goal the
        caller must blacklist/cancel (the brain owns goals and the
        freshest /frontiers assignment, so the rung only REQUESTS)."""
        c = self.cfg
        overrides: Dict[int, tuple] = {}
        blacklist: List[int] = []
        with self._lock:
            for i in range(min(self.n_robots, len(poses))):
                if not active[i]:
                    # Ineligible: recovery aborts (coast/manual outrank
                    # it) and the window restarts — coasting ticks must
                    # not read as "commanded but motionless".
                    if self._mode[i] != MONITOR:
                        self._end_recovery(i, tick, aborted=True)
                    self._window[i].clear()
                    continue
                if self._mode[i] != MONITOR:
                    overrides[i] = self._recovery_targets(i)
                    self._mode_ticks_left[i] -= 1
                    if self._mode_ticks_left[i] <= 0:
                        self._end_recovery(i, tick)
                    continue
                cmd = (abs(float(targets[i, 0]))
                       + abs(float(targets[i, 1]))) / 2.0
                self._window[i].append(
                    ((float(poses[i, 0]), float(poses[i, 1])), cmd))
                if len(self._window[i]) > c.stuck_window_ticks:
                    self._window[i].pop(0)
                if self._detect(i):
                    self.n_stuck_detections += 1
                    rung = self._rung[i]
                    if tick - self._last_recovery_end[i] \
                            > c.escalation_memory_ticks:
                        rung = 0        # clean stretch: restart ladder
                    self._start_rung(i, rung, tick)
                    if RUNGS[rung] == "blacklist":
                        blacklist.append(i)
                        self._end_recovery(i, tick)
                    else:
                        # The detection tick is the maneuver's first
                        # tick (override applied AND counted).
                        overrides[i] = self._recovery_targets(i)
                        self._mode_ticks_left[i] -= 1
        return overrides, blacklist

    # -- internals (caller holds the lock) ----------------------------------

    def _detect(self, i: int) -> bool:
        c = self.cfg
        w = self._window[i]
        if len(w) < c.stuck_window_ticks:
            return False
        n_commanded = sum(1 for _, cm in w if cm >= c.min_commanded_units)
        if n_commanded < c.stuck_commanded_frac * len(w):
            return False
        # Distance the window's commands SHOULD have produced vs what
        # odometry actually saw.
        commanded_m = sum(cm for _, cm in w) * self._m_per_unit_tick
        (x0, y0), _ = w[0]
        (x1, y1), _ = w[-1]
        return math.hypot(x1 - x0, y1 - y0) \
            < c.stuck_displacement_frac * commanded_m

    def _start_rung(self, i: int, rung: int, tick: int) -> None:
        with self._lock:
            name = RUNGS[rung]
            self.n_recoveries[name] += 1
            self.events.append((tick, i, f"stuck:rung={name}"))
            self._rung[i] = min(rung + 1, len(RUNGS) - 1)
            self._window[i].clear()
            if name == "rotate":
                self._mode[i] = ROTATE
                self._mode_ticks_left[i] = self.cfg.rotate_recovery_ticks
            elif name == "backup":
                self._mode[i] = BACKUP
                self._mode_ticks_left[i] = self.cfg.backup_recovery_ticks

    def _end_recovery(self, i: int, tick: int, aborted: bool = False
                      ) -> None:
        with self._lock:
            if self._mode[i] != MONITOR or not aborted:
                self.events.append(
                    (tick, i, "recovery_aborted" if aborted
                     else "recovery_done"))
            self._mode[i] = MONITOR
            self._mode_ticks_left[i] = 0
            self._last_recovery_end[i] = tick
            self._window[i].clear()

    def _recovery_targets(self, i: int) -> tuple:
        # Open-loop maneuvers in thymio wheel units; the brain clamps to
        # the motor range with everything else.
        if self._mode[i] == ROTATE:
            return (self._rotation_units, -self._rotation_units)
        return (-self._cruise_units, -self._cruise_units)   # backup

    # -- readers -------------------------------------------------------------

    def modes(self) -> List[str]:
        with self._lock:
            return list(self._mode)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "modes": list(self._mode),
                "rungs": list(self._rung),
                "n_stuck_detections": self.n_stuck_detections,
                "n_recoveries": dict(self.n_recoveries),
                "n_events": len(self.events),
            }
