"""Wide-window relocalization for quarantined robots.

A diverged robot's pose estimate is exactly what cannot be trusted, so
re-admission must come from the MAP, not the chain: each quarantined
scan is matched against the fleet's shared grid through the same
two-stage wide machinery loop closure uses (models/slam._loop_wide_cfgs:
a coarse sweep of the full loop window on a downsampled view, then a
fine full-resolution refine) — slam_toolbox's 8 m loop search window
repurposed as a relocalization basin, seeded at the last estimate (the
robot COASTS while diverged, so the true pose sits within the fault
window's accumulated error of the seed).

Verification: one accepted match is a basin, not an anchor — ghost walls
and corridor aliases produce legitimate-looking responses. A re-anchor
is VERIFIED only when `reloc_consecutive` consecutive scans accept with
response >= `reloc_min_response` AND their candidate poses agree within
the consistency radii. Any miss resets the streak.
"""

from __future__ import annotations

import functools
import math
import threading
from typing import Callable, List, Optional

import jax
import numpy as np

from jax_mapping.config import RecoveryConfig, SlamConfig
from jax_mapping.models.slam import _loop_matcher_cfg, _loop_wide_cfgs
from jax_mapping.ops import grid as G
from jax_mapping.ops import pyramid as PYR
from jax_mapping.ops import scan_match as M
from jax_mapping.utils import global_metrics as GM

Array = jax.Array


@functools.partial(jax.jit, static_argnums=(0,))
def relocalize_match(cfg: SlamConfig, grid: Array, ranges: Array,
                     guess: Array) -> M.MatchResult:
    """One wide-window relocalization attempt against the live shared
    map. Unlike loop verification this matches the LIVE grid — sound
    here because the diverged robot's garbage was quarantined, never
    fused, so the map holds only healthy evidence."""
    import jax.numpy as jnp
    g_c, m_c = _loop_wide_cfgs(cfg)
    wide = M.match(g_c, cfg.scan, m_c,
                   G.downsample_max(grid, cfg.loop.coarse_downsample),
                   ranges, guess)
    seed = jnp.where(wide.accepted, wide.pose, guess)
    return M.match(cfg.grid, cfg.scan, _loop_matcher_cfg(cfg), grid,
                   ranges, seed)


@functools.partial(jax.jit, static_argnums=(0, 1))
def _build_wide_pyramid(cfg: SlamConfig, n_levels: int, grid: Array,
                        origin_c: Array):
    """Wide-stage pyramid for one coarse-patch region, ONE jitted
    dispatch: the loop-window downsample + patch + likelihood field +
    max-pyramid. Caching this entry is what makes steady-state
    relocalization cheap — the 4096^2 downsample_max alone is real work
    to repeat every tick against an unchanged region."""
    import jax.numpy as jnp          # noqa: F401  (jit body convention)
    g_c, m_c = _loop_wide_cfgs(cfg)
    coarse = G.downsample_max(grid, cfg.loop.coarse_downsample)
    patch = jax.lax.dynamic_slice(
        coarse, (origin_c[0], origin_c[1]),
        (g_c.patch_cells, g_c.patch_cells))
    field = M.likelihood_field(g_c, m_c, patch)
    stride, n_steps = M.window_params(g_c, m_c)
    return M.build_levels(field, n_steps, stride, n_levels)


def _wrap(a: float) -> float:
    return (a + math.pi) % (2.0 * math.pi) - math.pi


class Relocalizer:
    """Per-robot candidate streak bookkeeping around relocalize_match.

    Host-side and deterministic; fed by the mapper's tick thread only,
    read by HTTP exporters (leaf lock)."""

    def __init__(self, cfg: RecoveryConfig, n_robots: int,
                 pyramid_cache: Optional[PYR.PyramidCache] = None):
        self.cfg = cfg
        self._lock = threading.Lock()
        #: Per-robot streak of consistent accepted candidates,
        #: newest last: list of (x, y, theta).
        self._streak: List[List[tuple]] = [[] for _ in range(n_robots)]
        #: Revision-keyed pyramid cache (ops/pyramid.py) for the pruned
        #: wide+fine stages: a quarantined robot re-attempts against the
        #: same map region every tick, and the region only changes when
        #: some OTHER robot fuses nearby — steady state is all hits.
        self.pyramid_cache = pyramid_cache or PYR.PyramidCache()
        self.n_attempts = 0
        self.n_accepted = 0
        self.n_verified = 0

    # -- pruned + cached matching ------------------------------------------

    def _stage_match(self, g_cfg, scan_cfg, m_cfg, n_levels, levels,
                     origin, ranges, guess) -> M.MatchResult:
        """One pruned stage through the coarse/refine split, timed as
        the `jax_mapping_stage_match_*` spans (forcing fetches end each
        span so it measures device work, not the enqueue — the mapper
        stage-timer convention)."""
        with GM.stages.stage("match.coarse_score"):
            resp_top, rasters_c, mass_ref = M.pyramid_coarse_scores(
                g_cfg, scan_cfg, m_cfg, n_levels, levels, origin, ranges,
                guess)
            jax.block_until_ready(resp_top)
        with GM.stages.stage("match.refine"):
            res = M.pyramid_refine(g_cfg, scan_cfg, m_cfg, n_levels,
                                   resp_top, levels, origin, ranges,
                                   rasters_c, mass_ref, guess)
            jax.block_until_ready(res.pose)
        return res

    def _cached_pyramid(self, key: tuple, revision: Optional[int],
                        build: Callable) -> tuple:
        def timed_build():
            with GM.stages.stage("match.pyramid_build"):
                levels = build()
                jax.block_until_ready(levels[-1])
            GM.counters.inc("match.pyramid_builds")
            return levels
        return self.pyramid_cache.get(key, revision, timed_build)

    def _match_pruned(self, cfg: SlamConfig, grid, ranges, guess,
                      region_rev_fn, grid_revision=None) -> M.MatchResult:
        """`relocalize_match` semantics through the cached pyramids: the
        wide basin sweep on the downsampled view, then the fine
        full-resolution refine, each stage's pyramid keyed on its patch
        region's revision. `region_rev_fn(row0, col0, span_cells) ->
        Optional[int]` is the mapper's dirty-tile revision probe; None
        (no serving/revision tracking) still prunes, just without
        reuse. `grid_revision` is the map revision AT the caller's grid
        snapshot: a region revision NEWER than it means a mutation
        landed between the snapshot and the probe, and caching a
        pyramid built from the older snapshot at the newer revision
        would serve stale data as current forever (the
        read-revision-BEFORE-content ordering hazard PR 4 fixed in the
        voxel serving snapshot) — such builds are not cached."""
        import jax.numpy as jnp

        def fresh(rev):
            if rev is None or (grid_revision is not None
                               and rev > grid_revision):
                return None
            return rev

        g_c, m_c = _loop_wide_cfgs(cfg)
        f = cfg.loop.coarse_downsample
        guess = np.asarray(guess, np.float32)
        _, n_c = M.window_params(g_c, m_c)
        lv_c = M.bnb_num_levels(m_c, n_c)
        m_f = _loop_matcher_cfg(cfg)
        _, n_f = M.window_params(cfg.grid, m_f)
        lv_f = M.bnb_num_levels(m_f, n_f)
        if lv_c == 0 or lv_f == 0:
            # Window too small to prune (exotic tiny configs): the
            # single-dispatch path already does the right thing.
            return relocalize_match(cfg, grid, jnp.asarray(ranges),
                                    jnp.asarray(guess))
        oc = PYR.patch_origin_host(g_c, guess[:2])
        rev_c = None if region_rev_fn is None else fresh(region_rev_fn(
            oc[0] * f, oc[1] * f, g_c.patch_cells * f))
        origin_c = jnp.asarray(np.asarray(oc, np.int32))
        levels_c = self._cached_pyramid(
            ("wide", oc[0], oc[1]), rev_c,
            lambda: _build_wide_pyramid(cfg, lv_c, grid, origin_c))
        wide = self._stage_match(g_c, cfg.scan, m_c, lv_c, levels_c,
                                 origin_c, jnp.asarray(ranges),
                                 jnp.asarray(guess))
        seed = (np.asarray(wide.pose, np.float32) if bool(wide.accepted)
                else guess)
        of = PYR.patch_origin_host(cfg.grid, seed[:2])
        rev_f = None if region_rev_fn is None else fresh(region_rev_fn(
            of[0], of[1], cfg.grid.patch_cells))
        origin_f = jnp.asarray(np.asarray(of, np.int32))
        levels_f = self._cached_pyramid(
            ("fine", of[0], of[1]), rev_f,
            lambda: PYR.build_match_pyramid(cfg.grid, m_f, lv_f, grid,
                                            origin_f))
        return self._stage_match(cfg.grid, cfg.scan, m_f, lv_f, levels_f,
                                 origin_f, jnp.asarray(ranges),
                                 jnp.asarray(seed))

    def attempt_for(self, robot: int, cfg: SlamConfig, grid, ranges,
                    guess, region_rev_fn=None,
                    grid_revision=None) -> Optional[np.ndarray]:
        """One attempt with robot `robot`'s freshest quarantined scan.
        Returns the VERIFIED re-anchor pose (3,) when the consistency
        streak completes, else None. The caller owns what happens next
        (fresh chain at the pose, watchdog readmit, FleetHealth clear).
        `grid_revision` = the map revision at the caller's `grid`
        snapshot (see `_match_pruned`: guards the pyramid cache against
        stamping a snapshot-built pyramid with a newer revision)."""
        import jax.numpy as jnp
        from jax_mapping.models.slam import scan_agreement
        if cfg.matcher.pruned:
            res = self._match_pruned(cfg, grid, ranges, guess,
                                     region_rev_fn, grid_revision)
        else:
            res = relocalize_match(cfg, grid, jnp.asarray(ranges),
                                   jnp.asarray(guess))
        accepted = bool(res.accepted)
        response = float(res.response)
        pose = np.asarray(res.pose, np.float32)
        c = self.cfg
        if accepted and response >= c.reloc_min_response:
            # Agreement gate at the CANDIDATE pose: the wide matcher can
            # find plausible basins even for a still-faulting sensor
            # (half the beams of a ghosting scan are real walls) — but
            # re-admitting one would resume fusing the same garbage the
            # watchdog just caught. A healthy scan at the true pose
            # clears this instantly; a faulting one waits out its fault.
            agreement = float(scan_agreement(cfg, grid,
                                             jnp.asarray(ranges),
                                             jnp.asarray(pose)))
            accepted = agreement >= c.reloc_min_agreement
        with self._lock:
            self.n_attempts += 1
            streak = self._streak[robot]
            if not (accepted and response >= c.reloc_min_response):
                streak.clear()
                return None
            self.n_accepted += 1
            # Consistency against the streak head: every candidate must
            # sit in the same basin as the first, or the streak restarts
            # from this candidate.
            if streak:
                x0, y0, t0 = streak[0]
                if (math.hypot(pose[0] - x0, pose[1] - y0)
                        > c.reloc_consistency_m
                        or abs(_wrap(float(pose[2]) - t0))
                        > c.reloc_consistency_rad):
                    streak.clear()
            streak.append((float(pose[0]), float(pose[1]),
                           float(pose[2])))
            if len(streak) < c.reloc_consecutive:
                return None
            self.n_verified += 1
            streak.clear()
            return pose

    def reset(self, robot: int) -> None:
        with self._lock:
            self._streak[robot].clear()

    def snapshot(self) -> dict:
        with self._lock:
            snap = {
                "n_attempts": self.n_attempts,
                "n_accepted": self.n_accepted,
                "n_verified": self.n_verified,
            }
        snap["pyramid_cache"] = self.pyramid_cache.snapshot()
        return snap
