"""Wide-window relocalization for quarantined robots.

A diverged robot's pose estimate is exactly what cannot be trusted, so
re-admission must come from the MAP, not the chain: each quarantined
scan is matched against the fleet's shared grid through the same
two-stage wide machinery loop closure uses (models/slam._loop_wide_cfgs:
a coarse sweep of the full loop window on a downsampled view, then a
fine full-resolution refine) — slam_toolbox's 8 m loop search window
repurposed as a relocalization basin, seeded at the last estimate (the
robot COASTS while diverged, so the true pose sits within the fault
window's accumulated error of the seed).

Verification: one accepted match is a basin, not an anchor — ghost walls
and corridor aliases produce legitimate-looking responses. A re-anchor
is VERIFIED only when `reloc_consecutive` consecutive scans accept with
response >= `reloc_min_response` AND their candidate poses agree within
the consistency radii. Any miss resets the streak.
"""

from __future__ import annotations

import functools
import math
import threading
from typing import List, Optional

import jax
import numpy as np

from jax_mapping.config import RecoveryConfig, SlamConfig
from jax_mapping.models.slam import _loop_matcher_cfg, _loop_wide_cfgs
from jax_mapping.ops import grid as G
from jax_mapping.ops import scan_match as M

Array = jax.Array


@functools.partial(jax.jit, static_argnums=(0,))
def relocalize_match(cfg: SlamConfig, grid: Array, ranges: Array,
                     guess: Array) -> M.MatchResult:
    """One wide-window relocalization attempt against the live shared
    map. Unlike loop verification this matches the LIVE grid — sound
    here because the diverged robot's garbage was quarantined, never
    fused, so the map holds only healthy evidence."""
    import jax.numpy as jnp
    g_c, m_c = _loop_wide_cfgs(cfg)
    wide = M.match(g_c, cfg.scan, m_c,
                   G.downsample_max(grid, cfg.loop.coarse_downsample),
                   ranges, guess)
    seed = jnp.where(wide.accepted, wide.pose, guess)
    return M.match(cfg.grid, cfg.scan, _loop_matcher_cfg(cfg), grid,
                   ranges, seed)


def _wrap(a: float) -> float:
    return (a + math.pi) % (2.0 * math.pi) - math.pi


class Relocalizer:
    """Per-robot candidate streak bookkeeping around relocalize_match.

    Host-side and deterministic; fed by the mapper's tick thread only,
    read by HTTP exporters (leaf lock)."""

    def __init__(self, cfg: RecoveryConfig, n_robots: int):
        self.cfg = cfg
        self._lock = threading.Lock()
        #: Per-robot streak of consistent accepted candidates,
        #: newest last: list of (x, y, theta).
        self._streak: List[List[tuple]] = [[] for _ in range(n_robots)]
        self.n_attempts = 0
        self.n_accepted = 0
        self.n_verified = 0

    def attempt_for(self, robot: int, cfg: SlamConfig, grid, ranges,
                    guess) -> Optional[np.ndarray]:
        """One attempt with robot `robot`'s freshest quarantined scan.
        Returns the VERIFIED re-anchor pose (3,) when the consistency
        streak completes, else None. The caller owns what happens next
        (fresh chain at the pose, watchdog readmit, FleetHealth
        clear)."""
        import jax.numpy as jnp
        from jax_mapping.models.slam import scan_agreement
        res = relocalize_match(cfg, grid, jnp.asarray(ranges),
                               jnp.asarray(guess))
        accepted = bool(res.accepted)
        response = float(res.response)
        pose = np.asarray(res.pose, np.float32)
        c = self.cfg
        if accepted and response >= c.reloc_min_response:
            # Agreement gate at the CANDIDATE pose: the wide matcher can
            # find plausible basins even for a still-faulting sensor
            # (half the beams of a ghosting scan are real walls) — but
            # re-admitting one would resume fusing the same garbage the
            # watchdog just caught. A healthy scan at the true pose
            # clears this instantly; a faulting one waits out its fault.
            agreement = float(scan_agreement(cfg, grid,
                                             jnp.asarray(ranges),
                                             jnp.asarray(pose)))
            accepted = agreement >= c.reloc_min_agreement
        with self._lock:
            self.n_attempts += 1
            streak = self._streak[robot]
            if not (accepted and response >= c.reloc_min_response):
                streak.clear()
                return None
            self.n_accepted += 1
            # Consistency against the streak head: every candidate must
            # sit in the same basin as the first, or the streak restarts
            # from this candidate.
            if streak:
                x0, y0, t0 = streak[0]
                if (math.hypot(pose[0] - x0, pose[1] - y0)
                        > c.reloc_consistency_m
                        or abs(_wrap(float(pose[2]) - t0))
                        > c.reloc_consistency_rad):
                    streak.clear()
            streak.append((float(pose[0]), float(pose[1]),
                           float(pose[2])))
            if len(streak) < c.reloc_consecutive:
                return None
            self.n_verified += 1
            streak.clear()
            return pose

    def reset(self, robot: int) -> None:
        with self._lock:
            self._streak[robot].clear()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "n_attempts": self.n_attempts,
                "n_accepted": self.n_accepted,
                "n_verified": self.n_verified,
            }
