"""RecoveryManager: one shared handle for the estimator guardrails.

The guardrails span three nodes — the mapper feeds the watchdog and runs
quarantine/relocalization, the brain runs the anti-stuck ladder and
advances the blacklist clock, the HTTP plane exports everything — so the
launch layer builds ONE manager and hands it to each of them, the same
wiring pattern as FleetHealth. `None` (recovery disabled) restores
pre-guardrail behavior exactly: every integration point gates on the
manager's presence.
"""

from __future__ import annotations

from typing import Optional

from jax_mapping.config import RecoveryConfig, RobotConfig
from jax_mapping.recovery.antistuck import AntiStuckLadder, FrontierBlacklist
from jax_mapping.recovery.relocalize import Relocalizer
from jax_mapping.recovery.watchdog import EstimatorWatchdog


class RecoveryManager:
    """Watchdog + relocalizer + anti-stuck ladder + blacklist, built
    together so their configs can never drift apart."""

    def __init__(self, cfg: RecoveryConfig, n_robots: int,
                 robot: Optional[RobotConfig] = None):
        self.cfg = cfg
        self.n_robots = n_robots
        self.watchdog = EstimatorWatchdog(cfg, n_robots)
        self.relocalizer = Relocalizer(cfg, n_robots)
        self.blacklist = FrontierBlacklist(cfg)
        self.antistuck = AntiStuckLadder(
            cfg, n_robots,
            rotation_units=(robot.rotation_speed_units
                            if robot is not None else 50),
            cruise_units=(robot.cruise_speed_units
                          if robot is not None else 100),
            m_per_unit_tick=(robot.speed_coeff_m_per_unit_s
                             / robot.control_rate_hz
                             if robot is not None else 3.027e-5))

    def snapshot(self) -> dict:
        """The /status "recovery" object."""
        return {
            "watchdog": self.watchdog.snapshot(),
            "relocalization": self.relocalizer.snapshot(),
            "antistuck": self.antistuck.snapshot(),
            "blacklist": self.blacklist.snapshot(),
        }
