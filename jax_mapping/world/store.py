"""Robocentric sliding-window world store (the bounded-memory
contract; ROG-Map's window idiom rebuilt on the tile lattice).

ONE store unifies the tile bookkeepings that grew up separately —
serving tiles, frontier dirty-tile scatter, decay invalidation, the
fused engine's touched-tile box, checkpoint state — behind a logical
tile lattice: `grid.size_cells` becomes the LOGICAL world extent
(set it as large as the mission needs; it allocates nothing), while
the device holds a fixed `window_tiles^2` window of it that shifts
with the robot. Device bytes are constant and independent of distance
traveled — the memory-safety contract the lifelong soak gates on.

Frames. The mapper runs ALL of its machinery (matcher, pyramids,
graph, loop closure, frontier, serving geometry) on a derived
window-sized `SlamConfig` (`window_slam_config`) — `slam_step` is
fully config-static, so no device code changes. Poses live in the
robocentric WINDOW frame; `offset_xy()` maps window → world
(`world = window + offset`), starts at exactly zero (the initial
window is centred on the logical origin) and advances by whole tiles.
On a shift the mapper translates its pose-like leaves by the shift
delta — graph edges are relative and scan rings are ranges-only, so
a uniform translation is the entire frame fix-up.

Shift = one jitted dispatch (`shift_window`: roll + re-zero of the
entering band, both shift amounts traced so ONE executable serves
every shift vector). Leaving tiles are extracted on device
(`_extract_tile`), landed in a host LRU, and spilled to disk with
per-tile CRC + generation stamps (`world/spill.py`); re-entering a
region rehydrates transparently — host hit → device scatter this
tick; disk hit → prefetch thread joined at the NEXT tick (a
deterministic one-tick unknown-degrade regardless of IO timing);
corrupt spill → the tile degrades to unknown with a flight event,
never an exception. The `MemoryGovernor` owns the host budget and
its load-shed ladder.

Every transition appends to a bounded `schedule` log — two same-seed
missions must produce bit-identical schedules (the FaultPlan
determinism doctrine extended to memory traffic).
"""

from __future__ import annotations

import dataclasses
import functools
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from jax_mapping.config import SlamConfig
from jax_mapping.utils import global_metrics as M

Tile = Tuple[int, int]

#: Schedule-log bound: big enough that a soak's full eviction history
#: fits (the determinism gate compares complete logs); the counter
#: keeps counting past it.
_SCHEDULE_CAP = 65536


# ---------------------------------------------------------------------------
# Jitted window primitives (compile_budget-pinned: max 1 variant each)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _jits():
    """Lazy jit construction (package import must not import jax)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def shift_window(grid, dr_cells, dc_cells):
        """Window shifted by (dr, dc) CELLS in logical space: content
        rolls by the negated shift, the entering band re-zeros to
        unknown. Both shifts traced → one executable for every shift
        vector (the zero-copy roll contract)."""
        wr, wc = grid.shape
        rolled = jnp.roll(grid, (-dr_cells, -dc_cells), axis=(0, 1))
        rows = jnp.arange(wr)
        cols = jnp.arange(wc)
        keep_r = (rows >= jnp.maximum(0, -dr_cells)) \
            & (rows < wr - jnp.maximum(0, dr_cells))
        keep_c = (cols >= jnp.maximum(0, -dc_cells)) \
            & (cols < wc - jnp.maximum(0, dc_cells))
        keep = keep_r[:, None] & keep_c[None, :]
        return jnp.where(keep, rolled, jnp.zeros((), grid.dtype))

    @functools.partial(jax.jit, static_argnums=(3,))
    def extract_tile(grid, r0, c0, t):
        return jax.lax.dynamic_slice(grid, (r0, c0), (t, t))

    @jax.jit
    def scatter_tile(grid, tile, r0, c0):
        return jax.lax.dynamic_update_slice(grid, tile, (r0, c0))

    # Publish the closure-built jits as module attributes so the
    # compile-budget snapshot (analysis/compilebudget.py walks module
    # vars for `_cache_size`) can pin their variant counts.
    globals().update(_shift_window=shift_window,
                     _extract_tile=extract_tile,
                     _scatter_tile=scatter_tile)
    return shift_window, extract_tile, scatter_tile


@functools.lru_cache(maxsize=None)
def _fuse_jit():
    """Global-coordinate fusion into the window (the store-level
    direct-drive API the bit-identity gate runs on): the inverse
    sensor model evaluates at GLOBAL cell coordinates — float-for-
    float the oracle big-grid computation — and the clip-add applies
    at the window-local offset, so a windowed run's live content is
    bit-identical to an oracle run's same region."""
    import jax
    import jax.numpy as jnp
    from jax_mapping.ops import grid as G

    @functools.partial(jax.jit, static_argnums=(0, 1))
    def fuse_patch_global(grid_cfg, scan_cfg, window, ranges, pose,
                          origin_global, origin_local):
        delta = G.classify_patch(grid_cfg, scan_cfg, ranges, pose,
                                 origin_global)
        p = grid_cfg.patch_cells
        cur = jax.lax.dynamic_slice(
            window, (origin_local[0], origin_local[1]), (p, p))
        new = jnp.clip(cur + delta, grid_cfg.logodds_min,
                       grid_cfg.logodds_max)
        return jax.lax.dynamic_update_slice(
            window, new, (origin_local[0], origin_local[1]))

    globals()["_fuse_patch_global"] = fuse_patch_global  # budget snapshot
    return fuse_patch_global


# ---------------------------------------------------------------------------
# Config derivation
# ---------------------------------------------------------------------------

def window_slam_config(cfg: SlamConfig) -> SlamConfig:
    """The window-sized `SlamConfig` the mapper's device machinery
    runs on when `world.windowed`: same resolution, patch, alignment,
    sensor and matcher parameters — only `grid.size_cells` shrinks to
    the window. `slam_step` is config-static, so this ONE derivation
    is the entire device-side integration."""
    w = cfg.world
    t = cfg.serving.tile_cells
    g = cfg.grid
    if g.size_cells % t:
        raise ValueError(
            f"grid.size_cells={g.size_cells} not divisible by "
            f"serving.tile_cells={t}")
    wc = w.window_tiles * t
    nt = g.size_cells // t
    if w.window_tiles > nt:
        raise ValueError(
            f"window_tiles={w.window_tiles} exceeds the logical "
            f"lattice ({nt} tiles)")
    if (nt - w.window_tiles) % 2:
        raise ValueError(
            f"logical minus window tiles ({nt} - {w.window_tiles}) "
            "must be even so the initial window centres on the "
            "logical origin (the zero-offset start contract)")
    if g.patch_cells > wc:
        raise ValueError(
            f"patch_cells={g.patch_cells} exceeds the window "
            f"({wc} cells); grow window_tiles or shrink the patch")
    if 2 * w.margin_tiles >= w.window_tiles:
        raise ValueError(
            f"margin_tiles={w.margin_tiles} leaves no interior in a "
            f"{w.window_tiles}-tile window")
    return cfg.replace(grid=dataclasses.replace(g, size_cells=wc))


class WorldStore:
    """Fixed-budget device window + host LRU + disk spill of the
    logical tile lattice.

    Locking: `_lock` guards the host-side maps (LRU, away-set,
    pending prefetch) — the mapper tick thread evicts/rehydrates
    while the serving thread composes mosaics and `/status` reads
    counters (the evict-vs-serve pair the racewatch gate drives).
    The caller (mapper) owns the device grid and serializes shifts
    under its own state lock."""

    def __init__(self, cfg: SlamConfig, spill_dir: Optional[str] = None):
        from jax_mapping.world.governor import MemoryGovernor
        from jax_mapping.world.spill import SpillStore

        self.full_cfg = cfg
        self.cfg = window_slam_config(cfg)
        w = cfg.world
        self.tile_cells = cfg.serving.tile_cells
        self.window_tiles = w.window_tiles
        self.window_cells = self.window_tiles * self.tile_cells
        self.logical_tiles = cfg.grid.size_cells // self.tile_cells
        self.margin_tiles = w.margin_tiles
        #: Initial (and re-anchorable) window origin on the logical
        #: tile lattice; the centred start makes offset_xy() == 0.
        self._anchor = (self.logical_tiles - self.window_tiles) // 2
        self.origin_tile: Tuple[int, int] = (self._anchor, self._anchor)

        self._lock = threading.Lock()
        #: (r, c) -> (gen, decay_epoch, float32 (t, t) array, coarse)
        self._host: "OrderedDict[Tile, tuple]" = OrderedDict()
        #: Every logical tile currently NOT resident that once was —
        #: host, disk, in-flight prefetch or lost: the serving
        #: evicted-marker mask.
        self._away: set = set()
        #: Disk prefetches in flight: tile -> (thread, result holder).
        self._pending: Dict[Tile, tuple] = {}

        dir_ = spill_dir if spill_dir is not None else w.spill_dir
        self.spill: Optional[SpillStore] = \
            SpillStore(dir_) if dir_ else None
        self.governor = MemoryGovernor(w)

        self.decay_epoch = 0
        self._gen = 0
        self.n_shifts = 0
        self.n_evictions = 0
        self.n_rehydrated_host = 0
        self.n_rehydrated_disk = 0
        self.n_lost = 0
        self.n_corrupt_spills = 0
        self.eviction_epoch = 0       # bumps per away-set change (ETag)
        #: Bounded memory-traffic log; the same-seed determinism gate
        #: compares two runs' complete logs.
        self.schedule: List[tuple] = []
        self.n_schedule_events = 0

    # -- frame math --------------------------------------------------------

    def offset_xy(self) -> np.ndarray:
        """(2,) float32 window→world translation: world = window +
        offset. Exactly zero at the centred start; advances by whole
        tiles, computed from the integer tile delta so the same shift
        sequence always yields the same float."""
        res = np.float32(self.full_cfg.grid.resolution_m)
        dc = np.int32((self.origin_tile[1] - self._anchor)
                      * self.tile_cells)
        dr = np.int32((self.origin_tile[0] - self._anchor)
                      * self.tile_cells)
        return np.array([np.float32(dc) * res, np.float32(dr) * res],
                        np.float32)

    def shift_delta_m(self, dr: int, dc: int) -> np.ndarray:
        """World-metre translation a (dr, dc)-tile shift adds to the
        offset (the amount the mapper subtracts from its pose-like
        leaves)."""
        res = np.float32(self.full_cfg.grid.resolution_m)
        return np.array(
            [np.float32(dc * self.tile_cells) * res,
             np.float32(dr * self.tile_cells) * res], np.float32)

    def desired_shift(self, poses_window: Sequence[np.ndarray]
                      ) -> Tuple[int, int]:
        """(dr, dc) tile shift that recentres the fleet, or (0, 0).

        Shifts only when some robot strays into the `margin_tiles`
        edge band (hysteresis — no churn while roaming the interior);
        recentres on the fleet centroid, clamped to the logical
        lattice. Assumes a clustered fleet (the lifelong regime);
        robots outside the shifted window clip at the edge like any
        out-of-grid pose."""
        t = self.tile_cells
        res = self.full_cfg.grid.resolution_m
        half = self.window_cells * res / 2.0
        wt = self.window_tiles
        m = self.margin_tiles
        tiles = []
        for p in poses_window:
            col = (float(p[0]) + half) / res
            row = (float(p[1]) + half) / res
            tiles.append((int(row // t), int(col // t)))
        trigger = any(
            tr < m or tr >= wt - m or tc < m or tc >= wt - m
            for tr, tc in tiles)
        if not trigger:
            return (0, 0)
        cr = sum(tr for tr, _ in tiles) / len(tiles)
        cc = sum(tc for _, tc in tiles) / len(tiles)
        dr = int(round(cr - (wt - 1) / 2.0))
        dc = int(round(cc - (wt - 1) / 2.0))
        lim = self.logical_tiles - wt
        r0, c0 = self.origin_tile
        dr = max(0, min(lim, r0 + dr)) - r0
        dc = max(0, min(lim, c0 + dc)) - c0
        return (dr, dc)

    # -- shift + eviction + rehydration -------------------------------------

    def shift(self, grid, dr: int, dc: int):
        """Shift the window by (dr, dc) tiles: evict the leaving band
        through the governor ladder, roll + re-zero in one jitted
        dispatch, rehydrate entering tiles (host → scatter now; disk
        → prefetch joined next tick). Returns the new device grid."""
        if (dr, dc) == (0, 0):
            return grid
        shift_window, extract_tile, scatter_tile = _jits()
        t = self.tile_cells
        wt = self.window_tiles
        r0, c0 = self.origin_tile
        leaving, entering = self._bands(dr, dc)

        # Extract leaving tiles from the OLD grid, then admit them
        # host-side (governor ladder decides spill/coarsen/refuse).
        for (wr, wc_) in leaving:
            tile = (r0 + wr, c0 + wc_)
            arr = np.asarray(extract_tile(
                grid, np.int32(wr * t), np.int32(wc_ * t), t))
            self._admit(tile, arr)

        grid = shift_window(grid, np.int32(dr * t), np.int32(dc * t))
        with self._lock:
            # Tick-thread single-writer, but the install is guarded so
            # no write site needs a baselined B3 exception; foreign
            # readers (serving compose, /status) still take the
            # point-in-time value bare by convention.
            self.origin_tile = (r0 + dr, c0 + dc)
        self.n_shifts += 1
        M.counters.inc("world.shifts")
        self._note("shift", dr, dc, self.origin_tile[0],
                   self.origin_tile[1])

        # Rehydrate what the entering band re-covers.
        nr0, nc0 = self.origin_tile
        for (wr, wc_) in entering:
            tile = (nr0 + wr, nc0 + wc_)
            grid = self._rehydrate(grid, tile, (wr, wc_), scatter_tile)
        return grid

    def _bands(self, dr: int, dc: int):
        """Window-tile coordinates of the (leaving, entering) bands of
        a (dr, dc)-tile shift. Leaving is in PRE-shift window coords,
        entering in POST-shift ones; a tile leaves (enters) when its
        row OR column does."""
        wt = self.window_tiles

        def band_leave(d, wt):
            if d > 0:
                return set(range(min(d, wt)))
            if d < 0:
                return set(range(max(0, wt + d), wt))
            return set()

        def band_enter(d, wt):
            if d > 0:
                return set(range(max(0, wt - d), wt))
            if d < 0:
                return set(range(min(-d, wt)))
            return set()

        rows_l, cols_l = band_leave(dr, wt), band_leave(dc, wt)
        rows_e, cols_e = band_enter(dr, wt), band_enter(dc, wt)
        leaving = [(r, c) for r in range(wt) for c in range(wt)
                   if r in rows_l or c in cols_l]
        entering = [(r, c) for r in range(wt) for c in range(wt)
                    if r in rows_e or c in cols_e]
        return leaving, entering

    def _admit(self, tile: Tile, arr: np.ndarray) -> None:
        """One evicted tile enters the host tier through the governor
        ladder. All-unknown tiles are not retained (nothing to lose —
        re-entry re-zeros anyway), but still leave the away-set mark
        if the tile ever held content."""
        with self._lock:
            self.n_evictions += 1
            M.counters.inc("world.evictions")
            if not arr.any():
                # Never-observed tile: re-entry re-creates it exactly.
                self._host.pop(tile, None)
                if self.spill is not None:
                    self.spill.discard(tile)
                self._away.discard(tile)
                return
            self._away.add(tile)
            self.eviction_epoch += 1
            rung = self.governor.observe(len(self._host) + 1)
            if rung >= 3 and self.spill is None:
                # Rung 3 with no deeper tier to shed into: refuse
                # admission — the newest content is dropped and any
                # stale spilled generation goes with it (a lost tile
                # must re-enter as unknown, not as old walls). With a
                # disk tier the admission lands and the shed below
                # spills the coldest tiles instead.
                self._host.pop(tile, None)
                if self.spill is not None:
                    self.spill.discard(tile)
                self.governor.n_refused += 1
                self.n_lost += 1
                M.counters.inc("world.tiles_lost")
                self._note("lost", tile[0], tile[1], "refused")
                self._flight("world_admission_refused", tile=list(tile))
                return
            self._gen += 1
            self._host[tile] = (self._gen, self.decay_epoch, arr, 1)
            self._host.move_to_end(tile)
            self._note("evict", tile[0], tile[1], self._gen)
            self._shed(rung)

    def _shed(self, rung: int) -> None:
        """Spill (or drop) the coldest host tiles down to the rung's
        target occupancy. Caller holds `_lock`."""
        target = (self.governor.effective_budget() if rung == 0
                  else self.governor.target_resident())
        coarsen = (self.full_cfg.world.retention_coarsen
                   if rung >= 2 else 1)
        while len(self._host) > target:
            tile, (gen, epoch, arr, coarse) = \
                self._host.popitem(last=False)
            if self.spill is None:
                self.governor.n_drops += 1
                self.n_lost += 1
                self._away.add(tile)
                M.counters.inc("world.tiles_lost")
                self._note("lost", tile[0], tile[1], "no_spill_tier")
                continue
            k = max(coarse, coarsen)
            out = arr
            if k > coarse:
                out = _coarsen(arr, k // coarse)
                self.governor.n_coarsened += 1
                M.counters.inc("world.tiles_coarsened")
            self.spill.put(tile, gen, out, epoch, coarse=k)
            self.governor.n_spills += 1
            M.counters.inc("world.tiles_spilled")
            self._note("spill", tile[0], tile[1], gen, k)

    def _rehydrate(self, grid, tile: Tile, slot: Tuple[int, int],
                   scatter_tile):
        """One entering tile: host hit scatters NOW; disk hit starts a
        prefetch joined at the next tick (one-tick unknown-degrade);
        miss stays unknown."""
        t = self.tile_cells
        with self._lock:
            entry = self._host.pop(tile, None)
            if entry is not None:
                gen, epoch, arr, coarse = entry
                self._away.discard(tile)
                self.eviction_epoch += 1
                if self.spill is not None:
                    self.spill.discard(tile)   # resident beats stale
                arr = self._catch_up(arr, epoch, coarse)
                self.n_rehydrated_host += 1
                M.counters.inc("world.rehydrated_host")
                self._note("rehydrate", tile[0], tile[1], "host")
                return scatter_tile(
                    grid, self._to_device(arr),
                    np.int32(slot[0] * t), np.int32(slot[1] * t))
            if self.spill is not None and tile in self.spill:
                holder: list = []
                th = threading.Thread(
                    target=self._prefetch_read, args=(tile, holder),
                    name=f"world-prefetch-{tile[0]}-{tile[1]}",
                    daemon=True)
                self._pending[tile] = (th, holder)
                th.start()
                self._note("prefetch", tile[0], tile[1])
                M.counters.inc("world.prefetches")
            elif tile in self._away:
                # Nothing to restore (the tile was lost — refused or
                # dropped): it is resident again, AS UNKNOWN, so the
                # evicted marker clears (the away-set invariant is
                # "once-seen and NOT resident"); the loss stays visible
                # through n_lost and the schedule log.
                self._away.discard(tile)
                self.eviction_epoch += 1
                self._note("reenter_unknown", tile[0], tile[1])
        return grid

    def _prefetch_read(self, tile: Tile, holder: list) -> None:
        """Prefetch-thread body: ONLY the disk read + CRC check runs
        off-thread; the device scatter happens at the next
        `poll_prefetch` on the tick thread, so the rehydrate schedule
        is deterministic regardless of IO timing."""
        holder.append(self.spill.get(tile))

    def poll_prefetch(self, grid):
        """Join finished (blocking on still-running — determinism over
        latency) prefetches and scatter them into the window; corrupt
        spills degrade to unknown with a flight event. Returns
        (grid, n_applied)."""
        with self._lock:
            pending = sorted(self._pending.items())
            self._pending.clear()
        if not pending:
            return grid, 0
        _, _, scatter_tile = _jits()
        t = self.tile_cells
        n = 0
        for tile, (th, holder) in pending:
            th.join()
            rec = holder[0] if holder else None
            slot = self._window_slot(tile)
            with self._lock:
                if rec is None:
                    self.n_corrupt_spills += 1
                    self.n_lost += 1
                    if self.spill is not None:
                        self.spill.discard(tile)
                    if slot is not None:
                        # Resident again (as unknown): the evicted
                        # marker clears, same as the lost-tile re-entry.
                        self._away.discard(tile)
                        self.eviction_epoch += 1
                    M.counters.inc("world.corrupt_spills")
                    self._note("corrupt", tile[0], tile[1])
                    self._flight("world_spill_corrupt",
                                 tile=list(tile))
                    continue
                if slot is None:
                    # The window moved on while the read was in
                    # flight: keep the tile warm in the host tier.
                    self._gen += 1
                    self._host[tile] = (self._gen, rec.decay_epoch,
                                        rec.data, rec.coarse)
                    self.spill.discard(tile)
                    self._note("rehydrate", tile[0], tile[1],
                               "disk_to_host")
                    continue
                self._away.discard(tile)
                self.eviction_epoch += 1
                self.spill.discard(tile)
                arr = rec.data
                if rec.coarse > 1:
                    arr = _upsample(arr, rec.coarse, self.tile_cells)
                arr = self._catch_up(arr, rec.decay_epoch, 1)
                self.n_rehydrated_disk += 1
                M.counters.inc("world.rehydrated_disk")
                self._note("rehydrate", tile[0], tile[1], "disk")
            grid = scatter_tile(
                grid, self._to_device(arr),
                np.int32(slot[0] * t), np.int32(slot[1] * t))
            n += 1
        return grid, n

    def _window_slot(self, tile: Tile) -> Optional[Tuple[int, int]]:
        r = tile[0] - self.origin_tile[0]
        c = tile[1] - self.origin_tile[1]
        if 0 <= r < self.window_tiles and 0 <= c < self.window_tiles:
            return (r, c)
        return None

    @staticmethod
    def _to_device(arr: np.ndarray):
        import jax.numpy as jnp
        return jnp.asarray(arr, dtype=jnp.float32)

    # -- decay exactness -----------------------------------------------------

    def note_decay_pass(self) -> None:
        """The mapper decayed the RESIDENT window (ops/grid.decay_grid,
        one jitted dispatch); spilled tiles catch up lazily at
        rehydrate time."""
        with self._lock:
            self.decay_epoch += 1

    def _catch_up(self, arr: np.ndarray, tile_epoch: int,
                  coarse: int) -> np.ndarray:
        """Apply the decay passes a tile missed while evicted — one
        SEQUENTIAL clip(x*f) per missed pass in float32, matching the
        device's per-pass arithmetic bit-for-bit (f^k compounded once
        rounds differently)."""
        k = self.decay_epoch - tile_epoch
        if k <= 0:
            return arr
        d = self.full_cfg.decay
        f = np.float32(d.factor)
        c = np.float32(d.evidence_cap)
        out = arr.astype(np.float32, copy=True)
        for _ in range(k):
            out = np.clip(out * f, -c, c)
        return out

    # -- chaos seams (resilience/faultplan.py) -------------------------------

    def corrupt_spill(self, n_tiles: int) -> List[Tile]:
        """`spill_corrupt` FaultPlan kind: flip a CRC-detectable bit in
        up to `n_tiles` spilled tiles, deterministically. No disk tier
        = nothing to corrupt (skip-noted by the plan)."""
        if self.spill is None:
            return []
        hit = self.spill.corrupt_tiles(int(n_tiles))
        for tile in hit:
            self._note("chaos_corrupt", tile[0], tile[1])
        return hit

    def hold_pressure(self, name: str, squeeze: float) -> None:
        """`memory_pressure` FaultPlan kind: synthetic budget squeeze;
        overlapping holds compose worst-of in the governor. Sheds
        immediately so the squeeze is visible the tick it lands."""
        self.governor.hold_pressure(name, squeeze)
        with self._lock:
            rung = self.governor.observe(len(self._host))
            self._note("pressure", name, round(float(squeeze), 4))
            self._shed(max(rung, 1))

    def release_pressure(self, name: str) -> None:
        self.governor.release_pressure(name)
        with self._lock:
            self.governor.observe(len(self._host))
            self._note("pressure_clear", name)

    # -- serving composition -------------------------------------------------

    def compose_serving(self, window_gray: np.ndarray):
        """(logical gray mosaic, (nt, nt) evicted mask) for the tile
        store: the resident window pastes at its origin, everything
        else reads unknown-127, and tiles currently away (host, disk,
        in-flight, lost) are flagged so `TileStore` emits typed
        evicted markers instead of re-encoding stale pixels."""
        L = self.logical_tiles * self.tile_cells
        t = self.tile_cells
        mosaic = np.full((L, L), 127, np.uint8)
        r0, c0 = self.origin_tile
        mosaic[r0 * t:r0 * t + self.window_cells,
               c0 * t:c0 * t + self.window_cells] = window_gray
        mask = np.zeros((self.logical_tiles, self.logical_tiles), bool)
        with self._lock:
            for (r, c) in self._away:
                mask[r, c] = True
        return mosaic, mask

    # -- checkpoint (io/checkpoint.py world sidecar) -------------------------

    def checkpoint_payload(self) -> Dict[str, np.ndarray]:
        """Flat-array payload for the checkpoint's world sidecar:
        window origin + epochs + away-set, plus the host tier — tiles
        flush to the spill file when a disk tier exists (the manifest
        then IS the spill index, lazily rehydrated on restore), and
        embed in the sidecar otherwise."""
        with self._lock:
            if self.spill is not None:
                # Flush host -> disk so restore needs only the file.
                while self._host:
                    tile, (gen, epoch, arr, coarse) = \
                        self._host.popitem(last=False)
                    self.spill.put(tile, gen, arr, epoch,
                                   coarse=coarse)
                self.spill.compact()
            payload = {
                "origin_tile": np.asarray(self.origin_tile, np.int64),
                "epochs": np.asarray(
                    [self.decay_epoch, self._gen,
                     self.eviction_epoch], np.int64),
                "away": np.asarray(sorted(self._away),
                                   np.int64).reshape(-1, 2),
            }
            if self.spill is None and self._host:
                meta, tiles = [], []
                for tile, (gen, epoch, arr, coarse) in \
                        self._host.items():
                    if coarse != 1:
                        arr = _upsample(arr, coarse, self.tile_cells)
                    meta.append([tile[0], tile[1], gen, epoch])
                    tiles.append(arr)
                payload["host_meta"] = np.asarray(meta, np.int64)
                payload["host_tiles"] = np.stack(tiles)
        return payload

    def restore_payload(self, payload: Dict[str, np.ndarray]) -> None:
        """Re-anchor at the checkpointed origin; away tiles rehydrate
        lazily on re-entry (disk tier) or from the embedded host
        tier."""
        with self._lock:
            origin = payload["origin_tile"]
            self.origin_tile = (int(origin[0]), int(origin[1]))
            epochs = payload["epochs"]
            self.decay_epoch = int(epochs[0])
            self._gen = int(epochs[1])
            self.eviction_epoch = int(epochs[2])
            self._away = {(int(r), int(c))
                          for r, c in np.asarray(payload["away"])}
            self._host.clear()
            self._pending.clear()
            if "host_meta" in payload:
                meta = np.asarray(payload["host_meta"])
                tiles = np.asarray(payload["host_tiles"], np.float32)
                for row, arr in zip(meta, tiles):
                    self._host[(int(row[0]), int(row[1]))] = (
                        int(row[2]), int(row[3]), arr, 1)

    # -- observability -------------------------------------------------------

    def _note(self, kind: str, *args) -> None:
        self.n_schedule_events += 1
        if len(self.schedule) < _SCHEDULE_CAP:
            self.schedule.append((kind,) + args)

    @staticmethod
    def _flight(event: str, **kw) -> None:
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record(event, **kw)

    def host_tiles(self) -> int:
        with self._lock:
            return len(self._host)

    def status(self) -> dict:
        """/status.world section (lock-held host reads, lock-free
        counters — the /status convention)."""
        with self._lock:
            host = len(self._host)
            away = len(self._away)
            pending = len(self._pending)
            host_bytes = sum(e[2].nbytes for e in self._host.values())
        s = {
            "windowed": True,
            "origin_tile": list(self.origin_tile),
            "window_tiles": self.window_tiles,
            "logical_tiles": self.logical_tiles,
            "device_window_bytes": self.window_cells ** 2 * 4,
            "host_tiles": host,
            "host_bytes": host_bytes,
            "away_tiles": away,
            "pending_prefetch": pending,
            "shifts": self.n_shifts,
            "evictions": self.n_evictions,
            "rehydrated_host": self.n_rehydrated_host,
            "rehydrated_disk": self.n_rehydrated_disk,
            "lost_tiles": self.n_lost,
            "corrupt_spills": self.n_corrupt_spills,
            "eviction_epoch": self.eviction_epoch,
            "decay_epoch": self.decay_epoch,
            "schedule_events": self.n_schedule_events,
            "governor": self.governor.status(),
        }
        if self.spill is not None:
            s["spill"] = self.spill.status()
        return s

    # -- store-level direct-drive fusion (the oracle gate's API) -------------

    def fuse_scan_global(self, window_grid, ranges, pose_world):
        """Fuse one scan into the window with the inverse sensor model
        evaluated at GLOBAL coordinates — float-identical to the
        oracle big-grid fusion (`ops/grid.classify_patch` at the same
        logical origin), applied at the window-local offset. The
        bit-identity gate drives the store through this; the bridge's
        windowed mapper runs the window-frame `slam_step` instead
        (matcher float drift makes bridge-level bit-identity
        unattainable — the soak gates ≥90% agreement there)."""
        import jax.numpy as jnp
        from jax_mapping.ops import grid as G
        g = self.full_cfg.grid
        fuse = _fuse_jit()
        pose = jnp.asarray(pose_world, jnp.float32)
        origin_global = G.patch_origin(g, pose[:2])
        og = np.asarray(origin_global)
        r0, c0 = self.origin_tile
        local = og - np.array([r0 * self.tile_cells,
                               c0 * self.tile_cells])
        wc = self.window_cells
        p = g.patch_cells
        if not (0 <= local[0] <= wc - p and 0 <= local[1] <= wc - p):
            raise ValueError(
                f"patch at logical {og.tolist()} does not fit the "
                f"window at origin {self.origin_tile} — shift first")
        return fuse(g, self.full_cfg.scan, window_grid,
                    jnp.asarray(ranges), pose,
                    jnp.asarray(og, jnp.int32),
                    jnp.asarray(local, jnp.int32))

    def close(self) -> None:
        # Drain in-flight prefetch reads BEFORE closing the spill file:
        # a daemon reader racing the close would die on a closed-file
        # error instead of returning its (now moot) tile.
        with self._lock:
            pending = list(self._pending.values())
            self._pending.clear()
        for th, _holder in pending:
            th.join()
        if self.spill is not None:
            self.spill.close()


# ---------------------------------------------------------------------------
# Rung-2 retention coarsening (host-side, lossy, bounded)
# ---------------------------------------------------------------------------

def _coarsen(arr: np.ndarray, k: int) -> np.ndarray:
    """Downsample by max-|logodds| per k x k block: walls survive
    coarsening (the pyramid's occupied-priority idea applied to
    evidence)."""
    t = arr.shape[0]
    b = arr.reshape(t // k, k, t // k, k).transpose(0, 2, 1, 3) \
        .reshape(t // k, t // k, k * k)
    idx = np.abs(b).argmax(axis=2)
    return np.take_along_axis(b, idx[..., None], axis=2)[..., 0] \
        .astype(np.float32)


def _upsample(arr: np.ndarray, k: int, t: int) -> np.ndarray:
    """Nearest-neighbour re-expansion of a coarsened tile back to the
    (t, t) lattice."""
    out = np.repeat(np.repeat(arr, k, axis=0), k, axis=1)
    return np.ascontiguousarray(out[:t, :t], dtype=np.float32)
