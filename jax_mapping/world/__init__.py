"""Bounded-memory robocentric world store (`WorldConfig.windowed`).

`store.WorldStore` — fixed-budget device window over the logical tile
lattice (shift = one jitted roll, eviction → host LRU → CRC-stamped
disk spill, transparent rehydration); `governor.MemoryGovernor` — the
watermark load-shed ladder; `spill.SpillStore` — the append-only
CRC-framed disk tier. `windowed=False` constructs nothing: bit-exact
pre-PR behavior (the knob-off doctrine)."""

from jax_mapping.world.governor import MemoryGovernor  # noqa: F401
from jax_mapping.world.spill import SpillStore  # noqa: F401
from jax_mapping.world.store import (  # noqa: F401
    WorldStore, window_slam_config,
)
