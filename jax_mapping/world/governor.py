"""Memory-pressure governor for the sliding-window world store.

The device window is constant-size by construction (the bounded-memory
contract); what can still grow without bound is the HOST side — the
LRU of evicted tiles and its disk spill. The governor owns that
budget: watermark-driven eviction cadence plus a load-shed ladder that
degrades retention gracefully instead of letting the host OOM.

Rungs (exported on `/status.world.governor` + the
`jax_mapping_world_governor_rung` gauge):

  0  normal      — LRU below the high watermark; overflow spills the
                   coldest tile to disk (or drops it with no disk tier).
  1  shrink      — above `host_high_watermark`: the retention ring
                   shrinks (spill cadence accelerates until occupancy
                   is back under the high watermark).
  2  coarsen     — above `host_critical_watermark`: spilled tiles are
                   additionally downsampled by `retention_coarsen`
                   (lossy, bounded; rehydrate upsamples).
  3  refuse      — at/over the effective budget: NEW evictions are
                   refused admission — the tile is dropped and will
                   re-enter as unknown (degrade, never die).

Synthetic pressure (the `memory_pressure` FaultPlan kind) composes
WORST-OF across overlapping holds: each named hold contributes a
squeeze fraction, the effective budget is scaled by the max active
squeeze, and clearing one hold re-reads the remainder — the
refcount-composition doctrine of the partition/weather kinds.
"""

from __future__ import annotations

import threading
from typing import Dict

from jax_mapping.config import WorldConfig

RUNG_NAMES = ("normal", "shrink", "coarsen", "refuse")


class MemoryGovernor:
    """Watermark ladder over the host evicted-tile budget."""

    def __init__(self, cfg: WorldConfig):
        self.cfg = cfg
        self._lock = threading.Lock()
        #: name -> squeeze fraction in (0, 1); worst-of composes.
        self._pressure: Dict[str, float] = {}
        self.rung = 0
        self.n_spills = 0
        self.n_drops = 0
        self.n_coarsened = 0
        self.n_refused = 0
        self.n_rung_changes = 0

    # -- synthetic pressure (FaultPlan memory_pressure) -------------------

    def hold_pressure(self, name: str, squeeze: float) -> None:
        """Arm one named squeeze hold; overlapping holds compose
        worst-of (max), the partition-refcount doctrine."""
        with self._lock:
            self._pressure[name] = float(squeeze)

    def release_pressure(self, name: str) -> None:
        with self._lock:
            self._pressure.pop(name, None)

    def pressure(self) -> float:
        with self._lock:
            return max(self._pressure.values(), default=0.0)

    # -- budget math -------------------------------------------------------

    def effective_budget(self) -> int:
        """Host tile budget after the worst active squeeze; never
        below one tile (a zero budget would divide the watermarks)."""
        return max(1, int(self.cfg.host_tile_budget
                          * (1.0 - self.pressure())))

    def target_resident(self) -> int:
        """Rung >= 1 shed target: back under the high watermark."""
        return max(1, int(self.effective_budget()
                          * self.cfg.host_high_watermark))

    def observe(self, resident_tiles: int) -> int:
        """Fold one occupancy sample into the ladder; returns the rung
        the caller must act at for THIS admission."""
        budget = self.effective_budget()
        occ = resident_tiles / budget
        if occ >= 1.0:
            rung = 3
        elif occ >= self.cfg.host_critical_watermark:
            rung = 2
        elif occ >= self.cfg.host_high_watermark:
            rung = 1
        else:
            rung = 0
        if rung != self.rung:
            self.n_rung_changes += 1
            self.rung = rung
        return rung

    def status(self) -> dict:
        # ONE lock region for the hold snapshot; the effective budget
        # recomputes from that same snapshot instead of re-entering the
        # lock via effective_budget() (which would pair a second
        # pressure reading with the first — the C2 tear class).
        with self._lock:
            holds = dict(self._pressure)
        pressure = max(holds.values(), default=0.0)
        eff = max(1, int(self.cfg.host_tile_budget * (1.0 - pressure)))
        return {
            "rung": self.rung,
            "rung_name": RUNG_NAMES[self.rung],
            "pressure": round(pressure, 4),
            "pressure_holds": len(holds),
            "budget_tiles": self.cfg.host_tile_budget,
            "effective_budget_tiles": eff,
            "spills": self.n_spills,
            "drops": self.n_drops,
            "coarsened": self.n_coarsened,
            "refused": self.n_refused,
            "rung_changes": self.n_rung_changes,
        }
