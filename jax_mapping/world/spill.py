"""Disk spill tier for evicted world tiles (the `io/checkpoint`
integrity doctrine applied to the sliding window's cold storage).

Append-only record file, the tenancy-journal framing:

    <u32 payload_len> <payload> <u32 crc32(payload)>

where `payload` is one JSON meta line + b"\\n" + the tile's raw bytes:

    {"tile": [r, c], "gen": 7, "decay": 3, "dtype": "float32",
     "shape": [256, 256], "coarse": 1, "crc": <crc32 of tile bytes>}

Two CRCs on purpose: the record CRC catches torn appends (the walk on
open truncates the tail to the last good record, never fatal — the
tenancy-journal recovery rule), while the inner tile CRC travels WITH
the tile so a bit flip inside an otherwise well-framed record (the
`spill_corrupt` chaos kind) is detected at READ time: `get()` returns
None and the caller degrades the tile to unknown with a flight event
instead of scattering garbage into the live map.

Newest generation wins: re-evicting a tile appends a new record and
the in-memory index moves; `compact()` rewrites only the live records
(the journal compaction idiom). Reads are offset seeks into the open
file — no index file on disk, the walk IS the recovery.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

_LEN = struct.Struct("<I")
_CRC = struct.Struct("<I")

#: Frame overhead per record, bytes (length prefix + trailing CRC).
_FRAME = _LEN.size + _CRC.size


class SpillRecord:
    """One rehydrated tile read back from disk (CRC-verified)."""

    __slots__ = ("tile", "gen", "decay_epoch", "coarse", "data")

    def __init__(self, tile: Tuple[int, int], gen: int,
                 decay_epoch: int, coarse: int, data: np.ndarray):
        self.tile = tile
        self.gen = gen
        self.decay_epoch = decay_epoch
        self.coarse = coarse
        self.data = data


class SpillStore:
    """Append-only CRC-framed tile spill file + in-memory index.

    Thread-safe: the world store's eviction runs on the mapper tick
    thread while disk rehydration reads from a prefetch thread; one
    lock serializes the file handle (reads seek, appends run at EOF).
    """

    FILENAME = "tiles.spill"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, self.FILENAME)
        self._lock = threading.Lock()
        #: (r, c) -> (gen, payload offset, payload length)
        self._index: Dict[Tuple[int, int], Tuple[int, int, int]] = {}
        self.n_appends = 0
        self.n_reads = 0
        self.n_corrupt_reads = 0
        self.n_truncated_bytes = 0
        self._open_and_recover()

    # -- recovery --------------------------------------------------------

    def _open_and_recover(self) -> None:
        """Walk the file; a torn/corrupt tail truncates to the last
        good record (the tenancy-journal rule: a crash mid-append must
        not orphan the whole spill)."""
        mode = "r+b" if os.path.exists(self.path) else "w+b"
        # Construction is single-threaded, but the recovery walk still
        # runs under `_lock` so every `_f`/`_index` write site in the
        # class is guarded (no baselined single-writer exception).
        with self._lock:
            self._f = open(self.path, mode)
            good_end = 0
            self._f.seek(0, os.SEEK_END)
            size = self._f.tell()
            self._f.seek(0)
            while True:
                head = self._f.read(_LEN.size)
                if len(head) < _LEN.size:
                    break
                (plen,) = _LEN.unpack(head)
                start = self._f.tell()
                if start + plen + _CRC.size > size:
                    break                   # torn append
                payload = self._f.read(plen)
                (crc,) = _CRC.unpack(self._f.read(_CRC.size))
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    break                   # corrupt frame: stop here
                meta = self._parse_meta(payload)
                if meta is None:
                    break
                tile = (int(meta["tile"][0]), int(meta["tile"][1]))
                cur = self._index.get(tile)
                if cur is None or int(meta["gen"]) >= cur[0]:
                    self._index[tile] = (int(meta["gen"]), start, plen)
                good_end = self._f.tell()
            if good_end < size:
                self.n_truncated_bytes = size - good_end
                self._f.truncate(good_end)
            self._f.seek(0, os.SEEK_END)

    @staticmethod
    def _parse_meta(payload: bytes) -> Optional[dict]:
        nl = payload.find(b"\n")
        if nl < 0:
            return None
        try:
            return json.loads(payload[:nl])
        except ValueError:
            return None

    # -- protocol --------------------------------------------------------

    def put(self, tile: Tuple[int, int], gen: int, data: np.ndarray,
            decay_epoch: int, coarse: int = 1) -> None:
        """Append one evicted tile; newest generation wins on read."""
        raw = np.ascontiguousarray(data).tobytes()
        meta = json.dumps({
            "tile": [int(tile[0]), int(tile[1])],
            "gen": int(gen),
            "decay": int(decay_epoch),
            "dtype": str(data.dtype),
            "shape": list(data.shape),
            "coarse": int(coarse),
            "crc": zlib.crc32(raw) & 0xFFFFFFFF,
        }, sort_keys=True).encode("ascii")
        payload = meta + b"\n" + raw
        frame = (_LEN.pack(len(payload)) + payload
                 + _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF))
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            off = self._f.tell() + _LEN.size
            self._f.write(frame)
            self._f.flush()
            self._index[(int(tile[0]), int(tile[1]))] = (
                int(gen), off, len(payload))
            self.n_appends += 1

    def get(self, tile: Tuple[int, int]) -> Optional[SpillRecord]:
        """Read back a tile, CRC-verified at BOTH layers; None on a
        miss or on corruption (the caller owns the unknown-degrade +
        flight event — this layer never raises on bad bytes)."""
        key = (int(tile[0]), int(tile[1]))
        with self._lock:
            entry = self._index.get(key)
            if entry is None:
                return None
            gen, off, plen = entry
            self._f.seek(off)
            payload = self._f.read(plen)
            self._f.seek(0, os.SEEK_END)
        self.n_reads += 1
        meta = self._parse_meta(payload)
        if meta is None:
            self.n_corrupt_reads += 1
            return None
        raw = payload[payload.find(b"\n") + 1:]
        if zlib.crc32(raw) & 0xFFFFFFFF != int(meta["crc"]):
            self.n_corrupt_reads += 1
            return None
        data = np.frombuffer(raw, dtype=np.dtype(meta["dtype"]))
        try:
            data = data.reshape(meta["shape"]).copy()
        except ValueError:
            self.n_corrupt_reads += 1
            return None
        return SpillRecord(key, gen, int(meta["decay"]),
                           int(meta.get("coarse", 1)), data)

    def discard(self, tile: Tuple[int, int]) -> None:
        """Drop a tile from the index (its bytes stay until compaction
        — the append-only contract)."""
        with self._lock:
            self._index.pop((int(tile[0]), int(tile[1])), None)

    def tiles(self) -> List[Tuple[int, int]]:
        with self._lock:
            return sorted(self._index)

    def __contains__(self, tile: Tuple[int, int]) -> bool:
        with self._lock:
            return (int(tile[0]), int(tile[1])) in self._index

    def nbytes(self) -> int:
        with self._lock:
            self._f.seek(0, os.SEEK_END)
            return self._f.tell()

    def compact(self) -> None:
        """Rewrite only the live (index-reachable) records — the
        journal compaction idiom, CRC frames preserved."""
        with self._lock:
            live = []
            for tile in sorted(self._index):
                gen, off, plen = self._index[tile]
                self._f.seek(off)
                live.append((tile, gen, self._f.read(plen)))
            self._f.seek(0)
            self._f.truncate(0)
            self._index.clear()
            for tile, gen, payload in live:
                frame = (_LEN.pack(len(payload)) + payload
                         + _CRC.pack(zlib.crc32(payload) & 0xFFFFFFFF))
                off = self._f.tell() + _LEN.size
                self._f.write(frame)
                self._index[tile] = (gen, off, len(payload))
            self._f.flush()

    # -- chaos seam ------------------------------------------------------

    def corrupt_tiles(self, n: int) -> List[Tuple[int, int]]:
        """Flip one bit inside the TILE BYTES of up to `n` spilled
        tiles, on disk, deterministically (sorted tile order) — the
        `spill_corrupt` FaultPlan seam. The frame CRC is rewritten so
        the corruption models silent media rot that the outer framing
        cannot see; only the inner tile CRC catches it at read time.
        Returns the tiles actually hit."""
        hit: List[Tuple[int, int]] = []
        with self._lock:
            for tile in sorted(self._index):
                if len(hit) >= n:
                    break
                gen, off, plen = self._index[tile]
                self._f.seek(off)
                payload = bytearray(self._f.read(plen))
                nl = payload.find(b"\n")
                if nl < 0 or nl + 1 >= len(payload):
                    continue
                # Flip the middle byte's low bit: deterministic, and
                # guaranteed inside the tile-bytes region.
                k = nl + 1 + (len(payload) - nl - 1) // 2
                payload[k] ^= 0x01
                self._f.seek(off)
                self._f.write(payload)
                # Re-stamp the frame CRC: silent rot, not a torn frame.
                self._f.write(_CRC.pack(zlib.crc32(bytes(payload))
                                        & 0xFFFFFFFF))
                hit.append(tile)
            self._f.flush()
            self._f.seek(0, os.SEEK_END)
        return hit

    def close(self) -> None:
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass

    def status(self) -> dict:
        with self._lock:
            n = len(self._index)
        return {"tiles": n, "appends": self.n_appends,
                "reads": self.n_reads,
                "corrupt_reads": self.n_corrupt_reads,
                "truncated_bytes": self.n_truncated_bytes}
