from jax_mapping.utils.profiling import (  # noqa: F401
    Counters, StageTimer, device_trace, global_metrics,
)
