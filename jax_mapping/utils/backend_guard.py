"""Wedged-TPU-tunnel guard shared by every operator entry point.

This image registers a remote-compile TPU PJRT plugin at interpreter
startup (sitecustomize, keyed on PALLAS_AXON_POOL_IPS). When the tunnel
behind it wedges, jax backend initialisation blocks forever — even
`jax.devices()` — in a way no in-process timeout can interrupt (the hang
is inside plugin C++ during init). Round 3's verdict found the two
commands a human operator actually types (`python -m jax_mapping.demo`,
`jax-mapping-ros`) were the only entry points without a guard: they hung
>=300 s while bench.py / conftest / __graft_entry__ all carried private
copies of the same defence.

This module is that defence, shared (VERDICT r3 item 2: "shared helper,
not a third copy"):

  1. `backend_probe_ok()` — run `jax.devices()` + one tiny jit compile
     in a BOUNDED subprocess (the compile matters: a half-wedged tunnel
     can enumerate devices instantly yet hang every compile RPC).
  2. `scrubbed_cpu_env()` — the ambient env minus every axon/TPU hook,
     pinned to the virtual CPU backend.
  3. `ensure_responsive_backend()` — probe, and if the backend cannot
     init promptly, re-exec THIS process once onto the scrubbed env.

Entry points call (3) before first jax use. Import of this module is
side-effect free and never imports jax in-process.
"""

from __future__ import annotations

import os
import subprocess
import sys

# Set in the re-exec'd child so the guard (and the bench's JSON labelling)
# knows the process already fell back; never re-probe or re-exec twice.
FALLBACK_FLAG = "_JAX_MAPPING_CPU_FALLBACK"

# Parent directory of the jax_mapping package: what PYTHONPATH must carry
# so the re-exec'd child can import it without the .axon_site site dir.
_PKG_PARENT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def probe_timeout_s() -> float:
    return float(os.environ.get("JAX_MAPPING_PROBE_S", "120"))


# Child code for backend_probe_ok, module-level so tests can pin the
# actual probe contents (not prose around them): must both enumerate
# devices AND compile+fetch through the backend.
_PROBE_CODE = ("import jax, jax.numpy as jnp; d = jax.devices(); "
               "v = jax.jit(lambda x: x + 1)(jnp.float32(1)); "
               "v.block_until_ready(); "
               "print(d[0].platform, len(d), float(v), flush=True)")


def backend_env_suspect() -> bool:
    """Is the wedge-capable plugin active in this environment at all?

    The hang mechanism requires the axon plugin to be registered
    (PALLAS_AXON_POOL_IPS at interpreter startup) or the platform pinned
    to it. A plain CPU/GPU environment cannot reproduce it, so entry
    points skip the probe subprocess entirely there — the guard must not
    tax the common healthy case with a redundant interpreter spawn.
    """
    if os.environ.get(FALLBACK_FLAG) == "1":
        return False  # already on the scrubbed env
    return bool(os.environ.get("PALLAS_AXON_POOL_IPS")
                or "axon" in os.environ.get("JAX_PLATFORMS", ""))


def backend_probe_ok(timeout_s: float | None = None) -> bool:
    """Can this environment's default jax backend initialise AND compile
    promptly?

    Runs `jax.devices()` plus one trivial jit compile in a bounded
    subprocess — the wedged tunnel hangs in ways no in-process deadline
    can interrupt. The compile step is load-bearing: round 5 observed a
    half-wedged tunnel state where device enumeration returns in ~1 s but
    every compile RPC (even a scalar add) blocks >5 min — an
    enumeration-only probe passes and the entry point then hangs at its
    first jit. A healthy remote tunnel compiles the scalar probe in
    seconds, well inside the default 120 s budget.
    """
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE], capture_output=True,
            text=True,
            timeout=timeout_s if timeout_s is not None else probe_timeout_s())
    except subprocess.TimeoutExpired:
        return False
    return proc.returncode == 0


def scrubbed_cpu_env(extra_env: dict | None = None) -> dict:
    """The ambient env with every axon/TPU hook removed and CPU pinned.

    Drops AXON*/PALLAS_AXON*/TPU_* vars (plugin registration keys), the
    .axon_site PYTHONPATH entry (where sitecustomize lives), pins
    JAX_PLATFORMS=cpu, and marks the child via FALLBACK_FLAG.
    """
    env = dict(os.environ)
    for k in list(env):
        if k.startswith(("AXON", "PALLAS_AXON", "TPU_")):
            env.pop(k)
    keep = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
            if p and ".axon_site" not in p
            and os.path.normpath(p) != _PKG_PARENT]
    env["PYTHONPATH"] = os.pathsep.join([_PKG_PARENT] + keep)
    env["JAX_PLATFORMS"] = "cpu"
    env[FALLBACK_FLAG] = "1"
    if extra_env:
        env.update(extra_env)
    return env


def ensure_responsive_backend(entry: str,
                              extra_env: dict | None = None,
                              argv: list | None = None) -> None:
    """Probe the default backend; re-exec onto virtual CPU if it's wedged.

    Call before the first jax use in an operator entry point. When the
    probe fails, this does not return — the process is replaced by
    `sys.executable + argv` (default: sys.argv, which is correct for CLI
    invocations) under `scrubbed_cpu_env()`. Idempotent: a process that
    already fell back, or whose env cannot host the wedge, returns
    immediately without spawning anything.

    `argv` exists for callers whose sys.argv is not theirs to replay
    (programmatic use under a test runner): pass the exact command line
    that re-enters the caller, or rely on the default only from __main__.
    """
    if not backend_env_suspect():
        return
    if backend_probe_ok():
        return
    print(f"{entry}: jax backend init/compile probe did not finish in "
          f"{probe_timeout_s():.0f}s (wedged TPU tunnel?); restarting on "
          "virtual CPU", file=sys.stderr, flush=True)
    cmd = [sys.executable] + (argv if argv is not None else sys.argv)
    os.execvpe(cmd[0], cmd, scrubbed_cpu_env(extra_env))
