"""Tracing, per-stage timing, and counters — the observability subsystem.

The reference has none of this: its diagnostics are bare prints and the
rclpy logger (SURVEY.md §5 "Tracing / profiling: none"); throughput was
judged by watching RViz. The TPU framework needs real instrumentation
because device work is asynchronous — wall-clock around a dispatch measures
nothing (bench.py's methodology note). Three tools:

  * `device_trace(dir)` — context manager around `jax.profiler` for XLA/TPU
    traces viewable in TensorBoard/Perfetto;
  * `StageTimer` — named wall-clock stages with count/mean/EWMA/max, for
    host-side loops (brain tick, mapper tick, HTTP handlers);
  * `Counters` — monotonic event counters (scans fused, drops, matches,
    loop closures) with atomic increment.

`global_metrics` is the process-wide registry the bridge nodes feed and the
HTTP `/metrics` endpoint serves (the reference's `/status` grown into a
proper metrics surface).
"""

from __future__ import annotations

import bisect
import contextlib
import threading
import time
from typing import Dict, Iterator, Optional, Tuple


class Counters:
    """Thread-safe monotonic counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + by

    def get(self, name: str) -> int:
        with self._lock:
            return self._counts.get(name, 0)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


#: Fixed log-spaced latency bucket edges (seconds): 0.25 ms doubling up
#: to ~8.2 s, + one overflow bucket. FIXED (not per-stage adaptive) so
#: histograms from two runs — or two same-seed chaos twins — are
#: directly comparable bucket-for-bucket, the FPGA-2D-LiDAR-SLAM
#: paper's stage-level pipeline-accounting idea applied host-side.
HIST_EDGES_S: Tuple[float, ...] = tuple(0.00025 * (2 ** k)
                                        for k in range(16))


class _Stage:
    __slots__ = ("count", "total_s", "ewma_s", "max_s", "buckets")

    def __init__(self) -> None:
        self.count = 0
        self.total_s = 0.0
        self.ewma_s = 0.0
        self.max_s = 0.0
        #: Per-bucket (non-cumulative) counts; [-1] is overflow.
        self.buckets = [0] * (len(HIST_EDGES_S) + 1)


class StageTimer:
    """Named wall-clock stages: `with timer.stage("fuse"): ...`.

    EWMA (alpha=0.1) gives a live rate estimate that survives startup
    outliers (first-jit compile); max catches stalls; the fixed
    log-bucket histogram (HIST_EDGES_S) is what p50/p99 dashboards and
    the `/metrics` `jax_mapping_stage_*_seconds` families read — an
    EWMA alone cannot answer "what fraction of ticks missed the
    control period".
    """

    def __init__(self, alpha: float = 0.1) -> None:
        self._lock = threading.Lock()
        self._stages: Dict[str, _Stage] = {}
        self.alpha = alpha

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    def observe(self, name: str, dt_s: float) -> None:
        """Record one already-measured duration against a stage — the
        entry point for code that times a region itself (the
        incremental frontier pipeline's recompute, devprof dispatch
        attribution) but must still report through the ONE stage
        mechanism (`/metrics` summary + fixed log-bucket histogram
        families) instead of a hand-built gauge."""
        with self._lock:
            st = self._stages.setdefault(name, _Stage())
            st.count += 1
            st.total_s += dt_s
            st.max_s = max(st.max_s, dt_s)
            st.ewma_s = (dt_s if st.count == 1
                         else (1 - self.alpha) * st.ewma_s
                         + self.alpha * dt_s)
            # bisect_left: first edge >= dt, i.e. `le` semantics;
            # past the last edge lands in the overflow bucket.
            st.buckets[bisect.bisect_left(HIST_EDGES_S, dt_s)] += 1

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": st.count,
                    "sum_ms": 1e3 * st.total_s,
                    "mean_ms": 1e3 * st.total_s / max(st.count, 1),
                    "ewma_ms": 1e3 * st.ewma_s,
                    "max_ms": 1e3 * st.max_s,
                } for name, st in self._stages.items()
            }

    def histograms(self) -> Dict[str, Dict[str, object]]:
        """Per-stage fixed log-bucket histograms: {"edges_s": ...,
        "buckets": per-bucket counts (last = overflow), "sum_s",
        "count"} — the MetricsRegistry's stage-histogram source."""
        with self._lock:
            return {
                name: {
                    "edges_s": HIST_EDGES_S,
                    "buckets": list(st.buckets),
                    "sum_s": st.total_s,
                    "count": st.count,
                } for name, st in self._stages.items()
            }


class Metrics:
    """Process-wide bundle: counters + stage timers."""

    def __init__(self) -> None:
        self.counters = Counters()
        self.stages = StageTimer()

    def snapshot(self) -> dict:
        return {"counters": self.counters.snapshot(),
                "stages": self.stages.snapshot()}


global_metrics = Metrics()


@contextlib.contextmanager
def device_trace(log_dir: str,
                 host_tracer_level: int = 2,
                 create_perfetto_trace: bool = False
                 ) -> Iterator[Optional[str]]:
    """XLA/TPU profiler trace around a block; view with TensorBoard's
    profile plugin or Perfetto. Yields the log dir, or None if the
    profiler is unavailable (it must never take the control loop down).

    `create_perfetto_trace=True` additionally writes the profiler's
    perfetto_trace.json.gz + a ui.perfetto.dev link — the same viewer
    `obs/export.py`'s host-side traces load into, so device and host
    timelines come out of one toolchain. Off by default: the perfetto
    writer blocks `stop_trace` while it serializes, which a control
    loop must opt into."""
    import jax
    try:
        jax.profiler.start_trace(
            log_dir, create_perfetto_trace=create_perfetto_trace)
        started = True
    except Exception:                               # noqa: BLE001
        started = False
    try:
        yield log_dir if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:                       # noqa: BLE001
                pass
