"""Mission multi-tenancy: thousands of concurrent worlds on one
accelerator (ROADMAP item 4).

"Millions of users" is not one giant fleet — it is MANY independent
missions, each tiny relative to the accelerator. This package adds a
TENANT axis to the mission hot path and a control plane to feed it:

* :mod:`jax_mapping.tenancy.megabatch` — the :class:`TenantBatch`
  pytree (independent mission states stacked along a leading,
  pow2-bucketed tenant dimension) and ONE jitted ``megabatch_step``
  that vmaps the existing `models/fleet` tick over that axis, so N
  missions cost one dispatch chain per tick instead of N.
* :mod:`jax_mapping.tenancy.controlplane` — admit / suspend / resume /
  evict for missions, bucket growth/shrink, admission pre-warm through
  the ISSUE 12 staged-warm-up ladder, eviction checkpoints through the
  generation-retention machinery, and per-tenant serving
  epoch/revision namespaces for `/tiles` delta sessions.
* :mod:`jax_mapping.tenancy.lanehealth` /
  :mod:`jax_mapping.tenancy.journal` — tenant blast-radius containment
  (ISSUE 17): the healthy -> suspect -> QUARANTINED hysteresis ladder
  fed by the megabatch's fused device health word, and the
  append-only CRC-per-record lifecycle journal + compaction snapshot
  that make the registry survive a plane crash (`restore()`).

Bit-identity is the contract: a tenant's trajectory inside a megabatch
equals its solo `fleet_step` trajectory bit-for-bit — same seed, any
bucket size, any co-tenants (tests/test_tenancy.py) — and a
quarantined co-tenant freezes via the same exact-no-op select pads
use, so containment never bends that contract.
"""

from jax_mapping.tenancy.megabatch import (HEALTH_MATCH_FLOOR,
                                           HEALTH_NONFINITE,
                                           HEALTH_POSE_JUMP,
                                           TenantBatch, bucket_capacity,
                                           lane_health_host,
                                           make_tenant_batch,
                                           megabatch_step,
                                           megabatch_tick)
from jax_mapping.tenancy.lanehealth import LaneHealthLadder
from jax_mapping.tenancy.journal import ControlJournal, read_registry
from jax_mapping.tenancy.controlplane import (AdmissionRejected,
                                              TenantControlPlane)

__all__ = ["TenantBatch", "bucket_capacity", "make_tenant_batch",
           "megabatch_step", "megabatch_tick", "TenantControlPlane",
           "HEALTH_NONFINITE", "HEALTH_POSE_JUMP",
           "HEALTH_MATCH_FLOOR", "lane_health_host",
           "LaneHealthLadder", "ControlJournal", "read_registry",
           "AdmissionRejected"]
