"""Durable control plane: an append-only, CRC-per-record lifecycle journal.

The `TenantControlPlane` registry was purely in-memory: a supervisor
crash forgot which tenants exist even though their checkpoints survive
on disk. This module makes the registry durable with the two-file
scheme every production control plane converges on:

* ``control.journal`` — append-only binary records, one per lifecycle
  transition (admit / suspend / resume / evict / quarantine / readmit
  / checkpoint watermark). Each record is ``<u32 length> <payload>
  <u32 crc32(payload)>`` with a JSON payload, so the io/checkpoint
  corruption doctrine applies verbatim: a torn tail (power loss
  mid-append) fails its length or CRC check, the reader TRUNCATES the
  file back to the last intact record, and replay proceeds — corrupt
  degrades, never crashes.
* ``registry.json`` — a compaction snapshot of the folded registry
  (written atomically via tmp+rename, CRC-stamped), taken every
  `journal_compact_every` records so replay cost stays bounded over a
  plane's lifetime. The snapshot stores the sequence number of the
  last folded record; ``read_registry`` loads the snapshot (falling
  back to full-journal replay when it is missing or rotten) and
  replays only the journal records with a HIGHER sequence number —
  "snapshot newer than journal tail" therefore reads cleanly as
  "nothing left to replay".

Replay folds records into ``{tid: {"seed", "epoch", "revision",
"steps", "state"}}``; `TenantControlPlane.restore()` then re-admits
every non-evicted tenant from its generation-retained checkpoint
through the StagedWarmup ladder with its epoch BUMPED (the PR 8 epoch
protocol: clients resync instead of seeing revision regressions).

Threading: appends run under the control plane's `_lock` — the
journal is leaf stdlib file IO whose ordering must match the registry
mutation order it records (the `step()` device-work-under-lock
precedent); readers run at restore time, before the plane serves.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Optional, Tuple

JOURNAL_NAME = "control.journal"
SNAPSHOT_NAME = "registry.json"

#: Lifecycle record kinds (the full containment vocabulary).
RECORD_KINDS = frozenset({
    "admit", "suspend", "resume", "evict", "quarantine", "readmit",
    "checkpoint", "restore",
})

_HEADER = struct.Struct("<I")            # record length prefix
_TRAILER = struct.Struct("<I")           # crc32 over the payload


def _crc(payload: bytes) -> int:
    return zlib.crc32(payload) & 0xFFFFFFFF


class ControlJournal:
    """One plane's journal + snapshot pair under `dirpath`."""

    def __init__(self, dirpath: str):
        self.dirpath = dirpath
        os.makedirs(dirpath, exist_ok=True)
        self.journal_path = os.path.join(dirpath, JOURNAL_NAME)
        self.snapshot_path = os.path.join(dirpath, SNAPSHOT_NAME)
        #: Monotonic record sequence; restored from disk so a reopened
        #: journal keeps extending the same ordering.
        self.seq = 0
        self.n_appends = 0
        self.n_compactions = 0
        registry, seq, _ = read_registry(dirpath)
        self.seq = seq
        self._registry = registry

    # -- append path (control plane, under its _lock) ------------------------

    def append(self, kind: str, tid: str, **fields) -> int:
        """Append one lifecycle record; returns its sequence number.
        The write is flushed (a crash loses at most the torn tail the
        reader truncates, never an acknowledged record's prefix)."""
        if kind not in RECORD_KINDS:
            raise ValueError(f"unknown journal record kind {kind!r}")
        self.seq += 1
        rec = {"seq": self.seq, "kind": kind, "tid": tid, **fields}
        payload = json.dumps(rec, sort_keys=True).encode()
        with open(self.journal_path, "ab") as f:
            f.write(_HEADER.pack(len(payload)) + payload
                    + _TRAILER.pack(_crc(payload)))
            f.flush()
            os.fsync(f.fileno())
        self._fold(self._registry, rec)
        self.n_appends += 1
        return self.seq

    def compact(self) -> None:
        """Fold the live registry into the snapshot (atomic tmp+rename,
        CRC-stamped) and truncate the journal: replay cost resets to
        zero records."""
        doc = {"seq": self.seq, "tenants": self._registry}
        payload = json.dumps(doc, sort_keys=True).encode()
        body = {"crc32": _crc(payload),
                "registry": doc}
        tmp = self.snapshot_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(body, f, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.snapshot_path)
        with open(self.journal_path, "wb") as f:
            f.flush()
            os.fsync(f.fileno())
        self.n_compactions += 1

    def registry(self) -> Dict[str, dict]:
        """The live folded registry (the caller copies if it mutates)."""
        return self._registry

    # -- fold ----------------------------------------------------------------

    @staticmethod
    def _fold(registry: Dict[str, dict], rec: dict) -> None:
        tid = rec.get("tid", "")
        if not tid:
            return
        row = registry.setdefault(tid, {
            "seed": 0, "epoch": -1, "revision": 0, "steps": 0,
            "state": "new"})
        # world_shape/world_dtype ride admit/checkpoint/restore records
        # so `restore()` can build a load template without the live
        # world array (checkpoints hold the bytes, the journal holds
        # the shape).
        for k in ("seed", "epoch", "revision", "steps",
                  "world_shape", "world_dtype"):
            if k in rec:
                row[k] = rec[k]
        kind = rec.get("kind")
        if kind in ("admit", "resume", "readmit"):
            row["state"] = "active"
        elif kind == "suspend":
            row["state"] = "suspended"
        elif kind == "quarantine":
            row["state"] = "quarantined"
        elif kind == "evict":
            row["state"] = "evicted"
        # "checkpoint" is a pure watermark and "restore" re-asserts a
        # lifecycle verbatim — both carry an explicit "state" field
        # (folded below) instead of a kind-implied one.
        if "state" in rec:
            row["state"] = rec["state"]


def read_journal(path: str, truncate_torn: bool = True
                 ) -> Tuple[list, int]:
    """(records, truncated_bytes) from an append-only journal file.
    A torn tail — short header, short payload, or CRC mismatch — ends
    the walk at the last intact record and (by default) truncates the
    file there, the io/checkpoint doctrine: corrupt degrades, never
    crashes, and the torn bytes can never resurrect."""
    records = []
    if not os.path.exists(path):
        return records, 0
    good_end = 0
    with open(path, "rb") as f:
        data = f.read()
    off = 0
    while off + _HEADER.size <= len(data):
        (length,) = _HEADER.unpack_from(data, off)
        end = off + _HEADER.size + length + _TRAILER.size
        if length > len(data) or end > len(data):
            break                        # torn mid-record
        payload = data[off + _HEADER.size: off + _HEADER.size + length]
        (crc,) = _TRAILER.unpack_from(data, end - _TRAILER.size)
        if _crc(payload) != crc:
            break                        # bit rot / torn payload
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        records.append(rec)
        good_end = end
        off = end
    truncated = len(data) - good_end
    if truncated and truncate_torn:
        with open(path, "rb+") as f:
            f.truncate(good_end)
    return records, truncated


def _read_snapshot(path: str) -> Optional[dict]:
    """The snapshot's {seq, tenants} doc, or None when missing/rotten
    (CRC mismatch, unparseable) — the caller then replays the full
    journal instead of crashing (the fallback doctrine)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            body = json.load(f)
        doc = body["registry"]
        payload = json.dumps(doc, sort_keys=True).encode()
        if _crc(payload) != body["crc32"]:
            return None
        return doc
    except (OSError, ValueError, KeyError, TypeError):
        return None


def read_registry(dirpath: str) -> Tuple[Dict[str, dict], int, dict]:
    """(registry, last_seq, meta): the folded tenant registry from
    snapshot + journal replay under `dirpath`. Records at or below the
    snapshot's sequence are skipped (an older journal tail than the
    snapshot replays to nothing — the "snapshot newer than journal
    tail" case); a missing/rotten snapshot degrades to full replay;
    a torn journal tail is truncated. `meta` reports what happened."""
    snap = _read_snapshot(os.path.join(dirpath, SNAPSHOT_NAME))
    registry: Dict[str, dict] = {}
    base_seq = 0
    if snap is not None:
        registry = {t: dict(row) for t, row in snap["tenants"].items()}
        base_seq = int(snap["seq"])
    records, truncated = read_journal(
        os.path.join(dirpath, JOURNAL_NAME))
    last_seq = base_seq
    n_replayed = 0
    for rec in records:
        seq = int(rec.get("seq", 0))
        if seq <= base_seq:
            continue                     # already folded in snapshot
        ControlJournal._fold(registry, rec)
        last_seq = max(last_seq, seq)
        n_replayed = n_replayed + 1
    return registry, last_seq, {
        "snapshot": snap is not None,
        "snapshot_seq": base_seq,
        "n_replayed": n_replayed,
        "torn_bytes_truncated": truncated,
    }
