"""Tenant control plane: admit / suspend / resume / evict missions.

The megabatch (`tenancy/megabatch.py`) makes N missions cost one
dispatch chain per tick; this module is the host-side plane that feeds
it over a mission's whole LIFETIME:

* **admit** — a mission joins the batch. When the post-admission
  bucket has no compiled variant yet, admission first pre-warms it
  through the ISSUE 12 `StagedWarmup` ladder (ROADMAP item 7b
  pairing): the warm call runs on a throwaway zeros batch, rides any
  armed AOT-snapshot / persistent-compile-cache tiers, runs the
  readiness gate against `analysis/compile_budget.json`, and
  re-baselines the dispatch profiler so warmed variants never count as
  live recompiles. Only then does the tenant join — an admission can
  never stall the live batch behind a compile.
* **suspend / resume** — a suspended tenant's state is held host-side
  and the batch COMPACTS (bucket shrink when a smaller bucket fits):
  suspended tenants are never ticked as eternal pad slots. Resume
  re-admits the held state and bumps the tenant's serving epoch.
* **evict** — the mission leaves for good; its final state checkpoints
  through the existing generation-retention machinery
  (`io/checkpoint.save_checkpoint`), so an evicted tenant can be
  re-admitted later from disk like a supervisor resume.

Each tenant owns a serving **epoch/revision namespace**: `revision`
advances once per ticked step, `epoch` bumps on every (re-)admission —
the restart-epoch contract per mission, so `/tiles?tenant=` delta
sessions key cache validity on (epoch, revision) and a resumed
mission can never 304 a stale pre-suspend tile as current
(`tile_store`).

Thread contract: the mission registry, slot order and live batch
mutate only under `_lock` (declared in `analysis/protection.py`,
racewatch-gated over cross-thread admit/evict); flight-recorder
events emit AFTER the lock releases (the StagedWarmup `_move`
discipline), and counters are read lock-free by the /status
convention.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig
from jax_mapping.models import fleet as FM
from jax_mapping.tenancy import megabatch as MB

#: The megabatch entry point's registry-qualified name (the devprof /
#: compile-budget naming contract).
MEGABATCH_ENTRY = "jax_mapping.tenancy.megabatch.megabatch_step"


class _Mission:
    """One tenant's host-side record (mutated only under the plane's
    `_lock`)."""

    __slots__ = ("tid", "seed", "epoch", "revision", "state", "world",
                 "dynamics", "steps", "held_state", "key")

    def __init__(self, tid: str, seed: int, world, key,
                 dynamics=None):
        self.tid = tid
        self.seed = seed
        self.epoch = -1            # first admit bumps to 0
        self.revision = 0
        self.state = "new"         # active | suspended | evicted
        self.world = world
        self.dynamics = dynamics
        self.steps = 0
        self.held_state: Optional[FM.FleetState] = None
        self.key = key


class TenantControlPlane:
    """Admit/evict/suspend for megabatched missions on one config."""

    def __init__(self, cfg: SlamConfig, world_res_m: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 compile_cache=None, devprof=None, pipeline=None):
        self.cfg = cfg
        #: Pipeline latency ledger (obs/pipeline.py) or None: tenant
        #: revision bumps and tile-store commits stamp under the
        #: tenant's OWN label (the serving-namespace contract applied
        #: to freshness telemetry), so `/metrics` pipeline histograms
        #: slice per tenant. Set-once wiring, read bare (the
        #: StagedWarmup convention).
        self.pipeline = pipeline
        self.world_res_m = (cfg.grid.resolution_m if world_res_m is None
                            else world_res_m)
        self.checkpoint_dir = checkpoint_dir
        from jax_mapping.resilience.warmup import StagedWarmup
        #: Admission pre-warm rides the warm-restart ladder: AOT pool /
        #: persistent cache when armed, cold compile otherwise, plus
        #: the compile-budget readiness gate and devprof rebaseline.
        self.warmup = StagedWarmup(cache=compile_cache, devprof=devprof)
        self._lock = threading.Lock()
        self._missions: Dict[str, _Mission] = {}
        #: Active lane order: lane i of the batch is mission
        #: `_order[i]`; pad lanes (i >= len(_order)) are inactive.
        self._order: List[str] = []
        #: Lane order the live batch was last stacked under — how
        #: `_rebuild` carries surviving lanes across admit/evict/
        #: suspend churn.
        self._prev_order: List[str] = []
        self._batch: Optional[MB.TenantBatch] = None
        self._last_diag = None
        self._warmed_buckets: set = set()
        # Observability (lock-free /status counter convention).
        self.n_admitted = 0
        self.n_evicted = 0
        self.n_suspended = 0
        self.n_resumed = 0
        self.n_prewarms = 0
        self.n_ticks = 0
        self.n_compactions = 0
        self._tile_stores: Dict[str, object] = {}

    # -- lifecycle -----------------------------------------------------------

    def admit(self, tid: str, world, seed: int = 0,
              state: Optional[FM.FleetState] = None,
              dynamics=None) -> None:
        """A mission joins the batch. `world` is the tenant's ground
        truth (all tenants share one world SHAPE — the batch stacks
        it); `state` resumes from a given FleetState (eviction
        re-admission), otherwise the mission initialises from its
        seed. Pre-warms the post-admission bucket variant first when
        it has not compiled yet."""
        world = jnp.asarray(world)
        key = jax.random.PRNGKey(seed)
        if state is None:
            state = FM.init_fleet_state(self.cfg, key)
        with self._lock:
            if tid in self._missions \
                    and self._missions[tid].state in ("active",
                                                      "suspended"):
                # Suspended tenants hold un-checkpointed state;
                # resume() is the sanctioned path back — an admit here
                # would silently reinitialise and destroy it.
                raise ValueError(
                    f"tenant {tid!r} is "
                    f"{self._missions[tid].state}; use resume()")
            n_next = len(self._order) + 1
            bucket = MB.bucket_capacity(
                n_next, self.cfg.tenancy.max_tenants,
                exact=self.cfg.tenancy.bit_exact_buckets)
        prewarmed = self._prewarm_bucket(bucket, state, world)
        with self._lock:
            # Re-check under the COMMIT lock: the pre-warm ran outside
            # it, so a racing admit of the same tid (or one that grew
            # the batch past the ladder) must lose here, not corrupt
            # the registry.
            existing = self._missions.get(tid)
            if existing is not None and existing.state in (
                    "active", "suspended"):
                raise ValueError(
                    f"tenant {tid!r} is {existing.state}; lost the "
                    "admission race")
            order2 = self._order + [tid]
            # Rebuild BEFORE any registry mutation: bucket_capacity
            # revalidation and the world-shape stack can both raise,
            # and a failed admission must leave the plane untouched
            # (no half-admitted tenant over a stale batch).
            batch2, prev2, compacted = self._rebuilt(
                order2, extra={tid: (state, world, key)})
            m = existing
            if m is None:
                m = _Mission(tid, seed, world, key, dynamics=dynamics)
                self._missions[tid] = m
            m.seed = seed
            m.world = world
            m.key = key
            if dynamics is not None:
                m.dynamics = dynamics
            m.epoch += 1
            if existing is not None:
                # Re-admission: epoch bump ⇒ revision bump, so an
                # (epoch, revision) ETag pair can never recur with
                # different content — a client's pre-eviction ETag
                # cannot 304 against the re-admitted mission's tiles
                # even if it races the store swap. (A brand-new
                # mission has no prior ETags to collide with.)
                m.revision += 1
            m.state = "active"
            m.held_state = None
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_admitted += 1
            epoch = m.epoch
            self._tile_stores.pop(tid, None)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_admit", tenant=tid, seed=seed,
                               epoch=epoch, bucket=bucket,
                               prewarmed=prewarmed)

    def suspend(self, tid: str) -> None:
        """Remove a tenant from the batch, holding its state host-side;
        the batch compacts (bucket shrink when a smaller bucket fits)
        instead of ticking the slot as a pad forever."""
        with self._lock:
            m = self._require(tid, "active")
            held = self._lane_state_locked(tid)
            order2 = [t for t in self._order if t != tid]
            batch2, prev2, compacted = self._rebuilt(order2)
            m.held_state = held
            m.state = "suspended"
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_suspended += 1
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_suspend", tenant=tid)

    def resume(self, tid: str) -> None:
        """Re-admit a suspended tenant from its held state; its serving
        epoch bumps (the per-mission restart-epoch contract)."""
        with self._lock:
            m = self._require(tid, "suspended")
            held, world, key = m.held_state, m.world, m.key
            bucket = MB.bucket_capacity(
                len(self._order) + 1, self.cfg.tenancy.max_tenants,
                exact=self.cfg.tenancy.bit_exact_buckets)
        prewarmed = self._prewarm_bucket(bucket, held, world)
        with self._lock:
            # Re-require SUSPENDED under the commit lock: a concurrent
            # evict() between the read above and here must win — a
            # resume that re-activated from the pre-evict snapshot
            # would silently undo the eviction (and contradict its
            # checkpoint + flight event).
            m = self._require(tid, "suspended")
            order2 = self._order + [tid]
            batch2, prev2, compacted = self._rebuilt(
                order2, extra={tid: (held, world, key)})
            m.epoch += 1
            m.revision += 1      # the admit() epoch⇒revision contract
            m.state = "active"
            m.held_state = None
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_resumed += 1
            epoch = m.epoch
            self._tile_stores.pop(tid, None)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_resume", tenant=tid,
                               epoch=epoch, bucket=bucket,
                               prewarmed=prewarmed)

    def evict(self, tid: str, checkpoint: Optional[bool] = None) -> Optional[str]:
        """A mission leaves for good: its final state checkpoints
        through the generation-retention machinery (when a checkpoint
        dir is configured) and its lane compacts out. Returns the
        checkpoint path, if one was written."""
        if checkpoint is None:
            checkpoint = self.cfg.tenancy.checkpoint_on_evict
        with self._lock:
            m = self._require(tid, ("active", "suspended"))
            if m.state == "active":
                final = self._lane_state_locked(tid)
                order2 = [t for t in self._order if t != tid]
                batch2, prev2, compacted = self._rebuilt(order2)
                self._order = order2
                self._batch = batch2
                self._prev_order = prev2
                if compacted:
                    self.n_compactions += 1
            else:
                final = m.held_state
            m.held_state = None
            m.state = "evicted"
            # Free the heavy references: a long-lived plane churning
            # through many distinct tenant ids must not pin one world
            # array per lifetime eviction. The record itself stays as
            # a tombstone — epoch continuity across a later
            # re-admission is a serving-correctness fact.
            m.world = None
            m.dynamics = None
            self.n_evicted += 1
            self._tile_stores.pop(tid, None)
        path = None
        if checkpoint and self.checkpoint_dir is not None:
            from jax_mapping.io.checkpoint import save_checkpoint
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            path = os.path.join(self.checkpoint_dir,
                                f"tenant_{tid}.ckpt")
            save_checkpoint(
                path, final, config_json=self.cfg.to_json(),
                retain_generations=(
                    self.cfg.resilience.checkpoint_retain_generations))
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_evict", tenant=tid,
                               checkpointed=path is not None)
        return path

    # -- stepping ------------------------------------------------------------

    def step(self, n: int = 1):
        """Advance every active tenant `n` ticks (one megabatch
        dispatch chain per tick). Returns the last tick's FleetDiag
        (leading tenant axis; inactive lanes meaningless), or None
        when no tenant is active.

        The tick runs under `_lock` (the MapperNode _state_lock
        precedent: device work inside the guarded section), so
        concurrent /status, /metrics and tile snapshots stall up to
        one tick — bounded by the megabatch dispatch plus any closure
        re-runs. A finer-grained scheme (tick a snapshot outside the
        lock, reconcile admissions on install) is a known follow-up,
        not a correctness issue."""
        diag = None
        for _ in range(n):
            stamped = []
            with self._lock:
                if not self._order:
                    return None
                refreshed = self._refreshed_worlds()
                if refreshed is not None:
                    self._batch = self._batch._replace(
                        worlds=refreshed)
                batch = self._batch
                self._batch, diag = MB.megabatch_tick(
                    self.cfg, batch, self.world_res_m)
                for tid in self._order:
                    m = self._missions[tid]
                    m.revision += 1
                    m.steps += 1
                    stamped.append((tid, m.revision, m.steps))
                self._last_diag = diag
                self.n_ticks += 1
            if self.pipeline is not None:
                # Install waypoints OUTSIDE the plane lock (the ledger
                # is a leaf lock of its own): one per tenant revision,
                # under the tenant's serving-namespace label.
                for tid, rev, steps in stamped:
                    self.pipeline.installed(rev, tick=steps,
                                            tenant=tid)
        return diag

    def _refreshed_worlds(self):
        """The batch's worlds array with any changed-geometry tenant
        rows re-uploaded (the SimNode `world_if_changed` idiom), or
        None when nothing changed. Pure reader + mission-record
        updates; the caller installs the result under `_lock`."""
        worlds = None
        for i, tid in enumerate(self._order):
            m = self._missions[tid]
            if m.dynamics is None:
                continue
            w = m.dynamics.world_if_changed(m.steps)
            if w is None:
                continue
            m.world = jnp.asarray(w)
            worlds = (self._batch.worlds if worlds is None else worlds)
            worlds = worlds.at[i].set(m.world)
        return worlds

    # -- state access --------------------------------------------------------

    def live_batch(self) -> Optional[MB.TenantBatch]:
        """The current device batch (None when no tenant is active) —
        the bench/test device-barrier handle."""
        with self._lock:
            return self._batch

    def tenant_state(self, tid: str) -> FM.FleetState:
        """The tenant's current FleetState — its live lane when
        active, the held state when suspended."""
        with self._lock:
            m = self._missions[tid]
            if m.state == "active":
                return self._lane_state_locked(tid)
            if m.held_state is not None:
                return m.held_state
            raise ValueError(f"tenant {tid!r} is {m.state}; no state held")

    def tenant_grid(self, tid: str):
        return self.tenant_state(tid).grid

    def epoch(self, tid: str) -> int:
        with self._lock:
            return self._missions[tid].epoch

    def revision(self, tid: str) -> int:
        with self._lock:
            return self._missions[tid].revision

    def tile_store(self, tid: str):
        """Per-tenant serving TileStore (lazily built): the tenant's
        grid rendered through the ordinary `to_gray` path, revisioned
        by the tenant's OWN (epoch, revision) namespace — `/tiles?
        tenant=` delta sessions stay per-mission correct across
        co-tenant churn and suspend/resume cycles."""
        with self._lock:
            store = self._tile_stores.get(tid)
            if store is None:
                # Validate BEFORE constructing anything: this sits on
                # the public /tiles?tenant= surface, and caching a
                # store per unknown/evicted id would let a client loop
                # over bogus ids and grow the dict without bound.
                self._require(tid, ("active", "suspended"))
        if store is not None:
            return store
        from jax_mapping.ops import grid as G
        from jax_mapping.serving.tiles import TileStore

        def _revision() -> int:
            return self.revision(tid)

        def _snapshot():
            with self._lock:
                m = self._missions[tid]
                if m.state == "evicted" or (
                        m.state != "active" and m.held_state is None):
                    raise ValueError(
                        f"tenant {tid!r} is {m.state}; nothing to serve")
                # Revision BEFORE content (the serving-snapshot
                # ordering): both reads sit in one lock section here,
                # but the order still documents the contract.
                rev = m.revision
                grid = (self._lane_state_locked(tid).grid
                        if m.state == "active" else m.held_state.grid)
            gray = np.asarray(G.to_gray(self.cfg.grid, grid))
            return rev, gray, None

        on_install = None
        if self.pipeline is not None:
            ledger = self.pipeline

            def on_install(rev, _tid=tid):
                ledger.encoded(rev, tenant=_tid)

        store = TileStore(self.cfg.serving, f"tenant:{tid}",
                          _revision, _snapshot, on_install=on_install)
        with self._lock:
            # First builder wins under concurrent HTTP readers.
            store = self._tile_stores.setdefault(tid, store)
        return store

    # -- internals -----------------------------------------------------------

    def _require(self, tid: str, states) -> _Mission:
        m = self._missions.get(tid)
        if m is None:
            raise KeyError(f"unknown tenant {tid!r}")
        allowed = (states,) if isinstance(states, str) else states
        if m.state not in allowed:
            raise ValueError(
                f"tenant {tid!r} is {m.state}, need {allowed}")
        return m

    def _lane_state_locked(self, tid: str) -> FM.FleetState:
        i = self._order.index(tid)
        return MB.lane_state(self._batch, i)

    def _rebuilt(self, order, extra: Optional[dict] = None):
        """(batch, prev_order, compacted) re-stacked for `order` —
        lanes already in the old batch slice out of it, `extra` maps
        not-yet-registered tids to their (state, world, key). Pure
        compute that can RAISE (ladder/ceiling refusal, world-shape
        mismatch) without touching any plane state: callers install
        the triple — and only then mutate the registry — under their
        own `with self._lock` block, so a failed rebuild rolls back to
        exactly the prior plane and every guarded-field write sits
        lexically inside a lock region (the B3 discipline)."""
        old_cap = (0 if self._batch is None
                   else int(self._batch.active.shape[0]))
        if not order:
            return None, [], old_cap > 0
        states, worlds, keys = [], [], []
        for tid in order:
            if extra is not None and tid in extra:
                s, w, k = extra[tid]
            else:
                m = self._missions[tid]
                s, w, k = self._old_lane(tid), m.world, m.key
            states.append(s)
            worlds.append(w)
            keys.append(k)
        cap = MB.bucket_capacity(len(order),
                                 self.cfg.tenancy.max_tenants,
                                 exact=self.cfg.tenancy.bit_exact_buckets)
        batch = MB.make_tenant_batch(states, worlds, keys,
                                     capacity=cap)
        return batch, list(order), cap < old_cap

    def _old_lane(self, tid: str) -> FM.FleetState:
        if self._batch is None or tid not in self._prev_order:
            raise KeyError(f"tenant {tid!r} has no live lane to carry")
        return MB.lane_state(self._batch, self._prev_order.index(tid))

    def _prewarm_bucket(self, bucket: int, template: FM.FleetState,
                        world) -> bool:
        """Compile (or warm-tier-load) the megabatch variant for
        `bucket` BEFORE the tenant joins, through the StagedWarmup
        ladder: begin_warming -> zeros pre-warm (AOT pool / persistent
        cache / cold compile) -> readiness gate vs compile_budget.json
        + devprof rebaseline -> ready. Returns True when a warm-up
        actually ran."""
        with self._lock:
            if not self.cfg.tenancy.prewarm_on_admit \
                    or bucket in self._warmed_buckets:
                return False
        from jax_mapping.obs.devprof import abstract_signature
        warm = MB.make_tenant_batch(
            [template], [world], [jax.random.PRNGKey(0)])
        # Pad the 1-mission template batch up to the target bucket by
        # abstractly widening the leading axis: the signature is what
        # compiles, not the values.
        def widen(x):
            return jax.ShapeDtypeStruct((bucket,) + tuple(x.shape[1:]),
                                        x.dtype)
        warm_abs = jax.tree.map(widen, warm)
        sig = abstract_signature(
            (self.cfg, warm_abs, self.world_res_m), {})
        self.warmup.begin_warming()
        # manifest=False: warm ONLY this bucket's signature — an
        # admission must not re-run the whole persisted AOT warm sweep
        # (that is the RESTART path's job, once).
        self.warmup.prewarm(signatures={MEGABATCH_ENTRY: [sig]},
                            force=True, manifest=False)
        self.warmup.mark_ready()
        with self._lock:
            self._warmed_buckets.add(bucket)
            self.n_prewarms += 1
        return True

    # -- exports -------------------------------------------------------------

    def status(self) -> dict:
        """The /status `tenancy` object (one consistent section)."""
        with self._lock:
            n_active = len(self._order)
            cap = (0 if self._batch is None
                   else int(self._batch.active.shape[0]))
            tenants = {
                tid: {"state": m.state, "epoch": m.epoch,
                      "revision": m.revision, "steps": m.steps,
                      "seed": m.seed}
                for tid, m in sorted(self._missions.items())}
            counters = dict(
                n_admitted=self.n_admitted, n_evicted=self.n_evicted,
                n_suspended=self.n_suspended, n_resumed=self.n_resumed,
                n_prewarms=self.n_prewarms, n_ticks=self.n_ticks,
                n_compactions=self.n_compactions)
            warmed = sorted(self._warmed_buckets)
        n_susp = sum(1 for t in tenants.values()
                     if t["state"] == "suspended")
        n_evic = sum(1 for t in tenants.values()
                     if t["state"] == "evicted")
        return {
            "n_active": n_active,
            "n_suspended": n_susp,
            "n_evicted": n_evic,
            "bucket_capacity": cap,
            "bucket_occupancy": (n_active / cap) if cap else 0.0,
            "pad_waste_frac": ((cap - n_active) / cap) if cap else 0.0,
            "warmed_buckets": warmed,
            "warmup": self.warmup.snapshot(),
            "tenants": tenants,
            **counters,
        }

    def metric_families(self):
        """`jax_mapping_tenant_*` gauge families for the declarative
        /metrics registry (obs/registry.py) — one consistent status
        snapshot per render."""
        from jax_mapping.obs.registry import Family
        s = self.status()
        return (
            Family("jax_mapping_tenant_active", "gauge",
                   (("", str(s["n_active"])),)),
            Family("jax_mapping_tenant_suspended", "gauge",
                   (("", str(s["n_suspended"])),)),
            Family("jax_mapping_tenant_evicted", "gauge",
                   (("", str(s["n_evicted"])),)),
            Family("jax_mapping_tenant_bucket_capacity", "gauge",
                   (("", str(s["bucket_capacity"])),)),
            Family("jax_mapping_tenant_bucket_occupancy", "gauge",
                   (("", f"{s['bucket_occupancy']:.4f}"),)),
            Family("jax_mapping_tenant_pad_waste_frac", "gauge",
                   (("", f"{s['pad_waste_frac']:.4f}"),)),
            Family("jax_mapping_tenant_ticks_total", "counter",
                   (("", str(s["n_ticks"])),)),
        )
