"""Tenant control plane: admit / suspend / resume / evict missions.

The megabatch (`tenancy/megabatch.py`) makes N missions cost one
dispatch chain per tick; this module is the host-side plane that feeds
it over a mission's whole LIFETIME:

* **admit** — a mission joins the batch. When the post-admission
  bucket has no compiled variant yet, admission first pre-warms it
  through the ISSUE 12 `StagedWarmup` ladder (ROADMAP item 7b
  pairing): the warm call runs on a throwaway zeros batch, rides any
  armed AOT-snapshot / persistent-compile-cache tiers, runs the
  readiness gate against `analysis/compile_budget.json`, and
  re-baselines the dispatch profiler so warmed variants never count as
  live recompiles. Only then does the tenant join — an admission can
  never stall the live batch behind a compile.
* **suspend / resume** — a suspended tenant's state is held host-side
  and the batch COMPACTS (bucket shrink when a smaller bucket fits):
  suspended tenants are never ticked as eternal pad slots. Resume
  re-admits the held state and bumps the tenant's serving epoch.
* **evict** — the mission leaves for good; its final state checkpoints
  through the existing generation-retention machinery
  (`io/checkpoint.save_checkpoint`), so an evicted tenant can be
  re-admitted later from disk like a supervisor resume.

Each tenant owns a serving **epoch/revision namespace**: `revision`
advances once per ticked step, `epoch` bumps on every (re-)admission —
the restart-epoch contract per mission, so `/tiles?tenant=` delta
sessions key cache validity on (epoch, revision) and a resumed
mission can never 304 a stale pre-suspend tile as current
(`tile_store`).

Blast-radius containment (ISSUE 17) adds three facilities on top:

* **lane health** (`TenancyConfig.lane_health`): `step()` folds the
  megabatch's device-computed health words through a per-tenant
  `LaneHealthLadder` (healthy -> suspect -> QUARANTINED). A suspect
  tenant's published revision FREEZES on its last-good content (the
  pre-flag lane state is held and served, so a frozen revision's
  bytes never drift under it); a quarantined tenant's lane freezes
  in place via the pad-style ``active=False`` select — an exact
  no-op, so co-tenants stay bit-identical to a no-fault run by the
  same construction pads use — and bounded seeded probes
  (finite-check + one solo-executable tick) gate re-admission, which
  bumps the epoch like any other re-admission.
* **durable registry** (`TenancyConfig.journal`): every lifecycle
  transition appends a CRC'd record to `tenancy/journal.py` under
  the checkpoint dir; `checkpoint_all()` snapshots live tenant state
  through the generation-retention machinery and `restore()` replays
  snapshot+journal to re-admit the SAME tenant set after a plane
  crash, every epoch bumped (the PR 8 epoch protocol — clients
  resync instead of seeing revision regressions).
* **chaos hooks** (`set_tenant_poison` / `state_jump_tenant`): the
  seam `resilience/faultplan.py`'s tenant kinds drive — lane-input
  mutation happens here, under the plane's own lock, never by
  reaching into the batch from outside.

Thread contract: the mission registry, slot order and live batch
mutate only under `_lock` (declared in `analysis/protection.py`,
racewatch-gated over cross-thread admit/evict); flight-recorder
events emit AFTER the lock releases (the StagedWarmup `_move`
discipline), and counters are read lock-free by the /status
convention. The health ladder and journal are LEAF structures owned
by `_lock` (the `_missions` convention — journal file IO ordering
must match the registry mutation order it records).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from jax_mapping.config import SlamConfig
from jax_mapping.models import fleet as FM
from jax_mapping.tenancy import megabatch as MB
from jax_mapping.tenancy.lanehealth import (HEALTHY, QUARANTINED,
                                            LaneHealthLadder)

#: The megabatch entry point's registry-qualified name (the devprof /
#: compile-budget naming contract).
MEGABATCH_ENTRY = "jax_mapping.tenancy.megabatch.megabatch_step"


class AdmissionRejected(RuntimeError):
    """Raised by `admit()` when `TenancyConfig.admission_queue_max`
    concurrent admissions are already in flight: bounded backpressure
    instead of unbounded serialization behind the commit lock — the
    caller retries or sheds, and the rejection is a
    `tenancy_admission_rejected` flight event + /status counter, not
    an invisible stall."""


class _Mission:
    """One tenant's host-side record (mutated only under the plane's
    `_lock`)."""

    __slots__ = ("tid", "seed", "epoch", "revision", "state", "world",
                 "dynamics", "steps", "held_state", "key")

    def __init__(self, tid: str, seed: int, world, key,
                 dynamics=None):
        self.tid = tid
        self.seed = seed
        self.epoch = -1            # first admit bumps to 0
        self.revision = 0
        self.state = "new"         # active | suspended | evicted
        self.world = world
        self.dynamics = dynamics
        self.steps = 0
        self.held_state: Optional[FM.FleetState] = None
        self.key = key


class TenantControlPlane:
    """Admit/evict/suspend for megabatched missions on one config."""

    def __init__(self, cfg: SlamConfig, world_res_m: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 compile_cache=None, devprof=None, pipeline=None):
        # Bounded-memory tenancy (ISSUE 18): under `cfg.world.windowed`
        # every mission lane runs at the WINDOW-sized grid — the plane
        # transforms its config ONCE here so init/stack/tick/serve/
        # checkpoint/restore all agree on lane shapes (a mixed-extent
        # plane would shape-reject its own checkpoints). Identity when
        # not windowed — bit-exact pre-PR.
        cfg = MB.windowed_mission_config(cfg)
        self.cfg = cfg
        #: Pipeline latency ledger (obs/pipeline.py) or None: tenant
        #: revision bumps and tile-store commits stamp under the
        #: tenant's OWN label (the serving-namespace contract applied
        #: to freshness telemetry), so `/metrics` pipeline histograms
        #: slice per tenant. Set-once wiring, read bare (the
        #: StagedWarmup convention).
        self.pipeline = pipeline
        self.world_res_m = (cfg.grid.resolution_m if world_res_m is None
                            else world_res_m)
        self.checkpoint_dir = checkpoint_dir
        from jax_mapping.resilience.warmup import StagedWarmup
        #: Admission pre-warm rides the warm-restart ladder: AOT pool /
        #: persistent cache when armed, cold compile otherwise, plus
        #: the compile-budget readiness gate and devprof rebaseline.
        self.warmup = StagedWarmup(cache=compile_cache, devprof=devprof)
        self._lock = threading.Lock()
        self._missions: Dict[str, _Mission] = {}
        #: Active lane order: lane i of the batch is mission
        #: `_order[i]`; pad lanes (i >= len(_order)) are inactive.
        self._order: List[str] = []
        #: Lane order the live batch was last stacked under — how
        #: `_rebuild` carries surviving lanes across admit/evict/
        #: suspend churn.
        self._prev_order: List[str] = []
        self._batch: Optional[MB.TenantBatch] = None
        self._last_diag = None
        self._warmed_buckets: set = set()
        # Observability (lock-free /status counter convention).
        self.n_admitted = 0
        self.n_evicted = 0
        self.n_suspended = 0
        self.n_resumed = 0
        self.n_prewarms = 0
        self.n_ticks = 0
        self.n_compactions = 0
        self.n_quarantined = 0
        self.n_admissions_rejected = 0
        self._tile_stores: Dict[str, object] = {}
        #: Blast-radius containment (ISSUE 17): the hysteresis ladder
        #: and chaos-poison set are leaf structures mutated only under
        #: `_lock`; `_admissions_in_flight` is the bounded-admission
        #: gauge behind `AdmissionRejected`.
        self._lanehealth = LaneHealthLadder(cfg.tenancy)
        self._poisoned: set = set()
        self._admissions_in_flight = 0
        #: Durable registry: armed by `TenancyConfig.journal` when a
        #: checkpoint dir exists. Set-once wiring (the warmup
        #: convention); appends run under `_lock`.
        self._journal = None
        if cfg.tenancy.journal and checkpoint_dir is not None:
            from jax_mapping.tenancy.journal import ControlJournal
            self._journal = ControlJournal(
                os.path.join(checkpoint_dir, "controlplane"))

    # -- lifecycle -----------------------------------------------------------

    def admit(self, tid: str, world, seed: int = 0,
              state: Optional[FM.FleetState] = None,
              dynamics=None) -> None:
        """A mission joins the batch. `world` is the tenant's ground
        truth (all tenants share one world SHAPE — the batch stacks
        it); `state` resumes from a given FleetState (eviction
        re-admission), otherwise the mission initialises from its
        seed. Pre-warms the post-admission bucket variant first when
        it has not compiled yet.

        When `admission_queue_max > 0`, at most that many admissions
        may be in flight at once (an admission spans its pre-warm, so
        an unbounded pile-up would serialize behind the commit lock
        for a compile each): excess admissions raise
        `AdmissionRejected` immediately instead of queueing."""
        qmax = self.cfg.tenancy.admission_queue_max
        with self._lock:
            if qmax > 0 and self._admissions_in_flight >= qmax:
                self.n_admissions_rejected += 1
                in_flight = self._admissions_in_flight
            else:
                self._admissions_in_flight += 1
                in_flight = None
        if in_flight is not None:
            from jax_mapping.obs.recorder import flight_recorder
            flight_recorder.record("tenancy_admission_rejected",
                                   tenant=tid, in_flight=in_flight,
                                   queue_max=qmax)
            raise AdmissionRejected(
                f"admission of {tid!r} rejected: {in_flight} "
                f"admission(s) already in flight "
                f"(admission_queue_max={qmax})")
        try:
            self._admit(tid, world, seed, state, dynamics)
        finally:
            with self._lock:
                self._admissions_in_flight -= 1

    def _admit(self, tid: str, world, seed: int,
               state: Optional[FM.FleetState], dynamics) -> None:
        world = jnp.asarray(world)
        key = jax.random.PRNGKey(seed)
        if state is None:
            state = FM.init_fleet_state(self.cfg, key)
        with self._lock:
            if tid in self._missions \
                    and self._missions[tid].state in ("active",
                                                      "suspended"):
                # Suspended tenants hold un-checkpointed state;
                # resume() is the sanctioned path back — an admit here
                # would silently reinitialise and destroy it.
                raise ValueError(
                    f"tenant {tid!r} is "
                    f"{self._missions[tid].state}; use resume()")
            n_next = len(self._order) + 1
            bucket = MB.bucket_capacity(
                n_next, self.cfg.tenancy.max_tenants,
                exact=self.cfg.tenancy.bit_exact_buckets)
        prewarmed = self._prewarm_bucket(bucket, state, world)
        with self._lock:
            # Re-check under the COMMIT lock: the pre-warm ran outside
            # it, so a racing admit of the same tid (or one that grew
            # the batch past the ladder) must lose here, not corrupt
            # the registry.
            existing = self._missions.get(tid)
            if existing is not None and existing.state in (
                    "active", "suspended"):
                raise ValueError(
                    f"tenant {tid!r} is {existing.state}; lost the "
                    "admission race")
            order2 = self._order + [tid]
            # Rebuild BEFORE any registry mutation: bucket_capacity
            # revalidation and the world-shape stack can both raise,
            # and a failed admission must leave the plane untouched
            # (no half-admitted tenant over a stale batch).
            batch2, prev2, compacted = self._rebuilt(
                order2, extra={tid: (state, world, key)})
            m = existing
            if m is None:
                m = _Mission(tid, seed, world, key, dynamics=dynamics)
                self._missions[tid] = m
            m.seed = seed
            m.world = world
            m.key = key
            if dynamics is not None:
                m.dynamics = dynamics
            m.epoch += 1
            if existing is not None:
                # Re-admission: epoch bump ⇒ revision bump, so an
                # (epoch, revision) ETag pair can never recur with
                # different content — a client's pre-eviction ETag
                # cannot 304 against the re-admitted mission's tiles
                # even if it races the store swap. (A brand-new
                # mission has no prior ETags to collide with.)
                m.revision += 1
            m.state = "active"
            m.held_state = None
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_admitted += 1
            epoch = m.epoch
            self._tile_stores.pop(tid, None)
            self._journal_append("admit", m)
        if self._journal is not None:
            # A journal-armed admission durably exists from tick zero:
            # a plane crash before the first checkpoint_all() must
            # restore the tenant, not report it lost.
            self._checkpoint_tenant(tid, state, world, key)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_admit", tenant=tid, seed=seed,
                               epoch=epoch, bucket=bucket,
                               prewarmed=prewarmed)

    def suspend(self, tid: str) -> None:
        """Remove a tenant from the batch, holding its state host-side;
        the batch compacts (bucket shrink when a smaller bucket fits)
        instead of ticking the slot as a pad forever."""
        with self._lock:
            m = self._require(tid, "active")
            held = self._lane_state_locked(tid)
            order2 = [t for t in self._order if t != tid]
            batch2, prev2, compacted = self._rebuilt(order2)
            if m.held_state is None:
                # A SUSPECT tenant already holds its last-good state —
                # suspending must not clobber it with the flagged lane.
                m.held_state = held
            m.state = "suspended"
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_suspended += 1
            # Out of the batch means out of the ladder: a later resume
            # re-enters with a clean bill of health.
            self._lanehealth.forget(tid)
            self._journal_append("suspend", m)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_suspend", tenant=tid)

    def resume(self, tid: str) -> None:
        """Re-admit a suspended tenant from its held state; its serving
        epoch bumps (the per-mission restart-epoch contract)."""
        with self._lock:
            m = self._require(tid, "suspended")
            held, world, key = m.held_state, m.world, m.key
            bucket = MB.bucket_capacity(
                len(self._order) + 1, self.cfg.tenancy.max_tenants,
                exact=self.cfg.tenancy.bit_exact_buckets)
        prewarmed = self._prewarm_bucket(bucket, held, world)
        with self._lock:
            # Re-require SUSPENDED under the commit lock: a concurrent
            # evict() between the read above and here must win — a
            # resume that re-activated from the pre-evict snapshot
            # would silently undo the eviction (and contradict its
            # checkpoint + flight event).
            m = self._require(tid, "suspended")
            order2 = self._order + [tid]
            batch2, prev2, compacted = self._rebuilt(
                order2, extra={tid: (held, world, key)})
            m.epoch += 1
            m.revision += 1      # the admit() epoch⇒revision contract
            m.state = "active"
            m.held_state = None
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
            self.n_resumed += 1
            epoch = m.epoch
            self._tile_stores.pop(tid, None)
            self._journal_append("resume", m)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_resume", tenant=tid,
                               epoch=epoch, bucket=bucket,
                               prewarmed=prewarmed)

    def evict(self, tid: str, checkpoint: Optional[bool] = None) -> Optional[str]:
        """A mission leaves for good: its final state checkpoints
        through the generation-retention machinery (when a checkpoint
        dir is configured) and its lane compacts out. Returns the
        checkpoint path, if one was written."""
        if checkpoint is None:
            checkpoint = self.cfg.tenancy.checkpoint_on_evict
        with self._lock:
            m = self._require(tid, ("active", "suspended",
                                    "quarantined"))
            if m.state in ("active", "quarantined"):
                # A quarantined tenant's live lane is its FROZEN
                # (possibly poisoned) state; the held last-good state
                # is what an eviction checkpoint must preserve.
                final = (m.held_state if m.state == "quarantined"
                         else self._lane_state_locked(tid))
                order2 = [t for t in self._order if t != tid]
                batch2, prev2, compacted = self._rebuilt(order2)
                self._order = order2
                self._batch = batch2
                self._prev_order = prev2
                if compacted:
                    self.n_compactions += 1
            else:
                final = m.held_state
            m.held_state = None
            m.state = "evicted"
            self._lanehealth.forget(tid)
            self._poisoned.discard(tid)
            self._journal_append("evict", m)
            # Free the heavy references: a long-lived plane churning
            # through many distinct tenant ids must not pin one world
            # array per lifetime eviction. The record itself stays as
            # a tombstone — epoch continuity across a later
            # re-admission is a serving-correctness fact.
            m.world = None
            m.dynamics = None
            self.n_evicted += 1
            self._tile_stores.pop(tid, None)
        path = None
        if checkpoint and self.checkpoint_dir is not None \
                and final is not None:
            from jax_mapping.io.checkpoint import save_checkpoint
            os.makedirs(self.checkpoint_dir, exist_ok=True)
            path = os.path.join(self.checkpoint_dir,
                                f"tenant_{tid}.ckpt")
            save_checkpoint(
                path, final, config_json=self.cfg.to_json(),
                retain_generations=(
                    self.cfg.resilience.checkpoint_retain_generations))
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_evict", tenant=tid,
                               checkpointed=path is not None)
        return path

    # -- stepping ------------------------------------------------------------

    def step(self, n: int = 1):
        """Advance every active tenant `n` ticks (one megabatch
        dispatch chain per tick). Returns the last tick's FleetDiag
        (leading tenant axis; inactive lanes meaningless), or None
        when no tenant is active.

        The tick runs under `_lock` (the MapperNode _state_lock
        precedent: device work inside the guarded section), so
        concurrent /status, /metrics and tile snapshots stall up to
        one tick — bounded by the megabatch dispatch plus any closure
        re-runs. A finer-grained scheme (tick a snapshot outside the
        lock, reconcile admissions on install) is a known follow-up,
        not a correctness issue."""
        diag = None
        armed = (self.cfg.tenancy.enabled
                 and self.cfg.tenancy.lane_health)
        for _ in range(n):
            stamped = []
            events = []
            with self._lock:
                if not self._order:
                    return None
                refreshed = self._refreshed_worlds()
                if refreshed is not None:
                    self._batch = self._batch._replace(
                        worlds=refreshed)
                # Last-good capture point: BEFORE the chaos seam, so a
                # poisoned tick's held state is the genuine pre-fault
                # content, not the injected garbage.
                batch_before = self._batch
                if self._poisoned:
                    self._inject_poison_locked()
                batch = self._batch
                self._batch, diag, health = MB.megabatch_tick(
                    self.cfg, batch, self.world_res_m)
                tick = self.n_ticks + 1
                frozen = (self._fold_health_locked(
                    health, batch_before, tick, events) if armed else ())
                for tid in self._order:
                    m = self._missions[tid]
                    if m.state != "active" or tid in frozen:
                        # Quarantined lanes are frozen no-ops and
                        # SUSPECT lanes do not publish: their revision
                        # stays pinned to the held last-good content
                        # (so a frozen revision's bytes never drift)
                        # and their pipeline label goes silent — which
                        # is exactly what lets the per-tenant SLO
                        # ingest-stall breach single out the sick
                        # tenant.
                        continue
                    m.revision += 1
                    m.steps += 1
                    stamped.append((tid, m.revision, m.steps))
                if armed:
                    self._run_probes_locked(tick, events)
                self._last_diag = diag
                self.n_ticks = tick
            from jax_mapping.obs.recorder import flight_recorder
            for name, kw in events:
                flight_recorder.record(name, **kw)
            if self.pipeline is not None:
                # Install waypoints OUTSIDE the plane lock (the ledger
                # is a leaf lock of its own): one per tenant revision,
                # under the tenant's serving-namespace label.
                for tid, rev, steps in stamped:
                    self.pipeline.installed(rev, tick=steps,
                                            tenant=tid)
        return diag

    def _refreshed_worlds(self):
        """The batch's worlds array with any changed-geometry tenant
        rows re-uploaded (the SimNode `world_if_changed` idiom), or
        None when nothing changed. Pure reader + mission-record
        updates; the caller installs the result under `_lock`."""
        worlds = None
        for i, tid in enumerate(self._order):
            m = self._missions[tid]
            if m.dynamics is None:
                continue
            w = m.dynamics.world_if_changed(m.steps)
            if w is None:
                continue
            m.world = jnp.asarray(w)
            worlds = (self._batch.worlds if worlds is None else worlds)
            worlds = worlds.at[i].set(m.world)
        return worlds

    # -- blast-radius containment (ISSUE 17) ---------------------------------

    def _fold_health_locked(self, health, batch_before, tick: int,
                            events: list):
        """Fold one tick's (B,) health words through the hysteresis
        ladder; returns the set of tids whose revision must FREEZE
        this tick (suspect or newly quarantined). Caller holds
        `_lock`; flight events append to `events` for post-release
        emission."""
        frozen = set()
        for i, tid in enumerate(self._order):
            m = self._missions[tid]
            if m.state != "active":
                continue
            word = int(health[i])
            if word and m.held_state is None:
                # Entering suspect: hold the PRE-tick lane — the exact
                # content of the currently published revision, which
                # is what keeps serving while the lane is sick.
                m.held_state = MB.lane_state(batch_before, i)
            verdict = self._lanehealth.observe(tid, word, tick)
            if word:
                frozen.add(tid)
            elif m.held_state is not None \
                    and self._lanehealth.state(tid) == HEALTHY:
                # Clean tick after a transient: the lane is its own
                # truth again; the next revision bump publishes it.
                m.held_state = None
            if verdict == QUARANTINED:
                # Freeze the lane in place via the pad-style inactive
                # select: an exact no-op (the pad contract), so
                # co-tenant lanes keep their bit-identical trajectory
                # by construction — no rebuild, no restack.
                self._batch = self._batch._replace(
                    active=self._batch.active.at[i].set(False))
                m.state = "quarantined"
                self.n_quarantined += 1
                self._poisoned.discard(tid)
                self._journal_append("quarantine", m, word=word)
                events.append(("tenancy_quarantine",
                               dict(tenant=tid, tick=tick, word=word,
                                    streak=self.cfg.tenancy
                                    .quarantine_persist_ticks)))
        return frozen

    def _run_probes_locked(self, tick: int, events: list) -> None:
        """Bounded seeded re-admission probes for quarantined tenants
        on the deterministic tick clock (same-seed runs probe at
        identical steps). A passing probe re-activates the lane from
        the held last-good state and bumps the epoch; a failing one
        burns one unit of the probe budget."""
        for tid in self._lanehealth.quarantined():
            m = self._missions.get(tid)
            if m is None or m.state != "quarantined":
                continue
            if not self._lanehealth.probe_due(tid, tick):
                continue
            ok = self._probe_locked(m)
            readmit = self._lanehealth.note_probe(tid, ok, tick)
            events.append(("tenancy_readmit_probe",
                           dict(tenant=tid, tick=tick, ok=ok)))
            if readmit:
                self._readmit_locked(tid, m, tick, events)

    def _probe_locked(self, m: "_Mission") -> bool:
        """One re-admission probe verdict: the held state must be
        finite in every float leaf AND survive one solo-executable
        tick (the identical `fleet_step` the solo oracle runs) with a
        clean health word — the ISSUE 17 revalidation gate."""
        held = m.held_state
        if held is None or m.world is None:
            return False
        for leaf in jax.tree_util.tree_leaves(held):
            a = np.asarray(leaf)
            if np.issubdtype(a.dtype, np.floating) \
                    and not np.isfinite(a).all():
                return False
        s1, d1 = FM.fleet_step(self.cfg, held, self.world_res_m,
                               m.world)
        return MB.lane_health_host(self.cfg, held, s1, d1) == 0

    def _readmit_locked(self, tid: str, m: "_Mission", tick: int,
                        events: list) -> None:
        """Re-activate a probe-verified tenant from its held state.
        In-batch lanes rewrite in place (`.at[i].set` per leaf — no
        restack, co-tenant values untouched); a restored-quarantined
        tenant without a live lane re-joins through the resume-style
        rebuild. Epoch bumps (re-admission contract), and epoch ⇒
        revision so no (epoch, revision) pair recurs."""
        if tid in self._order:
            i = self._order.index(tid)
            states = jax.tree.map(lambda b, s: b.at[i].set(s),
                                  self._batch.states, m.held_state)
            self._batch = self._batch._replace(
                states=states,
                active=self._batch.active.at[i].set(True))
        else:
            order2 = self._order + [tid]
            batch2, prev2, compacted = self._rebuilt(
                order2, extra={tid: (m.held_state, m.world, m.key)})
            self._order = order2
            self._batch = batch2
            self._prev_order = prev2
            if compacted:
                self.n_compactions += 1
        m.state = "active"
        m.held_state = None
        m.epoch += 1
        m.revision += 1
        self._tile_stores.pop(tid, None)
        self._journal_append("readmit", m)
        events.append(("tenancy_readmit",
                       dict(tenant=tid, tick=tick, epoch=m.epoch)))

    def _inject_poison_locked(self) -> None:
        """Chaos seam (`tenant_poison`): NaN every poisoned ACTIVE
        tenant's est-pose lane input before the tick. Quarantined
        lanes are skipped — their frozen state must stay byte-stable
        under the freeze select."""
        for tid in sorted(self._poisoned):
            m = self._missions.get(tid)
            if m is None or m.state != "active" \
                    or tid not in self._order:
                continue
            i = self._order.index(tid)
            states = self._batch.states
            states = states._replace(
                est_poses=states.est_poses.at[i].set(jnp.nan))
            self._batch = self._batch._replace(states=states)

    def set_tenant_poison(self, tid: str, active: bool) -> None:
        """Arm/clear NaN poisoning of one tenant's lane inputs (the
        `tenant_poison` FaultPlan kind's refcount boundary — the plane
        only sees on/off)."""
        with self._lock:
            if active:
                self._poisoned.add(tid)
            else:
                self._poisoned.discard(tid)

    def state_jump_tenant(self, tid: str, value_m: float) -> None:
        """Teleport one tenant's estimated poses by `value_m` metres
        (the `tenant_state_jump` FaultPlan kind): a survivable-state
        fault the MATCH-FLOOR sentinel catches (the jump corrupts the
        input, so the within-step pose delta stays small — scan
        matching against the tenant's own map is what degrades)."""
        with self._lock:
            m = self._missions.get(tid)
            if m is None or m.state != "active" \
                    or tid not in self._order:
                return
            i = self._order.index(tid)
            states = self._batch.states
            states = states._replace(
                est_poses=states.est_poses.at[i, :, :2].add(
                    jnp.float32(value_m)))
            self._batch = self._batch._replace(states=states)

    # -- durable control plane -----------------------------------------------

    def _journal_append(self, kind: str, m: "_Mission",
                        **extra) -> None:
        """Append one lifecycle record (caller holds `_lock`; the
        journal is a leaf whose ordering must match registry mutation
        order). Compaction folds in every `journal_compact_every`
        appends. No-op when the journal is unarmed."""
        if self._journal is None:
            return
        fields = dict(seed=m.seed, epoch=m.epoch, revision=m.revision,
                      steps=m.steps, **extra)
        if m.world is not None:
            fields["world_shape"] = [int(s) for s in m.world.shape]
            fields["world_dtype"] = str(m.world.dtype)
        self._journal.append(kind, m.tid, **fields)
        every = max(1, self.cfg.tenancy.journal_compact_every)
        if self._journal.n_appends % every == 0:
            self._journal.compact()

    def _live_ckpt_path(self, tid: str) -> str:
        """The containment checkpoint slot: distinct from evict's
        `tenant_{tid}.ckpt` (plain FleetState) because this one holds
        the `{fleet, key, world}` payload `restore()` needs — mixing
        formats in one generation chain would turn a fallback load
        into a template mismatch."""
        return os.path.join(self.checkpoint_dir,
                            f"tenant_{tid}.live.ckpt")

    def _checkpoint_tenant(self, tid: str, state, world, key) -> str:
        from jax_mapping.io.checkpoint import save_checkpoint
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        path = self._live_ckpt_path(tid)
        save_checkpoint(
            path, {"fleet": state, "key": key, "world": world},
            config_json=self.cfg.to_json(),
            retain_generations=(
                self.cfg.resilience.checkpoint_retain_generations))
        return path

    def checkpoint_all(self) -> List[str]:
        """Checkpoint every live tenant's current state (active: its
        lane; suspect/suspended/quarantined: the held state) through
        the generation-retention machinery, then journal a
        per-tenant watermark record — the durability heartbeat
        `restore()` replays. Returns the paths written."""
        if self.checkpoint_dir is None:
            return []
        with self._lock:
            todo = []
            for tid, m in self._missions.items():
                if m.state == "evicted" or m.world is None:
                    continue
                if m.held_state is not None:
                    state = m.held_state
                elif m.state == "active":
                    state = self._lane_state_locked(tid)
                else:
                    continue
                todo.append((tid, m, state, m.world, m.key))
        paths = [self._checkpoint_tenant(tid, state, world, key)
                 for tid, m, state, world, key in todo]
        with self._lock:
            for tid, m, *_ in todo:
                self._journal_append("checkpoint", m, state=m.state)
        return paths

    def restore(self) -> dict:
        """Replay snapshot+journal and re-admit the recorded tenant
        set from its containment checkpoints: active tenants re-join
        the batch through the StagedWarmup admission path; suspended
        and quarantined tenants restore held-state-only (a restored
        quarantine resumes its probe schedule on the new plane's
        clock). Every restored tenant's epoch AND revision advance
        past their journaled watermarks — the PR 8 epoch protocol, so
        `/tiles?tenant=` clients resync instead of seeing revision
        regressions. A tenant whose checkpoint generations are ALL
        unreadable is reported `lost`; the rest still restore (the
        corruption doctrine: degrade, never crash).

        Returns ``{"restored": [...], "lost": [...], "meta": {...}}``.
        """
        if self._journal is None:
            return {"restored": [], "lost": [],
                    "meta": {"journal": False}}
        from jax_mapping.io.checkpoint import (
            load_checkpoint_with_fallback)
        # Deep-copy the rows: re-admission below APPENDS journal
        # records whose fold mutates the live registry's row dicts —
        # reading the watermarks through aliased rows would clobber
        # the journaled epoch/revision with the fresh mission's zeros.
        registry = {tid: dict(row)
                    for tid, row in self._journal.registry().items()}
        restored, lost = [], []
        for tid, row in registry.items():
            if row.get("state") in ("evicted", "new", None):
                continue
            shape = row.get("world_shape")
            dtype = row.get("world_dtype", "float32")
            if shape is None:
                lost.append(tid)
                continue
            template = {
                "fleet": FM.init_fleet_state(
                    self.cfg, jax.random.PRNGKey(0)),
                "key": jax.random.PRNGKey(0),
                "world": jnp.zeros(tuple(shape), dtype),
            }
            try:
                payload, _, _ = load_checkpoint_with_fallback(
                    self._live_ckpt_path(tid), template)
            except Exception:                    # noqa: BLE001
                # FileNotFoundError, CheckpointCorrupt, or a template
                # mismatch down the generation chain — all mean the
                # same thing here: this tenant's state is gone.
                lost.append(tid)
                continue
            fleet = payload["fleet"]
            world = payload["world"]
            key = jnp.asarray(payload["key"])
            seed = int(row.get("seed", 0))
            if row["state"] == "active":
                self._admit(tid, world, seed, fleet, None)
                with self._lock:
                    m = self._missions[tid]
                    m.epoch = int(row.get("epoch", -1)) + 1
                    m.revision = int(row.get("revision", 0)) + 1
                    m.steps = int(row.get("steps", 0))
                    m.key = key
                    self._journal_append("restore", m,
                                         state="active")
            else:
                with self._lock:
                    m = _Mission(tid, seed, jnp.asarray(world), key)
                    m.epoch = int(row.get("epoch", -1)) + 1
                    m.revision = int(row.get("revision", 0)) + 1
                    m.steps = int(row.get("steps", 0))
                    m.state = row["state"]
                    m.held_state = fleet
                    self._missions[tid] = m
                    if row["state"] == "quarantined":
                        self._lanehealth.mark_quarantined(
                            tid, self.n_ticks)
                    self._journal_append("restore", m,
                                         state=row["state"])
            restored.append(tid)
        from jax_mapping.obs.recorder import flight_recorder
        flight_recorder.record("tenancy_restore",
                               restored=len(restored), lost=len(lost))
        return {"restored": restored, "lost": lost,
                "meta": {"journal": True}}

    def tenant_lifecycle(self, tid: str) -> str:
        """The tenant's lifecycle state string (`active` / `suspended`
        / `quarantined` / `evicted` / `unknown`) — the /tiles status
        stamp's source."""
        with self._lock:
            m = self._missions.get(tid)
            return "unknown" if m is None else m.state

    # -- state access --------------------------------------------------------

    def live_batch(self) -> Optional[MB.TenantBatch]:
        """The current device batch (None when no tenant is active) —
        the bench/test device-barrier handle."""
        with self._lock:
            return self._batch

    def tenant_state(self, tid: str) -> FM.FleetState:
        """The tenant's current FleetState — its live lane when
        active and healthy, the held last-good state when suspect /
        suspended / quarantined (a sick lane's garbage never serves)."""
        with self._lock:
            m = self._missions[tid]
            if m.held_state is not None:
                return m.held_state
            if m.state == "active":
                return self._lane_state_locked(tid)
            raise ValueError(f"tenant {tid!r} is {m.state}; no state held")

    def tenant_grid(self, tid: str):
        return self.tenant_state(tid).grid

    def epoch(self, tid: str) -> int:
        with self._lock:
            return self._missions[tid].epoch

    def revision(self, tid: str) -> int:
        with self._lock:
            return self._missions[tid].revision

    def tile_store(self, tid: str):
        """Per-tenant serving TileStore (lazily built): the tenant's
        grid rendered through the ordinary `to_gray` path, revisioned
        by the tenant's OWN (epoch, revision) namespace — `/tiles?
        tenant=` delta sessions stay per-mission correct across
        co-tenant churn and suspend/resume cycles."""
        with self._lock:
            store = self._tile_stores.get(tid)
            if store is None:
                # Validate BEFORE constructing anything: this sits on
                # the public /tiles?tenant= surface, and caching a
                # store per unknown/evicted id would let a client loop
                # over bogus ids and grow the dict without bound.
                self._require(tid, ("active", "suspended",
                                    "quarantined"))
        if store is not None:
            return store
        from jax_mapping.ops import grid as G
        from jax_mapping.serving.tiles import TileStore

        def _revision() -> int:
            return self.revision(tid)

        def _snapshot():
            with self._lock:
                m = self._missions[tid]
                if m.state == "evicted" or (
                        m.state != "active" and m.held_state is None):
                    raise ValueError(
                        f"tenant {tid!r} is {m.state}; nothing to serve")
                # Revision BEFORE content (the serving-snapshot
                # ordering): both reads sit in one lock section here,
                # but the order still documents the contract. A held
                # state (suspect / suspended / quarantined) serves in
                # preference to the live lane: the revision is frozen
                # on exactly that content, so a frozen (epoch,
                # revision) pair can never alias two different bodies.
                rev = m.revision
                grid = (m.held_state.grid
                        if m.held_state is not None
                        else self._lane_state_locked(tid).grid)
            gray = np.asarray(G.to_gray(self.cfg.grid, grid))
            return rev, gray, None

        on_install = None
        if self.pipeline is not None:
            ledger = self.pipeline

            def on_install(rev, _tid=tid):
                ledger.encoded(rev, tenant=_tid)

        store = TileStore(self.cfg.serving, f"tenant:{tid}",
                          _revision, _snapshot, on_install=on_install)
        with self._lock:
            # First builder wins under concurrent HTTP readers.
            store = self._tile_stores.setdefault(tid, store)
        return store

    # -- internals -----------------------------------------------------------

    def _require(self, tid: str, states) -> _Mission:
        m = self._missions.get(tid)
        if m is None:
            raise KeyError(f"unknown tenant {tid!r}")
        allowed = (states,) if isinstance(states, str) else states
        if m.state not in allowed:
            raise ValueError(
                f"tenant {tid!r} is {m.state}, need {allowed}")
        return m

    def _lane_state_locked(self, tid: str) -> FM.FleetState:
        i = self._order.index(tid)
        return MB.lane_state(self._batch, i)

    def _rebuilt(self, order, extra: Optional[dict] = None):
        """(batch, prev_order, compacted) re-stacked for `order` —
        lanes already in the old batch slice out of it, `extra` maps
        not-yet-registered tids to their (state, world, key). Pure
        compute that can RAISE (ladder/ceiling refusal, world-shape
        mismatch) without touching any plane state: callers install
        the triple — and only then mutate the registry — under their
        own `with self._lock` block, so a failed rebuild rolls back to
        exactly the prior plane and every guarded-field write sits
        lexically inside a lock region (the B3 discipline)."""
        old_cap = (0 if self._batch is None
                   else int(self._batch.active.shape[0]))
        if not order:
            return None, [], old_cap > 0
        states, worlds, keys = [], [], []
        for tid in order:
            if extra is not None and tid in extra:
                s, w, k = extra[tid]
            else:
                m = self._missions[tid]
                if m.state == "quarantined" \
                        and m.held_state is not None:
                    # A quarantined lane's live state may be poisoned
                    # garbage; the held last-good state is what
                    # carries across a co-tenant churn rebuild (the
                    # lane re-freezes below either way).
                    s, w, k = m.held_state, m.world, m.key
                else:
                    s, w, k = self._old_lane(tid), m.world, m.key
            states.append(s)
            worlds.append(w)
            keys.append(k)
        cap = MB.bucket_capacity(len(order),
                                 self.cfg.tenancy.max_tenants,
                                 exact=self.cfg.tenancy.bit_exact_buckets)
        batch = MB.make_tenant_batch(states, worlds, keys,
                                     capacity=cap)
        for i, tid in enumerate(order):
            m = self._missions.get(tid)
            if m is not None and m.state == "quarantined":
                # make_tenant_batch marks every real lane active;
                # quarantined lanes must come back FROZEN.
                batch = batch._replace(
                    active=batch.active.at[i].set(False))
        return batch, list(order), cap < old_cap

    def _old_lane(self, tid: str) -> FM.FleetState:
        if self._batch is None or tid not in self._prev_order:
            raise KeyError(f"tenant {tid!r} has no live lane to carry")
        return MB.lane_state(self._batch, self._prev_order.index(tid))

    def _prewarm_bucket(self, bucket: int, template: FM.FleetState,
                        world) -> bool:
        """Compile (or warm-tier-load) the megabatch variant for
        `bucket` BEFORE the tenant joins, through the StagedWarmup
        ladder: begin_warming -> zeros pre-warm (AOT pool / persistent
        cache / cold compile) -> readiness gate vs compile_budget.json
        + devprof rebaseline -> ready. Returns True when a warm-up
        actually ran."""
        with self._lock:
            if not self.cfg.tenancy.prewarm_on_admit \
                    or bucket in self._warmed_buckets:
                return False
        from jax_mapping.obs.devprof import abstract_signature
        warm = MB.make_tenant_batch(
            [template], [world], [jax.random.PRNGKey(0)])
        # Pad the 1-mission template batch up to the target bucket by
        # abstractly widening the leading axis: the signature is what
        # compiles, not the values.
        def widen(x):
            return jax.ShapeDtypeStruct((bucket,) + tuple(x.shape[1:]),
                                        x.dtype)
        warm_abs = jax.tree.map(widen, warm)
        sig = abstract_signature(
            (self.cfg, warm_abs, self.world_res_m), {})
        self.warmup.begin_warming()
        # manifest=False: warm ONLY this bucket's signature — an
        # admission must not re-run the whole persisted AOT warm sweep
        # (that is the RESTART path's job, once).
        self.warmup.prewarm(signatures={MEGABATCH_ENTRY: [sig]},
                            force=True, manifest=False)
        self.warmup.mark_ready()
        with self._lock:
            self._warmed_buckets.add(bucket)
            self.n_prewarms += 1
        return True

    # -- exports -------------------------------------------------------------

    def status(self) -> dict:
        """The /status `tenancy` object (one consistent section)."""
        with self._lock:
            # Quarantined tenants keep their (frozen) lane, so they
            # occupy a slot without being active — occupancy counts
            # slots, n_active counts live missions.
            occupied = len(self._order)
            n_active = sum(
                1 for t in self._order
                if self._missions[t].state == "active")
            cap = (0 if self._batch is None
                   else int(self._batch.active.shape[0]))
            tenants = {
                tid: {"state": m.state, "epoch": m.epoch,
                      "revision": m.revision, "steps": m.steps,
                      "seed": m.seed}
                for tid, m in sorted(self._missions.items())}
            counters = dict(
                n_admitted=self.n_admitted, n_evicted=self.n_evicted,
                n_suspended=self.n_suspended, n_resumed=self.n_resumed,
                n_prewarms=self.n_prewarms, n_ticks=self.n_ticks,
                n_compactions=self.n_compactions,
                n_quarantined=self.n_quarantined)
            warmed = sorted(self._warmed_buckets)
            health = self._lanehealth.snapshot()
            admission = {
                "in_flight": self._admissions_in_flight,
                "queue_max": self.cfg.tenancy.admission_queue_max,
                "n_rejected": self.n_admissions_rejected,
            }
            journal = None
            if self._journal is not None:
                journal = {"seq": self._journal.seq,
                           "n_appends": self._journal.n_appends,
                           "n_compactions": self._journal.n_compactions}
        n_susp = sum(1 for t in tenants.values()
                     if t["state"] == "suspended")
        n_evic = sum(1 for t in tenants.values()
                     if t["state"] == "evicted")
        n_quar = sum(1 for t in tenants.values()
                     if t["state"] == "quarantined")
        return {
            "n_active": n_active,
            "n_suspended": n_susp,
            "n_evicted": n_evic,
            "n_quarantined_now": n_quar,
            "bucket_capacity": cap,
            "bucket_occupancy": (occupied / cap) if cap else 0.0,
            "pad_waste_frac": ((cap - occupied) / cap) if cap else 0.0,
            "warmed_buckets": warmed,
            "warmup": self.warmup.snapshot(),
            "tenants": tenants,
            "health": health,
            "admission": admission,
            "journal": journal,
            **counters,
        }

    def metric_families(self):
        """`jax_mapping_tenant_*` gauge families for the declarative
        /metrics registry (obs/registry.py) — one consistent status
        snapshot per render."""
        from jax_mapping.obs.registry import Family
        s = self.status()
        return (
            Family("jax_mapping_tenant_active", "gauge",
                   (("", str(s["n_active"])),)),
            Family("jax_mapping_tenant_suspended", "gauge",
                   (("", str(s["n_suspended"])),)),
            Family("jax_mapping_tenant_evicted", "gauge",
                   (("", str(s["n_evicted"])),)),
            Family("jax_mapping_tenant_bucket_capacity", "gauge",
                   (("", str(s["bucket_capacity"])),)),
            Family("jax_mapping_tenant_bucket_occupancy", "gauge",
                   (("", f"{s['bucket_occupancy']:.4f}"),)),
            Family("jax_mapping_tenant_pad_waste_frac", "gauge",
                   (("", f"{s['pad_waste_frac']:.4f}"),)),
            Family("jax_mapping_tenant_ticks_total", "counter",
                   (("", str(s["n_ticks"])),)),
            Family("jax_mapping_tenant_quarantined", "gauge",
                   (("", str(s["n_quarantined_now"])),)),
            Family("jax_mapping_tenant_quarantines_total", "counter",
                   (("", str(s["n_quarantined"])),)),
            Family("jax_mapping_tenant_admission_rejected_total",
                   "counter",
                   (("", str(s["admission"]["n_rejected"])),)),
        )
