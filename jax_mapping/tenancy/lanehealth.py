"""Per-tenant lane-health hysteresis ladder: healthy -> suspect -> QUARANTINED.

The megabatch's device-computed health word (`megabatch.HEALTH_*`
bits, fused into the `megabatch_step` dispatch) says what a lane
PRODUCED this tick; this ladder says what the control plane should DO
about it, with the `recovery/watchdog.EstimatorWatchdog` semantics
lifted one level up, from robots to tenants:

* one flagged tick demotes healthy -> SUSPECT (the plane freezes the
  tenant's published revision there: a flagged tick never publishes,
  so "last-good revision" is exact, not approximate);
* `quarantine_persist_ticks` CONSECUTIVE flagged ticks declare
  QUARANTINED — the plane then freezes the lane in place via the
  pad-style ``active=False`` select (an exact no-op for co-tenants on
  the EXACT_BUCKETS ladder, by the same construction pads use);
* a clean tick returns suspect -> healthy, but there is NO flag-based
  exit from quarantine (the watchdog asymmetry: a quarantined lane is
  frozen and produces no fresh evidence) — re-admission happens only
  through a verified probe: `probe_due` schedules a bounded number of
  probes on the deterministic tick clock (same-seed chaos runs
  quarantine AND probe at identical steps), and the plane's probe
  finite-checks the held last-good state plus one solo-executable
  tick before `note_probe(ok=True)` approves resumption.

Threading: this is a LEAF data structure owned by the control plane
and mutated only under the plane's `_lock` (it takes no lock of its
own — the `_missions` registry convention; `analysis/protection.py`
declares the field). `transitions` is the assertion surface for
guardrail tests, mirroring `EstimatorWatchdog.transitions`.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from jax_mapping.config import TenancyConfig

#: Ladder states (per tenant).
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"


class _LaneHealth:
    __slots__ = ("state", "streak", "last_word", "n_flagged",
                 "quarantined_tick", "probes_used")

    def __init__(self):
        self.state = HEALTHY
        self.streak = 0
        self.last_word = 0
        self.n_flagged = 0
        self.quarantined_tick: Optional[int] = None
        self.probes_used = 0


class LaneHealthLadder:
    """Fold per-tick health words into per-tenant containment state."""

    def __init__(self, cfg: TenancyConfig):
        self.cfg = cfg
        self._lanes: Dict[str, _LaneHealth] = {}
        #: (tick, tenant, old_state, new_state) — the guardrail-test
        #: assertion surface; deterministic across same-seed runs.
        self.transitions: List[tuple] = []
        self.n_quarantines = 0
        self.n_readmits = 0
        self.n_probes = 0

    def _lane(self, tid: str) -> _LaneHealth:
        lane = self._lanes.get(tid)
        if lane is None:
            lane = self._lanes[tid] = _LaneHealth()
        return lane

    def observe(self, tid: str, word: int, tick: int) -> Optional[str]:
        """One tick's health word for `tid`. Returns QUARANTINED when
        THIS observation declares it (the caller then freezes the
        lane), else None. Quarantined lanes ignore further words —
        their lane is frozen, the word describes nothing new."""
        lane = self._lane(tid)
        if lane.state == QUARANTINED:
            return None
        if word == 0:
            lane.streak = 0
            lane.last_word = 0
            if lane.state == SUSPECT:
                lane.state = HEALTHY
                self.transitions.append((tick, tid, SUSPECT, HEALTHY))
            return None
        lane.streak += 1
        lane.last_word = word
        lane.n_flagged += 1
        if lane.state == HEALTHY:
            lane.state = SUSPECT
            self.transitions.append((tick, tid, HEALTHY, SUSPECT))
        if lane.streak >= max(1, self.cfg.quarantine_persist_ticks):
            lane.state = QUARANTINED
            lane.quarantined_tick = tick
            lane.probes_used = 0
            self.n_quarantines += 1
            self.transitions.append((tick, tid, SUSPECT, QUARANTINED))
            return QUARANTINED
        return None

    def probe_due(self, tid: str, tick: int) -> bool:
        """True when the deterministic probe schedule owes `tid` a
        re-admission probe at `tick`: every `readmit_probe_ticks`
        plane ticks after the quarantine declaration, at most
        `max_readmit_probes` times — the bounded budget that keeps a
        NaN-poisoned lane from buying a solo dispatch forever."""
        lane = self._lanes.get(tid)
        if lane is None or lane.state != QUARANTINED:
            return False
        if lane.probes_used >= max(0, self.cfg.max_readmit_probes):
            return False
        cadence = max(1, self.cfg.readmit_probe_ticks)
        elapsed = tick - (lane.quarantined_tick or 0)
        return elapsed > 0 and elapsed % cadence == 0

    def note_probe(self, tid: str, ok: bool, tick: int) -> bool:
        """Record one probe verdict. ok=True readmits (HEALTHY, clean
        streak — the watchdog `readmit` semantics) and returns True;
        the caller then re-activates the lane and bumps the tenant's
        epoch. ok=False burns one unit of the probe budget."""
        lane = self._lane(tid)
        self.n_probes += 1
        if not ok:
            lane.probes_used += 1
            return False
        lane.state = HEALTHY
        lane.streak = 0
        lane.last_word = 0
        lane.quarantined_tick = None
        lane.probes_used = 0
        self.n_readmits += 1
        self.transitions.append((tick, tid, QUARANTINED, HEALTHY))
        return True

    def mark_quarantined(self, tid: str, tick: int) -> None:
        """Re-assert a quarantine without fresh evidence — the
        `restore()` path: a journal-replayed quarantined tenant
        resumes its probe schedule from the restored plane's clock
        instead of silently coming back healthy."""
        lane = self._lane(tid)
        if lane.state == QUARANTINED:
            return
        old = lane.state
        lane.state = QUARANTINED
        lane.quarantined_tick = tick
        lane.probes_used = 0
        self.transitions.append((tick, tid, old, QUARANTINED))

    def state(self, tid: str) -> str:
        lane = self._lanes.get(tid)
        return HEALTHY if lane is None else lane.state

    def forget(self, tid: str) -> None:
        """Drop a tenant's ladder entry (eviction): a later re-admission
        of the same id starts with a clean bill of health."""
        self._lanes.pop(tid, None)

    def quarantined(self) -> List[str]:
        return sorted(t for t, lane in self._lanes.items()
                      if lane.state == QUARANTINED)

    def snapshot(self) -> dict:
        """The /status.tenancy.health export (caller holds the plane's
        `_lock`, the owning-lock convention)."""
        return {
            "lanes": {
                tid: {"state": lane.state, "streak": lane.streak,
                      "last_word": lane.last_word,
                      "n_flagged": lane.n_flagged,
                      "probes_used": lane.probes_used}
                for tid, lane in sorted(self._lanes.items())},
            "n_quarantines": self.n_quarantines,
            "n_readmits": self.n_readmits,
            "n_probes": self.n_probes,
            "transitions": list(self.transitions)[-32:],
        }
